"""Per-tenant metering and cost attribution (``obs.usage``): the
ISSUE-20 exactness contract.

- **Device-seconds telescope bitwise.** Every nanosecond an engine
  spends computing lands on exactly one tenant and exactly one
  request — ``busy_ns == sum(per-tenant) == sum(per-request)`` as
  integer identities, under a deterministic TickingClock, INCLUDING
  runs with forced preemption + requeue (single-engine) and a killed
  replica's router-level requeue (local 2-replica fleet).
- **Page-second integrals close.** ``PagedKVCache`` stamps pages-held
  x time per sequence in integer nanoseconds; after cancel (and after
  the multi-process kill drill) every interval is closed —
  ``alloc == free``, no open stamps, ``verify()`` holds.
- **The drill's journals bill correctly.** The cached 2-replica kill
  drill (one execution per process, shared with chaos_run/
  fleet_report) journals ``tenant.usage`` engine truth per rank and
  tenant-stamped request records whose rollup carries the drill
  tenant — and the live scrape-vs-truth bitwise gauge gate already
  ran inside the drill itself.
"""
import atexit
import shutil
import tempfile

import pytest

from paddle_tpu.obs.usage import (TickingClock, engine_tenant_usage,
                                  rollup_requests, router_tenant_usage)
from paddle_tpu.serving import (ManualClock, PagedKVCache, Scheduler,
                                ServeEngine, TinyLM)

# share one executable cache across this module's engines (same
# geometry class as tests/test_serve_fleet.py: pay each distinct
# compile once, hydrate everywhere else)
_AOT_DIR = tempfile.mkdtemp(prefix="pt_usage_aot_")
atexit.register(shutil.rmtree, _AOT_DIR, ignore_errors=True)


def _engine(pages=8, page_size=2, max_seq_len=8, token_budget=64,
            clock=None):
    cache = PagedKVCache(pages, page_size, 2, 8,
                         max_seq_len=max_seq_len)
    eng = ServeEngine(TinyLM(num_heads=2, head_dim=8), cache,
                      scheduler=Scheduler(cache,
                                          token_budget=token_budget,
                                          clock=clock),
                      aot_cache_dir=_AOT_DIR)
    return eng, cache


class TestDeviceSecondTelescoping:
    def test_busy_equals_tenant_and_request_sums_bitwise(self):
        """A preemption-free two-tenant run: the TickingClock makes
        every prefill/decode span a deterministic integer-ns value and
        the three ledgers (busy, per-tenant, per-request) must agree
        as INT equalities, not approximately."""
        eng, cache = _engine(pages=16, page_size=4, max_seq_len=16,
                             clock=TickingClock())
        ra = eng.submit([3, 1, 4], max_new_tokens=4, tenant="a")
        rb = eng.submit([2, 7], max_new_tokens=3, tenant="b")
        eng.run()
        assert ra.state == "FINISHED" and rb.state == "FINISHED"
        eng.usage.verify()   # the telescoping identity, asserted
        m = eng.usage
        assert m.busy_ns > 0
        assert m.busy_ns == sum(m.device_ns.values())
        assert m.busy_ns == sum(m.request_ns.values())
        assert m.busy_ns == m.prefill_ns + m.decode_ns
        assert set(m.device_ns) == {"a", "b"}

    def test_telescoping_survives_preemption_and_requeue(self):
        """The acceptance fixture: a pool sized to force preemption +
        arrival-order requeue mid-decode. Preempted lanes drop out of
        the decode split (an all-preempted pass charges nobody), yet
        the integer ledgers still close bitwise and the page-second
        integrals all end closed."""
        eng, cache = _engine(clock=TickingClock())
        reqs = [eng.submit([1, 2], max_new_tokens=6,
                           tenant=f"t{i % 2}")
                for i in range(4)]
        eng.run(max_steps=200)
        assert all(r.state == "FINISHED" for r in reqs)
        assert eng.scheduler.preemptions >= 1, \
            "pool was sized to force preemption; fixture went vacuous"
        eng.usage.verify()
        m = eng.usage
        assert m.busy_ns == sum(m.device_ns.values()) \
            == sum(m.request_ns.values())
        assert set(m.device_ns) == {"t0", "t1"}
        # a preempted request's pages were freed and re-allocated: its
        # integral accumulates across incarnations and ends closed
        pu = cache.page_usage()
        assert not pu["open"]
        assert pu["seq_allocs"] == pu["seq_frees"]
        assert cache.verify()
        eu = engine_tenant_usage(eng)
        assert eu["busy_ns"] == m.busy_ns
        assert sum(t["device_ns"] for t in eu["tenants"].values()) \
            == m.busy_ns
        assert sum(t["page_ns"] for t in eu["tenants"].values()) > 0

    def test_routed_fleet_kill_requeue_still_telescopes(self):
        """Router-level loss: a local 2-replica fleet on a shared
        TickingClock, one replica killed with a request in flight. The
        victim's metered nanoseconds die with it (exactly as a real
        machine loss); every SURVIVING engine's ledger must still
        close bitwise, and the router's per-tenant rollup must count
        the requeue and serve every token to completion."""
        from paddle_tpu.resilience import ReplicaSupervisor
        from paddle_tpu.serving.fleet import (ReplicaPool, ReplicaSpec,
                                              Router, TenantPolicy)

        clock = TickingClock()
        pool = ReplicaPool(
            ReplicaSpec(vocab_size=32, pages=64, page_size=4,
                        max_seq_len=32, token_budget=128,
                        aot_cache_dir=_AOT_DIR, warm=False),
            replicas=2, mode="local", clock=clock,
            supervisor=ReplicaSupervisor(sleep=lambda s: None))
        router = Router(pool, clock=clock, tenants={
            "a": TenantPolicy(weight=3.0),
            "b": TenantPolicy(weight=1.0)})
        reqs = [router.submit([1, 2, 3], max_new_tokens=3,
                              tenant=("a" if i % 2 else "b"),
                              rid=f"u{i}") for i in range(4)]
        router.dispatch()
        victim = reqs[0].replica_id
        pool.replicas[victim].kill()
        router.check_replicas()       # requeue + relaunch
        for _ in range(300):
            router.step()
            clock.advance(0.01)
            if not router.inflight and not router.queue_depth:
                break
        assert all(r.state == "FINISHED" for r in reqs)
        assert any(r.requeues for r in reqs), \
            "kill stranded nobody — requeue fixture went vacuous"
        for eng in pool.local_engines():
            eng.usage.verify()
            eu = engine_tenant_usage(eng)
            assert eu["busy_ns"] == sum(
                t["device_ns"] for t in eu["tenants"].values())
            assert eu["page_open"] == 0
            assert eu["seq_allocs"] == eu["seq_frees"]
        tu = router_tenant_usage(router)
        assert set(tu["tenants"]) == {"a", "b"}
        assert tu["served_total"] > 0
        assert sum(d["requeued"] for d in tu["tenants"].values()) >= 1
        assert all(d["completed"] == 2 for d in tu["tenants"].values())
        router.close()


class TestPageSecondClosure:
    def test_cancel_mid_flight_closes_the_integral(self):
        """A cancelled request's pages free immediately and its
        page-second integral closes at the cancel stamp — the
        hand-computable ManualClock twin of the chaos-kill closure the
        drill facet below asserts."""
        clock = ManualClock()
        eng, cache = _engine(pages=16, page_size=4, max_seq_len=16,
                             clock=clock)
        keep = eng.submit([5, 6, 7], max_new_tokens=3, tenant="a")
        doomed = eng.submit([1, 2, 3, 4, 5], max_new_tokens=8,
                            tenant="b")
        clock.advance(1.0)
        eng.step()                    # both prefilled: pages held
        held = len(cache.page_table(doomed.rid))
        assert held >= 1
        clock.advance(2.0)
        eng.cancel(doomed)
        # 2 pages x 3 s (alloc at t=1 inside the step... the exact
        # value depends on the prefill stamp, so assert closure and
        # positivity, not a constant: the hand-computed-constant gate
        # lives in tools/usage_report.py --self-test)
        assert cache.closed_page_ns(doomed.rid) > 0
        eng.run(max_steps=100)
        assert keep.state == "FINISHED"
        pu = cache.page_usage()
        assert not pu["open"]
        assert pu["seq_allocs"] == pu["seq_frees"] == 2
        assert cache.verify()


class TestDrillTenantFacet:
    """Satellites on the CACHED multi-process kill drill (one
    execution per process — tier-1 pays for one drill total). The
    drill itself already ran the live gate: scraped ``tenant_*``
    gauges bitwise-equal to ``router_tenant_usage`` truth."""

    def test_drill_metered_the_drill_tenant(self):
        from paddle_tpu.serving.fleet import drill

        res = drill.drill_result()
        assert not res["failures"], res["failures"]
        tu = res["tenant_usage"]
        assert tu and set(tu["tenants"]) == {"drill"}
        d = tu["tenants"]["drill"]
        assert d["completed"] == len(res["requests"])
        assert d["requeued"] >= 1          # the kill's strands
        assert d["share"] == 1.0 and d["weight_share"] == 1.0
        # single tenant: its served tokens ARE the fleet total
        assert d["served_tokens"] == tu["served_total"] > 0

    def test_rank_journals_carry_closed_engine_usage(self):
        """Each rank's final ``tenant.usage`` event (the worker's
        before-goodbye engine truth; a hard-killed incarnation never
        writes one — machine loss loses its meter, as billed) must be
        internally closed: busy == sum(tenant device-ns), zero open
        page intervals, alloc == free."""
        from paddle_tpu.obs import fleet as obs_fleet
        from paddle_tpu.serving.fleet import drill

        res = drill.drill_result()
        assert not res["failures"], res["failures"]
        agg = obs_fleet.aggregate(res["run_dir"])
        tu = agg["tenant_usage"]
        assert tu is not None
        assert set(tu["replicas"]), "no rank journaled tenant.usage"
        for rank, e in tu["replicas"].items():
            assert e["busy_ns"] == sum(
                t["device_ns"] for t in e["tenants"].values()), \
                f"rank {rank} engine ledger leaked nanoseconds: {e}"
            assert e["page_open"] == 0, \
                f"rank {rank} left open page intervals: {e}"
            assert set(e["tenants"]) <= {"drill"}
        # the pooled request records rebuild the bill per tenant
        assert set(tu["tenants"]) == {"drill"}
        row = tu["tenants"]["drill"]
        assert row["completed"] >= len(res["requests"])
        assert row["device_ns"] > 0 and row["page_ns"] > 0
        # router journal carried the tenant.summary -> fleet fairness
        assert tu["router"] is not None
        assert set(tu["router"]["tenants"]) == {"drill"}

    def test_usage_report_renders_the_drill_chargeback(self):
        """tools/usage_report.py over the drill's run dir: the
        chargeback table bills the drill tenant with nonzero
        device-ms and closed replica ledgers (TELESCOPED lines)."""
        import importlib.util
        import os

        from paddle_tpu.serving.fleet import drill

        res = drill.drill_result()
        assert not res["failures"], res["failures"]
        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        spec = importlib.util.spec_from_file_location(
            "usage_report", os.path.join(root, "tools",
                                         "usage_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        u = mod.load_usage(res["run_dir"])
        assert "drill" in u["tenants"]
        assert u["tenants"]["drill"]["device_ns"] > 0
        table = mod.render_usage(u)
        assert "drill" in table
        assert "TELESCOPED" in table and "LEAK" not in table
        # A-vs-A on the real artifact: no self-regression
        rep = mod.diff_usage(u, u)
        assert not rep["regression"], rep
