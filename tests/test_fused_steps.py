"""Fused multi-step execution (ISSUE 6 tentpole #1/#2): Executor.run_steps
and TrainStep.run_fused drive K microbatches through one lax.scan
executable; the DevicePrefetcher overlaps host->device feed with
compute. Correctness pins: trajectories vs K sequential steps, state
advancement, error surfaces, and the journal's steps_fused records."""
import json
import os

import numpy as np
import pytest

import jax

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn as nn
import paddle_tpu.ops as ops
from paddle_tpu import optim
from paddle_tpu.io_ import (DevicePrefetcher, prefetch_to_device,
                            executor_feed_shardings)


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def _build_mlp(batch=16, lr=0.05):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 8])
        y = fluid.data(name="y", shape=[batch, 1])
        h = fluid.layers.fc(x, size=16, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return prog, startup, loss


def _feeds(K, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randn(batch, 8).astype(np.float32),
             "y": rng.randn(batch, 1).astype(np.float32)}
            for _ in range(K)]


# -- Executor.run_steps ------------------------------------------------------


class TestRunSteps:
    def test_prestacked_dict_matches_feed_list(self, static_mode):
        K = 4
        feeds = _feeds(K)
        pt.seed(0)
        prog, startup, loss = _build_mlp()
        exe = fluid.Executor()
        exe.run(startup)
        (a,) = exe.run_steps(prog, feeds=feeds, fetch_list=[loss])

        pt.seed(0)
        prog2, startup2, loss2 = _build_mlp()
        exe2 = fluid.Executor()
        exe2.run(startup2)
        stacked = {n: np.stack([f[n] for f in feeds])
                   for n in feeds[0]}
        (b,) = exe2.run_steps(prog2, feeds=stacked, fetch_list=[loss2],
                              steps=K)
        assert a.tobytes() == b.tobytes()

    def test_persistables_advance_like_sequential(self, static_mode):
        """After a fused window the scope's parameters are bitwise what
        K sequential runs leave behind."""
        from paddle_tpu.static_.program import global_scope

        K = 4
        feeds = _feeds(K)
        pt.seed(0)
        prog, startup, loss = _build_mlp()
        exe = fluid.Executor()
        exe.run(startup)
        for f in feeds:
            exe.run(prog, feed=f, fetch_list=[loss])
        entry = next(iter(exe._cache.values()))
        seq_params = {n: np.asarray(global_scope().find_var(n))
                      for n in entry.updated}

        pt.seed(0)
        prog2, startup2, loss2 = _build_mlp()
        exe2 = fluid.Executor()
        exe2.run(startup2)
        exe2.run_steps(prog2, feeds=feeds, fetch_list=[loss2])
        entry2 = next(iter(exe2._cache.values()))
        assert tuple(entry2.updated)  # something persisted
        # identical builds list their persistables in the same order
        # (names differ by the unique-name counter)
        assert len(entry2.updated) == len(entry.updated)
        for n1, n2 in zip(entry.updated, entry2.updated):
            got = np.asarray(global_scope().find_var(n2))
            assert got.tobytes() == seq_params[n1].tobytes(), (n1, n2)

    def test_feed_validation_errors(self, static_mode):
        pt.seed(0)
        prog, startup, loss = _build_mlp()
        exe = fluid.Executor()
        exe.run(startup)
        feeds = _feeds(2)
        with pytest.raises(ValueError, match="at least one feed"):
            exe.run_steps(prog, feeds=[], fetch_list=[loss])
        with pytest.raises(ValueError, match="steps=3 but 2"):
            exe.run_steps(prog, feeds=feeds, fetch_list=[loss], steps=3)
        bad = [feeds[0], {"x": feeds[1]["x"]}]
        with pytest.raises(ValueError, match="same variables"):
            exe.run_steps(prog, feeds=bad, fetch_list=[loss])
        with pytest.raises(ValueError, match="explicit steps"):
            exe.run_steps(prog, feeds={"x": np.zeros((2, 16, 8))},
                          fetch_list=[loss])
        with pytest.raises(ValueError, match="leading microbatch axis"):
            exe.run_steps(
                prog, feeds={"x": np.zeros((2, 16, 8), np.float32),
                             "y": np.zeros((16, 1), np.float32)},
                fetch_list=[loss], steps=2)

    def test_multi_fetch_stacks_every_fetch(self, static_mode):
        K = 3
        pt.seed(0)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.data(name="x", shape=[4, 2])
            h = fluid.layers.fc(x, size=2)
            s = fluid.layers.reduce_sum(h)
            m = fluid.layers.reduce_mean(h)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(1)
        feeds = [{"x": rng.randn(4, 2).astype(np.float32)}
                 for _ in range(K)]
        outs = exe.run_steps(prog, feeds=feeds, fetch_list=[s, m])
        assert len(outs) == 2
        assert outs[0].shape == (K,) and outs[1].shape == (K,)
        seq = [exe.run(prog, feed=f, fetch_list=[s, m]) for f in feeds]
        for k in range(K):
            assert np.asarray(seq[k][0]).tobytes() == \
                outs[0][k].tobytes()
            assert np.asarray(seq[k][1]).tobytes() == \
                outs[1][k].tobytes()

    def test_journal_records_steps_fused(self, static_mode, tmp_path):
        from paddle_tpu.obs.journal import RunJournal

        K = 4
        pt.seed(0)
        prog, startup, loss = _build_mlp()
        exe = fluid.Executor()
        exe.run(startup)
        feeds = _feeds(K)
        with RunJournal(str(tmp_path / "run"), compute_flops=False):
            exe.run_steps(prog, feeds=feeds, fetch_list=[loss])
            exe.run(prog, feed=feeds[0], fetch_list=[loss])
        recs = [json.loads(line) for line in
                open(tmp_path / "run" / "journal.jsonl")]
        steps = [r for r in recs if r["t"] == "step"]
        assert len(steps) == 2  # one record per DISPATCH, not per K
        fused, single = steps
        assert fused["steps_fused"] == K
        assert fused["examples"] == 16 * K
        assert fused["loss"] is not None  # trajectory endpoint scalar
        assert "steps_fused" not in single
        compiles = [r for r in recs if r["t"] == "event"
                    and r["kind"] == "compile"]
        assert any(e.get("steps_fused") == K for e in compiles)
        # run summary weights fused windows: 2 records, K+1 opt steps
        (end,) = [r for r in recs if r["t"] == "run_end"]
        assert end["summary"]["steps"] == 2
        assert end["summary"]["optimizer_steps"] == K + 1
        assert end["summary"]["productive_steps"] == K + 1

    def test_fetch_async_journal_does_not_sync(self, static_mode,
                                               tmp_path):
        """Async fetches must journal metadata-only summaries — no
        hidden scalar device read on the step path."""
        from paddle_tpu.obs.journal import RunJournal

        pt.seed(0)
        prog, startup, loss = _build_mlp()
        exe = fluid.Executor()
        exe.run(startup)
        f = _feeds(1)[0]
        with RunJournal(str(tmp_path / "run"), compute_flops=False):
            (lazy,) = exe.run(prog, feed=f, fetch_list=[loss],
                              fetch_async=True)
            assert isinstance(lazy, jax.Array)
        recs = [json.loads(line) for line in
                open(tmp_path / "run" / "journal.jsonl")]
        (step,) = [r for r in recs if r["t"] == "step"]
        assert step["loss"] is None  # not read off-device
        assert step["fetches"][0] == {"shape": [], "dtype": "float32"}


# -- TrainStep.run_fused -----------------------------------------------------


def _eager_setup(opt_cls=None, **opt_kw):
    pt.seed(0)
    model = nn.Linear(8, 1)
    opt_cls = opt_cls or optim.SGD
    opt = opt_cls(learning_rate=0.05, parameters=model.parameters(),
                  **opt_kw)
    step = pt.TrainStep(model, opt,
                        lambda m, x, y: ops.mean((m(x) - y) ** 2))
    return model, opt, step


def _eager_batches(K, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(16, 8).astype(np.float32),
             rng.randn(16, 1).astype(np.float32)) for _ in range(K)]


class TestRunFused:
    @pytest.mark.parametrize("opt_cls,kw", [
        (optim.SGD, {}),
        (optim.Momentum, {"momentum": 0.9}),
        (optim.AdamW, {}),
    ])
    def test_matches_sequential_trajectory(self, opt_cls, kw):
        K = 6
        batches = _eager_batches(K)
        m1, o1, s1 = _eager_setup(opt_cls, **kw)
        pt.seed(7)
        seq = [float(np.asarray(s1(*b)._data)) for b in batches]

        m2, o2, s2 = _eager_setup(opt_cls, **kw)
        pt.seed(7)
        traj = np.asarray(s2.run_fused(batches)._data)
        assert traj.shape == (K,)
        # same ops / keys / lr; XLA may fuse the scan body marginally
        # differently than the standalone step, so float tolerance
        np.testing.assert_allclose(traj, seq, rtol=1e-5, atol=1e-7)
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(
                np.asarray(p1._data), np.asarray(p2._data),
                rtol=1e-5, atol=1e-7)
        assert o2._global_step == K == o1._global_step

    def test_one_compile_entry_per_window_shape(self):
        _, _, step = _eager_setup()
        batches = _eager_batches(4)
        step.run_fused(batches)
        step.run_fused(batches)  # same shape: cached
        fused_sigs = [s for s in step._compiled
                      if isinstance(s, tuple) and s and s[0] == "fused"]
        assert len(fused_sigs) == 1
        step.run_fused(_eager_batches(2), steps=2)  # new K: new entry
        fused_sigs = [s for s in step._compiled
                      if isinstance(s, tuple) and s and s[0] == "fused"]
        assert len(fused_sigs) == 2

    def test_prestacked_matches_list_form(self):
        K = 4
        batches = _eager_batches(K)
        _, _, s1 = _eager_setup()
        pt.seed(9)
        a = np.asarray(s1.run_fused(batches)._data)
        _, _, s2 = _eager_setup()
        pt.seed(9)
        stacked = (np.stack([b[0] for b in batches]),
                   np.stack([b[1] for b in batches]))
        b = np.asarray(s2.run_fused(stacked, steps=K)._data)
        assert a.tobytes() == b.tobytes()

    def test_shape_mismatch_raises(self):
        _, _, step = _eager_setup()
        rows = _eager_batches(3)
        rows[1] = (rows[1][0][:8], rows[1][1][:8])
        with pytest.raises(ValueError, match="uniform shapes"):
            step.run_fused(rows)
        with pytest.raises(ValueError, match="steps must be >= 1"):
            step.run_fused([], steps=0)

    def test_stochastic_model_uses_per_step_keys(self):
        """Dropout inside the fused window: per-step pre-drawn keys give
        the sequential trajectory (same host RNG stream)."""
        import paddle_tpu.nn.functional as F

        def make():
            pt.seed(0)
            model = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5),
                                  nn.Linear(8, 1))
            opt = optim.SGD(learning_rate=0.05,
                            parameters=model.parameters())
            return model, pt.TrainStep(
                model, opt, lambda m, x, y: ops.mean((m(x) - y) ** 2))

        K = 4
        batches = _eager_batches(K)
        _, s1 = make()
        pt.seed(42)
        seq = [float(np.asarray(s1(*b)._data)) for b in batches]
        _, s2 = make()
        pt.seed(42)
        traj = np.asarray(s2.run_fused(batches)._data)
        np.testing.assert_allclose(traj, seq, rtol=1e-5, atol=1e-7)
        assert len(set(np.round(traj, 6))) > 1  # dropout actually varied

    def test_collective_profile_covers_fused_entry(self):
        """The fused sig's captured arg structs support the PR-5
        collective profiling path (no collectives on one host device,
        but the lowering must succeed and profile as zero)."""
        _, _, step = _eager_setup()
        step.run_fused(_eager_batches(2), steps=2)
        prof = step.collective_profile()
        assert prof is not None and prof["n_ops"] == 0


# -- DevicePrefetcher --------------------------------------------------------


class TestDevicePrefetcher:
    def test_batches_arrive_in_order_as_device_arrays(self):
        feeds = [{"x": np.full((4, 2), i, np.float32)} for i in range(6)]
        got = list(prefetch_to_device(feeds, depth=2))
        assert len(got) == 6
        for i, b in enumerate(got):
            assert isinstance(b["x"], jax.Array)
            assert float(np.asarray(b["x"])[0, 0]) == float(i)

    def test_tuple_batches_and_tensor_unwrap(self):
        t = pt.to_tensor(np.ones((2, 2), np.float32))
        (a, b), = list(prefetch_to_device([(t, np.zeros(3))]))
        assert isinstance(a, jax.Array) and isinstance(b, jax.Array)

    def test_shardings_batch_container_mismatch_raises(self):
        """A shardings spec that can't be matched to the batch container
        must fail loudly (in batch order), never silently fall back to
        default placement."""
        sh = {"x": None}
        it = prefetch_to_device([(np.zeros(2, np.float32),)],
                                shardings=sh, depth=2)
        with pytest.raises(TypeError, match="cannot be matched"):
            next(it)
        it2 = prefetch_to_device([{"x": np.zeros(2, np.float32)}],
                                 shardings=[None], depth=2)
        with pytest.raises(TypeError, match="cannot be matched"):
            next(it2)

    def test_shardings_key_and_length_mismatches_raise(self):
        """Name-level mismatches fail loudly too: a shardings dict
        sharing no key with the batch, or a sequence longer than the
        batch — while a SUPERSET dict (executor_feed_shardings' '@lr'
        next to an {'x','y'} batch) stays legal."""
        batch = {"x": np.zeros(2, np.float32)}
        it = prefetch_to_device([batch], shardings={"X": None}, depth=2)
        with pytest.raises(TypeError, match="share no key"):
            next(it)
        it2 = prefetch_to_device([(np.zeros(2, np.float32),)],
                                 shardings=[None, None], depth=2)
        with pytest.raises(TypeError, match="extra entries"):
            next(it2)
        # superset dict is fine
        got = list(prefetch_to_device([batch],
                                      shardings={"x": None, "@lr": None}))
        assert isinstance(got[0]["x"], jax.Array)

    def test_executor_feed_shardings_strips_fused_scan_axis(self):
        """For a fused (steps=K) DP entry the helper returns PER-STEP
        shardings (leading scan axis stripped) so loader batches land
        on the batch-axis layout, and round-trip through run_steps."""
        if jax.local_device_count() < 2:
            pytest.skip("needs the 8-fake-device mesh")
        pt.enable_static()
        try:
            pt.seed(0)
            K = 2
            prog, startup, loss = _build_mlp()
            cp = fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
            exe = fluid.Executor()
            exe.run(startup)
            feeds = _feeds(K)
            exe.run_steps(cp, feeds=feeds, fetch_list=[loss], steps=K)
            entry = next(iter(exe._cache.values()))
            assert entry.steps == K
            sh = executor_feed_shardings(entry)
            assert sh["x"].spec[0] == "data"  # per-step batch axis
            got = list(prefetch_to_device(feeds, shardings=sh))
            assert got[0]["x"].sharding.spec[0] == "data"
            assert got[0]["x"].shape == (16, 8)  # per-step, not stacked
            (traj,) = exe.run_steps(cp, feeds=got, fetch_list=[loss],
                                    steps=K)
            assert np.isfinite(traj).all()
        finally:
            pt.disable_static()

    def test_device_array_feeds_pass_through_unconverted(self):
        """A prefetched (committed, device-resident) feed must reach the
        executable without a host round-trip: the executor keeps the
        very same jax arrays (and TrainStep keeps device batch items)."""
        x = jax.device_put(np.ones((4, 2), np.float32))
        from paddle_tpu.static_.executor import Executor

        assert Executor._as_device(x) is x
        assert Executor._feed_shape_dtype(x) == ((4, 2), "float32")
        from paddle_tpu.framework.jit import _as_array

        assert _as_array(x) is x

    def test_honors_committed_shardings_from_entry(self):
        """Batches land pre-sharded on the compiled entry's committed
        feed shardings (the DP data-axis layout)."""
        if jax.local_device_count() < 2:
            pytest.skip("needs the 8-fake-device mesh")
        pt.enable_static()
        try:
            pt.seed(0)
            prog, startup, loss = _build_mlp()
            cp = fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
            exe = fluid.Executor()
            exe.run(startup)
            f = _feeds(1)[0]
            exe.run(cp, feed=f, fetch_list=[loss])
            entry = next(iter(exe._cache.values()))
            sh = executor_feed_shardings(entry)
            assert set(sh) == {"@lr", "x", "y"}  # the fed LR scalar too
            got = list(prefetch_to_device([f], shardings=sh))
            xs = got[0]["x"].sharding
            assert xs.spec and xs.spec[0] == "data"
            assert got[0]["x"].sharding.mesh.devices.size == \
                jax.local_device_count()
            # and the prefetched batch is directly runnable
            (lv,) = exe.run(cp, feed=got[0], fetch_list=[loss])
            assert np.isfinite(lv).all()
        finally:
            pt.disable_static()

    def test_executor_feed_shardings_single_device_entry(self, ):
        pt.enable_static()
        try:
            pt.seed(0)
            prog, startup, loss = _build_mlp()
            exe = fluid.Executor()
            exe.run(startup)
            exe.run(prog, feed=_feeds(1)[0], fetch_list=[loss])
            entry = next(iter(exe._cache.values()))
            sh = executor_feed_shardings(entry)
            assert sh == {"@lr": None, "x": None, "y": None}
        finally:
            pt.disable_static()
