"""Optimizer tests (model: reference tests/unittests/test_optimizer.py,
test_adam_op.py, test_imperative_optimizer.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optim as optim


def make_problem():
    """Tiny least-squares problem; every optimizer must reduce the loss."""
    rng = np.random.RandomState(0)
    w_true = rng.randn(4, 1).astype("float32")
    X = rng.randn(64, 4).astype("float32")
    y = X @ w_true
    model = nn.Linear(4, 1)
    return model, pt.to_tensor(X), pt.to_tensor(y)


def run_steps(model, X, y, opt, n=20):
    losses = []
    for _ in range(n):
        loss = nn.functional.mse_loss(model(X), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("factory", [
    lambda p: optim.SGD(0.1, parameters=p),
    lambda p: optim.Momentum(0.05, momentum=0.9, parameters=p),
    lambda p: optim.Momentum(0.05, momentum=0.9, use_nesterov=True, parameters=p),
    lambda p: optim.Adagrad(0.5, parameters=p),
    lambda p: optim.Adadelta(5.0, parameters=p),
    lambda p: optim.RMSProp(0.05, parameters=p),
    lambda p: optim.RMSProp(0.05, centered=True, momentum=0.5, parameters=p),
    lambda p: optim.Adam(0.1, parameters=p),
    lambda p: optim.AdamW(0.1, weight_decay=0.01, parameters=p),
    lambda p: optim.Adamax(0.1, parameters=p),
    lambda p: optim.Lamb(0.1, parameters=p),
    lambda p: optim.Ftrl(0.5, parameters=p),
], ids=["sgd", "momentum", "nesterov", "adagrad", "adadelta", "rmsprop",
        "rmsprop_centered", "adam", "adamw", "adamax", "lamb", "ftrl"])
def test_optimizer_decreases_loss(factory):
    model, X, y = make_problem()
    opt = factory(model.parameters())
    losses = run_steps(model, X, y, opt)
    assert losses[-1] < losses[0] * 0.9


def test_sgd_matches_manual():
    model, X, y = make_problem()
    w0 = model.weight.numpy().copy()
    opt = optim.SGD(0.1, parameters=model.parameters())
    loss = nn.functional.mse_loss(model(X), y)
    loss.backward()
    g = model.weight.grad.numpy()
    opt.step()
    np.testing.assert_allclose(model.weight.numpy(), w0 - 0.1 * g, rtol=1e-5)


def test_adam_bias_correction_first_step():
    model, X, y = make_problem()
    w0 = model.weight.numpy().copy()
    opt = optim.Adam(0.01, parameters=model.parameters())
    loss = nn.functional.mse_loss(model(X), y)
    loss.backward()
    g = model.weight.grad.numpy()
    opt.step()
    # after bias correction the first step is lr * g/(|g| + eps) ~ lr*sign(g)
    step = w0 - model.weight.numpy()
    np.testing.assert_allclose(step, 0.01 * g / (np.abs(g) + 1e-8), rtol=1e-3)


def test_weight_decay_coupled():
    m = nn.Linear(3, 3, bias_attr=False)
    opt = optim.SGD(0.1, parameters=m.parameters(), weight_decay=0.5)
    x = pt.to_tensor(np.zeros((2, 3), "float32"))
    loss = pt.mean(m(x))  # zero grad wrt weight
    loss.backward()
    w0 = m.weight.numpy().copy()
    opt.step()
    np.testing.assert_allclose(m.weight.numpy(), w0 - 0.1 * 0.5 * w0, rtol=1e-5)


def test_grad_clip_global_norm():
    m = nn.Linear(4, 4)
    clip = optim.ClipGradByGlobalNorm(0.1)
    opt = optim.SGD(1.0, parameters=m.parameters(), grad_clip=clip)
    x = pt.to_tensor(np.random.randn(8, 4).astype("float32") * 100)
    loss = pt.mean(m(x) ** 2)
    loss.backward()
    w0 = m.weight.numpy().copy()
    opt.step()
    # total applied step must have norm <= lr * clip_norm (plus bias part)
    delta = np.linalg.norm(m.weight.numpy() - w0)
    assert delta <= 0.1 + 1e-5


def test_clip_by_value():
    clip = optim.ClipGradByValue(0.5)
    import jax.numpy as jnp

    out = clip([(None, jnp.asarray(np.array([-2.0, 0.2, 3.0], "float32")))])
    np.testing.assert_allclose(np.asarray(out[0][1]), [-0.5, 0.2, 0.5])


def test_lr_scheduler_with_optimizer():
    model, X, y = make_problem()
    sched = optim.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    opt = optim.SGD(sched, parameters=model.parameters())
    assert opt.get_lr() == 0.1
    sched.step()
    sched.step()
    assert np.isclose(opt.get_lr(), 0.05)


@pytest.mark.parametrize("sched,checks", [
    (lambda: optim.lr.ExponentialDecay(1.0, 0.5), [(0, 1.0), (1, 0.5), (2, 0.25)]),
    (lambda: optim.lr.PiecewiseDecay([2, 4], [1.0, 0.5, 0.1]),
     [(0, 1.0), (2, 0.5), (4, 0.1)]),
    (lambda: optim.lr.PolynomialDecay(1.0, 10, end_lr=0.0, power=1.0),
     [(0, 1.0), (5, 0.5), (10, 0.0)]),
    (lambda: optim.lr.CosineAnnealingDecay(1.0, 10),
     [(0, 1.0), (10, 0.0)]),
    (lambda: optim.lr.StepDecay(1.0, 3, 0.1), [(0, 1.0), (3, 0.1), (6, 0.01)]),
    (lambda: optim.lr.MultiStepDecay(1.0, [2, 5], 0.1),
     [(0, 1.0), (2, 0.1), (5, 0.01)]),
    (lambda: optim.lr.LambdaDecay(2.0, lambda e: 1.0 / (e + 1)),
     [(0, 2.0), (1, 1.0), (3, 0.5)]),
], ids=["exp", "piecewise", "poly", "cosine", "step", "multistep", "lambda"])
def test_scheduler_values(sched, checks):
    s = sched()
    for epoch, want in checks:
        s.step(epoch)
        assert np.isclose(s(), want, atol=1e-7), (epoch, s(), want)


def test_linear_warmup():
    s = optim.lr.LinearWarmup(0.5, warmup_steps=10, start_lr=0.0, end_lr=0.5)
    s.step(0)
    assert np.isclose(s(), 0.0)
    s.step(5)
    assert np.isclose(s(), 0.25)
    s.step(15)
    assert np.isclose(s(), 0.5)


def test_noam():
    s = optim.lr.NoamDecay(d_model=512, warmup_steps=100, learning_rate=1.0)
    s.step(50)
    lr_warm = s()
    s.step(100)
    lr_peak = s()
    s.step(10000)
    assert s() < lr_peak and lr_warm < lr_peak


def test_reduce_on_plateau():
    s = optim.lr.ReduceOnPlateau(1.0, patience=1, factor=0.5)
    s.step(metrics=1.0)
    s.step(metrics=1.0)
    s.step(metrics=1.0)
    assert s() == 0.5


def test_optimizer_state_roundtrip():
    model, X, y = make_problem()
    opt = optim.Adam(0.1, parameters=model.parameters())
    run_steps(model, X, y, opt, n=3)
    state = opt.state_dict()

    model2, _, _ = make_problem()
    model2.set_state_dict(model.state_dict())
    opt2 = optim.Adam(0.1, parameters=model2.parameters())
    # rename keys to match model2's parameter names
    names1 = [p.name for p in model.parameters()]
    names2 = [p.name for p in model2.parameters()]
    remap = {}
    for k, v in state.items():
        if k.startswith("@"):
            remap[k] = v
            continue
        pname, slot = k.rsplit(".", 1)
        remap[f"{names2[names1.index(pname)]}.{slot}"] = v
    opt2.set_state_dict(remap)
    l1 = run_steps(model, X, y, opt, n=2)
    l2 = run_steps(model2, X, y, opt2, n=2)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_ema():
    m = nn.Linear(2, 2)
    ema = optim.ExponentialMovingAverage(m, decay=0.5)
    w0 = m.weight.numpy().copy()
    m.weight.set_value(w0 + 1.0)
    ema.update()
    ema.apply()
    assert not np.allclose(m.weight.numpy(), w0 + 1.0)
    ema.restore()
    np.testing.assert_allclose(m.weight.numpy(), w0 + 1.0)


def test_lookahead():
    model, X, y = make_problem()
    inner = optim.SGD(0.1, parameters=model.parameters())
    opt = optim.LookAhead(inner, alpha=0.5, k=2)
    losses = run_steps(model, X, y, opt, n=10)
    assert losses[-1] < losses[0]


def test_minimize():
    model, X, y = make_problem()
    opt = optim.SGD(0.1, parameters=model.parameters())
    l0 = float(nn.functional.mse_loss(model(X), y))
    for _ in range(5):
        loss = nn.functional.mse_loss(model(X), y)
        opt.minimize(loss)
        opt.clear_grad()
    assert float(nn.functional.mse_loss(model(X), y)) < l0
