"""nets.py composites, the SSD stack (bipartite_match/target_assign/
ssd_loss/detection_output), and the dataset readers
(ref: fluid/nets.py, layers/detection.py:518,1198,1287,1390,
python/paddle/dataset/)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import ops
from paddle_tpu.nn import nets


class TestNets:
    def test_simple_img_conv_pool(self):
        x = pt.to_tensor(np.random.RandomState(0)
                         .randn(2, 3, 16, 16).astype("float32"))
        out = nets.simple_img_conv_pool(x, num_filters=8, filter_size=3,
                                        pool_size=2, pool_stride=2,
                                        conv_padding=1, act="relu")
        assert list(out.shape) == [2, 8, 8, 8]
        assert (np.asarray(out.numpy()) >= 0).all()

    def test_img_conv_group(self):
        x = pt.to_tensor(np.random.RandomState(1)
                         .randn(2, 3, 16, 16).astype("float32"))
        out = nets.img_conv_group(x, conv_num_filter=[8, 8], pool_size=2,
                                  conv_act="relu",
                                  conv_with_batchnorm=True,
                                  pool_stride=2)
        assert list(out.shape) == [2, 8, 8, 8]

    def test_sequence_conv_pool(self):
        x = pt.to_tensor(np.random.RandomState(2)
                         .randn(2, 6, 4).astype("float32"))
        lens = pt.to_tensor(np.array([6, 3], "int32"))
        out = nets.sequence_conv_pool(x, num_filters=5, filter_size=3,
                                      act="tanh", pool_type="max",
                                      lengths=lens)
        assert list(out.shape) == [2, 5]

    def test_glu_halves_width(self):
        x = pt.to_tensor(np.random.RandomState(3)
                         .randn(4, 10).astype("float32"))
        out = nets.glu(x)
        assert list(out.shape) == [4, 5]
        a, b = np.split(np.asarray(x.numpy()), 2, axis=-1)
        want = a * (1 / (1 + np.exp(-b)))
        np.testing.assert_allclose(np.asarray(out.numpy()), want,
                                   atol=1e-5)

    def test_scaled_dot_product_attention(self):
        rng = np.random.RandomState(4)
        q = pt.to_tensor(rng.randn(2, 5, 8).astype("float32"))
        kv = pt.to_tensor(rng.randn(2, 7, 8).astype("float32"))
        out = nets.scaled_dot_product_attention(q, kv, kv, num_heads=2)
        assert list(out.shape) == [2, 5, 8]
        with pytest.raises(ValueError):
            nets.scaled_dot_product_attention(q, kv, kv, num_heads=3)


class TestSSDStack:
    def test_bipartite_match_greedy(self):
        # gt0 best matches prior1 (0.9); gt1 takes prior0 (0.8)
        d = np.array([[[0.7, 0.9, 0.1], [0.8, 0.85, 0.0]]], "float32")
        idx, dist = ops.bipartite_match(pt.to_tensor(d))
        idx = np.asarray(idx.numpy())[0]
        assert idx[1] == 0 and idx[0] == 1  # greedy global-max order
        assert idx[2] == -1

    def test_bipartite_per_prediction_extension(self):
        d = np.array([[[0.9, 0.6, 0.2]]], "float32")
        idx, _ = ops.bipartite_match(pt.to_tensor(d),
                                     match_type="per_prediction",
                                     dist_threshold=0.5)
        idx = np.asarray(idx.numpy())[0]
        assert idx[0] == 0          # bipartite winner
        assert idx[1] == 0          # above threshold -> also matched
        assert idx[2] == -1         # below threshold

    def test_target_assign(self):
        x = np.arange(12, dtype="float32").reshape(1, 3, 4)
        match = np.array([[1, -1, 2, 0]], "int32")
        out, w = ops.target_assign(pt.to_tensor(x), pt.to_tensor(match),
                                   mismatch_value=-7)
        out = np.asarray(out.numpy())[0]
        w = np.asarray(w.numpy())[0]
        np.testing.assert_allclose(out[0], x[0, 1])
        assert (out[1] == -7).all() and w[1, 0] == 0.0
        np.testing.assert_allclose(out[3], x[0, 0])

    def _ssd_inputs(self, seed=0):
        rng = np.random.RandomState(seed)
        P, G, C = 8, 2, 4
        prior = np.stack([
            np.linspace(0.0, 0.7, P), np.linspace(0.0, 0.7, P),
            np.linspace(0.2, 0.9, P), np.linspace(0.2, 0.9, P)],
            axis=1).astype("float32")
        gt = np.array([[[0.05, 0.05, 0.25, 0.25],
                        [0.55, 0.55, 0.85, 0.85]]], "float32")
        lab = np.array([[1, 3]], "int64")
        loc = rng.randn(1, P, 4).astype("float32") * 0.1
        conf = rng.randn(1, P, C).astype("float32") * 0.1
        return loc, conf, gt, lab, prior

    def test_ssd_loss_finite_and_grads(self):
        loc, conf, gt, lab, prior = self._ssd_inputs()
        loct = pt.to_tensor(loc); loct.stop_gradient = False
        conft = pt.to_tensor(conf); conft.stop_gradient = False
        loss = ops.ssd_loss(loct, conft, pt.to_tensor(gt),
                            pt.to_tensor(lab), pt.to_tensor(prior),
                            [0.1, 0.1, 0.2, 0.2])
        assert list(loss.shape) == [1]
        loss.sum().backward()
        assert np.isfinite(np.asarray(loct.grad.numpy())).all()
        assert np.abs(np.asarray(conft.grad.numpy())).sum() > 0

    def test_ssd_loss_trains(self):
        loc, conf, gt, lab, prior = self._ssd_inputs()
        loct = pt.to_tensor(loc); loct.stop_gradient = False
        conft = pt.to_tensor(conf); conft.stop_gradient = False
        losses = []
        for _ in range(30):
            loss = ops.ssd_loss(loct, conft, pt.to_tensor(gt),
                                pt.to_tensor(lab), pt.to_tensor(prior),
                                [0.1, 0.1, 0.2, 0.2]).sum()
            losses.append(float(loss))
            loss.backward()
            loct._replace(loct._data - 0.5 * loct.grad._data)
            conft._replace(conft._data - 0.5 * conft.grad._data)
            loct.grad = None
            conft.grad = None
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_detection_output_roundtrip(self):
        """Perfect loc deltas (zeros) + confident scores recover priors."""
        P, C = 4, 3
        prior = np.array([[0.1, 0.1, 0.3, 0.3], [0.2, 0.6, 0.4, 0.9],
                          [0.6, 0.1, 0.9, 0.4], [0.55, 0.5, 0.95, 0.95]],
                         "float32")
        loc = np.zeros((1, P, 4), "float32")
        scores = np.full((1, P, C), 0.01, "float32")
        scores[0, 0, 1] = 0.95
        scores[0, 2, 2] = 0.9
        out, counts = ops.detection_output(
            pt.to_tensor(loc), pt.to_tensor(scores),
            pt.to_tensor(prior), score_threshold=0.5, nms_threshold=0.4,
            nms_top_k=P, keep_top_k=P)
        n = int(np.asarray(counts.numpy())[0])
        o = np.asarray(out.numpy())[0]
        assert n == 2
        assert int(o[0, 0]) == 1 and int(o[1, 0]) == 2
        np.testing.assert_allclose(o[0, 2:], prior[0], atol=1e-5)
        np.testing.assert_allclose(o[1, 2:], prior[2], atol=1e-5)


class TestDatasets:
    def test_mnist_shapes_and_determinism(self):
        from paddle_tpu import dataset

        a = list(dataset.mnist.test()())
        b = list(dataset.mnist.test()())
        assert len(a) == 512
        assert a[0][0].shape == (784,)
        np.testing.assert_array_equal(a[0][0], b[0][0])

    def test_uci_housing_learnable(self):
        """fit_a_line on the synthetic housing data reaches low loss."""
        from paddle_tpu import dataset

        xs, ys = zip(*list(dataset.uci_housing.train()()))
        X = np.stack(xs); Y = np.stack(ys)[:, 0]
        # closed-form ridge fit must explain the data
        w = np.linalg.lstsq(
            np.concatenate([X, np.ones((len(X), 1), "float32")], 1),
            Y, rcond=None)[0]
        pred = np.concatenate([X, np.ones((len(X), 1), "float32")],
                              1) @ w
        assert np.mean((pred - Y) ** 2) < 0.05

    def test_imdb_classes_separable(self):
        from paddle_tpu import dataset

        wd = dataset.imdb.word_dict()
        samples = list(dataset.imdb.train(wd)())[:50]
        half = len(wd) // 2
        for ids, lab in samples:
            frac_hi = np.mean(np.asarray(ids) >= half)
            assert (frac_hi > 0.5) == bool(lab)

    def test_wmt16_mapping_deterministic(self):
        from paddle_tpu import dataset

        src, trg_in, trg_next = next(dataset.wmt16.train(100, 100)())
        assert src[0] == 0 and src[-1] == 1
        assert trg_in[0] == 0 and trg_next[-1] == 1
        body = src[1:-1]
        np.testing.assert_array_equal(
            trg_next[:-1], [(w % 97) + 3 for w in body])

    def test_conll05_structure(self):
        from paddle_tpu import dataset

        s = next(dataset.conll05.test()())
        assert len(s) == 9
        L = len(s[0])
        assert all(len(f) == L for f in s)
        assert sum(s[7]) == 1  # exactly one predicate mark


class TestTargetAssignNegatives:
    def test_negative_indices_trainable(self):
        x = np.arange(8, dtype="float32").reshape(1, 2, 4)
        match = np.array([[0, -1, -1, 1]], "int32")
        negs = np.array([[1]], "int32")  # prior 1 is a mined negative
        out, w = ops.target_assign(pt.to_tensor(x), pt.to_tensor(match),
                                   negative_indices=pt.to_tensor(negs),
                                   mismatch_value=0)
        w = np.asarray(w.numpy())[0]
        assert w[0, 0] == 1.0   # matched
        assert w[1, 0] == 1.0   # mined negative: trainable
        assert w[2, 0] == 0.0   # unmatched, unmined: ignored
        assert w[3, 0] == 1.0


class TestSSDMatchType:
    def test_bipartite_only_matches_fewer(self):
        loc = np.zeros((1, 6, 4), "float32")
        conf = np.zeros((1, 6, 3), "float32")
        prior = np.stack([np.linspace(0, 0.6, 6)] * 2
                         + [np.linspace(0.3, 0.9, 6)] * 2,
                         axis=1).astype("float32")
        gt = np.array([[[0.0, 0.0, 0.35, 0.35]]], "float32")
        lab = np.array([[1]], "int64")
        l_bi = float(ops.ssd_loss(
            pt.to_tensor(loc), pt.to_tensor(conf), pt.to_tensor(gt),
            pt.to_tensor(lab), pt.to_tensor(prior),
            match_type="bipartite").sum())
        l_pp = float(ops.ssd_loss(
            pt.to_tensor(loc), pt.to_tensor(conf), pt.to_tensor(gt),
            pt.to_tensor(lab), pt.to_tensor(prior),
            match_type="per_prediction").sum())
        assert np.isfinite([l_bi, l_pp]).all()
        with pytest.raises(ValueError):
            ops.ssd_loss(pt.to_tensor(loc), pt.to_tensor(conf),
                         pt.to_tensor(gt), pt.to_tensor(lab),
                         pt.to_tensor(prior), match_type="nope")


class TestFitALineBook:
    def test_uci_housing_reader_pipeline_static(self):
        """Book ch.1 fit_a_line, reference-shaped: dataset reader ->
        paddle.batch(shuffle(...)) -> DataFeeder -> static Executor
        (ref: tests/book/test_fit_a_line.py)."""
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu import dataset, optim
        from paddle_tpu.io_ import reader as rd

        pt.seed(0)
        train_reader = rd.batch(
            rd.shuffle(dataset.uci_housing.train(), buf_size=256),
            batch_size=101, drop_last=True)

        pt.enable_static()
        try:
            main, startup = pt.static.Program(), pt.static.Program()
            with pt.program_guard(main, startup):
                x = pt.static.data("x", [101, 13], "float32")
                y = pt.static.data("y", [101, 1], "float32")
                pred = nn.Linear(13, 1)(x)
                loss = F.mse_loss(pred, y)
                opt = optim.SGD(learning_rate=0.05)
                opt.minimize(loss)
        finally:
            pt.disable_static()
        exe = pt.static.Executor()
        exe.run(startup)
        feeder = pt.io.DataFeeder(feed_list=[x, y])
        losses = []
        for epoch in range(12):
            for batch in train_reader():
                lv, = exe.run(main, feed=feeder.feed(batch),
                              fetch_list=[loss])
                losses.append(float(lv))
        assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])
