"""Layer-system tests (model: reference tests/unittests/test_layers.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def randn(*shape):
    return np.random.RandomState(sum(shape) + 7).randn(*shape).astype("float32")


class TestLayerBase:
    def test_parameters_and_naming(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        names = [n for n, _ in m.named_parameters()]
        assert names == ["0.weight", "0.bias", "2.weight", "2.bias"]
        assert len(m.parameters()) == 4

    def test_train_eval_propagates(self):
        m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_state_dict_roundtrip(self):
        m1 = nn.Linear(5, 3)
        m2 = nn.Linear(5, 3)
        m2.set_state_dict(m1.state_dict())
        x = pt.to_tensor(randn(2, 5))
        np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)

    def test_buffers_in_state_dict(self):
        bn = nn.BatchNorm2D(4)
        sd = bn.state_dict()
        assert "_mean" in sd and "_variance" in sd

    def test_apply_and_sublayers(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
        count = []
        m.apply(lambda l: count.append(type(l).__name__))
        assert "Linear" in count and "Sequential" in count

    def test_hooks(self):
        m = nn.Linear(3, 3)
        calls = []
        h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
        m(pt.to_tensor(randn(1, 3)))
        assert calls == [1]
        h.remove()
        m(pt.to_tensor(randn(1, 3)))
        assert calls == [1]

    def test_layer_containers(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4
        ld = nn.LayerDict({"a": nn.Linear(2, 2)})
        assert "a" in ld


class TestLayersForward:
    def test_linear_matches_numpy(self):
        m = nn.Linear(6, 4)
        x = randn(3, 6)
        got = m(pt.to_tensor(x)).numpy()
        want = x @ m.weight.numpy() + m.bias.numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_conv_bn_pool_shapes(self):
        m = nn.Sequential(
            nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8), nn.ReLU(),
            nn.MaxPool2D(2), nn.Conv2D(8, 16, 3, stride=2, padding=1),
            nn.AdaptiveAvgPool2D(1), nn.Flatten())
        out = m(pt.to_tensor(randn(2, 3, 16, 16)))
        assert out.shape == [2, 16]

    def test_batchnorm_updates_running_stats(self):
        bn = nn.BatchNorm1D(4)
        before = bn._mean.numpy().copy()
        x = pt.to_tensor(randn(16, 4, 8) + 3.0)
        bn(x)
        after = bn._mean.numpy()
        assert not np.allclose(before, after)

    def test_batchnorm_eval_uses_running_stats(self):
        bn = nn.BatchNorm2D(4)
        bn.eval()
        x = randn(2, 4, 5, 5)
        got = bn(pt.to_tensor(x)).numpy()
        w, b = bn.weight.numpy(), bn.bias.numpy()
        want = x * w.reshape(1, -1, 1, 1) / np.sqrt(1.0 + 1e-5) + b.reshape(1, -1, 1, 1)
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_layernorm(self):
        ln = nn.LayerNorm(8)
        x = randn(4, 8)
        got = ln(pt.to_tensor(x)).numpy()
        mu = x.mean(-1, keepdims=True)
        sd = x.std(-1, keepdims=True)
        np.testing.assert_allclose(got, (x - mu) / np.sqrt(sd**2 + 1e-5),
                                   rtol=1e-4, atol=1e-5)

    def test_embedding_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(pt.to_tensor(np.array([[0, 1], [2, 0]])))
        assert out.shape == [2, 2, 4]
        np.testing.assert_allclose(out.numpy()[0, 0], np.zeros(4))

    def test_dropout_train_eval(self):
        d = nn.Dropout(0.5)
        x = pt.to_tensor(np.ones((100, 100), "float32"))
        train_out = d(x).numpy()
        assert (train_out == 0).any()
        d.eval()
        np.testing.assert_allclose(d(x).numpy(), np.ones((100, 100)))

    def test_conv_transpose_shape(self):
        m = nn.Conv2DTranspose(4, 8, 3, stride=2, padding=1, output_padding=1)
        out = m(pt.to_tensor(randn(1, 4, 8, 8)))
        assert out.shape == [1, 8, 16, 16]


class TestActivationsAndLosses:
    def test_activation_layers(self):
        x = pt.to_tensor(randn(3, 5))
        for cls in [nn.ReLU, nn.GELU, nn.Sigmoid, nn.Tanh, nn.Softmax,
                    nn.LeakyReLU, nn.Hardswish, nn.Silu]:
            out = cls()(x)
            assert out.shape == [3, 5]

    def test_cross_entropy_matches_manual(self):
        logits = randn(6, 9)
        labels = np.array([0, 1, 2, 3, 4, 5])
        got = float(F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels)))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[np.arange(6), labels]).mean()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = randn(4, 5)
        labels = np.array([0, 1, -100, 2])
        got = float(F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels),
                                    ignore_index=-100))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[[0, 1, 3], [0, 1, 2]]).mean()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_soft_label_ce(self):
        logits = randn(4, 5)
        soft = np.abs(randn(4, 5))
        soft = soft / soft.sum(-1, keepdims=True)
        got = float(F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(soft),
                                    soft_label=True))
        logp = logits - logits.max(-1, keepdims=True)
        logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
        want = -(soft * logp).sum(-1).mean()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_bce_with_logits(self):
        x, y = randn(8), (randn(8) > 0).astype("float32")
        got = float(F.binary_cross_entropy_with_logits(
            pt.to_tensor(x), pt.to_tensor(y)))
        p = 1 / (1 + np.exp(-x))
        want = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_mse_l1_smooth(self):
        x, y = randn(5, 3), randn(5, 3)
        assert np.isclose(float(F.mse_loss(pt.to_tensor(x), pt.to_tensor(y))),
                          ((x - y) ** 2).mean(), rtol=1e-5)
        assert np.isclose(float(F.l1_loss(pt.to_tensor(x), pt.to_tensor(y))),
                          np.abs(x - y).mean(), rtol=1e-5)

    def test_kl_div(self):
        rng = np.random.RandomState(3)
        p = np.abs(rng.randn(4, 6).astype("float32")) + 0.1
        p = p / p.sum(-1, keepdims=True)
        q = np.abs(rng.randn(4, 6).astype("float32")) + 0.1
        q = q / q.sum(-1, keepdims=True)
        got = float(F.kl_div(pt.to_tensor(np.log(q)), pt.to_tensor(p),
                             reduction="sum"))
        want = (p * (np.log(p) - np.log(q))).sum()
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_ctc_loss_simple(self):
        # T=4, B=1, C=3: uniform distribution; loss must be positive finite
        T, B, C, S = 4, 2, 3, 2
        logits = randn(T, B, C)
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        labels = np.array([[1, 2], [2, 1]])
        loss = F.ctc_loss(pt.to_tensor(logp), pt.to_tensor(labels),
                          pt.to_tensor(np.array([T, T])),
                          pt.to_tensor(np.array([S, S])))
        v = float(loss)
        assert np.isfinite(v) and v > 0

    def test_loss_layers(self):
        x = pt.to_tensor(randn(4, 3), stop_gradient=False)
        y = pt.to_tensor(np.array([0, 1, 2, 0]))
        loss = nn.CrossEntropyLoss()(x, y)
        loss.backward()
        assert x.grad is not None


class TestRNN:
    def test_simple_rnn_cell(self):
        cell = nn.SimpleRNNCell(4, 8)
        x = pt.to_tensor(randn(2, 4))
        h, new = cell(x)
        assert h.shape == [2, 8]

    def test_lstm_forward_backward(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        x = pt.to_tensor(randn(3, 5, 4), stop_gradient=False)
        out, (h, c) = lstm(x)
        assert out.shape == [3, 5, 8]
        assert h.shape == [2, 3, 8] and c.shape == [2, 3, 8]
        pt.mean(out).backward()
        assert lstm[0].weight_ih.grad is not None

    def test_bidirectional_gru(self):
        gru = nn.GRU(4, 6, direction="bidirect")
        out, h = gru(pt.to_tensor(randn(2, 7, 4)))
        assert out.shape == [2, 7, 12]
        assert h.shape == [2, 2, 6]

    def test_sequence_length_masking(self):
        cell = nn.SimpleRNNCell(3, 5)
        r = nn.RNN(cell)
        x = randn(2, 6, 3)
        lens = np.array([6, 3])
        full, _ = r(pt.to_tensor(x), sequence_length=pt.to_tensor(lens))
        # outputs past the length must be zero for the short sequence
        np.testing.assert_allclose(full.numpy()[1, 3:], np.zeros((3, 5)),
                                   atol=1e-6)

    def test_rnn_matches_manual_loop(self):
        cell = nn.SimpleRNNCell(3, 4)
        x = randn(1, 5, 3)
        out, _ = nn.RNN(cell)(pt.to_tensor(x))
        # manual per-step eager loop
        h = pt.zeros([1, 4])
        outs = []
        for t in range(5):
            h, _ = cell(pt.to_tensor(x[:, t]), h)
            outs.append(h.numpy())
        np.testing.assert_allclose(out.numpy()[0], np.concatenate(outs),
                                   rtol=1e-5, atol=1e-5)


class TestTransformer:
    def test_mha_self_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = pt.to_tensor(randn(2, 5, 16), stop_gradient=False)
        out = mha(x)
        assert out.shape == [2, 5, 16]
        pt.mean(out).backward()
        assert mha.q_proj.weight.grad is not None

    def test_encoder_decoder(self):
        model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=2,
                               num_decoder_layers=2, dim_feedforward=32)
        src = pt.to_tensor(randn(2, 6, 16))
        tgt = pt.to_tensor(randn(2, 4, 16))
        out = model(src, tgt)
        assert out.shape == [2, 4, 16]

    def test_causal_mask_changes_output(self):
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x = pt.to_tensor(randn(1, 4, 8))
        mask = nn.Transformer.generate_square_subsequent_mask(4)
        free = mha(x).numpy()
        masked = mha(x, attn_mask=mask).numpy()
        assert not np.allclose(free, masked)

    def test_decoder_cache_incremental(self):
        layer = nn.TransformerDecoderLayer(8, 2, 16, dropout=0.0)
        dec = nn.TransformerDecoder(layer, 2)
        dec.eval()
        memory = pt.to_tensor(randn(1, 5, 8))
        cache = dec.gen_cache(memory)
        step1 = pt.to_tensor(randn(1, 1, 8))
        out, cache = dec(step1, memory, cache=cache)
        assert out.shape == [1, 1, 8]
        out2, cache = dec(pt.to_tensor(randn(1, 1, 8)), memory, cache=cache)
        assert cache[0][0].k.shape[2] == 2


class TestReviewRegressions:
    def test_stacked_transformer_unique_param_names(self):
        # deepcopy'd layers must NOT share parameter names (optimizer state
        # is keyed by name)
        enc = nn.TransformerEncoder(
            nn.TransformerEncoderLayer(8, 2, 16), 3)
        params = enc.parameters()
        names = [p.name for p in params]
        assert len(names) == len(set(names)), "duplicate parameter names"

    def test_stacked_transformer_trains(self):
        import paddle_tpu.optim as optim

        enc = nn.TransformerEncoder(nn.TransformerEncoderLayer(8, 2, 16,
                                                               dropout=0.0), 2)
        opt = optim.Adam(0.01, parameters=enc.parameters())
        x = pt.to_tensor(randn(2, 4, 8))
        tgt = pt.to_tensor(randn(2, 4, 8))
        losses = []
        for _ in range(5):
            loss = F.mse_loss(enc(x), tgt)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # each param got its own accumulator slot
        assert len(opt._accumulators) == len(enc.parameters())

    def test_adamw_decay_exclusion(self):
        import paddle_tpu.optim as optim

        m = nn.Linear(3, 3)
        bias_name = m.bias.name
        opt = optim.AdamW(0.01, weight_decay=0.5,
                          parameters=m.parameters(),
                          apply_decay_param_fun=lambda n: n != bias_name)
        x = pt.to_tensor(np.zeros((2, 3), "float32"))
        b0 = m.bias.numpy().copy()
        w0 = m.weight.numpy().copy()
        loss = pt.sum(m(x)) * 0.0  # zero grads
        loss.backward()
        opt.step()
        # bias excluded from decay AND zero grad -> unchanged
        np.testing.assert_allclose(m.bias.numpy(), b0, atol=1e-7)
        # weight decayed by lr*coeff even with zero grad
        np.testing.assert_allclose(m.weight.numpy(),
                                   w0 - 0.01 * 0.5 * w0, rtol=1e-4)

    def test_attention_dropout_on_weights(self):
        # with full dropout on attention weights, output must be all zeros
        q = pt.to_tensor(randn(1, 2, 4, 8))
        out = F.sdpa_bhld(q, q, q, dropout_p=0.999999, training=True)
        np.testing.assert_allclose(out.numpy(), 0.0, atol=1e-5)
        out2 = F.sdpa_bhld(q, q, q, dropout_p=0.999999, training=False)
        assert np.abs(out2.numpy()).sum() > 0

    def test_conv_transpose_channel_last_and_output_size(self):
        from paddle_tpu.ops.conv import conv1d_transpose

        w = pt.to_tensor(randn(4, 6, 3))
        x_cf = pt.to_tensor(randn(2, 4, 5))
        y_cf = conv1d_transpose(x_cf, w, stride=2)
        x_cl = pt.to_tensor(np.transpose(x_cf.numpy(), (0, 2, 1)))
        y_cl = conv1d_transpose(x_cl, w, stride=2, data_format="NLC")
        np.testing.assert_allclose(np.transpose(y_cl.numpy(), (0, 2, 1)),
                                   y_cf.numpy(), rtol=1e-4, atol=1e-5)
        y_sz = conv1d_transpose(x_cf, w, stride=2, output_size=12)
        assert y_sz.shape[2] == 12
