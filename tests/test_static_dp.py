"""Static-graph data parallelism (VERDICT r3 Missing #1 / Next #3).

The reference's CompiledProgram.with_data_parallel / ParallelExecutor
replicate the graph per device and NCCL-all-reduce grads
(python/paddle/fluid/parallel_executor.py:28). Here the Executor jits the
ONE program over a Mesh(('data',)) with the feed batch axis sharded —
XLA partitions and inserts the grad all-reduce — so an 8-device DP run
of a global batch must match a single-device run of the same batch.
Runs on the 8-device virtual CPU mesh from conftest.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as pt
import paddle_tpu.fluid as fluid


def _build_mlp_program(lr=0.1, batch=16):
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 8])
        y = fluid.data(name="y", shape=[batch, 1])
        h = fluid.layers.fc(x, size=16, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        opt = fluid.optimizer.SGD(learning_rate=lr)
        opt.minimize(loss)
    return prog, startup, loss


def _train(program_like, steps=4, batch=16):
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    losses = []
    prog, startup, loss = program_like
    exe.run(startup)
    for _ in range(steps):
        xb = rng.randn(batch, 8).astype(np.float32)
        yb = rng.randn(batch, 1).astype(np.float32)
        (lv,) = exe.run(prog, feed={"x": xb, "y": yb},
                        fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    return losses


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def test_dp_matches_single_device(static_mode):
    """Same global batch: 8-way sharded DP losses == single-device."""
    pt.seed(0)
    single = _train(_build_mlp_program())
    pt.seed(0)
    prog, startup, loss = _build_mlp_program()
    compiled = fluid.CompiledProgram(prog).with_data_parallel(
        loss_name=loss.name)
    dp = _train((compiled, startup, loss))
    assert np.allclose(single, dp, rtol=1e-4, atol=1e-5), (single, dp)


def test_dp_shards_batch_axis(static_mode):
    """The compiled DP executable really shards the feed over the mesh."""
    pt.seed(0)
    prog, startup, loss = _build_mlp_program()
    compiled = fluid.CompiledProgram(prog).with_data_parallel(
        loss_name=loss.name)
    exe = fluid.Executor()
    exe.run(startup)
    xb = np.zeros((16, 8), np.float32)
    yb = np.zeros((16, 1), np.float32)
    exe.run(compiled, feed={"x": xb, "y": yb}, fetch_list=[loss])
    # the executor compiled under the DP cache key, and the jit carries
    # batch-axis shardings: the traced executable's input sharding for
    # the feed spans all devices
    assert any(k.data_parallel for k in exe._cache)  # named CacheKey field
    (compiled_entry,) = exe._cache.values()
    feed_shardings = compiled_entry.feed_shardings
    ndev = jax.local_device_count()
    assert all(s.mesh.devices.size == ndev for s in feed_shardings)
    assert any(s.spec and s.spec[0] == "data" for s in feed_shardings)


def test_parallel_executor_api(static_mode):
    """fluid.ParallelExecutor front: run(fetch_list, feed) works and
    matches plain-Executor training."""
    pt.seed(0)
    single = _train(_build_mlp_program())
    pt.seed(0)
    prog, startup, loss = _build_mlp_program()
    fluid.Executor().run(startup)
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                main_program=prog)
    assert pe.device_count == jax.local_device_count()
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(4):
        xb = rng.randn(16, 8).astype(np.float32)
        yb = rng.randn(16, 1).astype(np.float32)
        (lv,) = pe.run(fetch_list=[loss], feed={"x": xb, "y": yb})
        losses.append(float(np.asarray(lv)))
    assert np.allclose(single, losses, rtol=1e-4, atol=1e-5)


def test_dp_indivisible_batch_errors_by_default(static_mode):
    """Reference ParallelExecutor semantics: a batch that can't split
    across the devices errors (a silent replication would hand the user
    0% of the DP speedup they asked for)."""
    pt.seed(0)
    prog, startup, loss = _build_mlp_program(batch=6)
    compiled = fluid.CompiledProgram(prog).with_data_parallel(
        loss_name=loss.name)
    exe = fluid.Executor()
    exe.run(startup)
    xb = np.random.RandomState(0).randn(6, 8).astype(np.float32)
    yb = np.zeros((6, 1), np.float32)
    with pytest.raises(ValueError, match="allow_replicated_fallback"):
        exe.run(compiled, feed={"x": xb, "y": yb}, fetch_list=[loss])


def test_dp_indivisible_batch_replicates_with_optout(static_mode):
    """ExecutionStrategy.allow_replicated_fallback=True restores the
    run-replicated behavior, loudly (RuntimeWarning)."""
    pt.seed(0)
    prog, startup, loss = _build_mlp_program(batch=6)
    strat = fluid.ExecutionStrategy()
    strat.allow_replicated_fallback = True
    compiled = fluid.CompiledProgram(prog).with_data_parallel(
        loss_name=loss.name, exec_strategy=strat)
    exe = fluid.Executor()
    exe.run(startup)
    xb = np.random.RandomState(0).randn(6, 8).astype(np.float32)
    yb = np.zeros((6, 1), np.float32)
    with pytest.warns(RuntimeWarning, match="fully replicated"):
        (lv,) = exe.run(compiled, feed={"x": xb, "y": yb},
                        fetch_list=[loss])
    assert np.isfinite(np.asarray(lv)).all()


def test_dp_indivisible_aux_feed_replicates_quietly(static_mode):
    """An auxiliary feed whose leading dim doesn't divide the mesh must
    NOT trip the divisibility error while the batch feeds shard fine —
    it just replicates (the correct placement for a non-batch input)."""
    import warnings

    pt.seed(0)
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[16, 8])
        y = fluid.data(name="y", shape=[16, 1])
        coef = fluid.data(name="coef", shape=[3])  # aux, 3 % 8 != 0
        h = fluid.layers.fc(x, size=16, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y)) + \
            fluid.layers.reduce_mean(coef)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    compiled = fluid.CompiledProgram(prog).with_data_parallel(
        loss_name=loss.name)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # no warning either
        (lv,) = exe.run(compiled,
                        feed={"x": rng.randn(16, 8).astype(np.float32),
                              "y": rng.randn(16, 1).astype(np.float32),
                              "coef": np.ones(3, np.float32)},
                        fetch_list=[loss])
    assert np.isfinite(np.asarray(lv)).all()
