"""paddle_tpu.obs: metrics registry, span tracer, and the
instrumentation woven through executor / dispatch / dataloader /
resilience / checkpoint IO.

The registry is process-wide by design, so tests that assert absolute
values call ``obs.metrics.reset()`` first (reset zeroes in place and
keeps registrations — exactly what the hot paths' interned references
rely on).
"""
import json
import os
import tempfile
import threading
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import obs


@pytest.fixture
def tracing():
    """Clean, enabled tracer for one test; restores the prior state."""
    was_on = obs.tracing_enabled()
    obs.clear_trace()
    obs.enable_tracing()
    yield
    if not was_on:
        obs.disable_tracing()
    obs.clear_trace()


# -- metrics registry --------------------------------------------------------


class TestMetricsRegistry:
    def test_counter_gauge_histogram_roundtrip(self):
        reg = obs.Registry()
        c = reg.counter("c")
        c.inc()
        c.inc(4)
        g = reg.gauge("g")
        g.set(7)
        g.dec(2)
        h = reg.histogram("h")
        for v in (1.0, 2.0, 100.0):
            h.observe(v)
        snap = reg.snapshot()
        assert snap["c"] == 5
        assert snap["g"] == 5
        assert snap["h"]["count"] == 3
        assert snap["h"]["min"] == 1.0 and snap["h"]["max"] == 100.0
        assert snap["h"]["sum"] == pytest.approx(103.0)

    def test_get_or_create_interns_by_name(self):
        reg = obs.Registry()
        assert reg.counter("x") is reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")  # name already a Counter

    def test_snapshot_is_json_safe_and_reset_keeps_registrations(self):
        reg = obs.Registry()
        c = reg.counter("a.b")
        c.inc(3)
        reg.histogram("a.h").observe(1.5)
        json.dumps(reg.snapshot())  # plain data, no instrument objects
        reg.reset()
        snap = reg.snapshot()
        assert snap["a.b"] == 0
        assert snap["a.h"] == {"count": 0}
        assert reg.counter("a.b") is c  # same object, zeroed in place
        c.inc()
        assert reg.snapshot()["a.b"] == 1

    def test_thread_safety_smoke(self):
        reg = obs.Registry()
        c = reg.counter("n")
        h = reg.histogram("h")

        def work():
            for i in range(1000):
                c.inc()
                h.observe(float(i % 7))

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 8000
        assert h.count == 8000

    def test_histogram_percentiles_ordered(self):
        h = obs.Histogram("lat", buckets=(1, 2, 4, 8, 16, 32))
        rng = np.random.RandomState(0)
        for v in rng.uniform(0.0, 30.0, size=2000):
            h.observe(v)
        p50, p90, p99 = (h.percentile(q) for q in (50, 90, 99))
        assert 0.0 < p50 < p90 < p99 <= 30.0
        assert p50 == pytest.approx(15.0, abs=2.0)  # uniform median

    def test_histogram_rejects_unsorted_buckets(self):
        with pytest.raises(ValueError):
            obs.Histogram("bad", buckets=(5, 1))


# -- span tracer -------------------------------------------------------------


class TestTrace:
    def test_disabled_span_records_nothing(self):
        was_on = obs.tracing_enabled()
        obs.disable_tracing()
        try:
            obs.clear_trace()
            with obs.span("ghost"):
                pass
            assert obs.trace_events() == []
        finally:
            if was_on:
                obs.enable_tracing()

    def test_nested_spans_chrome_roundtrip(self, tracing):
        with obs.span("outer", step=1):
            with obs.span("inner", kind="child"):
                pass
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "trace.json")
            n = obs.export_chrome_trace(path)
            with open(path) as f:
                doc = json.load(f)  # valid JSON or this raises
        assert n == 2
        spans = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        outer, inner = spans["outer"], spans["inner"]
        assert outer["args"] == {"step": 1}
        assert inner["tid"] == outer["tid"]
        # containment: the child lies inside the parent's [ts, ts+dur]
        assert inner["ts"] >= outer["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
        assert all(e["ph"] in ("X", "M") for e in doc["traceEvents"])

    def test_ring_buffer_bounds_span_count(self, tracing):
        obs.enable_tracing(capacity=16)
        try:
            for i in range(64):
                with obs.span(f"s{i}"):
                    pass
            events = obs.trace_events()
            assert len(events) == 16
            assert events[-1]["name"] == "s63"  # newest win
        finally:
            obs.enable_tracing(capacity=obs.trace.DEFAULT_CAPACITY)

    def test_unserializable_attr_degrades_to_str(self, tracing):
        with obs.span("odd", what=object()):
            pass
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.json")
            obs.export_chrome_trace(path)
            with open(path) as f:
                doc = json.load(f)
        (ev,) = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert "object object" in ev["args"]["what"]


# -- instrumentation: static executor ----------------------------------------


def _build_train_parts():
    import paddle_tpu.fluid as fluid

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[8, 4])
        y = fluid.data(name="y", shape=[8, 1])
        out = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return prog, startup, loss


def _feed(i=0):
    rng = np.random.RandomState(i)
    return {"x": rng.randn(8, 4).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}


class TestExecutorInstrumentation:
    def test_train_loop_cache_counters_and_trace(self, tracing):
        import paddle_tpu.fluid as fluid

        pt.enable_static()
        try:
            pt.seed(0)
            prog, startup, loss = _build_train_parts()
            obs.metrics.reset()
            exe = fluid.Executor()
            exe.run(startup)  # empty program: no compile, no counters
            for i in range(3):
                exe.run(prog, feed=_feed(i), fetch_list=[loss])
            snap = obs.snapshot()
            # one program signature => exactly one compile; the acceptance
            # contract: snapshot's hit/miss counts match the compile count
            assert snap["executor.jit_cache.misses"] == 1
            assert snap["executor.jit_cache.hits"] == 2
            assert snap["executor.compile_ms"]["count"] == 1
            assert snap["executor.run_ms"]["count"] == 3
            assert snap["executor.fetch_ms"]["count"] == 3
            assert exe.cache_stats() == {"hits": 2, "misses": 1, "size": 1}
            # optimize-pass attribution reached the registry
            assert snap["analysis.pass.verifier.ms"]["count"] >= 1
        finally:
            pt.disable_static()
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.json")
            obs.export_chrome_trace(path)
            with open(path) as f:
                names = [e["name"] for e in json.load(f)["traceEvents"]]
        assert names.count("executor.compile") == 1
        assert names.count("executor.run") == 3

    def test_cache_stats_per_executor_not_global(self):
        import paddle_tpu.fluid as fluid

        pt.enable_static()
        try:
            pt.seed(0)
            prog, startup, loss = _build_train_parts()
            a, b = fluid.Executor(), fluid.Executor()
            a.run(startup)
            a.run(prog, feed=_feed(), fetch_list=[loss])
            a.run(prog, feed=_feed(), fetch_list=[loss])
            assert a.cache_stats() == {"hits": 1, "misses": 1, "size": 1}
            assert b.cache_stats() == {"hits": 0, "misses": 0, "size": 0}
        finally:
            pt.disable_static()


# -- instrumentation: eager dispatch sampling --------------------------------


class TestDispatchSampling:
    def test_off_by_default_and_counts_when_enabled(self):
        obs.metrics.reset()
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        pt.add(a, a)
        assert obs.snapshot().get("dispatch.ops_total", 0) == 0
        obs.enable_op_sampling()
        try:
            pt.add(a, a)
            pt.matmul(a, a)
        finally:
            obs.disable_op_sampling()
        pt.add(a, a)  # after disable: not counted
        snap = obs.snapshot()
        assert snap["dispatch.ops_total"] == 2
        assert snap["dispatch.op.matmul"] == 1
        assert snap["dispatch.op.add"] == 1

    def test_stride_sampling_scales_counts(self):
        obs.metrics.reset()
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        obs.enable_op_sampling(every=4)
        try:
            for _ in range(8):
                pt.add(a, a)
        finally:
            obs.disable_op_sampling()
        # one in four sampled, scaled back up: unbiased total estimate
        assert obs.snapshot()["dispatch.ops_total"] == 8


# -- instrumentation: dataloader ---------------------------------------------


class _Squares(pt.io.Dataset):
    def __len__(self):
        return 16

    def __getitem__(self, i):
        return np.float32(i * i)


class TestDataLoaderInstrumentation:
    def test_wait_histograms_and_queue_gauge(self, tracing):
        from paddle_tpu.io_.dataloader import DataLoader

        obs.metrics.reset()
        dl = DataLoader(_Squares(), batch_size=4, num_workers=2,
                        return_list=False)
        batches = [np.asarray(b) for b in dl]
        assert len(batches) == 4
        snap = obs.snapshot()
        assert snap["dataloader.producer_wait_ms"]["count"] == 4
        assert snap["dataloader.consumer_wait_ms"]["count"] >= 4
        assert "dataloader.queue_depth" in snap
        # 4 batch waits (+1 recorded for the end-of-epoch wait that
        # raised StopIteration)
        assert sum(1 for e in obs.trace_events()
                   if e["name"] == "dataloader.next") >= 4

    def test_worker_restart_counter(self):
        from paddle_tpu.io_.dataloader import DataLoader
        from paddle_tpu.resilience import inject

        obs.metrics.reset()
        with inject.chaos("loader_worker", at=2):
            dl = DataLoader(_Squares(), batch_size=4, num_workers=2,
                            return_list=False)
            batches = [np.asarray(b) for b in dl]
        assert len(batches) == 4  # restart budget absorbed the crash
        assert obs.snapshot()["dataloader.worker_restarts"] == 1


# -- instrumentation: resilience ---------------------------------------------


class TestResilienceInstrumentation:
    def test_chaos_retry_ticks_global_counter(self):
        from paddle_tpu.resilience import (GuardedExecutor, RecoveryPolicy,
                                           inject)

        pt.enable_static()
        try:
            pt.seed(0)
            prog, startup, loss = _build_train_parts()
            obs.metrics.reset()
            gexe = GuardedExecutor(policy=RecoveryPolicy(
                sleep=lambda s: None))
            gexe.run(startup)
            with inject.chaos("transient_execute", times=2):
                for i in range(3):
                    gexe.run(prog, feed=_feed(i), fetch_list=[loss])
            snap = obs.snapshot()
            assert snap["resilience.retries"] == 2 == gexe.stats.retries
            assert snap["resilience.steps"] == 3 == gexe.stats.steps
        finally:
            pt.disable_static()

    def test_skip_step_mirrors_into_registry(self):
        from paddle_tpu.resilience import (GuardedExecutor, RecoveryPolicy,
                                           inject)

        pt.enable_static()
        try:
            pt.seed(0)
            prog, startup, loss = _build_train_parts()
            obs.metrics.reset()
            gexe = GuardedExecutor(policy=RecoveryPolicy(
                on_nonfinite="skip_step", sleep=lambda s: None))
            gexe.run(startup)
            with inject.chaos("nan_feed", at=2, seed=3):
                for i in range(3):
                    gexe.run(prog, feed=_feed(i), fetch_list=[loss])
            snap = obs.snapshot()
            assert snap["resilience.nonfinite"] == 1
            assert snap["resilience.skipped"] == 1
            assert snap["resilience.steps"] == 2
        finally:
            pt.disable_static()


# -- instrumentation: checkpoint IO ------------------------------------------


class TestCheckpointInstrumentation:
    def test_save_load_verify_fallback_metrics(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.framework.io import (load_checkpoint,
                                             save_checkpoint,
                                             verify_checkpoint)

        obs.metrics.reset()
        with tempfile.TemporaryDirectory() as d:
            pt.seed(0)
            m = nn.Linear(4, 2)
            save_checkpoint(d, 1, model=m)
            save_checkpoint(d, 2, model=m)
            ok, _ = verify_checkpoint(os.path.join(d, "ckpt_2"))
            assert ok
            with open(os.path.join(d, "ckpt_2", "model.pdparams"),
                      "r+b") as f:
                f.truncate(4)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                assert load_checkpoint(d, model=nn.Linear(4, 2)) == 1
        snap = obs.snapshot()
        assert snap["checkpoint.saves"] == 2
        assert snap["checkpoint.save_ms"]["count"] == 2
        assert snap["checkpoint.loads"] == 1
        assert snap["checkpoint.load_ms"]["count"] == 1
        assert snap["checkpoint.verify_ms"]["count"] == 1
        assert snap["checkpoint.fallbacks"] == 1


# -- profiler rebases --------------------------------------------------------


class TestProfilerRebase:
    def test_step_timer_p99_and_registry(self):
        from paddle_tpu.utils.profiler import StepTimer

        obs.metrics.reset()
        t = StepTimer(skip_first=1)
        for _ in range(5):
            with t.step():
                pass
        s = t.summary()
        assert s["steps"] == 4
        assert s["p50_ms"] <= s["p90_ms"] <= s["p99_ms"] <= s["max_ms"]
        assert obs.snapshot()["step_timer.step_ms"]["count"] == 4
        t.reset()
        assert t.summary() == {"steps": 0}

    def test_fluid_profiler_block_records_spans(self):
        import paddle_tpu.fluid as fluid

        was_on = obs.tracing_enabled()
        obs.disable_tracing()  # the profiler window must enable it itself
        obs.clear_trace()
        try:
            with fluid.profiler.profiler("All", "total"):
                with fluid.profiler.span("user.block", tag=1):
                    pass
            names = [e["name"] for e in obs.trace_events()]
            assert "user.block" in names
            assert "profiler.window" in names
            # the window closed tracing again (it was off before)
            assert not obs.tracing_enabled()
        finally:
            if was_on:
                obs.enable_tracing()
            obs.clear_trace()
