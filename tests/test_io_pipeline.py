"""Data pipeline + native runtime tests (model: reference
tests/unittests/test_multiprocess_dataloader_*.py, reader decorators)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.io_ import (
    Dataset, IterableDataset, TensorDataset, ConcatDataset, ComposeDataset,
    Subset, random_split, BatchSampler, RandomSampler, SequenceSampler,
    WeightedRandomSampler, DistributedBatchSampler, DataLoader,
    default_collate_fn,
)
from paddle_tpu.io_ import reader as R
from paddle_tpu.runtime import RingBuffer, Arena, RecordWriter, ShardReader, get_lib


class _Sq(Dataset):
    def __init__(self, n=20):
        self.n = n

    def __getitem__(self, i):
        return np.float32(i), np.int64(i * i)

    def __len__(self):
        return self.n


class TestDatasets:
    def test_tensor_dataset(self):
        ds = TensorDataset([np.arange(10), np.arange(10) * 2])
        assert len(ds) == 10
        a, b = ds[3]
        assert a == 3 and b == 6

    def test_concat_subset_split(self):
        d1, d2 = _Sq(5), _Sq(7)
        cat = ConcatDataset([d1, d2])
        assert len(cat) == 12
        assert cat[6][0] == 1.0  # second dataset idx 1
        sub = Subset(d1, [4, 0])
        assert sub[0][0] == 4.0
        a, b = random_split(_Sq(10), [7, 3], generator=0)
        assert len(a) == 7 and len(b) == 3
        assert sorted(a.indices + b.indices) == list(range(10))

    def test_compose(self):
        ds = ComposeDataset([_Sq(4), _Sq(4)])
        s = ds[2]
        assert s == (2.0, 4, 2.0, 4)


class TestSamplers:
    def test_sequence_random(self):
        ds = _Sq(10)
        assert list(SequenceSampler(ds)) == list(range(10))
        r = list(RandomSampler(ds, generator=3))
        assert sorted(r) == list(range(10))

    def test_weighted(self):
        w = [0.0, 0.0, 1.0]
        s = list(WeightedRandomSampler(w, 20))
        assert all(i == 2 for i in s)

    def test_batch_sampler(self):
        bs = BatchSampler(dataset=_Sq(10), batch_size=3)
        batches = list(bs)
        assert len(batches) == 4 and len(batches[-1]) == 1
        bs = BatchSampler(dataset=_Sq(10), batch_size=3, drop_last=True)
        assert len(list(bs)) == 3

    def test_distributed_batch_sampler(self):
        parts = []
        for rank in range(2):
            s = DistributedBatchSampler(_Sq(10), batch_size=2,
                                        num_replicas=2, rank=rank)
            parts.append([i for b in s for i in b])
        assert len(parts[0]) == len(parts[1]) == 5
        assert set(parts[0] + parts[1]) == set(range(10))


class TestDataLoader:
    @pytest.mark.parametrize("workers", [0, 2])
    def test_loader_batches(self, workers):
        dl = DataLoader(_Sq(20), batch_size=4, num_workers=workers)
        out = list(dl)
        assert len(out) == 5
        x, y = out[0]
        assert x.shape == [4] and y.shape == [4]
        # deterministic order even with workers
        np.testing.assert_allclose(out[1][0].numpy(), [4, 5, 6, 7])

    def test_loader_shuffle_epoch(self):
        dl = DataLoader(_Sq(16), batch_size=4, shuffle=True)
        seen = sorted(float(v) for x, _ in dl for v in x.numpy())
        assert seen == list(map(float, range(16)))

    def test_iterable_dataset(self):
        class It(IterableDataset):
            def __iter__(self):
                for i in range(7):
                    yield np.float32(i)

        dl = DataLoader(It(), batch_size=3)
        shapes = [x[0].shape for x in dl]
        assert shapes == [[3], [3], [1]]

    def test_collate_nested(self):
        batch = [{"a": np.ones(2), "b": (1, 2.0)} for _ in range(3)]
        out = default_collate_fn(batch)
        assert out["a"].shape == (3, 2)
        assert out["b"][0].dtype == np.int64

    def test_worker_exception_propagates(self):
        class Bad(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                if i == 2:
                    raise ValueError("bad sample")
                return np.float32(i)

        dl = DataLoader(Bad(), batch_size=1, num_workers=2)
        with pytest.raises(ValueError, match="bad sample"):
            list(dl)


class TestReaders:
    def test_batch_shuffle_firstn(self):
        r = lambda: iter(range(10))
        assert list(R.batch(r, 3)()) == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
        assert sorted(x for b in R.batch(R.shuffle(r, 5), 2)() for x in b) == \
            list(range(10))
        assert list(R.firstn(r, 3)()) == [0, 1, 2]

    def test_map_chain_compose_cache(self):
        r = lambda: iter(range(5))
        assert list(R.map_readers(lambda x: x * 2, r)()) == [0, 2, 4, 6, 8]
        assert list(R.chain(r, r)()) == list(range(5)) * 2
        c = R.cache(r)
        assert list(c()) == list(c()) == list(range(5))

    def test_xmap_ordered(self):
        r = lambda: iter(range(20))
        got = list(R.xmap_readers(lambda x: x + 100, r, 4, 8, order=True)())
        assert got == [x + 100 for x in range(20)]

    def test_buffered(self):
        r = lambda: iter(range(50))
        assert list(R.buffered(r, 8)()) == list(range(50))

    def test_prefetch_to_device_decorator(self):
        """Reader-creator form of the double-buffered device feed:
        batches come back as jax arrays, in order."""
        import jax

        r = lambda: iter(np.full((3, 2), i, np.float32) for i in range(5))
        got = list(R.prefetch_to_device(r, depth=2)())
        assert len(got) == 5
        assert all(isinstance(b, jax.Array) for b in got)
        assert [float(np.asarray(b)[0, 0]) for b in got] == \
            [0.0, 1.0, 2.0, 3.0, 4.0]

    def test_data_feeder(self):
        f = R.DataFeeder(feed_list=["x", "y"])
        feed = f.feed([(np.ones(3), 0), (np.zeros(3), 1)])
        assert feed["x"].shape == (2, 3)
        assert feed["y"].tolist() == [0, 1]


class TestNativeRuntime:
    def test_lib_builds(self):
        assert get_lib() is not None, "native runtime must compile"

    def test_ring_roundtrip_threads(self):
        import threading

        rb = RingBuffer(4)
        items = [bytes([i]) * (i + 1) for i in range(50)]

        def produce():
            for it in items:
                rb.push(it)
            rb.close()

        t = threading.Thread(target=produce)
        t.start()
        got = []
        while True:
            b = rb.pop()
            if b is None:
                break
            got.append(b)
        t.join()
        assert got == items

    def test_ring_timeout(self):
        rb = RingBuffer(2)
        with pytest.raises(TimeoutError):
            rb.pop(timeout_ms=50)

    def test_arena_stats(self):
        a = Arena(1 << 16)
        a.alloc(100)
        a.alloc(200)
        st = a.stats()
        assert st["alloc_count"] == 2 and st["in_use"] >= 300
        a.reset()
        assert a.stats()["in_use"] == 0

    def test_record_shards(self, tmp_path):
        paths = []
        for s in range(3):
            p = str(tmp_path / f"s{s}.rec")
            with RecordWriter(p) as w:
                for i in range(40):
                    w.write(f"{s}:{i}".encode())
            paths.append(p)
        rs = ShardReader(paths, n_threads=3)
        recs = sorted(r.decode() for r in rs)
        assert len(recs) == 120
        rs.close()

    def test_corrupt_record_detected(self, tmp_path):
        p = str(tmp_path / "bad.rec")
        with RecordWriter(p) as w:
            w.write(b"payload-abcdef")
        # flip a payload byte
        with open(p, "r+b") as f:
            f.seek(-3, 2)
            f.write(b"X")
        rs = ShardReader([p], n_threads=1)
        with pytest.raises(OSError):
            list(rs)
        rs.close()


def test_native_shuffle_pool_and_stream():
    """runtime ShufflePool (cc PtShufflePool): lossless, shuffled,
    deterministic per seed; io_.reader.shuffle_stream streams through
    it with a producer thread."""
    import pickle

    from paddle_tpu.runtime import ShufflePool, get_lib
    from paddle_tpu.io_.reader import shuffle_stream

    p = ShufflePool(capacity=16, seed=5, min_fill=8)
    for i in range(16):
        p.push(pickle.dumps(i))
    p.close()
    drawn = []
    while True:
        b = p.pop(timeout_ms=2000)
        if b is None:
            break
        drawn.append(pickle.loads(b))
    assert sorted(drawn) == list(range(16))

    out = list(shuffle_stream(lambda: iter(range(100)), buf_size=32,
                              seed=3)())
    assert sorted(out) == list(range(100))
    assert out != list(range(100))
    out2 = list(shuffle_stream(lambda: iter(range(100)), buf_size=32,
                               seed=3)())
    assert sorted(out2) == list(range(100))
    # NB: the draw SEQUENCE is seed-deterministic but the output also
    # depends on pool fill level at each pop (producer/consumer timing),
    # so run-to-run equality is not guaranteed — losslessness is.

    # exceptions propagate, not truncate
    def bad():
        yield 1
        raise RuntimeError("boom")

    import pytest

    with pytest.raises(RuntimeError):
        list(shuffle_stream(bad, buf_size=4, seed=1)())
