"""SLO engine (ISSUE 19): windowed time-series over the metrics plane
(obs.timeseries), error-budget burn-rate alerting (obs.slo), and the
live fleet statusz plane (obs.export /statusz).

Covers the PR's acceptance contract:
- window math is EXACT under a ManualClock: counter deltas/rates,
  gauge trends, windowed histogram percentiles and threshold
  fractions (exact at bucket bounds), identical over in-process
  registry snapshots and scraped exposition text;
- the Google-SRE multi-window burn-rate ladder fires the fast page at
  the hand-computed instant, clears on recovery, never double-fires
  while latched — and both the journaled ``slo.fire`` burn values and
  the scraped ``slo_burn_rate`` gauge are BITWISE the evaluator's
  floats;
- the live fleet drill: a 2-replica routed fleet with one degraded
  replica pages with that replica attributed as worst offender on
  /statusz and in the ``slo.fire`` event, the evaluator rides the
  router's EXISTING throttled autoscale exposition (scrape budget
  unchanged), and ``tools/slo_report.py`` reconstructs the same
  alert timeline from the journals post-hoc.
"""
import json
import os
import urllib.request

import pytest

from paddle_tpu import obs
from paddle_tpu.obs import export as obs_export
from paddle_tpu.obs import fleet as obs_fleet
from paddle_tpu.obs import journal
from paddle_tpu.obs import slo as obs_slo
from paddle_tpu.obs import timeseries as obs_ts
from paddle_tpu.serving import ManualClock


@pytest.fixture(autouse=True)
def _no_global_journal():
    yield
    if journal.ACTIVE is not None:
        journal.ACTIVE.close()
    journal.ACTIVE = None


def _hist_payload(buckets, flat_counts, total=None):
    """Snapshot-shaped histogram payload from per-bucket (plus
    overflow) counts."""
    cum, c = [], 0
    for n in flat_counts:
        c += n
        cum.append(c)
    return ("histogram", (tuple(buckets), tuple(cum), c,
                          float(total if total is not None else 0.0)))


# -- the windowing layer ------------------------------------------------------


class TestSeriesStore:
    def test_counter_delta_and_rate_are_exact(self):
        clock = ManualClock()
        store = obs_ts.SeriesStore(interval_s=1.0, clock=clock)
        for i in range(11):
            store.observe({"req": ("counter", float(5 * i))},
                          now=float(i))
        assert store.counter_delta("req", 4.0, now=10.0) == 20.0
        assert store.counter_rate("req", 4.0, now=10.0) == 5.0
        # a window predating history falls back to the oldest sample
        # (partial windows read what exists, the budget-accounting rule)
        assert store.counter_delta("req", 1e9, now=10.0) == 50.0

    def test_counter_reset_clamps_to_zero(self):
        store = obs_ts.SeriesStore(clock=ManualClock())
        store.observe({"req": ("counter", 100.0)}, now=0.0)
        store.observe({"req": ("counter", 3.0)}, now=1.0)  # restart
        assert store.counter_delta("req", 10.0, now=1.0) == 0.0

    def test_gauge_last_and_trend(self):
        store = obs_ts.SeriesStore(clock=ManualClock())
        store.observe({"depth": ("gauge", 2.0)}, now=0.0)
        store.observe({"depth": ("gauge", 9.0)}, now=5.0)
        assert store.gauge_last("depth") == 9.0
        assert store.gauge_delta("depth", 5.0, now=5.0) == 7.0

    def test_sample_enforces_cadence_observe_does_not(self):
        clock = ManualClock()
        store = obs_ts.SeriesStore(interval_s=10.0, clock=clock)
        calls = []

        def snap():
            calls.append(1)
            return {"g": ("gauge", 1.0)}

        assert store.sample(snap, now=0.0) == 0.0
        assert store.sample(snap, now=3.0) is None  # not due: no call
        assert len(calls) == 1
        assert store.sample(snap, now=10.0) == 10.0

    def test_windowed_histogram_percentile_and_fraction(self):
        store = obs_ts.SeriesStore(clock=ManualClock())
        buckets = (10.0, 20.0, 40.0)
        store.observe({"lat": _hist_payload(buckets, (0, 0, 0, 0))},
                      now=0.0)
        # inside the window: 6 obs <=10, 2 in (10,20], 2 in (20,40]
        store.observe({"lat": _hist_payload(buckets, (6, 2, 2, 0))},
                      now=60.0)
        b, counts, count, _ = store.hist_window("lat", 60.0, now=60.0)
        assert b == buckets and counts == (6, 2, 2, 0) and count == 10
        assert store.percentile("lat", 50, 60.0, now=60.0) == \
            pytest.approx(8.333333333333334)
        # threshold AT a bucket bound is exact: 2 of 10 strictly above
        assert store.fraction_above("lat", 20.0, 60.0, now=60.0) == \
            (2.0, 10.0)
        # between bounds it is conservative: the straddling (10,20]
        # bucket counts as above
        assert store.fraction_above("lat", 15.0, 60.0, now=60.0) == \
            (4.0, 10.0)

    def test_hist_window_is_a_true_delta(self):
        store = obs_ts.SeriesStore(clock=ManualClock())
        buckets = (10.0, 20.0)
        store.observe({"lat": _hist_payload(buckets, (5, 1, 0))},
                      now=0.0)
        store.observe({"lat": _hist_payload(buckets, (5, 4, 2))},
                      now=30.0)
        _, counts, count, _ = store.hist_window("lat", 30.0, now=30.0)
        assert counts == (0, 3, 2) and count == 5

    def test_exposition_snapshot_matches_registry_snapshot(self):
        """The multi-process path and the in-process path must produce
        the SAME windowed numbers: snapshotting a registry directly and
        snapshotting its rendered exposition text are interchangeable
        SeriesStore feeds (histogram bucket layout included — the +Inf
        bucket folds into the overflow slot, never into the bounds)."""
        reg = obs.metrics.Registry()
        h = reg.histogram("unit.lat_ms", buckets=(1.0, 10.0))
        for v in (0.5, 5.0, 50.0, 7.0):
            h.observe(v)
        reg.counter("unit.hits").inc(7)
        reg.gauge("unit.depth").set(3.0)

        direct = obs_ts.registry_snapshot(reg)
        text = "\n".join(obs_export.registry_lines(reg)) + "\n"
        scraped = obs_ts.exposition_snapshot(text)

        assert scraped["paddle_tpu_unit_hits"] == ("counter", 7.0)
        assert scraped["paddle_tpu_unit_depth"] == ("gauge", 3.0)
        kind, (b, cum, count, total) = \
            scraped["paddle_tpu_unit_lat_ms"]
        dkind, (db, dcum, dcount, dtotal) = direct["unit.lat_ms"]
        assert kind == dkind == "histogram"
        assert b == db == (1.0, 10.0)
        assert cum == dcum == (1, 3, 4)
        assert count == dcount == 4 and total == dtotal == 62.5

    def test_retention_is_bounded_by_horizon(self):
        store = obs_ts.SeriesStore(interval_s=1.0, horizon_s=10.0,
                                   clock=ManualClock())
        for i in range(100):
            store.observe({"g": ("gauge", float(i))}, now=float(i))
        ring = store._rings["g"]
        assert len(ring.samples) <= 12


# -- burn-rate alerting -------------------------------------------------------


def _availability_fixture(clock, ev):
    """40 clean warmup ticks, then bad ticks at 50% rejects: 60 s
    ticks, 100 requests/tick (the tools/slo_report.py fixture)."""
    state = {"rej": 0.0, "disp": 0.0}

    def tick(n_rej, n_disp):
        state["rej"] += n_rej
        state["disp"] += n_disp
        clock.advance(60.0)
        return ev.observe(
            text={"serving.router.rejected":
                  ("counter", state["rej"]),
                  "serving.router.dispatched":
                  ("counter", state["disp"])},
            now=clock())

    for _ in range(40):
        tick(0, 100)
    return tick


class TestBurnRateAlerting:
    def test_page_fires_at_hand_computed_instant_and_is_bitwise(
            self, tmp_path):
        """The acceptance core: under ManualClock the 14.4x page fires
        at the 9th bad tick with burn values BITWISE equal to the
        hand-computed fractions, the journaled slo.fire carries the
        same floats, it never refires while latched, and it clears at
        the 4th clean tick."""
        run_dir = str(tmp_path / "run")
        j = journal.start_run(run_dir)
        clock = ManualClock()
        ev = obs_slo.SLOEvaluator({"availability": 0.99}, clock=clock,
                                  interval_s=60.0,
                                  include_registry=False)
        tick = _availability_fixture(clock, ev)
        budget = 1.0 - 0.99

        page_fires = []
        for k in range(1, 13):
            for t in tick(50, 50):
                if t["kind"] == "slo.fire" and t["severity"] == "page":
                    page_fires.append((k, t))
        assert [k for k, _ in page_fires] == [9]
        fire = page_fires[0][1]
        # 5m window: 5 all-bad ticks -> frac 250/500; 30m window: 9 of
        # 30 ticks bad -> frac 450/3000. Bitwise, not approx.
        assert fire["burn_short"] == (250.0 / 500.0) / budget
        assert fire["burn_long"] == (450.0 / 3000.0) / budget
        assert ev._alerts[("availability", "page")]["fires"] == 1

        page_clears = []
        for m in range(1, 8):
            for t in tick(0, 100):
                if t["kind"] == "slo.clear" and \
                        t["severity"] == "page":
                    page_clears.append(m)
        assert page_clears == [4]

        # the scraped gauges parse back to EXACTLY the evaluator floats
        vals = obs_export.parse_prometheus_text(
            obs_export.prometheus_text(slo=ev))
        for label in ("1m", "5m", "30m", "3h"):
            key = (f'paddle_tpu_slo_burn_rate{{objective='
                   f'"availability",window="{label}"}}')
            assert vals[key] == ev.burn[("availability", label)]
        assert vals['paddle_tpu_slo_budget_remaining'
                    '{objective="availability"}'] == \
            ev.budget_left["availability"]

        # the journal carries the identical floats
        ev.journal_summary()
        j.close()
        journal.ACTIVE = None
        run = obs_fleet.load_journal(run_dir)
        fires = [e for e in run["events"]
                 if e.get("kind") == "slo.fire"
                 and e.get("severity") == "page"]
        assert len(fires) == 1
        assert fires[0]["burn_short"] == fire["burn_short"]
        assert fires[0]["burn_long"] == fire["burn_long"]

    def test_no_signal_means_no_alert(self):
        ev = obs_slo.SLOEvaluator({"availability": 0.99},
                                  clock=ManualClock(),
                                  include_registry=False)
        assert ev.observe(text={}, now=1.0) == []
        assert ev.burn[("availability", "5m")] is None
        assert ev.active_alerts() == []

    def test_spec_validation(self):
        with pytest.raises(ValueError):
            obs_slo.SLOSpec("bad", "latency", target=1.0,
                            threshold_ms=1.0)  # zero budget
        with pytest.raises(ValueError):
            obs_slo.SLOSpec("bad", "nonsense")
        with pytest.raises(ValueError):
            obs_slo.SLOSpec("bad", "latency")  # no threshold
        with pytest.raises(KeyError):
            obs_slo.specs_from_dict({"nope": 1})
        with pytest.raises(ValueError):
            obs_slo.AlertPolicy("page", "30m", "5m", 2.0)  # inverted

    def test_evaluate_run_post_hoc(self, tmp_path):
        """The post-hoc twin: exact pooled percentiles + availability
        from reject events + goodput from the serving-clock span."""
        run_dir = str(tmp_path / "run")
        j = journal.RunJournal(run_dir, flush_every=1,
                               compute_flops=False).start()
        for i, ttft_s in enumerate((0.1, 0.2, 0.4, 0.8)):
            j.record_request(rid=f"r{i}", state="FINISHED",
                             arrival_t=0.0, first_token_t=ttft_s,
                             finish_t=10.0, prompt_tokens=4,
                             output_tokens=5)
        j.event("router.reject", rid="rX", reason="queue_full")
        j.close()

        rep = obs_slo.evaluate_run(
            run_dir, {"ttft_p99_ms": 500.0, "availability": 0.9,
                      "goodput_tps": 1.0})
        rows = {r["name"]: r for r in rep["objectives"]}
        assert rows["ttft_p99_ms"]["value"] == 800.0
        assert rows["ttft_p99_ms"]["ok"] is False
        assert rows["availability"]["value"] == 1.0 - 1.0 / 5.0
        assert rows["availability"]["ok"] is False
        assert rows["goodput_tps"]["value"] == 20.0 / 10.0
        assert rows["goodput_tps"]["ok"] is True
        assert rep["violations"] == ["ttft_p99_ms", "availability"]

        # tightening nothing: an empty run dir has no journals at all
        with pytest.raises(FileNotFoundError):
            obs_slo.evaluate_run(str(tmp_path / "empty"),
                                 {"availability": 0.9})

    def test_serving_anomaly_detectors_fire_on_windowed_spike(self):
        """The evaluator's tick record reaches the serving anomaly
        detectors: a stable windowed TTFT p99 followed by a spike fires
        ``ttft_spike`` exactly once per excursion."""
        from paddle_tpu.obs import anomaly

        clock = ManualClock()
        eng = anomaly.AnomalyEngine(
            detectors=anomaly.serving_detectors(""))
        ev = obs_slo.SLOEvaluator(
            {"ttft_p99_ms": 250.0}, clock=clock, interval_s=10.0,
            include_registry=False, anomaly_engine=eng)
        buckets = (10.0, 1000.0)
        flat = [0, 0, 0]

        def tick(bucket_idx, n=10):
            flat[bucket_idx] += n
            clock.advance(10.0)
            ev.observe(
                text={"serving.ttft_ms":
                      _hist_payload(buckets, tuple(flat))},
                now=clock())

        for _ in range(8):
            tick(0)        # stable: p99 inside the <=10ms bucket
        assert eng.fired == []
        tick(1)            # excursion: p99 jumps into (10,1000]
        assert [f["name"] for f in eng.fired] == ["ttft_spike"]
        tick(1)            # sustained: latched, no refire
        assert len(eng.fired) == 1


# -- statusz ------------------------------------------------------------------


class TestStatusz:
    def _evaluator_with_signal(self):
        clock = ManualClock()
        ev = obs_slo.SLOEvaluator({"availability": 0.99}, clock=clock,
                                  interval_s=60.0,
                                  include_registry=False)
        tick = _availability_fixture(clock, ev)
        for _ in range(10):
            tick(50, 50)   # page + warn latched
        return ev

    def test_statusz_data_and_html(self):
        ev = self._evaluator_with_signal()
        data = obs_export.statusz_data(slo=ev)
        assert data["slo"]["active_alerts"]
        objs = {o["name"]: o for o in data["slo"]["objectives"]}
        assert objs["availability"]["burn"]["5m"] == \
            ev.burn[("availability", "5m")]
        html = obs_export.render_statusz_html(data)
        assert html.startswith("<!DOCTYPE html>")
        assert "FIRING: availability" in html
        assert "SLO burn" in html

    def test_http_statusz_endpoint_html_and_json(self):
        ev = self._evaluator_with_signal()
        exp = obs_export.MetricsExporter(engines=[], slo=ev)
        port = exp.start()
        try:
            base = f"http://127.0.0.1:{port}"
            with urllib.request.urlopen(base + "/statusz",
                                        timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/html")
                html = resp.read().decode("utf-8")
            with urllib.request.urlopen(
                    base + "/statusz?format=json", timeout=10) as resp:
                assert resp.headers["Content-Type"].startswith(
                    "application/json")
                data = json.loads(resp.read().decode("utf-8"))
            with urllib.request.urlopen(base + "/nope",
                                        timeout=10) as resp:
                pass
        except urllib.error.HTTPError as e:
            assert e.code == 404   # the unknown path, not the others
        finally:
            exp.stop()
        assert "FIRING" in html
        # the JSON body carries the live burn values, not a rendering
        objs = {o["name"]: o for o in data["slo"]["objectives"]}
        assert objs["availability"]["burn"]["5m"] == \
            ev.burn[("availability", "5m")]


# -- the fleet drill ----------------------------------------------------------


class TestFleetDrill:
    def test_degraded_replica_pages_with_worst_offender_attribution(
            self, tmp_path):
        """ISSUE 19 acceptance drill: 2 local replicas under a routed
        ManualClock fleet, one degraded (stalled for the first 12
        router iterations, so its early requests wait seconds for
        their first token while the healthy replica answers within an
        iteration); the latency
        page fires with THAT replica attributed worst offender in the
        slo.fire event and on /statusz; the SLO evaluator consumed the
        router's EXISTING throttled exposition (exactly one per tick —
        the autoscaler's scrape budget unchanged); and slo_report
        reconstructs the identical alert timeline from the run dir."""
        from paddle_tpu.serving.fleet import (ReplicaPool, ReplicaSpec,
                                              Router)
        from paddle_tpu.serving.fleet.autoscale import Autoscaler

        obs.metrics.reset()
        run_dir = str(tmp_path / "run")
        j = journal.start_run(run_dir)
        clock = ManualClock()
        pool = ReplicaPool(
            ReplicaSpec(vocab_size=32, pages=64, page_size=4,
                        max_seq_len=32, token_budget=128),
            replicas=2, mode="local", clock=clock)
        ev = obs_slo.SLOEvaluator(
            {"ttft_p99_ms": {"threshold_ms": 500.0, "target": 0.999}},
            clock=clock, interval_s=0.5)
        asc = Autoscaler(min_replicas=2, max_replicas=2, clock=clock)
        router = Router(pool, clock=clock, autoscaler=asc, slo=ev,
                        autoscale_interval_s=0.5)

        victim = pool.replicas[1]
        victim_id = victim.replica_id
        healthy_id = pool.replicas[0].replica_id
        real_pump = victim.pump
        pumps = {"n": 0}

        def stalled_pump(steps=1):
            # degraded for the first 12 router iterations: anything
            # dispatched to the victim early waits multi-second for
            # its first token (ManualClock-deterministic badness),
            # then the replica recovers and drains
            pumps["n"] += 1
            if pumps["n"] <= 12:
                return 0
            return real_pump(steps)

        victim.pump = stalled_pump

        expo = {"n": 0}
        real_expo = router.exposition

        def counting_expo():
            expo["n"] += 1
            return real_expo()

        router.exposition = counting_expo

        steps = 0
        for i in range(40):
            if i < 2:
                # pairs: the second of a pair lands on the victim
                # while the healthy replica holds the first (least-
                # outstanding placement), and the light load keeps
                # every healthy TTFT under the threshold — the ONLY
                # bad requests are the stalled victim's
                # max_new_tokens=1: first token == finish, so the
                # per-replica attribution gauge (finished-request
                # percentiles) updates in the SAME tick the registry
                # histogram records the bad TTFT
                router.submit([1, 2, 3], max_new_tokens=1)
                router.submit([1, 2, 3], max_new_tokens=1)
            router.step()
            steps += 1
            clock.advance(0.5)
        for _ in range(200):
            if not router.inflight and not router.queue_depth:
                break
            router.step()
            steps += 1
            clock.advance(0.5)
        assert not router.inflight and not router.queue_depth

        # scrape budget: ONE exposition per throttled tick, shared by
        # the autoscaler and the SLO evaluator — attaching SLO
        # monitoring added zero scrapes (every step ticks here because
        # the clock advances exactly one interval per step)
        assert expo["n"] == steps
        assert ev.ticks == steps

        # the page fired, attributing the degraded replica
        page_fires = [e for e in ev.alert_log
                      if e["kind"] == "slo.fire"
                      and e["severity"] == "page"]
        assert page_fires, "degraded fleet never paged"
        assert page_fires[0]["worst_replica"] == str(victim_id)
        assert ev._alerts[("ttft_p99_ms", "page")]["fires"] == 1

        # /statusz (live HTTP): topology + the same worst offender
        exp = obs_export.MetricsExporter(engines=[], router=router,
                                         slo=ev)
        port = exp.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/statusz?format=json",
                    timeout=10) as resp:
                data = json.loads(resp.read().decode("utf-8"))
        finally:
            exp.stop()
        assert {r["replica"] for r in data["fleet"]} >= \
            {victim_id, healthy_id}
        per = data["replica_slo"]
        assert per[str(victim_id)]["ttft_p99_ms"] > \
            per[str(healthy_id)]["ttft_p99_ms"]
        log = data["slo"]["alert_log"]
        assert any(e["kind"] == "slo.fire"
                   and e["severity"] == "page"
                   and e["worst_replica"] == str(victim_id)
                   for e in log)

        router.close()   # journals router.summary + slo.summary
        j.close()
        journal.ACTIVE = None

        # post-hoc: slo_report reconstructs the same timeline from the
        # journals alone
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "slo_report_drill", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "tools", "slo_report.py"))
        tool = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tool)
        rep = tool.report(run_dir)
        assert rep["slo"] is not None
        got = [(t["at"], t["kind"], t["objective"], t["severity"],
                t["worst_replica"])
               for t in rep["slo"]["timeline"]]
        want = [(t["at"], t["kind"], t["objective"], t["severity"],
                 t.get("worst_replica"))
                for t in ev.alert_log]
        assert got == want
        assert rep["slo"]["summary"]["ttft_p99_ms"]["fires"] >= 1
