"""Dataset modules added for full paddle.dataset parity: flowers,
voc2012, wmt14, sentiment, mq2007, image utilities, common file tools.
Ref: python/paddle/dataset/{flowers,voc2012,wmt14,sentiment,mq2007,
image,common}.py."""
import os

import numpy as np

import paddle_tpu.dataset as D


def test_extra_dataset_readers():
    s = next(D.flowers.train()())
    assert s[0].shape == (3, 224, 224) and 0 <= s[1] < 102
    img, lab = next(D.voc2012.train()())
    assert img.shape == (3, 64, 64) and lab.shape == (64, 64)
    src, tin, tnext = next(D.wmt14.train(1000)())
    assert tin[0] == 0 and tnext[-1] == 1 and len(tin) == len(tnext)
    d1, d2 = D.wmt14.get_dict(100)
    assert d1[5] == "w5"
    ids, y = next(D.sentiment.train()())
    assert y in (0, 1) and len(D.sentiment.get_word_dict()) == 5000
    a, b = next(D.mq2007.train("pairwise")())
    assert a.shape == (46,) and b.shape == (46,)
    x, r = next(D.mq2007.train("listwise")())
    assert x.shape[1] == 46 and len(r) == x.shape[0]
    f, rel = next(D.mq2007.train("pointwise")())
    assert f.shape == (46,) and rel in (0, 1, 2)


def test_image_utilities():
    im = np.random.rand(100, 80, 3).astype("float32")
    out = D.image.simple_transform(im, 72, 64, True,
                                   rng=np.random.RandomState(0))
    assert out.shape == (3, 64, 64)
    out2 = D.image.simple_transform(im, 72, 64, False,
                                    mean=[0.5, 0.5, 0.5])
    assert out2.shape == (3, 64, 64)
    assert D.image.left_right_flip(im).shape == im.shape
    assert D.image.resize_short(im, 50).shape[0] == 62  # 100*50/80


def test_common_split_cluster_convert(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    files = D.common.split(lambda: iter(range(25)), 10)
    assert len(files) == 3
    rd = D.common.cluster_files_reader(str(tmp_path / "*.pickle"), 2, 0)
    got = list(rd())
    assert len(got) == 15 and got[0] == 0   # files 0 and 2 of 3
    rd1 = D.common.cluster_files_reader(str(tmp_path / "*.pickle"), 2, 1)
    assert len(list(rd1())) == 10           # file 1
    outs = D.common.convert(str(tmp_path), lambda: iter(range(7)), 5,
                            "rec")
    assert len(outs) == 2
    assert all(os.path.getsize(p) > 0 for p in outs)


def test_convert_roundtrips_through_native_shard_reader(tmp_path):
    """dataset.common.convert writes the crc-framed record format the
    native threaded ShardReader consumes — full pipeline round-trip."""
    import pickle

    from paddle_tpu.runtime import ShardReader

    files = D.common.convert(str(tmp_path), lambda: iter(range(23)), 10,
                             "chunk")
    assert len(files) == 3
    got = sorted(pickle.loads(b) for b in ShardReader(files, n_threads=2))
    assert got == list(range(23))
