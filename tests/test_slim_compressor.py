"""slim 1.x Compressor framework (ref: fluid/contrib/slim/): yaml-
configured strategies over eager models — uniform pruning with
persistent masks, distillation via feature hooks, QAT scheduling,
SAController search, GraphWrapper program inspection, quantization
passes, and the recorded MKLDNN/NAS descopes.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import slim

RNG = np.random.RandomState(0)
W = RNG.randn(16, 1).astype("float32")


def _reader(n=6, b=8):
    def r():
        for _ in range(n):
            X = RNG.randn(b, 16).astype("float32")
            yield X, X @ W

    return r


def _mlp():
    pt.seed(0)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 1))


def _loss_fn(model, x, y):
    return pt.mean((model(x) - y) ** 2)


class TestCompressorPrune:
    def test_yaml_config_uniform_prune(self, tmp_path):
        cfg = tmp_path / "slim.yaml"
        cfg.write_text(
            "version: 1.0\n"
            "strategies:\n"
            "  prune_s:\n"
            "    class: UniformPruneStrategy\n"
            "    target_ratio: 0.5\n"
            "    start_epoch: 0\n"
            "    pruned_params: '.*weight.*|.*_w_.*'\n"
            "compressor:\n"
            "  epoch: 2\n"
            "  strategies: [prune_s]\n")
        model = _mlp()
        opt = pt.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
        evals = []
        comp = slim.Compressor(
            model=model, train_reader=_reader(), train_optimizer=opt,
            loss_fn=_loss_fn,
            eval_func=lambda m: -float(_loss_fn(
                m, pt.to_tensor(RNG.randn(8, 16).astype("float32")),
                pt.to_tensor(np.zeros((8, 1), "float32"))).numpy()))
        comp.config(str(cfg))
        assert comp.epoch == 2 and len(comp.strategies) == 1
        comp.run()
        strat = comp.strategies[0]
        # ~half the weights dead, and still dead after training steps
        assert 0.4 < strat.sparsity() < 0.6
        for p, m in strat.pruner.masks.values():
            w = np.asarray(p.numpy())
            assert np.all(w[~np.asarray(m)] == 0)

    def test_sensitive_strategy_with_given_sensitivities(self):
        model = _mlp()
        names = [n for n, p in model.named_parameters()
                 if len(p.shape) >= 2]
        sens = {n: {0.1: 0.01, 0.3: 0.05, 0.5: 0.2} for n in names}
        strat = slim.SensitivePruneStrategy(
            target_ratio=0.1, sensitivities=sens,
            pruned_params=".*weight.*|.*_w_.*")
        opt = pt.optimizer.SGD(learning_rate=1e-2,
                               parameters=model.parameters())
        comp = slim.Compressor(model=model, train_reader=_reader(2),
                               train_optimizer=opt, loss_fn=_loss_fn,
                               epoch=1)
        comp.strategies = [strat]
        comp.run()
        assert strat.sparsity() > 0.0


class TestDistillation:
    def test_l2_and_soft_label_distillers(self):
        teacher = _mlp()
        student = _mlp()
        # make teacher differ
        for p in teacher.parameters():
            p.set_value(np.asarray(p.numpy()) * 1.5)
        dist = slim.DistillationStrategy(
            distillers=[
                slim.L2Distiller("0", "0"),
                slim.SoftLabelDistiller("2", "2",
                                        teacher_temperature=2.0)],
            start_epoch=0, end_epoch=5, teacher=teacher)
        opt = pt.optimizer.Adam(learning_rate=1e-2,
                                parameters=student.parameters())
        comp = slim.Compressor(model=student, train_reader=_reader(4),
                               train_optimizer=opt, loss_fn=_loss_fn,
                               epoch=1)
        comp.strategies = [dist]
        comp.run()
        # distiller terms were computable on the last batch
        terms = dist.loss_terms(comp.context)
        assert len(terms) == 2
        assert all(np.isfinite(float(t.numpy())) for t in terms)

    def test_missing_sublayer_raises(self):
        with pytest.raises(ValueError):
            slim.DistillationStrategy(
                distillers=[slim.L2Distiller("nope", "nope")],
                teacher=_mlp()).on_compression_begin(
                    slim.Context(train_graph=_mlp()))


class TestQuantStrategyAndPasses:
    def test_quantization_strategy_wraps(self):
        model = _mlp()
        opt = pt.optimizer.SGD(learning_rate=1e-3,
                               parameters=model.parameters())
        comp = slim.Compressor(model=model, train_reader=_reader(2),
                               train_optimizer=opt, loss_fn=_loss_fn,
                               epoch=1)
        comp.strategies = [slim.QuantizationStrategy(start_epoch=0)]
        out = comp.run()
        kinds = {type(l).__name__ for _, l in out.named_sublayers()}
        assert "QATLinear" in kinds

    def test_pass_pipeline_and_transpiler(self):
        x = pt.to_tensor(RNG.randn(4, 16).astype("float32"))
        model = _mlp()
        ref = np.asarray(model(x).numpy())
        slim.QuantizationTransformPass().apply(model)
        qat_out = np.asarray(model(x).numpy())
        assert np.abs(qat_out - ref).max() < 0.5  # fake-quant approx
        slim.QuantizationFreezePass().apply(model)
        kinds = {type(l).__name__ for _, l in model.named_sublayers()}
        assert "QuantizedLinear" in kinds

        m2 = _mlp()
        tp = slim.QuantizeTranspiler()
        tp.training_transpile(m2)
        tp.freeze_program(m2)
        kinds = {type(l).__name__ for _, l in m2.named_sublayers()}
        assert "QuantizedLinear" in kinds
        assert slim.TransformForMobilePass().apply(m2) is m2

    def test_out_scale_observers(self):
        model = _mlp()
        p = slim.OutScaleForTrainingPass(moving_rate=0.5)
        p.apply(model)
        x = pt.to_tensor(RNG.randn(4, 16).astype("float32"))
        model(x)
        model(x)
        assert p.out_scales and all(v > 0 for v in p.out_scales.values())
        slim.OutScaleForInferencePass(training_pass=p).apply(model)
        assert model._out_threshold == p.out_scales
        p.remove()

    def test_mkldnn_and_nas_descopes(self):
        with pytest.raises(NotImplementedError):
            slim.MKLDNNPostTrainingQuantStrategy()
        with pytest.raises(NotImplementedError):
            slim.LightNASStrategy()
        with pytest.raises(NotImplementedError):
            slim.ControllerServer()


class TestSearcher:
    def test_sa_controller_finds_optimum(self):
        # reward = number of 1-tokens; SA should find the all-ones vector
        ctl = slim.SAController(range_table=[2] * 8, seed=3,
                                init_temperature=1.0, reduce_rate=0.7)
        for _ in range(200):
            t = ctl.next_tokens()
            ctl.update(t, float(sum(t)))
        assert ctl.max_reward == 8.0
        assert ctl.best_tokens == [1] * 8


class TestGraphWrapper:
    def test_program_inspection(self):
        pt.enable_static()
        try:
            import paddle_tpu.fluid as fluid

            main, startup = pt.static.Program(), pt.static.Program()
            with pt.static.program_guard(main, startup):
                x = pt.static.data("x", [4, 16], "float32")
                h = fluid.layers.fc(x, size=8, act="relu")
                fluid.layers.fc(h, size=1)
            g = slim.GraphWrapper(main)
            params = g.all_parameters()
            assert len(params) == 4  # 2x (w, b)
            assert g.numel_params() == 16 * 8 + 8 + 8 * 1 + 1
            ops = g.ops()
            assert any(o.type() == "linear" for o in ops)
            w = params[0]
            assert w.is_parameter() and len(w.outputs()) >= 1
        finally:
            pt.disable_static()


def test_deep_import_spellings():
    from paddle_tpu.fluid.contrib.slim.core.compressor import Compressor
    from paddle_tpu.fluid.contrib.slim.prune.prune_strategy import (
        UniformPruneStrategy)
    from paddle_tpu.fluid.contrib.slim.quantization.quantization_pass \
        import QuantizationFreezePass
    from paddle_tpu.fluid.contrib.slim.graph.graph_wrapper import (
        GraphWrapper)
    from paddle_tpu.fluid.contrib.slim.searcher.controller import (
        SAController)
    from paddle_tpu.fluid.contrib.quantize.quantize_transpiler import (
        QuantizeTranspiler)
    import paddle_tpu.fluid as fluid

    assert fluid.contrib.slim.Compressor is Compressor
    assert fluid.contrib.Compressor is Compressor
    assert fluid.contrib.QuantizeTranspiler is QuantizeTranspiler


def test_config_factory_named_sections(tmp_path):
    """1.x schema: pruners:/distillers: entries referenced BY NAME from
    strategy specs (ref core/config.py)."""
    cfg = tmp_path / "slim.yaml"
    cfg.write_text(
        "version: 1.0\n"
        "pruners:\n"
        "  pruner_1:\n"
        "    class: MagnitudePruner\n"
        "strategies:\n"
        "  prune_s:\n"
        "    class: UniformPruneStrategy\n"
        "    pruner: 'pruner_1'\n"
        "    target_ratio: 0.25\n"
        "    pruned_params: '.*_w_.*'\n"
        "compressor:\n"
        "  epoch: 1\n"
        "  strategies: [prune_s]\n")
    factory = slim.ConfigFactory(str(cfg))
    strat = factory.instance("prune_s")
    assert isinstance(strat.pruner, slim.MagnitudePruner)

    model = _mlp()
    opt = pt.optimizer.SGD(learning_rate=1e-2,
                           parameters=model.parameters())
    comp = slim.Compressor(model=model, train_reader=_reader(2),
                           train_optimizer=opt, loss_fn=_loss_fn)
    comp.config(str(cfg))
    comp.run()
    assert 0.15 < comp.strategies[0].sparsity() < 0.35


def test_sensitive_strategy_auto_scan():
    """Without precomputed sensitivities the strategy runs the scan
    itself via eval_func."""
    model = _mlp()
    strat = slim.SensitivePruneStrategy(target_ratio=0.05,
                                        pruned_params=".*_w_.*")
    Xe = RNG.randn(8, 16).astype("float32")
    Ye = Xe @ W
    opt = pt.optimizer.SGD(learning_rate=1e-2,
                           parameters=model.parameters())
    comp = slim.Compressor(
        model=model, train_reader=_reader(1), train_optimizer=opt,
        loss_fn=_loss_fn,
        eval_func=lambda m: -float(_loss_fn(
            m, pt.to_tensor(Xe), pt.to_tensor(Ye)).numpy()),
        epoch=1)
    comp.strategies = [strat]
    comp.run()
    assert strat.sensitivities  # scan ran
    assert strat.sparsity() > 0.0


def test_quant_strategy_saves_int8(tmp_path):
    import os

    model = _mlp()
    opt = pt.optimizer.SGD(learning_rate=1e-3,
                           parameters=model.parameters())
    comp = slim.Compressor(model=model, train_reader=_reader(2),
                           train_optimizer=opt, loss_fn=_loss_fn,
                           epoch=1)
    comp.strategies = [slim.QuantizationStrategy(
        start_epoch=0,
        float_model_save_path=str(tmp_path / "f32"),
        int8_model_save_path=str(tmp_path / "int8"))]
    out = comp.run()
    assert os.path.exists(tmp_path / "f32" / "model.pdparams")
    assert os.path.exists(tmp_path / "int8" / "model.pdparams")
    kinds = {type(l).__name__ for _, l in out.named_sublayers()}
    assert "QuantizedLinear" in kinds  # converted at compression end
