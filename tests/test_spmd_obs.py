"""SPMD observability (obs.spmd): collective accounting, sharding
introspection, per-device telemetry.

Covers the PR's acceptance contract:
- HLO collective parsing against canned snippets (hand-computed byte
  volumes; async -start/-done pairs; explicit and iota replica groups;
  mesh-axis attribution) — no TPU needed;
- an 8-fake-device ``with_data_parallel`` run reports nonzero
  all-reduce bytes attributed to the 'data' axis, and the
  ShardingReport shows the feeds sharded with 1/8 per-device
  footprints;
- journal integration: a ``sharding`` event per compile, per-step comm
  deltas once the lazy entry analysis lands, and the run summary's
  comm accounting;
- per-device memory gauges + Chrome-trace device lanes degrade cleanly
  on backends without ``memory_stats`` (host CPU);
- TrainStep.collective_profile on a DistributedTrainStep sees the DP
  grad all-reduce.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu import optim
from paddle_tpu.obs import journal, mfu, spmd, trace


@pytest.fixture(autouse=True)
def _no_global_journal():
    yield
    if journal.ACTIVE is not None:
        journal.ACTIVE.close()
    journal.ACTIVE = None


# -- HLO parsing (no backend work) -------------------------------------------


class TestHloParsing:
    def test_all_reduce_bytes_hand_computed(self):
        hlo = ("%all-reduce = f32[128,64]{1,0} all-reduce("
               "f32[128,64]{1,0} %dot), channel_id=1, "
               "replica_groups=[1,8]<=[8], use_global_device_ids=true, "
               "to_apply=%add")
        prof = spmd.collective_profile(hlo)
        assert prof["counts"] == {"all-reduce": 1}
        assert prof["bytes"] == {"all-reduce": 128 * 64 * 4}
        assert prof["total_bytes"] == 32768
        # 8-ring: 2*(8-1)/8 of the payload on the wire
        assert prof["wire_bytes"] == int(32768 * 1.75)

    def test_tuple_result_and_bf16(self):
        hlo = ("%a2a = (bf16[8,8]{1,0}, bf16[8,8]{1,0}) all-to-all("
               "bf16[8,8]{1,0} %a, bf16[8,8]{1,0} %b), "
               "replica_groups={{0,1},{2,3},{4,5},{6,7}}")
        prof = spmd.collective_profile(hlo)
        assert prof["bytes"] == {"all-to-all": 2 * 8 * 8 * 2}
        # groups of 2: (2-1)/2 of the payload
        assert prof["wire_bytes"] == 8 * 8 * 2

    def test_async_pair_counts_once(self):
        hlo = ("%s = f32[16]{0} all-gather-start(f32[2]{0} %p), "
               "replica_groups=[1,8]<=[8], dimensions={0}\n"
               "%d = f32[16]{0} all-gather-done(f32[16]{0} %s)")
        prof = spmd.collective_profile(hlo)
        assert prof["counts"] == {"all-gather": 1}
        assert prof["bytes"] == {"all-gather": 64}

    def test_async_tuple_start_picks_result_not_sum(self):
        # real XLA async form: -start results are (operand, result[,
        # context]) bundles; summing would double-count the payload
        hlo = ("%s = (f32[2]{0}, f32[16]{0}) all-gather-start("
               "f32[2]{0} %p), replica_groups=[1,8]<=[8], "
               "dimensions={0}\n"
               "%cp = (f32[32]{0}, f32[32]{0}, u32[], u32[]) "
               "collective-permute-start(f32[32]{0} %q), "
               "source_target_pairs={{0,1},{1,0}}")
        prof = spmd.collective_profile(hlo)
        assert prof["bytes"] == {"all-gather": 64,
                                 "collective-permute": 128}

    def test_reduce_scatter_wire_counts_full_payload(self):
        # result is ONE shard (16*4=64B) of a 4-device group: the ring
        # still moves (4-1)/4 of the FULL 256B payload = 192B
        hlo = ("%rs = f32[16]{0} reduce-scatter(f32[64]{0} %x), "
               "replica_groups=[2,4]<=[8], dimensions={0}, "
               "to_apply=%add")
        prof = spmd.collective_profile(hlo)
        assert prof["bytes"] == {"reduce-scatter": 64}
        assert prof["wire_bytes"] == 3 * 64

    def test_non_collective_lines_ignored(self):
        hlo = ("%gte = f32[4,4]{1,0} get-tuple-element((f32[4,4]{1,0}, "
               "f32[4,4]{1,0}) %all-to-all.2), index=0\n"
               "ROOT %t = (f32[]) tuple(f32[] %c)")
        prof = spmd.collective_profile(hlo)
        assert prof["n_ops"] == 0
        assert prof["total_bytes"] == 0

    def test_iota_replica_groups_with_transpose(self):
        # [4,2]<=[2,4]T(1,0): iota(8).reshape(2,4).T.reshape(4,2)
        groups = spmd._parse_groups("[4,2]<=[2,4]T(1,0)")
        assert groups == [[0, 4], [1, 5], [2, 6], [3, 7]]

    def test_axis_attribution_single_and_multi(self):
        axes = {"data": 2, "model": 4}
        ids = np.arange(8).reshape(2, 4)
        # all-reduce over 'model': devices sharing the data coordinate
        hlo_m = ("%ar = f32[4]{0} all-reduce(f32[4]{0} %x), "
                 "replica_groups=[2,4]<=[8], to_apply=%add")
        prof = spmd.collective_profile(hlo_m, mesh=(axes, ids))
        assert prof["by_axis"] == {"model": 16}
        # all-reduce over 'data': groups {0,4},{1,5},{2,6},{3,7}
        hlo_d = ("%ar = f32[4]{0} all-reduce(f32[4]{0} %x), "
                 "replica_groups=[4,2]<=[2,4]T(1,0), to_apply=%add")
        prof = spmd.collective_profile(hlo_d, mesh=(axes, ids))
        assert prof["by_axis"] == {"data": 16}
        # one group spanning everything: the full axis product
        hlo_all = ("%ar = f32[4]{0} all-reduce(f32[4]{0} %x), "
                   "replica_groups=[1,8]<=[8], to_apply=%add")
        prof = spmd.collective_profile(hlo_all, mesh=(axes, ids))
        assert prof["by_axis"] == {"data+model": 16}

    def test_unattributable_groups_fall_back_to_question_mark(self):
        axes = {"data": 2, "model": 4}
        ids = np.arange(8).reshape(2, 4)
        hlo = ("%ar = f32[4]{0} all-reduce(f32[4]{0} %x), "
               "replica_groups={{0,3},{1,2},{4,7},{5,6}}, "
               "to_apply=%add")
        prof = spmd.collective_profile(hlo, mesh=(axes, ids))
        assert prof["by_axis"] == {"?": 16}

    def test_collective_permute_source_target_pairs(self):
        hlo = ("%cp = f32[32]{0} collective-permute(f32[32]{0} %p), "
               "source_target_pairs={{0,1},{1,2},{2,3},{3,0}}")
        prof = spmd.collective_profile(hlo)
        assert prof["counts"] == {"collective-permute": 1}
        assert prof["bytes"] == {"collective-permute": 128}
        assert prof["wire_bytes"] == 128  # permute: payload moves once

    def test_merge_profiles(self):
        a = spmd.collective_profile(
            "%x = f32[4]{0} all-reduce(f32[4]{0} %p), "
            "replica_groups=[1,2]<=[2], to_apply=%add")
        merged = spmd.merge_profiles([a, a, None])
        assert merged["counts"] == {"all-reduce": 2}
        assert merged["total_bytes"] == 2 * a["total_bytes"]
        assert spmd.merge_profiles([None, {}]) is None


class TestRoofline:
    def test_comm_share_math(self):
        rl = spmd.comm_roofline(
            {"total_bytes": 1 << 20, "wire_bytes": 2 << 20},
            flops=1e9, peak=1e12, bw=200e9)
        comm_s = (2 << 20) / 200e9
        assert rl["comm_time_s"] == pytest.approx(comm_s)
        assert rl["compute_time_s"] == pytest.approx(1e-3)
        assert rl["comm_share"] == pytest.approx(
            comm_s / (comm_s + 1e-3))
        assert rl["bound"] == "compute"

    def test_missing_inputs_yield_none_not_fiction(self):
        rl = spmd.comm_roofline({"total_bytes": 10, "wire_bytes": 10},
                                flops=None, peak=None, bw=None)
        assert rl["comm_share"] is None
        assert rl["bound"] is None

    def test_ici_bandwidth_env_override(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ICI_BW", "123e9")
        assert spmd.ici_bandwidth() == pytest.approx(123e9)


# -- live 8-fake-device data-parallel ----------------------------------------


def _dp_program(B):
    import paddle_tpu.fluid as fluid

    main, startup = pt.static.Program(), pt.static.Program()
    with pt.program_guard(main, startup):
        x = pt.static.data("x", [B, 8], "float32")
        y = pt.static.data("y", [B], "int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = F.cross_entropy(logits, y)
        optim.Momentum(0.01, 0.9).minimize(loss)
    return main, startup, loss


class TestDataParallelAccounting:
    def test_entry_reports_nonzero_all_reduce_and_feed_sharding(self):
        from paddle_tpu.static_.compiler import CompiledProgram

        ndev = len(__import__("jax").devices())
        assert ndev == 8  # conftest contract
        B = 2 * ndev
        pt.enable_static()
        try:
            main, startup, loss = _dp_program(B)
        finally:
            pt.disable_static()
        exe = pt.static.Executor()
        exe.run(startup)
        cp = CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(B, 8).astype("float32"),
                "y": rng.randint(0, 4, (B,)).astype("int64")}
        exe.run(cp, feed=feed, fetch_list=[loss])

        compiled = next(iter(exe._cache.values()))
        prof = mfu.entry_analysis(compiled)["collectives"]
        assert prof is not None and prof["n_ops"] > 0
        assert prof["bytes"].get("all-reduce", 0) > 0
        assert prof["by_axis"].get("data", 0) > 0

        rep = spmd.sharding_report(compiled)
        assert rep["mesh"] == {"data": ndev}
        by_name = {r["name"]: r for r in rep["vars"]}
        assert by_name["x"]["spec"] == "data"
        assert by_name["x"]["per_device_bytes"] * ndev == \
            by_name["x"]["bytes"]
        persist = [r for r in rep["vars"]
                   if r["role"].startswith("persistable")]
        assert persist and all(r["spec"] == "replicated"
                               for r in persist)
        assert all(r["per_device_bytes"] == r["bytes"] for r in persist)

        stats = exe.cache_stats(per_entry=True)
        e = stats["entries"][0]
        assert e["collectives"]["bytes"]["all-reduce"] > 0
        assert e["mesh"] == {"data": ndev}

    def test_single_device_entry_reports_no_collectives(self):
        pt.enable_static()
        try:
            main, startup, loss = _dp_program(4)
        finally:
            pt.disable_static()
        exe = pt.static.Executor()
        exe.run(startup)
        feed = {"x": np.zeros((4, 8), "float32"),
                "y": np.zeros((4,), "int64")}
        exe.run(main, feed=feed, fetch_list=[loss])
        compiled = next(iter(exe._cache.values()))
        prof = mfu.entry_analysis(compiled)["collectives"]
        assert prof is not None and prof["n_ops"] == 0
        rep = spmd.sharding_report(compiled)
        assert rep["mesh"] is None
        assert all(r["spec"] == "replicated" for r in rep["vars"])

    def test_journal_sharding_event_and_step_comm(self, tmp_path):
        from paddle_tpu.static_.compiler import CompiledProgram

        ndev = len(__import__("jax").devices())
        B = 2 * ndev
        pt.enable_static()
        try:
            main, startup, loss = _dp_program(B)
        finally:
            pt.disable_static()
        exe = pt.static.Executor()
        exe.run(startup)
        cp = CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(B, 8).astype("float32"),
                "y": rng.randint(0, 4, (B,)).astype("int64")}
        run_dir = str(tmp_path / "run")
        with journal.RunJournal(run_dir, flush_every=1):
            exe.run(cp, feed=feed, fetch_list=[loss])
            # force the lazy analysis to land, then step again so the
            # journal's non-blocking lookup attributes comm
            compiled = next(iter(exe._cache.values()))
            mfu.entry_analysis(compiled)
            exe.run(cp, feed=feed, fetch_list=[loss])

        recs = []
        with open(os.path.join(run_dir, "journal.jsonl")) as f:
            for line in f:
                if line.strip():
                    recs.append(json.loads(line))
        shardings = [r for r in recs if r.get("t") == "event"
                     and r.get("kind") == "sharding"]
        assert len(shardings) == 1  # one per compiled entry
        assert shardings[0]["mesh"] == {"data": ndev}
        specs = {v["name"]: v["spec"] for v in shardings[0]["vars"]}
        assert specs.get("x") == "data"
        comm_steps = [r for r in recs if r.get("t") == "step"
                      and r.get("comm")]
        assert comm_steps, "no step carried comm after analysis landed"
        assert comm_steps[-1]["comm"]["all_reduce_bytes"] > 0
        end = [r for r in recs if r.get("t") == "run_end"]
        assert end and end[0]["summary"]["comm_bytes_per_step"] > 0

    def test_backend_event_carries_per_device_identity(self, tmp_path):
        run_dir = str(tmp_path / "run")
        with journal.RunJournal(run_dir, flush_every=1) as j:
            j.record_step(loss=1.0, step_ms=1.0)
        recs = []
        with open(os.path.join(run_dir, "journal.jsonl")) as f:
            for line in f:
                if line.strip():
                    recs.append(json.loads(line))
        be = [r for r in recs if r.get("t") == "event"
              and r.get("kind") == "backend"]
        assert len(be) == 1
        assert be[0]["platform"] == "cpu"
        assert be[0]["device_count"] == 8
        assert be[0]["device_kinds"] == {"cpu": 8}
        assert len(be[0]["devices"]) == 8
        assert {d["id"] for d in be[0]["devices"]} == set(range(8))


# -- TrainStep profile --------------------------------------------------------


class TestTrainStepProfile:
    def test_distributed_step_sees_dp_all_reduce(self):
        import paddle_tpu.nn as nn
        from paddle_tpu import distributed as dist
        from paddle_tpu.dist import env as denv

        mesh = denv.init_mesh({"data": 8})
        try:
            model = nn.Linear(8, 4)
            opt = optim.Momentum(learning_rate=0.1, momentum=0.9,
                                 parameters=model.parameters())
            step = dist.DistributedTrainStep(
                model, opt,
                lambda m, x, y: F.cross_entropy(m(x), y), mesh=mesh)
            x = np.random.RandomState(0).randn(16, 8).astype("float32")
            y = np.random.RandomState(1).randint(
                0, 4, (16,)).astype("int64")
            assert step.collective_profile() is None  # pre-first-step
            step(x, y)
            prof = step.collective_profile()
            assert prof is not None
            assert prof["bytes"].get("all-reduce", 0) > 0
            assert prof["by_axis"].get("data", 0) > 0
            assert step.collective_profile() is prof  # cached
        finally:
            denv.set_mesh(None)

    def test_plain_trainstep_profiles_without_collectives(self):
        import paddle_tpu.nn as nn

        model = nn.Linear(4, 2)
        opt = optim.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
        step = pt.TrainStep(
            model, opt, lambda m, x, y: F.cross_entropy(m(x), y))
        x = np.zeros((4, 4), "float32")
        y = np.zeros((4,), "int64")
        step(x, y)
        prof = step.collective_profile()
        assert prof is not None and prof["n_ops"] == 0


# -- per-device telemetry -----------------------------------------------------


class TestDeviceTelemetry:
    def test_memory_stats_none_safe_on_cpu(self):
        stats = spmd.device_memory_stats()
        assert len(stats) == 8
        assert {d["id"] for d in stats} == set(range(8))
        # host CPU exposes no memory_stats: fields degrade to None,
        # never raise
        assert all(d["bytes_in_use"] is None for d in stats)
        got, high = spmd.update_device_gauges()
        assert len(got) == 8 and high is None

    def test_device_counter_lanes_in_chrome_trace(self, tmp_path):
        was = trace.tracing_enabled()
        trace.enable_tracing()
        try:
            trace.clear_trace()
            trace.device_counter(0, "bytes_in_use", 123.0,
                                 label="device 0 (fake)")
            trace.device_counter(3, "bytes_in_use", 456.0)
            path = str(tmp_path / "trace.json")
            trace.export_chrome_trace(path)
        finally:
            if not was:
                trace.disable_tracing()
            trace.clear_trace()
        with open(path) as f:
            events = json.load(f)["traceEvents"]
        counters = [e for e in events if e["ph"] == "C"]
        assert {e["pid"] for e in counters} == \
            {trace.DEVICE_PID_BASE, trace.DEVICE_PID_BASE + 3}
        names = {e["args"]["name"] for e in events
                 if e["ph"] == "M" and e["pid"] >= trace.DEVICE_PID_BASE}
        assert "device 0 (fake)" in names and "device 3" in names

    def test_device_counter_noop_when_tracing_off(self):
        assert not trace.tracing_enabled()
        trace.device_counter(0, "bytes_in_use", 1.0)
        assert not trace.trace_events()


# -- run_report comm gate -----------------------------------------------------


def test_diff_flags_comm_appearing_from_zero_baseline():
    """A TP-only base run (comm recorded, zero all-reduce) regressing to
    ANY all-reduce must trip the comm gate — 0 is a valid baseline."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "run_report_spmd_test", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "run_report.py"))
    rr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rr)

    def run_with(ar_bytes):
        return {"steps": [{"step": i + 1, "loss": 1.0, "step_ms": 5.0,
                           "comm": {"all_reduce_bytes": ar_bytes,
                                    "total_bytes": ar_bytes + 100}}
                          for i in range(5)],
                "anomalies": [], "summary": None, "events": [],
                "header": None, "parse_errors": []}

    rep = rr.diff_runs(run_with(0), run_with(4096))
    assert rep["comm_regression"] and rep["regression"]
    assert not rr.diff_runs(run_with(0), run_with(0))["comm_regression"]
    assert not rr.diff_runs(run_with(100), run_with(101))["comm_regression"]


# -- persistable footprint (framework/io) ------------------------------------


def test_persistable_footprint_matches_scope_bytes():
    from paddle_tpu.framework.io import persistable_footprint

    pt.enable_static()
    try:
        main, startup, _ = _dp_program(8)
    finally:
        pt.disable_static()
    exe = pt.static.Executor()
    exe.run(startup)
    fp = persistable_footprint(main)
    assert fp["total_bytes"] > 0
    by_name = {r["name"]: r for r in fp["vars"]}
    # fc weight: 8x16 f32 = 512 bytes (the first fc's weight)
    w = [r for r in fp["vars"] if r["shape"] == (8, 16)]
    assert w and w[0]["bytes"] == 8 * 16 * 4
    assert all(r["bytes"] is not None for r in by_name.values())
