"""HLO-native memory/model stats (VERDICT r3 Missing #4 / Next #7):
memory_usage reads the compiled executable's real reservation;
summary() builds the per-layer param/FLOP table via forward hooks.
Refs: fluid/contrib/memory_usage_calc.py:46, model_stat.py:40."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn.functional as F
import paddle_tpu.optim as optim
from paddle_tpu.models.vision import LeNet, resnet50
from paddle_tpu.utils import stats


def test_compiled_stats_trainstep():
    def fn(a, b):
        return (a @ b).sum()

    out = stats.compiled_stats(fn, np.zeros((128, 64), "float32"),
                               np.zeros((64, 32), "float32"))
    assert isinstance(out["memory"], dict)
    if out["cost"].get("flops"):
        # 2*M*N*K matmul MACs (backend may fold the reduce)
        assert out["cost"]["flops"] >= 2 * 128 * 64 * 32 * 0.5


def test_memory_usage_static_program():
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            x = pt.static.data("x", [32, 1, 28, 28], "float32")
            y = pt.static.data("y", [32], "int64")
            model = LeNet()
            loss = F.cross_entropy(model(x), y)
            optim.SGD(0.1, parameters=model.parameters()).minimize(loss)
    finally:
        pt.disable_static()
    pt.static.Executor().run(startup)
    lo, hi, unit = fluid.contrib.memory_usage(main, batch_size=32)
    assert unit == "B"
    # at minimum the feed (32*1*28*28*4) and the ~61k LeNet params
    assert hi >= 32 * 28 * 28 * 4
    assert lo <= hi


def test_model_summary_resnet50():
    model = resnet50()
    out = stats.summary(model, (1, 3, 64, 64), print_table=False)
    assert out["total_params"] > 2.3e7            # ~25.5M
    assert out["total_flops"] > 1e8               # conv FLOPs counted
    assert any(r["layer"] == "Conv2D" for r in out["rows"])
    conv_rows = [r for r in out["rows"] if r["layer"] == "Conv2D"]
    assert all(r["flops"] > 0 for r in conv_rows)


def test_model_summary_matches_parameter_count():
    model = LeNet()
    out = stats.summary(model, (2, 1, 28, 28), print_table=False)
    want = sum(int(np.prod(p.shape)) if len(p.shape) else 1
               for p in model.parameters())
    assert out["total_params"] == want


def test_contrib_namespaces():
    assert fluid.contrib.memory_usage_calc.memory_usage is \
        fluid.contrib.memory_usage
    assert callable(fluid.contrib.model_stat.summary)
    assert callable(fluid.contrib.op_frequence.op_freq_statistic)


def test_summary_counts_composite_direct_params():
    """Params created directly on a composite layer (one with children)
    must be counted (leaf-only-hook regression)."""
    import paddle_tpu.nn as nn

    class WithDirect(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)
            self.extra = self.create_parameter((7,))

        def forward(self, v):
            return self.fc(v) + self.extra[:4]

    m = WithDirect()
    out = stats.summary(m, (2, 4), print_table=False)
    want = sum(int(np.prod(p.shape)) for p in m.parameters())
    assert out["total_params"] == want  # includes the direct (7,) param


def test_paddle_summary_and_flops_entry_points():
    model = LeNet()
    out = pt.summary(model, (1, 1, 28, 28))
    assert out["total_params"] > 0
    assert pt.flops(model, (1, 1, 28, 28)) == out["total_flops"] > 0


def test_memory_usage_dynamic_dims_default_batch():
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            x = pt.static.data("x", [-1, 4], "float32")
            import paddle_tpu.fluid.layers as L
            out = L.fc(x, size=3)
    finally:
        pt.disable_static()
    pt.static.Executor().run(startup)
    lo, hi, unit = stats.memory_usage(main)  # no batch_size given
    assert lo > 0 and unit == "B"


def test_flops_custom_ops():
    import paddle_tpu.nn as nn

    class Odd(nn.Layer):
        def forward(self, v):
            return v * 2.0

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.odd = Odd()
            self.fc = nn.Linear(4, 4)

        def forward(self, v):
            return self.fc(self.odd(v))

    base = pt.flops(Net(), (2, 4))
    with_custom = pt.flops(Net(), (2, 4),
                           custom_ops={Odd: lambda m, i, o: 1000})
    assert with_custom == base + 1000


def test_summary_reports_trainable_params():
    model = LeNet()
    for p in model.parameters():
        if p.ndim == 1:
            p.trainable = False  # freeze biases
    out = stats.summary(model, (1, 1, 28, 28), print_table=False)
    frozen = sum(int(np.prod(p.shape)) for p in model.parameters()
                 if p.ndim == 1)
    assert out["trainable_params"] == out["total_params"] - frozen
