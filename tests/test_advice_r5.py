"""Regression tests for the round-5 advisor fixes (ADVICE.md r4):
summary() dynamic-batch shapes, prune_conv_pair divisibility guard,
beam_search_xla token dtype contract.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


def test_summary_dynamic_batch_none():
    """(None, C, H, W) is ONE shape with a dynamic batch, not a list of
    shapes; dynamic dims probe with 1 (ref model_stat.py substitutes 1)."""
    from paddle_tpu.utils.stats import summary

    m = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
                      nn.Flatten(), nn.Linear(4 * 28 * 28, 10))
    out = summary(m, (None, 1, 28, 28), print_table=False)
    assert out["total_params"] > 0
    shapes = [r["output_shape"] for r in out["rows"] if r["output_shape"]]
    assert all(s[0] == 1 for s in shapes)


def test_summary_dynamic_batch_minus_one():
    """(-1, C, ...) must not reach np.zeros (negative dims ValueError)."""
    from paddle_tpu.utils.stats import summary

    m = nn.Linear(8, 3)
    out = summary(m, (-1, 8), print_table=False)
    assert out["total_params"] == 8 * 3 + 3


def test_prune_conv_pair_indivisible_raises():
    """Linear rows not a multiple of conv out-channels (e.g. global
    pooling between them) must raise, not silently drop rows."""
    from paddle_tpu.slim import prune_conv_pair

    conv = nn.Conv2D(3, 8, 3)
    lin = nn.Linear(12, 4)  # 12 % 8 != 0
    w_before = np.asarray(conv.weight.numpy()).copy()
    with pytest.raises(ValueError, match="not a multiple"):
        prune_conv_pair(conv, lin, ratio=0.5)
    # the error path must leave the pair untouched and runnable
    assert conv._out_channels == 8
    assert np.array_equal(np.asarray(conv.weight.numpy()), w_before)


def test_prune_conv_pair_divisible_still_works():
    from paddle_tpu.slim import prune_conv_pair

    conv = nn.Conv2D(3, 8, 3)
    lin = nn.Linear(8 * 4, 5)
    keep = prune_conv_pair(conv, lin, ratio=0.5)
    assert len(keep) == 4
    assert tuple(lin.weight._data.shape) == (16, 5)
    assert conv.weight._data.shape[0] == 4


def test_beam_xla_token_dtype_matches_eager():
    """Both decode paths must hand back the same ("int64") token dtype so
    callers can concatenate with int64 prompt ids interchangeably."""
    from paddle_tpu.inference.decoder import beam_search, beam_search_xla

    V, B, K, L = 7, 2, 3, 5

    def step_fn(cur, state, t):
        logits = pt.to_tensor(
            np.tile(np.linspace(0.0, 1.0, V, dtype=np.float32),
                    (cur.shape[0], 1)))
        return logits, state

    tok_e, _ = beam_search(step_fn, None, B, bos_id=0, eos_id=1,
                           beam_size=K, max_len=L)
    tok_x, _ = beam_search_xla(step_fn, None, B, bos_id=0, eos_id=1,
                               beam_size=K, max_len=L)
    assert tok_e.dtype == tok_x.dtype, (tok_e.dtype, tok_x.dtype)
    ref64 = pt.ops.full([1], 0, dtype="int64").dtype
    assert tok_x.dtype == ref64
