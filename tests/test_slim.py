"""slim pruning + distillation (VERDICT r3 Missing #2 / Next #5).

Model: the reference's slim tests (contrib/slim/tests/
test_filter_pruning.py style) — train a small model, compress, assert
the accuracy cost is bounded and the compression is real.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optim as optim
from paddle_tpu import slim
from paddle_tpu.models.vision import LeNet


def _digits(n=256, seed=0):
    rng = np.random.RandomState(seed)
    means = rng.randn(10, 1, 28, 28).astype("float32") * 2.0
    y = rng.randint(0, 10, n)
    x = means[y] + rng.randn(n, 1, 28, 28).astype("float32") * 0.5
    return x, y.astype("int64")


def _accuracy(model, x, y):
    model.eval()
    pred = np.asarray(model(pt.to_tensor(x)).numpy()).argmax(-1)
    model.train()
    return float((pred == y).mean())


@pytest.fixture(scope="module")
def trained_lenet():
    pt.seed(0)
    x, y = _digits()
    model = LeNet()
    opt = optim.Adam(2e-3, parameters=model.parameters())
    step = pt.TrainStep(model, opt,
                        lambda m, xb, yb: F.cross_entropy(m(xb), yb))
    for _ in range(30):
        step(x, y)
    acc = _accuracy(model, x, y)
    assert acc > 0.9, acc
    return model, x, y, acc


def test_magnitude_prune_keeps_accuracy(trained_lenet):
    model, x, y, acc = trained_lenet
    saved = {p.name: np.asarray(p.numpy()) for p in model.parameters()}
    try:
        pruner = slim.MagnitudePruner()
        pruner.prune(model, ratio=0.5)
        assert 0.45 <= pruner.sparsity() <= 0.55
        pruned_acc = _accuracy(model, x, y)
        assert pruned_acc >= acc - 0.15, (acc, pruned_acc)
    finally:
        for p in model.parameters():
            p._data = jnp.asarray(saved[p.name])


def test_magnitude_prune_hurts_at_extreme(trained_lenet):
    """95% magnitude pruning must visibly damage the model — proves the
    mask really zeroes weight mass, not a no-op."""
    model, x, y, acc = trained_lenet
    saved = {p.name: np.asarray(p.numpy()) for p in model.parameters()}
    try:
        slim.MagnitudePruner().prune(model, ratio=0.95)
        assert _accuracy(model, x, y) < acc - 0.2
    finally:
        for p in model.parameters():
            p._data = jnp.asarray(saved[p.name])


def test_structured_prune_zeroes_whole_channels(trained_lenet):
    model, x, y, acc = trained_lenet
    saved = {p.name: np.asarray(p.numpy()) for p in model.parameters()}
    try:
        pruner = slim.StructuredPruner(pruning_axis=0)
        conv_params = [p for p in model.parameters() if p.ndim == 4]
        pruner.prune(conv_params, ratio=0.25)
        for p in conv_params:
            w = np.asarray(p.numpy())
            ch_mass = np.abs(w).sum(axis=(1, 2, 3))
            n_zero = int((ch_mass == 0.0).sum())
            assert n_zero == int(np.round(0.25 * w.shape[0])), p.name
    finally:
        for p in model.parameters():
            p._data = jnp.asarray(saved[p.name])


def test_reapply_after_optimizer_step(trained_lenet):
    """Dense optimizer updates regrow pruned weights; reapply() must
    re-zero them (the training-loop contract)."""
    model, x, y, _ = trained_lenet
    saved = {p.name: np.asarray(p.numpy()) for p in model.parameters()}
    def zeros_frac():
        tot = z = 0
        for p in model.parameters():
            if p.ndim >= 2:
                w = np.asarray(p.numpy())
                tot += w.size
                z += int((w == 0.0).sum())
        return z / tot

    try:
        pruner = slim.MagnitudePruner()
        pruner.prune(model, ratio=0.5)
        zf_pruned = zeros_frac()
        assert zf_pruned >= 0.45
        opt = optim.SGD(0.05, parameters=model.parameters())
        step = pt.TrainStep(model, opt,
                            lambda m, xb, yb: F.cross_entropy(m(xb), yb))
        step(x[:64], y[:64])
        assert zeros_frac() < zf_pruned - 0.2  # dense update regrew them
        pruner.reapply()
        assert zeros_frac() >= 0.45            # reapply re-zeroed
    finally:
        for p in model.parameters():
            p._data = jnp.asarray(saved[p.name])


def test_sensitivity_scan_and_ratio_selection(trained_lenet):
    model, x, y, acc = trained_lenet
    conv_params = [p for p in model.parameters() if p.ndim == 4][:2]
    sens = slim.sensitivity(model, lambda: _accuracy(model, x, y),
                            params=conv_params, ratios=(0.2, 0.6))
    assert set(sens) == {p.name for p in conv_params}
    # scan must restore weights: accuracy unchanged afterwards
    assert abs(_accuracy(model, x, y) - acc) < 1e-6
    ratios = slim.sensitive_prune_ratios(sens, target_loss=0.5)
    assert all(r in (0.2, 0.6) for r in ratios.values())


def test_real_channel_removal_lenet():
    """prune_conv_pair physically shrinks conv1 and rewires conv2;
    the pruned network still runs and keeps most of its accuracy."""
    pt.seed(1)
    x, y = _digits(seed=1)
    model = LeNet()
    opt = optim.Adam(2e-3, parameters=model.parameters())
    step = pt.TrainStep(model, opt,
                        lambda m, xb, yb: F.cross_entropy(m(xb), yb))
    for _ in range(30):
        step(x, y)
    acc = _accuracy(model, x, y)
    convs = [m for m in model.sublayers() if isinstance(m, nn.Conv2D)]
    c0 = int(convs[0].weight.shape[0])
    keep = slim.prune_conv_pair(convs[0], convs[1], ratio=0.5)
    assert len(keep) == c0 - int(np.round(0.5 * c0))
    assert convs[0].weight.shape[0] == len(keep)
    assert convs[1].weight.shape[1] == len(keep)
    # the physically smaller network still runs end to end
    assert np.asarray(model(pt.to_tensor(x[:4])).numpy()).shape == (4, 10)
    # and recovers with the standard post-surgery fine-tune (fresh
    # optimizer: slot shapes changed with the weights)
    opt2 = optim.Adam(2e-3, parameters=model.parameters())
    step2 = pt.TrainStep(model, opt2,
                         lambda m, xb, yb: F.cross_entropy(m(xb), yb))
    for _ in range(15):
        step2(x, y)
    pruned_acc = _accuracy(model, x, y)
    assert pruned_acc >= acc - 0.1, (acc, pruned_acc)


def test_soft_label_distillation_trains_student():
    """Student distilled from a trained teacher must learn the task
    (TrainStep(models=[teacher]) carries the frozen teacher)."""
    pt.seed(0)
    x, y = _digits(n=128)

    class Tiny(nn.Layer):
        def __init__(self):
            super().__init__()
            self.flat = nn.Flatten()
            self.fc = nn.Linear(784, 10)

        def forward(self, v):
            return self.fc(self.flat(v))

    teacher = LeNet()
    topt = optim.Adam(2e-3, parameters=teacher.parameters())
    tstep = pt.TrainStep(teacher, topt,
                         lambda m, xb, yb: F.cross_entropy(m(xb), yb))
    for _ in range(25):
        tstep(x, y)
    teacher.eval()
    for p in teacher.parameters():
        p.trainable = False
        p.stop_gradient = True

    student = Tiny()
    cfg = slim.DistillConfig(task_weight=0.5, soft_label_weight=0.5,
                             temperature=3.0)

    def loss_fn(m, xb, yb):
        s_logits = m(xb)
        t_logits = teacher(xb)
        return slim.distill_loss(F.cross_entropy(s_logits, yb),
                                 t_logits, s_logits, cfg)

    sopt = optim.Adam(2e-3, parameters=student.parameters())
    sstep = pt.TrainStep(student, sopt, loss_fn, models=[teacher])
    losses = [float(sstep(x, y)) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.6, losses
    assert _accuracy(student, x, y) > 0.6


def test_distill_losses_are_taped():
    """The student side of every distillation loss must receive
    gradients (regression: raw-jnp implementations were invisible to
    the autograd tape)."""
    rng = np.random.RandomState(0)
    s = pt.to_tensor(rng.randn(4, 10).astype("float32"))
    s.stop_gradient = False
    t = pt.to_tensor(rng.randn(4, 10).astype("float32"))
    slim.soft_label_distill(t, s).backward()
    assert s.grad is not None
    assert float(np.abs(np.asarray(s.grad.numpy())).sum()) > 0.0

    sa = pt.to_tensor(rng.randn(2, 3, 4, 4).astype("float32"))
    sb = pt.to_tensor(rng.randn(2, 5, 4, 4).astype("float32"))
    sa.stop_gradient = False
    sb.stop_gradient = False
    ta = pt.to_tensor(rng.randn(2, 3, 4, 4).astype("float32"))
    tb = pt.to_tensor(rng.randn(2, 5, 4, 4).astype("float32"))
    slim.fsp_distill([(ta, tb)], [(sa, sb)]).backward()
    assert sa.grad is not None and sb.grad is not None
    assert float(np.abs(np.asarray(sa.grad.numpy())).sum()) > 0.0

    s2 = pt.to_tensor(rng.randn(4, 8).astype("float32"))
    s2.stop_gradient = False
    slim.l2_distill(pt.to_tensor(rng.randn(4, 8).astype("float32")),
                    s2).backward()
    assert s2.grad is not None


def test_distill_loss_feature_guard():
    t = pt.to_tensor(np.zeros((2, 4), "float32"))
    with pytest.raises(ValueError):
        slim.distill_loss(pt.to_tensor(np.float32(0.0)), t, t,
                          slim.DistillConfig(l2_weight=1.0),
                          teacher_feats=[t], student_feats=None)


def test_fsp_matrix_shape_and_l2():
    a = pt.to_tensor(np.random.RandomState(0)
                     .randn(2, 3, 4, 4).astype("float32"))
    b = pt.to_tensor(np.random.RandomState(1)
                     .randn(2, 5, 4, 4).astype("float32"))
    m = slim.fsp_matrix(a, b)
    assert np.asarray(m.numpy()).shape == (2, 3, 5)
    assert float(np.asarray(slim.l2_distill(a, a).numpy())) == 0.0
    loss = slim.fsp_distill([(a, b)], [(a, b)])
    assert float(np.asarray(loss.numpy())) == 0.0


def test_magnitude_prune_exact_k_on_ties():
    """A constant-filled parameter pruned at ratio 0.1 must lose exactly
    10% of entries, not all of them (threshold-comparison regression)."""
    import paddle_tpu.nn as nn2

    lin = nn2.Linear(8, 8)
    lin.weight._data = jnp.full((8, 8), 0.5)
    pruner = slim.MagnitudePruner()
    pruner.prune([lin.weight], ratio=0.1)
    w = np.asarray(lin.weight.numpy())
    assert int((w == 0.0).sum()) == int(round(0.1 * 64))
