"""vision.transforms tests (ref: python/paddle/dataset/image.py)."""
import numpy as np

import paddle_tpu
from paddle_tpu.vision import (Compose, Resize, CenterCrop, RandomCrop,
                               RandomHorizontalFlip, Normalize, ToCHW,
                               resize_short, center_crop, simple_transform)


def test_resize_short_scales_short_side():
    im = np.random.RandomState(0).rand(40, 80, 3).astype("float32")
    out = resize_short(im, 20)
    assert out.shape == (20, 40, 3)
    tall = resize_short(im.transpose(1, 0, 2), 20)
    assert tall.shape == (40, 20, 3)


def test_resize_preserves_constant_image():
    im = np.full((30, 50, 3), 0.7, "float32")
    out = resize_short(im, 16)
    np.testing.assert_allclose(out, 0.7, atol=1e-6)


def test_center_crop():
    im = np.arange(36, dtype="float32").reshape(6, 6)
    out = center_crop(im, 2)
    np.testing.assert_allclose(out, [[14, 15], [20, 21]])


def test_simple_transform_eval_deterministic():
    im = np.random.RandomState(1).rand(40, 40, 3).astype("float32")
    a = simple_transform(im, 32, 24, is_train=False, mean=[0.5, 0.5, 0.5])
    b = simple_transform(im, 32, 24, is_train=False, mean=[0.5, 0.5, 0.5])
    assert a.shape == (3, 24, 24)
    np.testing.assert_array_equal(a, b)


def test_compose_pipeline():
    rng_seeded = Compose([Resize(32), RandomCrop(24, seed=0),
                          RandomHorizontalFlip(seed=0), ToCHW(),
                          Normalize([0.5] * 3, [0.25] * 3)])
    im = np.random.RandomState(2).rand(48, 64, 3).astype("float32")
    out = rng_seeded(im)
    assert out.shape == (3, 24, 24)
    assert abs(float(out.mean())) < 2.0
