"""fluid.incubate compat tests: role makers, split_files, the CTR
MultiSlotDataGenerator text protocol, save/load_program.
Ref: python/paddle/fluid/incubate/fleet/base/role_maker.py,
data_generator/__init__.py, fleet/utils/utils.py."""
import numpy as np
import paddle_tpu as pt
import paddle_tpu.fluid.incubate as inc


def test_incubate_surface():

    rm = inc.UserDefinedRoleMaker(current_id=1, role=inc.Role.WORKER, worker_num=4)
    assert rm.is_worker() and not rm.is_first_worker() and rm.worker_num() == 4
    rm2 = inc.UserDefinedCollectiveRoleMaker(0, ["a:1", "b:2"])
    assert rm2.is_first_worker() and rm2.get_trainer_endpoints() == ["a:1", "b:2"]
    rm3 = inc.PaddleCloudRoleMaker()
    assert rm3.worker_num() >= 1
    rm3.barrier_worker()
    files = [f"part-{i}" for i in range(10)]
    mine = inc.split_files(files, trainer_id=1, trainers=4)
    assert mine == ["part-1", "part-5", "part-9"]

    class Gen(inc.MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("click", [1]), ("feat", [3, 4, 5])]
            return it

    lines = list(Gen().run_from_memory([""]))
    assert lines == ["1 1 3 3 4 5"], lines

    import tempfile
    pt.enable_static()
    prog = pt.static.Program()
    with pt.static.program_guard(prog):
        x = pt.static.data("x", [2, 3], "float32")
    pt.disable_static()
    p = tempfile.mktemp()
    inc.save_program(prog, p)
    txt = inc.load_program(p)
    assert "x" in txt
    print("INCUBATE OK")


def test_dist_launch_spawns_ranked_workers(tmp_path):
    """dist/launch.py: PADDLE_TRAINER_* env per child (ref:
    distributed/launch.py)."""
    import subprocess
    import sys

    script = tmp_path / "child.py"
    script.write_text(
        "import os\n"
        "print('rank', os.environ['PADDLE_TRAINER_ID'], 'of',\n"
        "      os.environ['PADDLE_TRAINERS_NUM'])\n")
    logdir = tmp_path / "logs"
    rc = subprocess.call(
        [sys.executable, "-m", "paddle_tpu.dist.launch",
         "--nproc_per_node=2", f"--log_dir={logdir}", str(script)],
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert rc == 0
    logs = sorted(p.read_text() for p in logdir.iterdir())
    assert "rank 0 of 2" in logs[0] and "rank 1 of 2" in logs[1]


def test_launch_endpoints():
    from paddle_tpu.dist.launch import get_cluster_endpoints

    eps = get_cluster_endpoints("10.0.0.1,10.0.0.2", 2, 6170)
    assert eps == ["10.0.0.1:6170", "10.0.0.1:6171",
                   "10.0.0.2:6170", "10.0.0.2:6171"]
