"""Fused-step jit + serialization tests (model: reference
test_imperative_*.py jit tests and test_inference_model_io.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optim as optim
import paddle_tpu.nn.functional as F


def _problem():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    Y = (X @ rng.randn(8, 1)).astype("float32")
    return X, Y


class TestTrainStep:
    def test_fused_step_trains(self):
        X, Y = _problem()
        model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = optim.Adam(0.05, parameters=model.parameters())
        step = pt.TrainStep(model, opt,
                            lambda m, x, y: F.mse_loss(m(x), y))
        losses = [float(step(X, Y)) for _ in range(30)]
        assert losses[-1] < losses[0] * 0.1
        assert len(step._compiled) == 1  # one compilation for fixed shapes

    def test_fused_matches_eager(self):
        X, Y = _problem()

        def build():
            pt.seed(7)
            m = nn.Sequential(nn.Linear(8, 4), nn.Tanh(), nn.Linear(4, 1))
            o = optim.SGD(0.1, parameters=m.parameters())
            return m, o

        m1, o1 = build()
        m2, o2 = build()
        for n, p in m1.named_parameters():
            dict(m2.named_parameters())[n].set_value(p)

        step = pt.TrainStep(m1, o1, lambda m, x, y: F.mse_loss(m(x), y))
        fused = [float(step(X, Y)) for _ in range(5)]

        eager = []
        for _ in range(5):
            loss = F.mse_loss(m2(pt.to_tensor(X)), pt.to_tensor(Y))
            loss.backward()
            o2.step()
            o2.clear_grad()
            eager.append(float(loss))
        np.testing.assert_allclose(fused, eager, rtol=1e-4)

    def test_fused_step_with_clip_and_bn(self):
        X = np.random.RandomState(1).randn(32, 4, 6, 6).astype("float32")
        Y = np.random.RandomState(2).randint(0, 2, 32).astype("int64")
        model = nn.Sequential(nn.Conv2D(4, 8, 3), nn.BatchNorm2D(8), nn.ReLU(),
                              nn.Flatten(), nn.Linear(8 * 4 * 4, 2))
        opt = optim.Momentum(0.05, parameters=model.parameters(),
                             grad_clip=optim.ClipGradByGlobalNorm(1.0))
        step = pt.TrainStep(model, opt,
                            lambda m, x, y: F.cross_entropy(m(x), y))
        before = model[1]._mean.numpy().copy()
        l0 = float(step(X, Y))
        for _ in range(10):
            l = float(step(X, Y))
        assert l < l0
        assert not np.allclose(model[1]._mean.numpy(), before), \
            "BN running stats must update through the fused step"

    def test_dropout_varies_inside_jit(self):
        model = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5))
        fwd = pt.to_static(model)
        x = np.ones((4, 8), "float32")
        a = fwd(x).numpy()
        b = fwd(x).numpy()
        assert not np.allclose(a, b), "dropout mask must differ per call"


class TestSaveLoad:
    def test_save_load_state_dict(self, tmp_path):
        m = nn.Linear(4, 3)
        p = str(tmp_path / "model.pdparams")
        pt.save(m.state_dict(), p)
        m2 = nn.Linear(4, 3)
        m2.set_state_dict(pt.load(p))
        x = pt.to_tensor(np.random.randn(2, 4).astype("float32"))
        np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)

    def test_inference_model_roundtrip(self, tmp_path):
        pt.enable_static()
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            x = pt.static.data("x", [4, 6], "float32")
            net = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 2))
            out = net(x)
        pt.disable_static()
        exe = pt.static.Executor()
        exe.run(startup)
        X = np.random.RandomState(0).randn(4, 6).astype("float32")
        want = exe.run(main, feed={"x": X}, fetch_list=[out])[0]

        prefix = str(tmp_path / "infer")
        pt.framework.save_inference_model(prefix, [x], [out], exe,
                                          program=main)
        prog2, feeds, fetches = pt.framework.load_inference_model(prefix, exe)
        got = exe.run(prog2, feed={feeds[0]: X}, fetch_list=fetches)[0]
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_checkpoint_resume(self, tmp_path):
        X, Y = _problem()
        d = str(tmp_path / "ckpts")

        m = nn.Linear(8, 1)
        sched = optim.lr.StepDecay(0.1, step_size=5)
        opt = optim.Adam(sched, parameters=m.parameters())
        for i in range(3):
            loss = F.mse_loss(m(pt.to_tensor(X)), pt.to_tensor(Y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            sched.step()
            pt.framework.save_checkpoint(d, i, m, opt, sched, keep_last=2)

        assert sorted(os.listdir(d)) == ["ckpt_1", "ckpt_2"]  # rotation

        m2 = nn.Linear(8, 1)
        sched2 = optim.lr.StepDecay(0.1, step_size=5)
        opt2 = optim.Adam(sched2, parameters=m2.parameters())
        step = pt.framework.load_checkpoint(d, m2, opt2, sched2)
        assert step == 2
        np.testing.assert_allclose(m2.weight.numpy(), m.weight.numpy())
        assert sched2.last_epoch == sched.last_epoch

    def test_load_checkpoint_empty_dir(self, tmp_path):
        assert pt.framework.load_checkpoint(str(tmp_path / "none")) is None


class TestReviewRegressions:
    def test_trainstep_with_frozen_param(self):
        from paddle_tpu.nn import ParamAttr

        m = nn.Sequential(
            nn.Linear(4, 6, weight_attr=ParamAttr(trainable=False)),
            nn.Linear(6, 1))
        opt = optim.SGD(0.1, parameters=m.parameters())
        step = pt.TrainStep(m, opt, lambda mm, x, y: F.mse_loss(mm(x), y))
        w_frozen = m[0].weight.numpy().copy()
        x = np.random.randn(8, 4).astype("float32")
        y = np.random.randn(8, 1).astype("float32")
        l0 = float(step(x, y))
        for _ in range(5):
            l = float(step(x, y))
        assert l < l0
        np.testing.assert_allclose(m[0].weight.numpy(), w_frozen)

    def test_static_grad_duplicate_input(self):
        pt.enable_static()
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            x = pt.static.data("x", [3], "float32")
            xv = pt.static.default_main_program().global_block.create_var(
                name="xv", shape=[3], dtype="float32", persistable=True)
            pt.static.global_scope().set(
                "xv", np.array([1.0, 2.0, 3.0], "float32"))
            xv.is_parameter = True
            xv.stop_gradient = False
            y = pt.sum(xv * xv)  # d/dx (x*x) must be 2x, not x
            grads = pt.static.append_backward(y, parameter_list=[xv])
        pt.disable_static()
        exe = pt.static.Executor()
        out = exe.run(main, feed={"x": np.zeros(3, "float32")},
                      fetch_list=[grads[0][1]])
        np.testing.assert_allclose(out[0], [2.0, 4.0, 6.0], rtol=1e-6)

    def test_multi_precision_trainstep(self):
        m = nn.Linear(4, 4)
        m.bfloat16()
        opt = optim.Adam(0.01, parameters=m.parameters(),
                         multi_precision=True)
        step = pt.TrainStep(m, opt, lambda mm, x, y: F.mse_loss(
            mm(x).astype("float32"), y))
        x = np.random.randn(8, 4).astype("float32")
        y = np.random.randn(8, 4).astype("float32")
        step(x, y)
        step(x, y)
        name = m.weight.name
        master = opt._accumulators[name]["master"]
        import jax.numpy as jnp

        assert master.dtype == jnp.float32
        # master must track the bf16 param (same values up to rounding)
        np.testing.assert_allclose(np.asarray(master, dtype=np.float32),
                                   m.weight.numpy().astype(np.float32),
                                   atol=1e-2)
        # and must have actually moved from init
        assert opt._accumulators[name]["beta1_pow"] < 1.0

    def test_state_dict_prefix_skips_nonpersistable(self):
        m = nn.Linear(2, 2)
        m.register_buffer("scratch", pt.zeros([1]), persistable=False)
        sd = m.state_dict(structured_name_prefix="model.")
        assert "model.weight" in sd
        assert not any("scratch" in k for k in sd)

    def test_static_gradients_multi_target(self):
        pt.enable_static()
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            blk = pt.static.default_main_program().global_block
            w = blk.create_var(name="w2", shape=[2], dtype="float32",
                               persistable=True)
            pt.static.global_scope().set("w2", np.array([1.0, 1.0], "float32"))
            w.is_parameter = True
            w.stop_gradient = False
            a = pt.sum(w * 2.0)
            b = pt.sum(w * 3.0)
            g = pt.static.gradients([a, b], [w])
        pt.disable_static()
        exe = pt.static.Executor()
        out = exe.run(main, feed={}, fetch_list=[g[0]])
        np.testing.assert_allclose(out[0], [5.0, 5.0], rtol=1e-6)

    def test_inference_model_with_assign(self, tmp_path):
        pt.enable_static()
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            x = pt.static.data("x", [2, 3], "float32")
            lin = nn.Linear(3, 3)
            out = lin(x)
        pt.disable_static()
        exe = pt.static.Executor()
        prefix = str(tmp_path / "m")
        pt.framework.save_inference_model(prefix, [x], [out], exe,
                                          program=main)
        prog, feeds, fetches = pt.framework.load_inference_model(prefix, exe)
        X = np.ones((2, 3), "float32")
        r = exe.run(prog, feed={feeds[0]: X}, fetch_list=fetches)[0]
        assert r.shape == (2, 3)


def test_set_compilation_cache_persists_executables(tmp_path):
    """pt.set_compilation_cache(dir) must actually write compiled
    executables to disk (the cross-process warm-start path bench.py
    uses on hardware)."""
    import os

    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    d = str(tmp_path / "xla_cache")
    try:
        assert pt.set_compilation_cache(d, min_compile_time_secs=0.0) == d
        m = nn.Linear(64, 32)
        opt = pt.optim.SGD(parameters=m.parameters(), learning_rate=0.1)
        step = pt.TrainStep(m, opt,
                            lambda mm, x, y: ((mm(x) - y) ** 2).mean())
        step(np.zeros((8, 64), "float32"), np.zeros((8, 32), "float32"))
        assert os.listdir(d), "no executables persisted"
    finally:
        pt.set_compilation_cache(None)
