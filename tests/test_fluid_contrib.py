"""fluid.contrib compat tests (ops + rnn_impl + slim/reader extras).

Mirrors python/paddle/fluid/contrib/: layers/rnn_impl.py (BasicGRUUnit,
basic_gru, BasicLSTMUnit, basic_lstm), layers/nn.py (fused ops, CTR and
text-matching family), metric_op.py (ctr_metric_bundle),
extend_optimizer, slim WeightQuantization, distributed_batch_reader.
"""
import numpy as np
import paddle_tpu as pt
import paddle_tpu.fluid.contrib as C
import paddle_tpu.ops as ops


def test_fluid_contrib_surface():
    pt.seed(0)

    B, L, D, H = 2, 5, 4, 6
    x = pt.to_tensor(np.random.randn(B, L, D).astype("float32"))
    h0 = pt.to_tensor(np.zeros((1, B, H), "float32"))

    out, h = C.basic_gru(x, h0, H)
    assert list(out.shape) == [B, L, H]
    out, h, c = C.basic_lstm(x, h0, h0, H)
    assert list(out.shape) == [B, L, H]
    gu = C.BasicGRUUnit(hidden_size=H)
    nh = gu(pt.to_tensor(np.random.randn(B, D).astype("float32")),
            pt.to_tensor(np.zeros((B, H), "float32")))
    assert list(nh.shape) == [B, H]
    lu = C.BasicLSTMUnit(hidden_size=H)
    nh, nc = lu(pt.to_tensor(np.random.randn(B, D).astype("float32")),
                pt.to_tensor(np.zeros((B, H), "float32")),
                pt.to_tensor(np.zeros((B, H), "float32")))
    assert list(nh.shape) == [B, H]
    print("basic rnn ok")

    a = pt.to_tensor(np.random.randn(B, 3).astype("float32"))
    b = pt.to_tensor(np.random.randn(B, 3).astype("float32"))
    fe = C.fused_elemwise_activation(a, b, ["relu", "elementwise_add"])
    assert np.allclose(np.asarray(fe.numpy()),
                       np.maximum(np.asarray(a.numpy()) + np.asarray(b.numpy()), 0))
    print("fused act ok")

    scores = pt.to_tensor(np.random.randn(B, 3, L).astype("float32"))
    lens = pt.to_tensor(np.array([5, 3], "int32"))
    tp = C.sequence_topk_avg_pooling(scores, None, None, [1, 2], 3, lengths=lens)
    assert list(tp.shape) == [B, 6]
    sn = np.asarray(scores.numpy())
    assert abs(np.asarray(tp.numpy())[1, 0] - sn[1, 0, :3].max()) < 1e-5
    print("topk avg pool ok")

    w = pt.to_tensor((np.random.randn(D, 3, D) * 0.1).astype("float32"))
    mm, _ = C.match_matrix_tensor(x, x, 3, weight=w)
    assert list(mm.shape) == [B, 3, L, L]
    print("match matrix ok")

    table = pt.to_tensor(np.random.randn(10, D).astype("float32"))
    ids = pt.to_tensor(np.random.randint(0, 10, (B, L)))
    fe2 = C.fused_embedding_seq_pool(ids, weight=table, lengths=lens)
    assert list(fe2.shape) == [B, D]
    tn = np.asarray(table.numpy())[np.asarray(ids.numpy())[1, :3]].sum(0)
    assert np.allclose(np.asarray(fe2.numpy())[1], tn, atol=1e-5)
    print("fused emb pool ok")

    xb = pt.to_tensor(np.random.randn(4, 6).astype("float32"))
    sh = C.shuffle_batch(xb)
    assert sorted(np.asarray(sh.numpy())[:, 0].tolist()) == \
        sorted(np.asarray(xb.numpy())[:, 0].tolist())
    pc = C.partial_concat([xb, xb], start_index=1, length=2)
    assert list(pc.shape) == [4, 4]
    ps = C.partial_sum([xb, xb], start_index=1, length=2)
    assert np.allclose(np.asarray(ps.numpy()),
                       2 * np.asarray(xb.numpy())[:, 1:3])
    print("shuffle/partial ok")

    # tdm_child: node 1 has children 2,3 (leaf items 20, 30)
    tree = np.zeros((5, 5), "int32")
    tree[1] = [0, 0, 0, 2, 3]
    tree[2] = [20, 1, 1, 0, 0]
    tree[3] = [30, 1, 1, 0, 0]
    ch, leaf = C.tdm_child(pt.to_tensor(np.array([1], "int32")), 5, 2,
                           tree_info=pt.to_tensor(tree))
    assert np.asarray(ch.numpy()).reshape(-1).tolist() == [2, 3]
    assert np.asarray(leaf.numpy()).reshape(-1).tolist() == [1, 1]
    print("tdm_child ok")

    rp = pt.to_tensor((np.random.randn(9, D, 2) * 0.1).astype("float32"))
    ra = C.rank_attention(pt.to_tensor(np.random.randn(B, D).astype("float32")),
                          pt.to_tensor(np.array([[1], [2]], "int32")),
                          None, None, max_rank=3, rank_param=rp)
    assert list(ra.shape) == [B, 2]
    print("rank attention ok")

    emb = pt.to_tensor(np.random.randn(64, 3).astype("float32"))
    ph = C.search_pyramid_hash(pt.to_tensor(np.random.randint(1, 50, (B, L))),
                               num_emb=6, space_len=64, pyramid_layer=3,
                               rand_len=3, embedding=emb)
    assert list(ph.shape) == [B, 6]
    print("pyramid hash ok")

    stats = C.ctr_metric_bundle(pt.to_tensor(np.array([0.2, 0.8], "float32")),
                                pt.to_tensor(np.array([0.0, 1.0], "float32")))
    assert len(stats) == 6 and abs(float(np.asarray(stats[4].numpy())) - 1.0) < 1e-6
    print("ctr bundle ok")

    from paddle_tpu import optim
    Dec = C.extend_with_decoupled_weight_decay(optim.SGD)
    from paddle_tpu.nn.layer import Layer
    class M(Layer):
        def __init__(self):
            super().__init__()
            self.w = self.create_parameter((2,))
    m = M()
    o = Dec(0.1, parameters=m.parameters(), coeff=0.01)
    loss = ops.sum(m.w * m.w); loss.backward(); o.step()
    print("decoupled wd ok")

    wq = C.WeightQuantization(None, state_dict={"w": np.random.randn(4, 4).astype("float32")})
    q = wq.quantize_weight_to_int()
    assert "w" in q and q["w"][0].dtype == np.int8
    print("weight quant ok")

    def reader():
        for i in range(6):
            yield i
    dr = C.distributed_batch_reader(reader)
    from paddle_tpu.dist import env as denv

    world = denv.get_world_size()
    rank = denv.get_rank()
    assert list(dr()) == [i for i in range(6) if i % world == rank]
    print("dist reader ok")

    mnms = C.multiclass_nms2(
        pt.to_tensor(np.random.rand(1, 4, 4).astype("float32") * 10),
        pt.to_tensor(np.random.rand(1, 2, 4).astype("float32")),
        0.01, 4, 4, background_label=-1)
    assert len(mnms) == 3
    print("nms2 ok")

    vc_w = pt.to_tensor((np.random.randn(2, 1, 3, 3) * 0.1).astype("float32"))
    vc = C.var_conv_2d(pt.to_tensor(np.random.randn(2, 1, 6, 6).astype("float32")),
                       pt.to_tensor(np.array([6, 4], "int32")),
                       pt.to_tensor(np.array([6, 3], "int32")),
                       1, 2, 3, weight=vc_w)
    assert list(vc.shape) == [2, 2, 6, 6]
    print("var_conv ok")
    print("CONTRIB OK")
