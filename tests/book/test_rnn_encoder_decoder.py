"""Book ch: rnn_encoder_decoder (ref: tests/book/
test_rnn_encoder_decoder.py) — GRU encoder + GRU decoder through the
fluid DecodeHelper stack (TrainingHelper teacher forcing,
GreedyEmbeddingHelper inference), trained on a copy task."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.ops as ops
from paddle_tpu import optim
from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn.layers.common import Embedding, Linear
from paddle_tpu.nn.layers.rnn import GRU, GRUCell
from paddle_tpu.fluid.rnn import (BasicDecoder, TrainingHelper,

                                  GreedyEmbeddingHelper)
from paddle_tpu.inference.decoder import dynamic_decode

V, E, H, L, B = 12, 16, 32, 6, 8
BOS, EOS = 1, 2


class Seq2Seq(Layer):
    def __init__(self):
        super().__init__()
        self.src_emb = Embedding(V, E)
        self.tgt_emb = Embedding(V, E)
        self.encoder = GRU(E, H)
        self.cell = GRUCell(E, H)
        self.proj = Linear(H, V)

    def encode(self, src):
        _, h = self.encoder(self.src_emb(src))
        return h[0]                       # (B, H) final state

    def train_loss(self, src, tgt_in, tgt_out, lengths):
        state = self.encode(src)
        helper = TrainingHelper(self.tgt_emb(tgt_in), lengths)
        dec = BasicDecoder(self.cell, helper, output_fn=self.proj)
        outs, _ = dynamic_decode(dec, state, max_step_num=int(L))
        logits = outs["cell_outputs"]     # (B, T, V)
        import paddle_tpu.nn.functional as F

        T = logits.shape[1]
        return F.cross_entropy(
            ops.reshape(logits, [-1, V]),
            ops.reshape(tgt_out[:, :T], [-1]))

    def greedy(self, src, max_len=8):
        state = self.encode(src)
        helper = GreedyEmbeddingHelper(
            lambda ids: self.tgt_emb(ids.reshape([-1])),
            pt.to_tensor(np.full((int(src.shape[0]),), BOS, "int64")),
            end_token=EOS)
        dec = BasicDecoder(self.cell, helper, output_fn=self.proj)
        outs, _ = dynamic_decode(dec, state, max_step_num=max_len)
        return outs["sample_ids"]



def test_rnn_encoder_decoder_copy_task():
    pt.seed(0)
    rng = np.random.RandomState(0)
    model = Seq2Seq()
    opt = optim.Adam(parameters=model.parameters(), learning_rate=5e-3)

    src_np = rng.randint(3, V, (B, L)).astype("int64")
    tgt_in = np.concatenate([np.full((B, 1), BOS, "int64"), src_np[:, :-1]], 1)
    lengths = pt.to_tensor(np.full((B,), L, "int64"))

    losses = []
    for i in range(60):
        loss = model.train_loss(pt.to_tensor(src_np), pt.to_tensor(tgt_in),
                                pt.to_tensor(src_np), lengths)
        loss.backward()
        opt.step(); opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    print("first/last loss:", round(losses[0], 3), round(losses[-1], 3))
    assert losses[-1] < losses[0] * 0.3, losses[-1]

    model.eval()
    decoded = np.asarray(model.greedy(pt.to_tensor(src_np), max_len=L).numpy())
    acc = (decoded[:, :L] == src_np).mean()
    print("copy accuracy:", round(float(acc), 3))
    assert acc > 0.6, acc
    print("SEQ2SEQ OK")
