"""Book-style e2e NLP tests (model: reference tests/book/test_word2vec.py,
test_understand_sentiment.py, test_machine_translation.py + the BERT/GPT
recipes): each model trains a few steps on synthetic data, loss decreases."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
import paddle_tpu.optim as optim
from paddle_tpu.models.nlp import (
    NGramLM, SkipGram, skipgram_loss, ConvSentiment, StackedLSTMSentiment,
    WMTTransformer, wmt_loss, BertForPretraining, bert_tiny,
    bert_pretrain_loss, GPT, gpt_tiny, gpt_loss)
from paddle_tpu.models.rec import TwoTowerRecommender, DeepFM, rating_loss

VOCAB = 120


def _fit(model, loss_fn, batch, steps=10, lr=1e-2):
    opt = optim.Adam(lr, parameters=model.parameters())
    step = pt.TrainStep(model, opt, loss_fn)
    return [float(step(*batch)) for _ in range(steps)]


class TestWord2Vec:
    def test_ngram_lm_trains(self):
        rng = np.random.RandomState(0)
        ctx = rng.randint(0, VOCAB, (64, 4)).astype("int64")
        nxt = ctx[:, 0]  # learnable deterministic mapping
        losses = _fit(NGramLM(VOCAB, 16, 64),
                      lambda m, c, t: F.cross_entropy(m(c), t), (ctx, nxt))
        assert losses[-1] < losses[0] * 0.5, losses

    def test_skipgram_negative_sampling(self):
        rng = np.random.RandomState(0)
        center = rng.randint(0, VOCAB, (64,)).astype("int64")
        context = rng.randint(0, VOCAB, (64, 5)).astype("int64")
        label = np.zeros((64, 5), "float32")
        label[:, 0] = 1.0  # first candidate is the true context
        losses = _fit(SkipGram(VOCAB, 16), skipgram_loss,
                      (center, context, label))
        assert losses[-1] < losses[0], losses


class TestSentiment:
    def _data(self):
        rng = np.random.RandomState(0)
        ids = rng.randint(2, VOCAB, (32, 16)).astype("int64")
        y = (ids[:, 0] > VOCAB // 2).astype("int64")  # first-token rule
        return ids, y

    def test_conv_net(self):
        ids, y = self._data()
        losses = _fit(ConvSentiment(VOCAB, 32, 16),
                      lambda m, i, t: F.cross_entropy(m(i), t), (ids, y))
        assert losses[-1] < losses[0] * 0.7, losses

    def test_stacked_lstm(self):
        ids, y = self._data()
        losses = _fit(StackedLSTMSentiment(VOCAB, 32, 32, num_layers=2),
                      lambda m, i, t: F.cross_entropy(m(i), t), (ids, y),
                      steps=12)
        assert losses[-1] < losses[0] * 0.8, losses


class TestMachineTranslation:
    def test_wmt_transformer_trains_and_decodes(self):
        rng = np.random.RandomState(0)
        src = rng.randint(2, 50, (16, 10)).astype("int64")
        tgt_full = np.concatenate(
            [np.zeros((16, 1), "int64"), (src + 1) % 60], axis=1)
        tgt_in, tgt_lab = tgt_full[:, :-1], tgt_full[:, 1:]
        model = WMTTransformer(50, 60, d_model=32, nhead=4, num_layers=2,
                               dim_feedforward=64, dropout=0.0, max_len=32)
        losses = _fit(model,
                      lambda m, s, ti, tl: wmt_loss(m, s, ti, tl, pad_id=None),
                      (src, tgt_in, tgt_lab), steps=12, lr=3e-3)
        assert losses[-1] < losses[0] * 0.8, losses
        out = model.greedy_decode(src[:2], max_len=6)
        assert out.shape == [2, 6]
        assert int(out[0, 0]) == model.bos_id


class TestBertPretrain:
    def test_mlm_nsp_loss_decreases(self):
        rng = np.random.RandomState(0)
        cfg = bert_tiny(dropout=0.0)
        B, L = 8, 24
        ids = rng.randint(0, cfg.vocab_size, (B, L)).astype("int64")
        tt = np.zeros((B, L), "int64")
        am = np.ones((B, L), "int64")
        mlm = np.where(rng.rand(B, L) < 0.15, ids, -100).astype("int64")
        nsp = rng.randint(0, 2, (B,)).astype("int64")
        model = BertForPretraining(cfg)
        losses = _fit(model, lambda m, *b: bert_pretrain_loss(m, *b),
                      (ids, tt, am, mlm, nsp), steps=10, lr=3e-3)
        assert losses[-1] < losses[0] * 0.8, losses


class TestGPT:
    def test_gpt_trains(self):
        rng = np.random.RandomState(0)
        cfg = gpt_tiny(dropout=0.0)
        ids = rng.randint(0, cfg.vocab_size, (4, 32)).astype("int64")
        labels = np.roll(ids, -1, axis=1)
        losses = _fit(GPT(cfg), gpt_loss, (ids, labels), steps=8, lr=3e-3)
        assert losses[-1] < losses[0] * 0.8, losses

    def test_generate_kv_cache_matches_full_forward(self):
        """Incremental KV-cache decode must agree with the dense forward."""
        cfg = gpt_tiny(dropout=0.0)
        pt.seed(3)
        model = GPT(cfg)
        model.eval()
        rng = np.random.RandomState(1)
        ids = rng.randint(0, cfg.vocab_size, (2, 8)).astype("int64")
        out = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                             temperature=0.0)
        assert out.shape == [2, 12]
        # greedy reference: re-run the full forward each step
        cur = ids
        for _ in range(4):
            logits = model(pt.to_tensor(cur))
            nxt = np.asarray(logits.numpy())[:, -1].argmax(-1)[:, None]
            cur = np.concatenate([cur, nxt.astype("int64")], axis=1)
        np.testing.assert_array_equal(out.numpy(), cur)

    def test_generate_xla_matches_eager_generate(self):
        """The single-executable decode (static KV cache + lax.scan)
        must reproduce the eager greedy decode token-for-token, and
        reuse its compiled executable across same-signature calls."""
        cfg = gpt_tiny(dropout=0.0)
        pt.seed(3)
        model = GPT(cfg)
        model.eval()
        rng = np.random.RandomState(1)
        ids = rng.randint(0, cfg.vocab_size, (2, 8)).astype("int64")
        eager = model.generate(pt.to_tensor(ids), max_new_tokens=6,
                               temperature=0.0)
        fused = model.generate_xla(ids, max_new_tokens=6, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(eager.numpy()),
                                      np.asarray(fused.numpy()))
        assert len(model._xla_gen_cache) == 1
        model.generate_xla(ids, max_new_tokens=6, temperature=0.0)
        assert len(model._xla_gen_cache) == 1
        # sampled path: right shape, tokens in range
        samp = model.generate_xla(ids, max_new_tokens=4, temperature=1.0,
                                  top_k=5, seed=7)
        s = np.asarray(samp.numpy())
        assert s.shape == (2, 12)
        assert (s >= 0).all() and (s < cfg.vocab_size).all()


class TestRecommender:
    def test_two_tower_trains(self):
        rng = np.random.RandomState(0)
        n = 64
        feats = [rng.randint(0, hi, (n,)).astype("int64")
                 for hi in (40, 2, 7, 21, 50, 19)]
        rating = (feats[0] % 5).astype("float32") + 0.5
        model = TwoTowerRecommender(40, 50)
        losses = _fit(model, rating_loss, (*feats, rating), steps=12, lr=5e-3)
        assert losses[-1] < losses[0] * 0.8, losses

    def test_deepfm_trains(self):
        rng = np.random.RandomState(0)
        n = 64
        fields = [10, 20, 30]
        ids = [rng.randint(0, v, (n,)).astype("int64") for v in fields]
        y = ((ids[0] + ids[1]) % 2).astype("float32")
        model = DeepFM(fields, embed_dim=8, hidden=(32, 32))

        def loss_fn(m, a, b, c, t):
            return F.binary_cross_entropy_with_logits(m(a, b, c), t)

        losses = _fit(model, loss_fn, (*ids, y), steps=12, lr=5e-3)
        assert losses[-1] < losses[0], losses


class TestGPTXlaWeights:
    def test_generate_xla_sees_weight_updates(self):
        """The cached decode executable must use CURRENT weights
        (constant-folding regression: params are jit arguments)."""
        cfg = gpt_tiny(dropout=0.0)
        pt.seed(5)
        model = GPT(cfg)
        model.eval()
        ids = np.random.RandomState(2).randint(
            0, cfg.vocab_size, (2, 6)).astype("int64")
        out1 = np.asarray(model.generate_xla(
            ids, max_new_tokens=4, temperature=0.0).numpy())
        for p in model.parameters():
            p._data = p._data * 0.0  # zero the model
        out2 = np.asarray(model.generate_xla(
            ids, max_new_tokens=4, temperature=0.0).numpy())
        eager2 = np.asarray(model.generate(
            pt.to_tensor(ids), max_new_tokens=4, temperature=0.0).numpy())
        np.testing.assert_array_equal(out2, eager2)  # matches CURRENT model
        # zero weights -> uniform logits -> argmax token 0 everywhere;
        # the pre-zeroing decode must differ (constant-folding signal)
        assert (out2[:, 6:] == 0).all()
        assert not np.array_equal(out1[:, 6:], out2[:, 6:])
