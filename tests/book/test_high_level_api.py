"""Book high-level-api chapter through the contrib Trainer/Inferencer
(ref: fluid/tests/book/high-level-api/test_recognize_digits_mlp_new_api
.py and test_fit_a_line_new_api.py): dataset reader -> paddle.batch ->
Trainer event loop -> test() -> save_params -> Inferencer — the exact
reference driver shape over the synthetic MNIST reader.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.contrib.trainer import EndEpochEvent, Trainer
from paddle_tpu.fluid.contrib.inferencer import Inferencer

BATCH_SIZE = 64


def inference_program():
    img = fluid.layers.data(name="img", shape=[1, 28, 28],
                            dtype="float32")
    hidden = fluid.layers.fc(input=img, size=64, act="tanh")
    hidden = fluid.layers.fc(input=hidden, size=64, act="tanh")
    return fluid.layers.fc(input=hidden, size=10, act="softmax")


def train_program():
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = inference_program()
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=predict, label=label)
    return [avg_cost, acc]


def optimizer_func():
    return fluid.optimizer.Adam(learning_rate=0.001)


def _mnist_batch(reader_fn, n_batches):
    def r():
        it = reader_fn()()  # dataset.train() -> reader -> iterator
        batch, n = [], 0
        for sample in it:
            img, label = sample
            batch.append((np.asarray(img, "float32").reshape(1, 28, 28),
                          np.asarray([label], "int64")))
            if len(batch) == BATCH_SIZE:
                yield batch
                batch, n = [], n + 1
                if n >= n_batches:
                    return

    return r


def test_recognize_digits_mlp_high_level_api(tmp_path):
    paddle.seed(0)
    params_dirname = str(tmp_path / "mlp_params")
    trainer = Trainer(train_func=train_program,
                      optimizer_func=optimizer_func)
    seen = {"acc": 0.0}

    def event_handler(event):
        if isinstance(event, EndEpochEvent):
            test_reader = _mnist_batch(paddle.dataset.mnist.test, 4)
            avg_cost, acc = trainer.test(reader=test_reader,
                                         feed_order=["img", "label"])
            seen["acc"] = float(np.asarray(acc))
            assert not np.isnan(float(np.asarray(avg_cost)))
            trainer.save_params(params_dirname)

    train_reader = _mnist_batch(paddle.dataset.mnist.train, 20)
    trainer.train(num_epochs=2, event_handler=event_handler,
                  reader=train_reader, feed_order=["img", "label"])
    assert seen["acc"] > 0.5, seen  # synthetic MNIST is easy

    inferencer = Inferencer(infer_func=inference_program,
                            param_path=params_dirname)
    batch = next(_mnist_batch(paddle.dataset.mnist.test, 1)())
    imgs = np.stack([b[0] for b in batch])
    labels = np.concatenate([b[1] for b in batch])
    (probs,) = inferencer.infer({"img": imgs})
    pred = np.argmax(np.asarray(probs), axis=1)
    assert (pred == labels).mean() > 0.5
