"""bf16 train-step regression tests: the standard TPU recipe bench.py uses
(bf16 params + f32 master weights via multi_precision) must work for both
vision (conv/BN chains) and transformer models.

Guards the round-2 bug where ``preferred_element_type`` made bf16 convs
return f32 (and, once cast back, broke the conv vjp) so every stacked bf16
conv net crashed (ref recipe: contrib/mixed_precision/fp16_lists.py:20).
"""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
import paddle_tpu.optim as optim
from paddle_tpu.models.vision import resnet18
from paddle_tpu.models.nlp.bert import (BertForPretraining, bert_tiny,
                                        bert_pretrain_loss)


def test_resnet_bf16_train_step():
    pt.seed(0)
    model = resnet18(num_classes=4)
    model.bfloat16()
    opt = optim.Momentum(learning_rate=1e-2, momentum=0.9,
                         parameters=model.parameters(), multi_precision=True)
    step = pt.TrainStep(
        model, opt,
        lambda m, x, y: F.cross_entropy(
            m(x.astype("bfloat16")).astype("float32"), y))
    rng = np.random.RandomState(0)
    x = rng.randn(8, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 4, (8,)).astype("int64")
    losses = [float(step(x, y)) for _ in range(3)]
    assert np.isfinite(losses).all(), losses
    # params stay bf16; the f32 master copies live in the optimizer state
    assert all(str(p.dtype) == "bfloat16" for p in model.parameters())


def test_resnet_bf16_forward_dtype():
    pt.seed(0)
    model = resnet18(num_classes=4)
    model.bfloat16()
    model.eval()
    x = pt.to_tensor(np.random.randn(2, 3, 32, 32).astype(np.float32))
    out = model(x.astype("bfloat16"))
    assert str(out.dtype) == "bfloat16", out.dtype


def test_bert_bf16_train_step():
    pt.seed(0)
    cfg = bert_tiny(dropout=0.0)
    model = BertForPretraining(cfg)
    model.bfloat16()
    opt = optim.AdamW(parameters=model.parameters(), learning_rate=1e-4,
                      multi_precision=True,
                      grad_clip=optim.ClipGradByGlobalNorm(1.0))
    step = pt.TrainStep(model, opt, bert_pretrain_loss)
    rng = np.random.RandomState(0)
    B, L = 2, 32
    ids = rng.randint(0, cfg.vocab_size, (B, L)).astype("int32")
    tt = np.zeros((B, L), "int32")
    am = np.ones((B, L), "int32")
    mlm = np.where(rng.rand(B, L) < 0.15, ids, -100).astype("int32")
    nsp = rng.randint(0, 2, (B,)).astype("int32")
    losses = [float(step(ids, tt, am, mlm, nsp)) for _ in range(3)]
    assert np.isfinite(losses).all(), losses


def test_conv_transpose_bf16():
    """Transposed conv shares the fractionally-strided path; keep it bf16."""
    from paddle_tpu import ops

    pt.seed(0)
    x = pt.to_tensor(
        np.random.randn(2, 4, 8, 8).astype(np.float32)).astype("bfloat16")
    w = pt.to_tensor(
        np.random.randn(4, 6, 3, 3).astype(np.float32)).astype("bfloat16")
    out = ops.conv2d_transpose(x, w, stride=2, padding=1, output_padding=1)
    assert str(out.dtype) == "bfloat16"
    assert list(out.shape) == [2, 6, 16, 16]
