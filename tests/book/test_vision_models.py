"""Book-style e2e vision tests (model: reference tests/book/
test_recognize_digits.py + test_image_classification.py — train a few
steps on synthetic data, assert the loss decreases)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
import paddle_tpu.optim as optim
from paddle_tpu.models.vision import (LeNet, resnet18, resnet50, vgg11,
                                      MobileNetV1, MobileNetV2)


def _digits(n=64, size=28, chans=1, classes=10, seed=0):
    """Separable synthetic 'digits': class mean + noise."""
    rng = np.random.RandomState(seed)
    means = rng.randn(classes, chans, size, size).astype("float32") * 2.0
    y = rng.randint(0, classes, n)
    x = means[y] + rng.randn(n, chans, size, size).astype("float32") * 0.5
    return x, y.astype("int64")


def _train(model, x, y, steps=8, lr=1e-3):
    opt = optim.Adam(lr, parameters=model.parameters())
    step = pt.TrainStep(model, opt,
                        lambda m, xb, yb: F.cross_entropy(m(xb), yb))
    return [float(step(x, y)) for _ in range(steps)]


class TestLeNetMNIST:
    def test_eager_train_loss_decreases(self):
        x, y = _digits()
        losses = _train(LeNet(), x, y, steps=10, lr=2e-3)
        assert losses[-1] < losses[0] * 0.5, losses

    def test_static_executor_train(self):
        """LeNet through the static Program/Executor path (book ch.2:
        fluid.Executor feed/fetch loop)."""
        x, y = _digits(n=32)
        pt.enable_static()
        try:
            main, startup = pt.static.Program(), pt.static.Program()
            with pt.program_guard(main, startup):
                xv = pt.static.data("x", [32, 1, 28, 28], "float32")
                yv = pt.static.data("y", [32], "int64")
                model = LeNet()
                loss = F.cross_entropy(model(xv), yv)
                opt = optim.Adam(2e-3, parameters=model.parameters())
                opt.minimize(loss)
        finally:
            pt.disable_static()
        exe = pt.static.Executor()
        exe.run(startup)
        losses = [exe.run(main, feed={"x": x, "y": y},
                          fetch_list=[loss])[0] for _ in range(10)]
        assert float(losses[-1]) < float(losses[0]) * 0.6, losses


class TestCIFARModels:
    """ResNet/VGG/MobileNet on small synthetic CIFAR-like data."""

    @pytest.mark.parametrize("factory", [resnet18, vgg11, MobileNetV1,
                                         MobileNetV2])
    def test_train_loss_decreases(self, factory):
        x, y = _digits(n=32, size=32, chans=3, classes=4)
        if factory is vgg11:
            # giant FC head: drop the dropout noise on 32 samples and use
            # a gentler rate so 8 steps show a monotone trend
            model = factory(num_classes=4, dropout=0.0)
            losses = _train(model, x, y, steps=8, lr=1e-4)
        else:
            model = factory(num_classes=4)
            losses = _train(model, x, y, steps=6, lr=1e-3)
        assert losses[-1] < losses[0], losses

    def test_resnet50_forward_backward(self):
        x, y = _digits(n=8, size=32, chans=3, classes=4)
        model = resnet50(num_classes=4)
        losses = _train(model, x, y, steps=3, lr=1e-3)
        assert np.isfinite(losses).all()


class TestSSDDetection:
    """Book-style SSD chapter: train a tiny SSD on synthetic boxes,
    confirm the loss drops and inference localizes (ref: the PaddleCV
    MobileNet-SSD recipe over layers/detection.py)."""

    def _data(self, n=16, size=64, seed=0):
        """Images with one bright square; the box is its extent."""
        rng = np.random.RandomState(seed)
        x = rng.rand(n, 3, size, size).astype("float32") * 0.1
        gt = np.zeros((n, 1, 4), "float32")
        lab = np.ones((n, 1), "int64")
        for i in range(n):
            cx, cy = rng.randint(16, size - 16, 2)
            half = rng.randint(8, 14)
            x1, y1 = max(cx - half, 0), max(cy - half, 0)
            x2, y2 = min(cx + half, size), min(cy + half, size)
            x[i, :, y1:y2, x1:x2] += 0.8
            gt[i, 0] = [x1 / size, y1 / size, x2 / size, y2 / size]
        return x, gt, lab

    def test_ssd_trains_and_infers(self):
        from paddle_tpu.models.vision import ssd_tiny

        pt.seed(0)
        x, gt, lab = self._data()
        model = ssd_tiny(num_classes=3)
        opt = optim.Adam(2e-3, parameters=model.parameters())
        step = pt.TrainStep(model, opt,
                            lambda m, xb, gb, lb: m.loss(xb, gb, lb))
        losses = [float(step(x, gt, lab)) for _ in range(12)]
        assert losses[-1] < losses[0], losses

        model.eval()
        out, counts = model.infer(pt.to_tensor(x[:2]),
                                  score_threshold=0.05)
        assert np.asarray(out.numpy()).shape[2] == 6
        assert np.isfinite(np.asarray(out.numpy())).all()


class TestYOLOv3Detection:
    """YOLOv3 chapter: two-head training on synthetic boxes
    (ref: PaddleCV yolov3 over layers/detection.py:895,1022)."""

    def test_yolov3_trains_and_infers(self):
        from paddle_tpu.models.vision import yolov3_tiny

        pt.seed(0)
        rng = np.random.RandomState(0)
        n, size = 8, 64
        x = rng.rand(n, 3, size, size).astype("float32") * 0.1
        gt = np.zeros((n, 2, 4), "float32")  # cxcywh normalized
        lab = np.zeros((n, 2), "int64")
        for i in range(n):
            cx, cy = rng.uniform(0.3, 0.7, 2)
            w = h = rng.uniform(0.2, 0.4)
            gt[i, 0] = [cx, cy, w, h]
            lab[i, 0] = rng.randint(0, 4)
            x1 = int((cx - w / 2) * size); x2 = int((cx + w / 2) * size)
            y1 = int((cy - h / 2) * size); y2 = int((cy + h / 2) * size)
            x[i, :, y1:y2, x1:x2] += 0.8
        model = yolov3_tiny(num_classes=4)
        opt = optim.Adam(1e-3, parameters=model.parameters())
        step = pt.TrainStep(model, opt,
                            lambda m, xb, gb, lb: m.loss(xb, gb, lb))
        losses = [float(step(x, gt, lab)) for _ in range(10)]
        assert losses[-1] < losses[0], losses

        model.eval()
        out, counts = model.infer(pt.to_tensor(x[:2]))
        o = np.asarray(out.numpy())
        assert o.shape[2] == 6 and np.isfinite(o).all()
