"""Faster R-CNN two-stage model e2e test: RPN targets + proposals +
RoI head losses train jointly; inference emits fixed-shape detections.
Ref: the PaddleCV two-stage recipe over detection.py:157/:2646/:2308 +
nn.py:6680."""
import numpy as np
import paddle_tpu as pt
from paddle_tpu import optim
from paddle_tpu.models.vision.faster_rcnn import faster_rcnn_tiny


def test_faster_rcnn_trains_and_infers():
    pt.seed(0)
    model = faster_rcnn_tiny()
    opt = optim.Momentum(learning_rate=0.01, momentum=0.9,
                         parameters=model.parameters())

    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(2, 3, 64, 64).astype("float32"))
    gt_boxes = pt.to_tensor(np.array([
        [[4, 4, 30, 30], [40, 40, 60, 60]],
        [[10, 10, 28, 28], [0, 0, 0, 0]]], "float32"))
    gt_labels = pt.to_tensor(np.array([[1, 3], [2, -1]], "int32"))

    losses = []
    for i in range(4):
        loss = model.loss(x, gt_boxes, gt_labels)
        loss.backward()
        opt.step(); opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    print("losses:", [round(v, 3) for v in losses])
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0], losses

    model.eval()
    cls, reg, rois, counts = model(x)
    assert list(rois.shape) == [2, 16, 4]
    assert list(cls.shape) == [32, 5]
    print("infer shapes ok; counts:", np.asarray(counts.numpy()))
    print("FRCNN OK")
