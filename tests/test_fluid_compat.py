"""fluid-era RNN/decode/array/op compat tests.

Mirrors reference API surfaces: python/paddle/fluid/layers/rnn.py
(dynamic_lstm/lstmp/gru, gru_unit, lstm_unit, lstm, decode helpers,
BasicDecoder, beam_search_decode), control_flow.py (StaticRNN,
tensor-array ops), sequence_lod.py (lod_reset, sequence_concat),
nn.py (unique_with_counts, hash, similarity_focus, pool/pad/crop,
spectral_norm, data_norm, deformable_conv), distributions.py
(MultivariateNormalDiag).
"""
import numpy as np
import pytest
import paddle_tpu as pt
import paddle_tpu.fluid.layers as L
from paddle_tpu.fluid.rnn import (dynamic_lstm, dynamic_gru, dynamic_lstmp,
                                  gru_unit, lstm_unit, lstm, StaticRNN,
                                  DynamicRNN, TrainingHelper,
                                  GreedyEmbeddingHelper, SampleEmbeddingHelper,
                                  BasicDecoder, beam_search_decode)
from paddle_tpu.inference.decoder import dynamic_decode

def test_fluid_rnn_and_op_compat():
    import paddle_tpu as pt
    pt.seed(0)

    def shp(t):
        return list(t.shape)

    B, T, H = 4, 6, 8
    x4 = pt.to_tensor(np.random.randn(B, T, 4 * H).astype("float32"))
    h, c = dynamic_lstm(x4, 4 * H, use_peepholes=True)
    assert shp(h) == [B, T, H] and shp(c) == [B, T, H], (shp(h), shp(c))
    h, c = dynamic_lstm(x4, 4 * H, use_peepholes=False, is_reverse=True)
    print("dynamic_lstm ok")

    hp, cp = dynamic_lstmp(x4, 4 * H, proj_size=5)
    assert shp(hp) == [B, T, 5] and shp(cp) == [B, T, H]
    print("dynamic_lstmp ok")

    x3 = pt.to_tensor(np.random.randn(B, T, 3 * H).astype("float32"))
    g = dynamic_gru(x3, H)
    assert shp(g) == [B, T, H]
    g2 = dynamic_gru(x3, H, origin_mode=True, is_reverse=True)
    print("dynamic_gru ok")

    xu = pt.to_tensor(np.random.randn(B, 3 * H).astype("float32"))
    hu = pt.to_tensor(np.zeros((B, H), "float32"))
    nh, rh, gate = gru_unit(xu, hu, 3 * H)
    assert shp(nh) == [B, H] and shp(gate) == [B, 3 * H]
    print("gru_unit ok")

    xt = pt.to_tensor(np.random.randn(B, 10).astype("float32"))
    nh, nc = lstm_unit(xt, pt.to_tensor(np.zeros((B, H), "float32")),
                       pt.to_tensor(np.zeros((B, H), "float32")))
    assert shp(nh) == [B, H]
    print("lstm_unit ok")

    xi = pt.to_tensor(np.random.randn(B, T, 10).astype("float32"))
    ih = pt.to_tensor(np.zeros((2, B, H), "float32"))
    out, lh, lc = lstm(xi, ih, ih, T, H, num_layers=2)
    assert shp(out) == [B, T, H], shp(out)
    print("lstm (stacked) ok")

    import paddle_tpu.ops as ops
    srnn = StaticRNN()
    srnn.step_input(xi)
    h0 = srnn.memory(shape=[H], batch_ref=xi)
    W = pt.to_tensor(np.random.randn(10 + H, H).astype("float32") * 0.1)
    srnn.step(lambda xt, h: (ops.tanh(ops.matmul(ops.concat([xt, h], axis=-1), W)),) * 2)
    outs = srnn()
    assert shp(outs) == [B, T, H]
    drnn = DynamicRNN()
    drnn.step_input(xi, lengths=pt.to_tensor(np.array([6, 3, 2, 1], "int32")))
    drnn.memory(shape=[H], batch_ref=xi)
    drnn.step(lambda xt, h: (ops.tanh(ops.matmul(ops.concat([xt, h], axis=-1), W)),) * 2)
    outs2 = drnn()
    assert float(np.abs(np.asarray(outs2[1, 3:].numpy())).sum()) == 0.0
    print("StaticRNN/DynamicRNN ok")

    V, E = 12, 8
    emb = pt.to_tensor(np.random.randn(V, E).astype("float32"))
    proj = pt.to_tensor(np.random.randn(H, V).astype("float32"))
    from paddle_tpu.nn.layers.rnn import GRUCell
    cell = GRUCell(E, H)
    helper = GreedyEmbeddingHelper(lambda ids: ops.index_select(emb, ids.reshape([-1]), axis=0),
                                   pt.to_tensor(np.zeros((B,), "int64")), end_token=1)
    dec = BasicDecoder(cell, helper, output_fn=lambda h: ops.matmul(h, proj))
    outs, _ = dynamic_decode(dec, cell.get_initial_states(pt.to_tensor(np.zeros((B, E), "float32"))), max_step_num=5)
    assert shp(outs["sample_ids"])[0] == B
    print("BasicDecoder greedy ok")

    helper2 = SampleEmbeddingHelper(lambda ids: ops.index_select(emb, ids.reshape([-1]), axis=0),
                                    pt.to_tensor(np.zeros((B,), "int64")), end_token=1)
    dec2 = BasicDecoder(cell, helper2, output_fn=lambda h: ops.matmul(h, proj))
    dynamic_decode(dec2, cell.get_initial_states(pt.to_tensor(np.zeros((B, E), "float32"))), max_step_num=4)
    print("SampleEmbeddingHelper ok")

    tgt = pt.to_tensor(np.random.randn(B, T, E).astype("float32"))
    helper3 = TrainingHelper(tgt, pt.to_tensor(np.array([6, 5, 4, 3], "int64")))
    dec3 = BasicDecoder(cell, helper3, output_fn=lambda h: ops.matmul(h, proj))
    dynamic_decode(dec3, cell.get_initial_states(pt.to_tensor(np.zeros((B, E), "float32"))), max_step_num=T)
    print("TrainingHelper ok")

    ids = pt.to_tensor(np.random.randint(0, V, (5, B, 3)).astype("int64"))
    par = pt.to_tensor(np.random.randint(0, 3, (5, B, 3)).astype("int64"))
    seqs, sc = beam_search_decode(ids, par, 3, 1)
    assert shp(seqs) == [5, B, 3]
    print("beam_search_decode ok")

    arr = L.create_array()
    L.array_write(pt.to_tensor(np.ones((2, 3), "float32")), 0, arr)
    L.array_write(pt.to_tensor(np.ones((2, 3), "float32")), 1, arr)
    t, sizes = L.tensor_array_to_tensor(arr, axis=0)
    assert shp(t) == [4, 3] and int(L.array_length(arr).item()) == 2
    xr, ln = L.lod_reset(pt.to_tensor(np.ones((6, 2), "float32")), target_lod=[0, 2, 6])
    assert list(np.asarray(ln.numpy())) == [2, 4]
    print("tensor arrays + lod_reset ok")

    u, inv, cnt = L.unique_with_counts(pt.to_tensor(np.array([2, 2, 3, 1, 1, 1], "int64")))
    assert sorted(np.asarray(cnt.numpy()).tolist()) == [1, 2, 3]
    hsh = L.hash(pt.to_tensor(np.random.randint(0, 100, (5, 2)).astype("int64")), hash_size=1000, num_hash=2)
    assert shp(hsh)[-1] == 2 and np.asarray(hsh.numpy()).max() < 1000
    pb = L.polygon_box_transform(pt.to_tensor(np.random.randn(2, 8, 4, 4).astype("float32")))
    assert shp(pb) == [2, 8, 4, 4]
    sf = L.similarity_focus(pt.to_tensor(np.random.randn(2, 3, 4, 5).astype("float32")), axis=1, indexes=[0])
    assert shp(sf) == [2, 3, 4, 5]
    print("unique/hash/polygon/similarity ok")

    img = pt.to_tensor(np.random.randn(2, 3, 8, 8).astype("float32"))
    assert shp(L.adaptive_pool2d(img, 2, "avg")) == [2, 3, 2, 2]
    vol = pt.to_tensor(np.random.randn(2, 3, 4, 8, 8).astype("float32"))
    assert shp(L.adaptive_pool3d(vol, 2, "avg")) == [2, 3, 2, 2, 2]
    assert shp(L.pool3d(vol, 2, "max", 2)) == [2, 3, 2, 4, 4]
    assert shp(L.pad2d(img, (1, 1, 2, 2), mode="reflect")) == [2, 3, 10, 12]
    assert shp(L.random_crop(img, [3, 4, 4])) == [2, 3, 4, 4]
    assert shp(L.resize_trilinear(vol, out_shape=[2, 4, 4])) == [2, 3, 2, 4, 4]
    print("pool/pad/crop/resize ok")

    w = pt.to_tensor(np.random.randn(6, 4).astype("float32"))
    wn = L.spectral_norm(w, dim=0, power_iters=5)
    s = np.linalg.svd(np.asarray(wn.numpy()), compute_uv=False)[0]
    assert abs(s - 1.0) < 0.1, s
    dn = L.data_norm(pt.to_tensor(np.random.randn(16, 5).astype("float32")))
    assert shp(dn) == [16, 5]
    offs = pt.to_tensor(np.zeros((2, 2 * 9, 8, 8), "float32"))
    msk = pt.to_tensor(np.ones((2, 9, 8, 8), "float32"))
    dw = pt.to_tensor((np.random.randn(4, 3, 3, 3) * 0.1).astype("float32"))
    dc = L.deformable_conv(img, offs, msk, 4, 3, padding=1, weight=dw)
    assert shp(dc) == [2, 4, 8, 8], shp(dc)
    import paddle_tpu.nn.functional as F
    ref = F.conv2d(img, dw, padding=1)
    assert np.allclose(np.asarray(dc.numpy()), np.asarray(ref.numpy()), atol=1e-4), \
        np.abs(np.asarray(dc.numpy()) - np.asarray(ref.numpy())).max()
    print("spectral/data/deformable ok (zero-offset == conv2d)")

    from paddle_tpu.distribution import MultivariateNormalDiag, kl_divergence
    d1 = MultivariateNormalDiag(np.zeros(3, "float32"), np.diag(np.ones(3, "float32")))
    d2 = MultivariateNormalDiag(np.ones(3, "float32"), np.diag(np.ones(3, "float32") * 2))
    assert shp(d1.sample()) == [3]
    assert float(np.asarray(kl_divergence(d1, d2).numpy())) > 0
    print("MultivariateNormalDiag ok")

    s1 = pt.to_tensor(np.arange(12, dtype="float32").reshape(2, 3, 2))
    s2 = pt.to_tensor(100 + np.arange(16, dtype="float32").reshape(2, 4, 2))
    l1 = pt.to_tensor(np.array([2, 3], "int32")); l2 = pt.to_tensor(np.array([1, 4], "int32"))
    cat, tot = L.sequence_concat([s1, s2], [l1, l2])
    cn = np.asarray(cat.numpy())
    assert cn.shape == (2, 7, 2)
    assert np.allclose(cn[0, :3, 0], [0, 2, 100]), cn[0, :, 0]
    assert list(np.asarray(tot.numpy())) == [3, 7]
    print("sequence_concat ok")
    print("ALL COMPAT OK")


def test_fluid_compat_review_fixes():
    """Grouped/deformable-group conv parity, data_norm NCHW, adaptive max
    mask, sequence_concat packing (regressions from review findings)."""
    import paddle_tpu as pt
    import paddle_tpu.fluid.layers as L
    import paddle_tpu.nn.functional as F

    pt.seed(0)
    img = pt.to_tensor(np.random.randn(2, 4, 8, 8).astype("float32"))
    dw = pt.to_tensor((np.random.randn(4, 2, 3, 3) * 0.1).astype("float32"))
    offs = pt.to_tensor(np.zeros((2, 18, 8, 8), "float32"))
    msk = pt.to_tensor(np.ones((2, 9, 8, 8), "float32"))
    dc = L.deformable_conv(img, offs, msk, 4, 3, padding=1, groups=2,
                           weight=dw)
    ref = F.conv2d(img, dw, padding=1, groups=2)
    assert np.abs(np.asarray(dc.numpy()) -
                  np.asarray(ref.numpy())).max() < 1e-4

    offs2 = pt.to_tensor(np.zeros((2, 36, 8, 8), "float32"))
    msk2 = pt.to_tensor(np.ones((2, 18, 8, 8), "float32"))
    dc2 = L.deformable_conv(img, offs2, msk2, 4, 3, padding=1, groups=2,
                            deformable_groups=2, weight=dw)
    assert np.abs(np.asarray(dc2.numpy()) -
                  np.asarray(ref.numpy())).max() < 1e-4

    dn = L.data_norm(pt.to_tensor(np.random.randn(2, 3, 4, 4)
                                  .astype("float32")))
    assert list(dn.shape) == [2, 3, 4, 4]

    out, mask = L.adaptive_pool2d(img, 2, "max", require_index=True)
    flat = np.asarray(img.numpy()).reshape(2, 4, -1)
    picked = np.take_along_axis(
        flat, np.asarray(mask.numpy()).reshape(2, 4, -1), axis=-1)
    assert np.allclose(picked.reshape(2, 4, 2, 2), np.asarray(out.numpy()))


def test_fluid_layers_full_api_parity():
    """Every name in the reference fluid.layers __all__ resolves here
    (py_reader-era readers raise NotImplementedError by design,
    SURVEY §4b)."""
    import paddle_tpu as pt
    import paddle_tpu.fluid.layers as L

    x = pt.to_tensor(np.arange(6, dtype="float32").reshape(3, 2))
    out = L.py_func(lambda a: a * 2, x, x)
    assert np.allclose(np.asarray(out.numpy()),
                       np.arange(6).reshape(3, 2) * 2)
    r, v = L.merge_selected_rows((pt.to_tensor(np.array([1, 1, 3])), x))
    assert np.allclose(np.asarray(v.numpy())[0], [2, 4])
    assert L.get_tensor_from_selected_rows(x) is x
    assert list(L.continuous_value_model(x, None, use_cvm=False).shape) \
        == [3, 0]
    f, idx, w = L.filter_by_instag(
        x, pt.to_tensor(np.array([1, 2, 1])), pt.to_tensor(np.array([1])))
    assert list(f.shape) == [2, 2]
    ro = L.reorder_lod_tensor_by_rank(x, pt.to_tensor(np.array([2, 0, 1])))
    assert np.allclose(np.asarray(ro.numpy())[0], [4, 5])
    with pytest.raises(NotImplementedError):
        L.py_reader(8, [[2]], ["float32"])
    assert L.double_buffer([1, 2]) == [1, 2]
    # the audit itself: nothing from the reference __all__ is absent
    import ast, os

    ref = set()
    ref_dir = "/root/reference/python/paddle/fluid/layers/"
    if os.path.isdir(ref_dir):
        for fn in os.listdir(ref_dir):
            if not fn.endswith(".py"):
                continue
            try:
                tree = ast.parse(open(ref_dir + fn).read())
            except Exception:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign):
                    for t_ in node.targets:
                        if isinstance(t_, ast.Name) and t_.id == "__all__":
                            try:
                                ref |= set(ast.literal_eval(node.value))
                            except Exception:
                                pass
        missing = sorted(n for n in ref if n not in dir(L))
        assert missing == [], f"fluid.layers gaps: {missing}"
