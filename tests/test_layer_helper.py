"""fluid.layer_helper + fluid.layers.utils + fluid.input surfaces
(ref: fluid/layer_helper.py, fluid/layers/utils.py, fluid/input.py):
the factory custom user layers are written against, the nest helpers
RNN cells use, and the module-import spellings for both.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.layer_helper import LayerHelper
from paddle_tpu.fluid.layers import utils


class TestLayerHelperStatic:
    def test_custom_fluid_layer_trains(self):
        """A reference-style custom layer: LayerHelper.create_parameter
        + functional math, trained through the static Executor."""

        def my_scale_shift(x, size):
            helper = LayerHelper("my_scale_shift", **locals())
            w = helper.create_parameter(helper.param_attr, [size],
                                        dtype="float32")
            b = helper.create_parameter(helper.bias_attr, [size],
                                        dtype="float32", is_bias=True)
            return x * w + b

        pt.enable_static()
        try:
            main, startup = pt.static.Program(), pt.static.Program()
            with pt.static.program_guard(main, startup):
                x = pt.static.data("x", [8, 4], "float32")
                y = pt.static.data("y", [8, 4], "float32")
                out = my_scale_shift(x, 4)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(out, y))
                pt.optimizer.SGD(learning_rate=0.2).minimize(loss)
            exe = pt.static.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            X = rng.randn(8, 4).astype("float32")
            Y = X * 3.0 + 0.5
            losses = [float(exe.run(main, feed={"x": X, "y": Y},
                                    fetch_list=[loss])[0])
                      for _ in range(30)]
            assert losses[-1] < losses[0] * 0.05
        finally:
            pt.disable_static()

    def test_helper_accessors_and_append_activation(self):
        helper = LayerHelper("thing", input=pt.ones([2, 3]), act="relu")
        assert helper.input().shape == [2, 3]
        assert helper.input_dtype() == "float32"
        out = helper.append_activation(pt.to_tensor(
            np.array([-1.0, 2.0], "float32")))
        assert np.allclose(out.numpy(), [0.0, 2.0])
        with pytest.raises(NotImplementedError, match="functional API"):
            helper.append_op(type="definitely_not_an_op")

    def test_append_op_registry_kernel(self):
        helper = LayerHelper("t2")
        out = helper.append_op(type="reshape",
                               inputs={"X": pt.ones([2, 3])},
                               attrs={"shape": (3, 2)})
        assert list(out.shape) == [3, 2]


class TestLayersUtils:
    def test_flatten_pack_roundtrip(self):
        nest = {"b": [1, (2, 3)], "a": 4}
        flat = utils.flatten(nest)
        assert flat == [4, 1, 2, 3]  # dict keys sorted
        packed = utils.pack_sequence_as(nest, flat)
        assert packed == {"a": 4, "b": [1, (2, 3)]}

    def test_map_structure(self):
        a = {"h": 1, "c": (2, 3)}
        b = {"h": 10, "c": (20, 30)}
        out = utils.map_structure(lambda x, y: x + y, a, b)
        assert out == {"h": 11, "c": (22, 33)}

    def test_assert_same_structure(self):
        utils.assert_same_structure([1, (2,)], [9, (8,)])
        with pytest.raises((ValueError, TypeError)):
            utils.assert_same_structure([1, 2], [1, [2]])
        assert utils.is_sequence([1]) and not utils.is_sequence("ab")


def test_fluid_input_module():
    from paddle_tpu.fluid.input import embedding, one_hot

    assert callable(embedding) and callable(one_hot)
    x = pt.to_tensor(np.array([0, 2], "int64"))
    oh = one_hot(x, 4)
    assert np.asarray(oh.numpy()).shape == (2, 4)


def test_module_import_spellings():
    import importlib

    for name in ("paddle_tpu.fluid.initializer",
                 "paddle_tpu.fluid.regularizer",
                 "paddle_tpu.fluid.clip", "paddle_tpu.fluid.metrics",
                 "paddle_tpu.fluid.nets", "paddle_tpu.fluid.optimizer",
                 "paddle_tpu.fluid.unique_name",
                 "paddle_tpu.fluid.backward"):
        mod = importlib.import_module(name)
        attr = getattr(fluid, name.rsplit(".", 1)[1])
        assert mod is attr, name
    from paddle_tpu.fluid.initializer import Xavier  # noqa: F401
    from paddle_tpu.fluid.backward import append_backward  # noqa: F401
