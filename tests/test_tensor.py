"""Tensor + eager-op basics (ref test model: tests/unittests/test_var_base.py)."""
import numpy as np
import pytest

import paddle_tpu as pt


def test_to_tensor_roundtrip():
    x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    assert x.dtype == pt.float32
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_scalar_and_int_dtypes():
    assert pt.to_tensor(3).dtype == pt.int64
    assert pt.to_tensor(3.0).dtype == pt.float32
    assert pt.to_tensor(True).dtype == pt.bool
    assert pt.to_tensor(np.array([1.0], dtype=np.float64)).dtype == pt.float32


def test_arithmetic_broadcast():
    a = pt.ones([2, 3])
    b = pt.arange(3, dtype="float32")
    c = a + b * 2 - 1.0
    np.testing.assert_allclose(c.numpy(), np.ones((2, 3)) + np.arange(3) * 2 - 1)


def test_scalar_keeps_dtype():
    a = pt.ones([2], dtype="bfloat16")
    assert (a * 2).dtype == pt.bfloat16
    assert (a + 1).dtype == pt.bfloat16


def test_matmul_and_T():
    a = pt.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    c = a @ b
    assert c.shape == [2, 4]
    np.testing.assert_allclose(c.numpy(), a.numpy() @ b.numpy())
    np.testing.assert_allclose(a.T.numpy(), a.numpy().T)


def test_getitem_setitem():
    x = pt.arange(12, dtype="float32").reshape([3, 4])
    np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
    x[0, 0] = 100.0
    assert x[0, 0].item() == 100.0


def test_methods_attached():
    x = pt.to_tensor([[1.0, -2.0], [3.0, -4.0]])
    np.testing.assert_allclose(x.abs().sum().item(), 10.0)
    np.testing.assert_allclose(x.mean(axis=0).numpy(), [2.0, -3.0])
    assert x.max().item() == 3.0
    assert x.argmax().item() == 2


def test_comparison_ops():
    a = pt.to_tensor([1.0, 2.0, 3.0])
    b = pt.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
    assert pt.allclose(a, a).item()


def test_cast():
    x = pt.ones([2], dtype="float32")
    assert x.astype("int32").dtype == pt.int32
    assert pt.cast(x, "bfloat16").dtype == pt.bfloat16


def test_creation_ops():
    assert pt.zeros([2, 2]).numpy().sum() == 0
    assert pt.full([2], 7).numpy().tolist() == [7.0, 7.0]
    assert pt.eye(3).numpy().trace() == 3
    assert pt.linspace(0, 1, 5).shape == [5]
    t = pt.tril(pt.ones([3, 3]))
    assert t.numpy()[0, 2] == 0


def test_manipulation_ops():
    x = pt.arange(24).reshape([2, 3, 4])
    assert pt.transpose(x, [2, 0, 1]).shape == [4, 2, 3]
    assert pt.concat([x, x], axis=1).shape == [2, 6, 4]
    assert pt.stack([x, x]).shape == [2, 2, 3, 4]
    parts = pt.split(x, [1, 2], axis=1)
    assert parts[0].shape == [2, 1, 4] and parts[1].shape == [2, 2, 4]
    assert pt.flatten(x, 1).shape == [2, 12]
    assert pt.squeeze(pt.unsqueeze(x, 0), 0).shape == [2, 3, 4]


def test_where_topk_sort():
    x = pt.to_tensor([3.0, 1.0, 2.0])
    v, i = pt.topk(x, 2)
    np.testing.assert_allclose(v.numpy(), [3, 2])
    np.testing.assert_array_equal(i.numpy(), [0, 2])
    np.testing.assert_allclose(pt.sort(x).numpy(), [1, 2, 3])
    out = pt.where(x > 1.5, x, pt.zeros_like(x))
    np.testing.assert_allclose(out.numpy(), [3, 0, 2])


def test_gather_scatter():
    x = pt.arange(10, dtype="float32")
    idx = pt.to_tensor([1, 3, 5])
    np.testing.assert_allclose(pt.gather(x, idx).numpy(), [1, 3, 5])
    upd = pt.scatter(pt.zeros([5]), pt.to_tensor([0, 2]), pt.to_tensor([1.0, 2.0]))
    np.testing.assert_allclose(upd.numpy(), [1, 0, 2, 0, 0])


def test_random_reproducible():
    pt.seed(7)
    a = pt.randn([4])
    pt.seed(7)
    b = pt.randn([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())


def test_linalg():
    a = np.array([[4.0, 2.0], [2.0, 3.0]], np.float32)
    x = pt.to_tensor(a)
    np.testing.assert_allclose(pt.inverse(x).numpy(), np.linalg.inv(a), atol=1e-5)
    np.testing.assert_allclose(pt.norm(x, p=2).item(), (np.abs(a) ** 2).sum() ** 0.5, rtol=1e-5)
    l = pt.cholesky(x)
    np.testing.assert_allclose((l @ l.T).numpy(), a, atol=1e-5)
