"""Round-5 fluid surface batch: static AMP (contrib.mixed_precision),
transpiler.collective, trainer_factory/FetchHandler, device_worker,
communicator, default_scope_funcs, log_helper, wrapped_decorator,
fleet_utils, incubate role makers + PS strategies + CollectiveOptimizer,
fluid.distributed.Fleet, dataset fetch/fetch_all, fluid-era activation
spellings.
"""
import logging
import time

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid


class TestStaticAMP:
    def _build(self, **dec_kw):
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.static.program_guard(main, startup):
            x = pt.static.data("x", [8, 16], "float32")
            y = pt.static.data("y", [8, 1], "float32")
            h = fluid.layers.fc(x, size=32, act="relu")
            p = fluid.layers.fc(h, size=1)
            loss = pt.mean((p - y) ** 2)
            from paddle_tpu.fluid.contrib.mixed_precision import decorate

            opt = decorate(pt.optimizer.SGD(learning_rate=0.05), **dec_kw)
            opt.minimize(loss)
        return main, startup, loss, opt

    def test_trains_grows_scale_and_skips_inf(self):
        """One executable: list-casted fwd/bwd, scaled loss, inf-guarded
        update, dynamic scale (ref: mixed_precision/decorator.py)."""
        pt.enable_static()
        try:
            main, startup, loss, opt = self._build(
                init_loss_scaling=128.0, incr_every_n_steps=4,
                decr_every_n_nan_or_inf=1, incr_ratio=2.0, decr_ratio=0.5)
            exe = pt.static.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            X = rng.randn(8, 16).astype("float32")
            Y = X @ rng.randn(16, 1).astype("float32")
            losses = [float(exe.run(main, feed={"x": X, "y": Y},
                                    fetch_list=[loss])[0])
                      for _ in range(12)]
            assert losses[-1] < losses[0] * 0.5
            s0 = opt.get_loss_scaling()
            assert s0 > 128.0  # grew on clean steps

            import paddle_tpu.static_.program as prog

            scope = prog.global_scope()
            pnames = [v.name for v in main.global_block.all_parameters()]
            before = {n: np.array(scope.find_var(n)) for n in pnames}
            Xbad = X.copy()
            Xbad[0, 0] = np.inf
            exe.run(main, feed={"x": Xbad, "y": Y}, fetch_list=[loss])
            for n in pnames:
                assert np.array_equal(before[n],
                                      np.array(scope.find_var(n))), n
            assert opt.get_loss_scaling() == s0 * 0.5
        finally:
            pt.disable_static()

    def test_scaled_loss_and_accessors(self):
        pt.enable_static()
        try:
            main, startup, loss, opt = self._build(init_loss_scaling=64.0)
            assert opt.get_scaled_loss() is not None
            assert opt.get_scaled_loss().name.endswith("@SCALED")
            assert opt.get_loss_scaling() == 64.0
        finally:
            pt.disable_static()


class TestTranspilerCollective:
    def test_grad_allreduce_marks_dp(self):
        """transpile() makes the program run through the SPMD DP path
        (ref: transpiler/collective.py GradAllReduce)."""
        from paddle_tpu.fluid.transpiler.collective import GradAllReduce

        pt.enable_static()
        try:
            main, startup = pt.static.Program(), pt.static.Program()
            with pt.static.program_guard(main, startup):
                x = pt.static.data("x", [16, 8], "float32")
                y = pt.static.data("y", [16, 1], "float32")
                p = fluid.layers.fc(x, size=1)
                loss = pt.mean((p - y) ** 2)
                pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
            t = GradAllReduce()
            t.transpile(startup_program=startup, main_program=main,
                        rank=0, endpoints=["a:1", "b:2"],
                        current_endpoint="a:1", wait_port=False)
            assert main._transpiled_dp and t.nranks == 2
            exe = pt.static.Executor()
            exe.run(startup)
            rng = np.random.RandomState(1)
            X = rng.randn(16, 8).astype("float32")
            Y = X @ rng.randn(8, 1).astype("float32")
            l0 = float(exe.run(main, feed={"x": X, "y": Y},
                               fetch_list=[loss])[0])
            for _ in range(20):
                lv = float(exe.run(main, feed={"x": X, "y": Y},
                                   fetch_list=[loss])[0])
            assert lv < l0 * 0.5
        finally:
            pt.disable_static()


class TestTrainerFactoryAndWorkers:
    def test_factory_default_and_named(self):
        from paddle_tpu.fluid.trainer_factory import TrainerFactory

        t = TrainerFactory()._create_trainer()
        assert t.proto_desc["class_name"] == "MultiTrainer"
        assert t.device_worker_name == "HogwildWorker"
        t2 = TrainerFactory()._create_trainer(
            {"trainer": "DistMultiTrainer", "device_worker": "DownpourSGD",
             "use_cvm": True})
        assert t2.device_worker_name == "DownpourWorker"
        assert t2.proto_desc["use_cvm"] is True

    def test_fetch_handler_monitor_polls_scope(self):
        from paddle_tpu.fluid.trainer_factory import (FetchHandler,
                                                      FetchHandlerMonitor)
        from paddle_tpu.static_.program import Scope

        scope = Scope()
        scope.set("acc", np.asarray([0.5]))
        seen = []

        class H(FetchHandler):
            def handler(self, res):
                seen.append(res["accuracy"])

        class V:  # duck-typed Variable
            name = "acc"

        h = H(var_dict={"accuracy": V()}, period_secs=0.05)
        mon = FetchHandlerMonitor(scope, h)
        mon.start()
        time.sleep(0.4)
        mon.stop()
        assert seen and np.allclose(seen[-1], [0.5])

    def test_device_worker_factory(self):
        from paddle_tpu.fluid.device_worker import (DeviceWorkerFactory,
                                                    Section)

        w = DeviceWorkerFactory()._create_device_worker("section")
        assert isinstance(w, Section)


class TestSmallModules:
    def test_log_helper_no_duplicate_handlers(self):
        from paddle_tpu.fluid.log_helper import get_logger

        a = get_logger("ptpu_test_log", logging.INFO, fmt="%(message)s")
        b = get_logger("ptpu_test_log", logging.INFO)
        assert a is b and len(a.handlers) == 1

    def test_wrapped_decorator_preserves_signature(self):
        import inspect

        from paddle_tpu.fluid.wrapped_decorator import (
            signature_safe_contextmanager)

        @signature_safe_contextmanager
        def guard(alpha, beta=2):
            yield alpha + beta

        assert list(inspect.signature(guard).parameters) == ["alpha",
                                                             "beta"]
        with guard(1) as v:
            assert v == 3

    def test_default_scope_funcs(self):
        from paddle_tpu.fluid import default_scope_funcs as dsf

        dsf.var("x")
        assert dsf.find_var("x") is None or dsf.find_var("x") is not None
        outer = dsf.get_cur_scope()
        dsf.enter_local_scope()
        assert dsf.get_cur_scope() is not outer
        dsf.leave_local_scope()
        assert dsf.get_cur_scope() is outer
        res = dsf.scoped_function(lambda: 42)
        assert res == 42

    def test_communicator_lifecycle(self):
        with pytest.warns(Warning):
            c = fluid.communicator.Communicator(pt.static.Program())
        c.start()
        assert c.is_running()
        c.stop()
        assert not c.is_running()


class TestFleetSurfaces:
    def test_role_makers(self):
        from paddle_tpu.fluid.incubate.fleet.base.role_maker import (
            GeneralRoleMaker, MPISymetricRoleMaker)

        rm = MPISymetricRoleMaker()
        rm.generate_role()
        assert rm._check_role_generation()
        assert rm.is_worker() and rm.worker_num() >= 1
        assert rm.all_gather(1) == [1]
        assert rm.all_reduce_worker(3) == 3
        GeneralRoleMaker().barrier_all()

    def test_ps_strategy_factory(self):
        from paddle_tpu.fluid.incubate.fleet.parameter_server.\
            distribute_transpiler.distributed_strategy import (
                StrategyFactory, TrainerRuntimeConfig)

        s = StrategyFactory.create_geo_strategy(7)
        assert s.get_program_config()["geo_sgd_need_push_nums"] == 7
        assert not s.get_program_config()["sync_mode"]
        sync = StrategyFactory.create_sync_strategy()
        assert sync.get_program_config()["sync_mode"]
        with pytest.raises(ValueError):
            sync.set_program_config({"bogus": 1})
        cfg = TrainerRuntimeConfig()
        assert "communicator_send_queue_size" in \
            cfg.get_communicator_flags()

    def test_collective_optimizer_static_dp(self):
        from paddle_tpu.fluid.incubate.fleet.collective import (
            CollectiveOptimizer)

        pt.enable_static()
        try:
            main, startup = pt.static.Program(), pt.static.Program()
            with pt.static.program_guard(main, startup):
                x = pt.static.data("x", [16, 4], "float32")
                y = pt.static.data("y", [16, 1], "float32")
                p = fluid.layers.fc(x, size=1)
                loss = pt.mean((p - y) ** 2)
                CollectiveOptimizer(
                    pt.optimizer.SGD(learning_rate=0.1)).minimize(loss)
            assert main._transpiled_dp
            exe = pt.static.Executor()
            exe.run(startup)
            rng = np.random.RandomState(2)
            X = rng.randn(16, 4).astype("float32")
            Y = X @ rng.randn(4, 1).astype("float32")
            l0 = float(exe.run(main, feed={"x": X, "y": Y},
                               fetch_list=[loss])[0])
            for _ in range(15):
                lv = float(exe.run(main, feed={"x": X, "y": Y},
                                   fetch_list=[loss])[0])
            assert lv < l0 * 0.5
        finally:
            pt.disable_static()

    def test_fleet_util(self, tmp_path):
        from paddle_tpu.fluid.incubate.fleet.utils.fleet_util import (
            FleetUtil)
        from paddle_tpu.static_.program import Scope

        fu = FleetUtil()
        fu.rank0_print("hello")
        scope = Scope()
        # AUC from bucketed pos/neg counts: perfect separation -> 1.0
        scope.set("_generated_var_2", np.array([0.0, 0.0, 0.0, 5.0]))
        scope.set("_generated_var_3", np.array([5.0, 0.0, 0.0, 0.0]))
        auc = fu.get_global_auc(scope)
        assert auc == pytest.approx(1.0)
        scope.set("acc_zero", np.ones((3,), "int64"))
        fu.set_zero("acc_zero", scope)
        assert np.all(np.asarray(scope.find_var("acc_zero")) == 0)
        with pytest.raises(NotImplementedError):
            fu.save_xbox_base_model("/tmp", 20260731)

    def test_fluid_distributed_fleet(self):
        from paddle_tpu.fluid.distributed import Fleet

        f = Fleet()
        f.init_worker()
        assert f.worker_num() >= 1 and f.worker_index() >= 0
        with pytest.raises(NotImplementedError):
            f.init_pserver()
        f.stop()

    def test_program_helpers(self, tmp_path):
        from paddle_tpu.fluid.fleet_utils import (check_pruned_program_vars,
                                                  graphviz, parse_program)

        pt.enable_static()
        try:
            main, startup = pt.static.Program(), pt.static.Program()
            with pt.static.program_guard(main, startup):
                x = pt.static.data("x", [4, 4], "float32")
                fluid.layers.fc(x, size=2)
            assert check_pruned_program_vars(main, main)
            p = parse_program(main, str(tmp_path))
            assert "fc" in open(p).read() or "Program" in open(p).read()
            d = graphviz(main.global_block, str(tmp_path), "g")
            assert open(d).read().startswith("digraph")
        finally:
            pt.disable_static()


class TestDatasetFetch:
    def test_fetch_all_and_wmt16_dict(self):
        import paddle_tpu.dataset as D

        D.common.fetch_all()  # every module's fetch() runs (no-ops)
        d = D.wmt16.get_dict("en", 30)
        assert d["<s>"] == 0 and len(d) == 30
        rd = D.wmt16.get_dict("en", 30, reverse=True)
        assert rd[0] == "<s>"
        sample = next(D.wmt16.validation()())
        assert len(sample) == 3


def test_fluid_activation_spellings():
    x = pt.to_tensor(np.array([-1.0, 0.1, 1.0], "float32"))
    out = fluid.layers.hard_shrink(x)
    assert np.allclose(out.numpy(), [-1.0, 0.0, 1.0])
    out2 = fluid.layers.tanh_shrink(x)
    assert np.allclose(out2.numpy(), x.numpy() - np.tanh(x.numpy()),
                       atol=1e-6)
