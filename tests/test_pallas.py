"""Pallas kernel parity tests — interpret mode vs jnp reference on CPU
(SURVEY §4: 'Pallas kernels: interpret-mode parity vs jnp reference')."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import (flash_attention, fused_layer_norm,
                                   softmax_cross_entropy)


def _sdpa_ref(q, k, v, causal, scale=None):
    scale = scale or 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        Lq, Lk = s.shape[-2], s.shape[-1]
        m = jnp.tril(jnp.ones((Lq, Lk), bool), k=Lk - Lq)
        s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_dense(self, causal):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(2, 2, 256, 64), jnp.float32)
        k = jnp.asarray(rng.randn(2, 2, 256, 64), jnp.float32)
        v = jnp.asarray(rng.randn(2, 2, 256, 64), jnp.float32)
        out = flash_attention(q, k, v, causal, None, 128, True)
        ref = _sdpa_ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, causal):
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 2, 128, 32), jnp.float32)
        k = jnp.asarray(rng.randn(1, 2, 128, 32), jnp.float32)
        v = jnp.asarray(rng.randn(1, 2, 128, 32), jnp.float32)

        def f_pallas(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, None, 64, True)
                           ** 2)

        def f_ref(q, k, v):
            return jnp.sum(_sdpa_ref(q, k, v, causal) ** 2)

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)

    def test_cross_attention_shapes(self):
        """Lq != Lk (decode / cross-attention)."""
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(1, 2, 64, 32), jnp.float32)
        k = jnp.asarray(rng.randn(1, 2, 256, 32), jnp.float32)
        v = jnp.asarray(rng.randn(1, 2, 256, 32), jnp.float32)
        out = flash_attention(q, k, v, True, None, 64, True)
        ref = _sdpa_ref(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_bf16_tolerance(self):
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(1, 2, 128, 64), jnp.bfloat16)
        k = jnp.asarray(rng.randn(1, 2, 128, 64), jnp.bfloat16)
        v = jnp.asarray(rng.randn(1, 2, 128, 64), jnp.bfloat16)
        out = flash_attention(q, k, v, True, None, 128, True)
        ref = _sdpa_ref(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                                   np.asarray(ref), atol=3e-2, rtol=3e-2)


class TestFusedLayerNorm:
    def test_forward_matches(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(64, 256), jnp.float32)
        g = jnp.asarray(rng.randn(256), jnp.float32)
        b = jnp.asarray(rng.randn(256), jnp.float32)
        out = fused_layer_norm(x, g, b, 1e-5, True)
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mean) / jnp.sqrt(var + 1e-5) * g + b
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_grads_match(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(32, 128), jnp.float32)
        g = jnp.asarray(rng.randn(128), jnp.float32)
        b = jnp.asarray(rng.randn(128), jnp.float32)

        def f_pallas(x, g, b):
            return jnp.sum(fused_layer_norm(x, g, b, 1e-5, True) ** 2)

        def f_ref(x, g, b):
            mean = x.mean(-1, keepdims=True)
            var = x.var(-1, keepdims=True)
            return jnp.sum(((x - mean) / jnp.sqrt(var + 1e-5) * g + b) ** 2)

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(x, g, b)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(x, g, b)
        for a, b_ in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=1e-4, rtol=1e-4)


class TestSoftmaxCE:
    def test_forward_matches(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(64, 4096), jnp.float32)
        lab = jnp.asarray(rng.randint(0, 4096, 64), jnp.int32)
        out = softmax_cross_entropy(x, lab, -100, True)
        lse = jax.scipy.special.logsumexp(x, axis=-1)
        ref = lse - x[jnp.arange(64), lab]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_ignore_index(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(16, 512), jnp.float32)
        lab = np.asarray(rng.randint(0, 512, 16), np.int32)
        lab[::2] = -100
        out = softmax_cross_entropy(x, jnp.asarray(lab), -100, True)
        assert np.all(np.asarray(out)[::2] == 0.0)
        assert np.all(np.asarray(out)[1::2] > 0.0)

    def test_grads_match(self):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(32, 1024), jnp.float32)
        lab = np.asarray(rng.randint(0, 1024, 32), np.int32)
        lab[:4] = -100
        labj = jnp.asarray(lab)

        def f_pallas(x):
            return jnp.sum(softmax_cross_entropy(x, labj, -100, True))

        def f_ref(x):
            lse = jax.scipy.special.logsumexp(x, axis=-1)
            per = lse - x[jnp.arange(32), jnp.maximum(labj, 0)]
            return jnp.sum(jnp.where(labj != -100, per, 0.0))

        gp = jax.grad(f_pallas)(x)
        gr = jax.grad(f_ref)(x)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                   atol=1e-5, rtol=1e-5)


class TestWiredPaths:
    """The F.sdpa / F.cross_entropy / layer_norm call sites route through
    the pallas kernels when enabled — parity vs the dense paths."""

    def _toggle(self, value):
        from paddle_tpu.ops import pallas as pk

        pk.set_enabled(value)

    def test_sdpa_routes_and_matches(self):
        import paddle_tpu as pt
        import paddle_tpu.nn.functional as F

        rng = np.random.RandomState(0)
        q = pt.to_tensor(rng.randn(2, 2, 128, 64).astype("float32"))
        k = pt.to_tensor(rng.randn(2, 2, 128, 64).astype("float32"))
        v = pt.to_tensor(rng.randn(2, 2, 128, 64).astype("float32"))
        self._toggle(False)
        dense = F.sdpa_bhld(q, k, v, is_causal=True).numpy()
        self._toggle(True)
        try:
            flash = F.sdpa_bhld(q, k, v, is_causal=True).numpy()
        finally:
            self._toggle(None)
        np.testing.assert_allclose(flash, dense, atol=2e-5, rtol=2e-5)

    def test_cross_entropy_routes_and_matches(self):
        import paddle_tpu as pt
        import paddle_tpu.nn.functional as F

        rng = np.random.RandomState(1)
        logits = pt.to_tensor(rng.randn(32, 512).astype("float32"))
        lab = rng.randint(0, 512, 32)
        lab[:4] = -100
        lab = pt.to_tensor(lab.astype("int64"))
        self._toggle(False)
        dense = float(F.cross_entropy(logits, lab).numpy())
        self._toggle(True)
        try:
            fused = float(F.cross_entropy(logits, lab).numpy())
        finally:
            self._toggle(None)
        np.testing.assert_allclose(fused, dense, atol=1e-5, rtol=1e-5)

    def test_layer_norm_routes_and_matches_with_grad(self):
        import paddle_tpu as pt
        import paddle_tpu.nn as nn

        rng = np.random.RandomState(2)
        x = rng.randn(16, 256).astype("float32")

        def run():
            pt.seed(5)
            ln = nn.LayerNorm(256)
            xt = pt.to_tensor(x, stop_gradient=False)
            out = ln(xt)
            loss = (out * out).mean()
            loss.backward()
            return out.numpy(), ln.weight.grad.numpy()

        self._toggle(False)
        dense_out, dense_gw = run()
        self._toggle(True)
        try:
            fused_out, fused_gw = run()
        finally:
            self._toggle(None)
        np.testing.assert_allclose(fused_out, dense_out, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(fused_gw, dense_gw, atol=1e-4, rtol=1e-4)
