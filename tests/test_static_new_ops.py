"""New op families through the STATIC Program/Executor path: the ops
registered this round (detection/rcnn/sequence/geometric) must record
into a Program and replay inside the compiled executable, not just run
eagerly (ref: the reference's OpDesc round-trip guarantees)."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn.functional as F


def test_static_records_new_ops():
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [2, 3, 8, 8], "float32")
            rois = fluid.layers.data("rois", [4, 4], "float32")
            pooled = fluid.layers.roi_align(
                x, rois, pooled_height=2, pooled_width=2,
                rois_num=pt.to_tensor(np.array([2, 2], "int32")))
            gs = fluid.layers.spectral_norm(
                fluid.layers.reshape(pooled, [4, -1]), power_iters=2)
        exe = fluid.Executor()
        exe.run(startup)
        out = exe.run(
            main,
            feed={"x": np.random.randn(2, 3, 8, 8).astype("float32"),
                  "rois": np.array([[0, 0, 4, 4]] * 4, "float32")},
            fetch_list=[pooled, gs])
        assert np.asarray(out[0]).shape == (4, 3, 2, 2)
        s = np.linalg.svd(np.asarray(out[1]), compute_uv=False)[0]
        assert abs(s - 1.0) < 0.2
    finally:
        pt.disable_static()


def test_static_sequence_and_geometric():
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [2, 3, 8, 8], "float32")
            up = fluid.layers.resize_bilinear(x, out_shape=[16, 16])
            pooled = fluid.layers.adaptive_pool2d(up, 4, "avg")
        exe = fluid.Executor()
        exe.run(startup)
        out = exe.run(
            main,
            feed={"x": np.random.randn(2, 3, 8, 8).astype("float32")},
            fetch_list=[pooled])
        assert np.asarray(out[0]).shape == (2, 3, 4, 4)
    finally:
        pt.disable_static()
