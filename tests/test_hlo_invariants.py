"""Perf-critical invariants asserted on the compiled (post-optimization)
HLO text + XLA memory analysis — CPU-runnable stand-ins for hardware perf
evidence while the TPU tunnel is down (VERDICT r4 Next #2).

The reference enforces analogous properties with IR passes over its graph
(paddle/fluid/framework/ir/graph_pattern_detector.cc); here the invariants
are asserted directly on what XLA will execute:
  (a) the static-DP executable contains grad all-reduces, the
      single-device one doesn't;
  (b) donation really aliases: every donated persistable (static
      Executor) / every param+opt-state leaf (TrainStep) has an
      input_output_alias entry, so params are not double-buffered;
  (c) the fused beam search is ONE while-loop executable with zero host
      transfers;
  (d) the fused train step performs no full-size copy of optimizer
      moment buffers (scalar beta-pow copies are immaterial).
"""
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn as nn
import paddle_tpu.optim as optim


def _build_mlp_program(lr=0.1, batch=16):
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 8])
        y = fluid.data(name="y", shape=[batch, 1])
        h = fluid.layers.fc(x, size=16, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        opt = fluid.optimizer.SGD(learning_rate=lr)
        opt.minimize(loss)
    return prog, startup, loss


def _compiled_text(exe, prog, feed, fetch, data_parallel):
    """Optimized-HLO text of the Executor's cached executable for a feed."""
    from paddle_tpu.static_.program import global_scope

    compiled = exe._compile(prog, feed, fetch, data_parallel=data_parallel)
    scope = global_scope()
    feeds = [jnp.asarray(np.asarray(feed[n])) for n in compiled.feed_names]
    upd = [scope.find_var(n) for n in compiled.updated]
    frz = [scope.find_var(n) for n in compiled.frozen]
    lowered = compiled.fn.lower(feeds, upd, frz)
    return lowered.compile().as_text(), compiled


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def _train_feed(prog):
    feed = {"x": np.zeros((16, 8), np.float32),
            "y": np.zeros((16, 1), np.float32)}
    if prog._lr_getter is not None:
        feed["@lr"] = np.asarray(prog._lr_getter(), np.float32)
    return feed


class TestStaticExecutorHLO:
    def test_dp_executable_has_allreduce_single_does_not(self, static_mode):
        pt.seed(0)
        prog, startup, loss = _build_mlp_program()
        exe = fluid.Executor()
        exe.run(startup)
        feed = _train_feed(prog)
        txt_dp, _ = _compiled_text(exe, prog, feed, [loss], True)
        txt_1, _ = _compiled_text(exe, prog, feed, [loss], False)
        assert "all-reduce" in txt_dp, "DP step lost its grad all-reduce"
        assert "all-reduce" not in txt_1

    def test_updated_persistables_are_aliased(self, static_mode):
        """donate_argnums=(1,) must alias EVERY updated persistable
        (params + opt slots) into the outputs — no double-buffering."""
        pt.seed(0)
        prog, startup, loss = _build_mlp_program()
        exe = fluid.Executor()
        exe.run(startup)
        feed = _train_feed(prog)
        txt, compiled = _compiled_text(exe, prog, feed, [loss], False)
        assert "input_output_alias" in txt
        n_updated = len(compiled.updated)
        assert n_updated >= 4  # 2xW, 2xb at minimum
        assert txt.count("alias") - txt.count("input_output_alias") \
            >= n_updated or txt.count("may-alias") >= n_updated, \
            f"expected >= {n_updated} alias entries"


class TestTrainStepHLO:
    def _compiled_step(self):
        from paddle_tpu.framework.jit import TrainStep
        from paddle_tpu.core import random as prandom

        m = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
        opt = optim.Adam(parameters=m.parameters(), learning_rate=1e-3)

        def loss_fn(model, x, y):
            d = model(x) - y
            return (d * d).mean()

        step = TrainStep(m, opt, loss_fn)
        x = np.zeros((16, 32), np.float32)
        y = np.zeros((16, 8), np.float32)
        step(x, y)
        fn = next(iter(step._compiled.values()))
        opt_state = {p.name: opt._accumulators[p.name]
                     for p in step._trainable}
        lowered = fn.lower([p._data for p in step._trainable],
                           [b._data for b in step._buffers], opt_state,
                           jnp.float32(1e-3), prandom.next_key(),
                           [jnp.asarray(x), jnp.asarray(y)], {})
        comp = lowered.compile()
        n_leaves = len(step._trainable) + len(step._buffers) + sum(
            len(v) for v in opt_state.values())
        return comp, n_leaves

    def test_all_params_and_state_aliased(self):
        comp, n_leaves = self._compiled_step()
        txt = comp.as_text()
        assert txt.count("may-alias") == n_leaves, \
            f"{txt.count('may-alias')} aliased of {n_leaves} donated leaves"
        ma = comp.memory_analysis()
        # aliased bytes must cover the params+state (less scalar slack):
        # if donation regressed, alias_size collapses and the step
        # double-buffers every parameter in HBM
        assert ma.alias_size_in_bytes >= 0.9 * ma.output_size_in_bytes

    def test_no_fullsize_copies_of_optimizer_state(self):
        comp, _ = self._compiled_step()
        txt = comp.as_text()
        bad = [ln for ln in txt.splitlines()
               if re.search(r"\w+\[\d[0-9,]*\]\S* copy\(\S*opt_state", ln)]
        assert not bad, "moment buffers copied instead of updated " \
            f"in place:\n" + "\n".join(bad[:5])


class TestFusedDecodeHLO:
    def test_beam_xla_single_while_no_host_transfers(self):
        from paddle_tpu.inference.decoder import beam_search_xla

        V, B, K, L = 11, 2, 3, 8

        def run(table):
            def step_fn(cur, state, t):
                logits = pt.Tensor(
                    jnp.tile(table, (cur.shape[0], 1)), _internal=True)
                return logits, state

            toks, scores = beam_search_xla(step_fn, None, B, bos_id=0,
                                           eos_id=1, beam_size=K, max_len=L)
            return toks._data, scores._data

        table = jnp.linspace(0.0, 1.0, V)
        txt = jax.jit(run).lower(table).compile().as_text()
        # op defs look like `%while.2 = (<tuple shape>) while(%tuple.N)`;
        # metadata op_names only ever contain "/while/" so ' while(' is
        # unambiguous
        n_while = txt.count(" while(")
        assert n_while == 1, f"expected ONE fused decode loop, got {n_while}"
        for marker in ("infeed", "outfeed", " send(", " recv(",
                       "SendToHost", "RecvFromHost"):
            assert marker not in txt, f"host transfer {marker!r} in decode"


class TestInt8PredictorHLO:
    def test_int8_weights_enter_executable_as_s8(self, tmp_path):
        """The int8 serving claim, proven on the compiled executable:
        quantized weights are s8[...] PARAMETERS of the HLO module (the
        resident HBM copy), and the convert to float happens inside the
        program (fused dequant), not on the host before the call."""
        import paddle_tpu.nn.functional as F
        from paddle_tpu.inference import Predictor
        from paddle_tpu.models.vision import LeNet
        from paddle_tpu.quant import quantize_inference_model

        pt.seed(0)
        pt.enable_static()
        try:
            main, startup = pt.static.Program(), pt.static.Program()
            with pt.program_guard(main, startup):
                x = pt.static.data("x", [8, 1, 28, 28], "float32")
                prob = F.softmax(LeNet()(x), axis=-1)
        finally:
            pt.disable_static()
        exe = pt.static.Executor()
        exe.run(startup)
        prefix = str(tmp_path / "lenet")
        pt.framework.io.save_inference_model(prefix, ["x"], [prob],
                                             program=main)
        quantized = quantize_inference_model(prefix)
        assert quantized

        pred = Predictor(prefix + "_int8")
        xs = np.zeros((8, 1, 28, 28), np.float32)
        pred.run({"x": xs})  # compile
        # entries are _PredictorEntry since PR 7 (fn + captured
        # arg_structs, the perf-gate/mfu contract) — lower from those
        (entry,) = pred._compiled.values()
        txt = entry.fn.lower(*entry.arg_structs).compile().as_text()
        assert re.search(r"s8\[\d", txt), "no int8 parameter in HLO"
        assert "convert" in txt, "dequant not inside the executable"


class TestDistributedHLOSignatures:
    """The collective 'signature' of each parallelism mode, pinned on
    compiled HLO: the cheapest regression guard for the mechanisms the
    bench can't measure without hardware."""

    def test_ring_attention_permutes_never_gathers(self):
        """Ring attention must rotate K/V blocks (collective-permute)
        and must NOT fall back to all-gathering the full sequence —
        that would silently forfeit the O(L/n) memory the mode exists
        for."""
        from paddle_tpu.dist import env as denv
        from paddle_tpu.dist.ring_attention import ring_attention
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:4]), ("sp",))
        denv.set_mesh(mesh)
        try:
            q = jnp.ones((2, 4, 16, 8))

            def ra(q):
                t = pt.Tensor(q, _internal=True)
                return ring_attention(t, t, t, axis_name="sp",
                                      causal=True)._data

            with mesh:
                txt = jax.jit(ra).lower(q).compile().as_text()
        finally:
            denv.set_mesh(None)
        assert txt.count("collective-permute(") >= 1, "no ring rotation"
        assert txt.count("all-gather(") == 0, \
            "ring attention gathered the full sequence"

    def test_moe_exactly_two_all_to_alls(self):
        """Expert parallel is dispatch + combine: exactly TWO all-to-all
        ops. More means a shuffle crept in; zero means tokens never
        crossed experts."""
        from paddle_tpu.dist import env as denv
        from paddle_tpu.dist.moe import MoEMLP
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:2]), ("expert",))
        denv.set_mesh(mesh)
        try:
            pt.seed(0)
            layer = MoEMLP(16, 32, num_experts=4)
            x = jnp.ones((2, 8, 16))

            def moe(x):
                return layer(pt.Tensor(x, _internal=True))._data

            with mesh:
                txt = jax.jit(moe).lower(x).compile().as_text()
        finally:
            denv.set_mesh(None)
        assert txt.count("all-to-all(") == 2, \
            f"expected dispatch+combine, got {txt.count('all-to-all(')}"

    def test_tp_block_megatron_signature(self):
        """Column->Row parallel pairs need exactly ONE all-reduce per
        row-parallel output (attn proj + mlp fc2 = 2 for a GPT block)
        and ZERO weight all-gathers — the Megatron communication
        contract the TP layers exist to honor."""
        from paddle_tpu.core import dispatch
        from paddle_tpu.dist import env as denv
        from paddle_tpu.models.nlp.gpt import GPTBlock, gpt_tiny
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:2]), ("model",))
        denv.set_mesh(mesh)
        try:
            pt.seed(0)
            cfg = gpt_tiny(dropout=0.0)
            blk = GPTBlock(cfg)
            blk.eval()
            x = jnp.ones((2, 16, cfg.hidden))

            def fwd(x):
                with dispatch.no_grad(), dispatch.fresh_tape():
                    return blk(pt.Tensor(x, _internal=True))._data

            with mesh:
                txt = jax.jit(fwd).lower(x).compile().as_text()
        finally:
            denv.set_mesh(None)
        assert txt.count("all-reduce(") == 2, \
            f"expected 2 partial-sum all-reduces, got " \
            f"{txt.count('all-reduce(')}"
        assert txt.count("all-gather(") == 0, "weights were all-gathered"


class TestStaticAMPHLO:
    def test_amp_step_is_one_guarded_bf16_executable(self, static_mode):
        """The fluid.contrib.mixed_precision step must stay ONE
        executable: list-driven bf16 casts present on the matmul path,
        the inf-guard select fused in, and the loss-scaling state
        updated through the same donated-alias mechanism as optimizer
        slots (no second program, no host round-trip)."""
        from paddle_tpu.fluid.contrib.mixed_precision import decorate

        pt.seed(0)
        prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.data(name="x", shape=[16, 8])
            y = fluid.data(name="y", shape=[16, 1])
            h = fluid.layers.fc(x, size=16, act="relu")
            out = fluid.layers.fc(h, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square_error_cost(out, y))
            opt = decorate(fluid.optimizer.SGD(learning_rate=0.1),
                           init_loss_scaling=256.0)
            opt.minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        feed = _train_feed(prog)
        txt, compiled = _compiled_text(exe, prog, feed, [loss], False)
        # (a) white-list casts made it into the compiled program
        assert "bf16" in txt, "no bf16 anywhere: list casts lost"
        # (b) the inf-guarded update lowered to selects
        assert "select(" in txt
        # (c) scaling state rides the donated persistables (aliased,
        # not copied back through host)
        assert "@amp@scale" in compiled.updated
        assert "@amp@good" in compiled.updated
        aliases = txt.count("input_output_alias")
        assert aliases >= 1
