"""Perf-critical invariants asserted on the compiled (post-optimization)
HLO text + XLA memory analysis — CPU-runnable stand-ins for hardware perf
evidence while the TPU tunnel is down (VERDICT r4 Next #2).

The reference enforces analogous properties with IR passes over its graph
(paddle/fluid/framework/ir/graph_pattern_detector.cc); here the invariants
are asserted directly on what XLA will execute:
  (a) the static-DP executable contains grad all-reduces, the
      single-device one doesn't;
  (b) donation really aliases: every donated persistable (static
      Executor) / every param+opt-state leaf (TrainStep) has an
      input_output_alias entry, so params are not double-buffered;
  (c) the fused beam search is ONE while-loop executable with zero host
      transfers;
  (d) the fused train step performs no full-size copy of optimizer
      moment buffers (scalar beta-pow copies are immaterial).
"""
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn as nn
import paddle_tpu.optim as optim


def _build_mlp_program(lr=0.1, batch=16):
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 8])
        y = fluid.data(name="y", shape=[batch, 1])
        h = fluid.layers.fc(x, size=16, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        opt = fluid.optimizer.SGD(learning_rate=lr)
        opt.minimize(loss)
    return prog, startup, loss


def _compiled_text(exe, prog, feed, fetch, data_parallel):
    """Optimized-HLO text of the Executor's cached executable for a feed."""
    from paddle_tpu.static_.program import global_scope

    compiled = exe._compile(prog, feed, fetch, data_parallel=data_parallel)
    scope = global_scope()
    feeds = [jnp.asarray(np.asarray(feed[n])) for n in compiled.feed_names]
    upd = [scope.find_var(n) for n in compiled.updated]
    frz = [scope.find_var(n) for n in compiled.frozen]
    lowered = compiled.fn.lower(feeds, upd, frz)
    return lowered.compile().as_text(), compiled


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def _train_feed(prog):
    feed = {"x": np.zeros((16, 8), np.float32),
            "y": np.zeros((16, 1), np.float32)}
    if prog._lr_getter is not None:
        feed["@lr"] = np.asarray(prog._lr_getter(), np.float32)
    return feed


class TestStaticExecutorHLO:
    def test_dp_executable_has_allreduce_single_does_not(self, static_mode):
        pt.seed(0)
        prog, startup, loss = _build_mlp_program()
        exe = fluid.Executor()
        exe.run(startup)
        feed = _train_feed(prog)
        txt_dp, _ = _compiled_text(exe, prog, feed, [loss], True)
        txt_1, _ = _compiled_text(exe, prog, feed, [loss], False)
        assert "all-reduce" in txt_dp, "DP step lost its grad all-reduce"
        assert "all-reduce" not in txt_1

    def test_updated_persistables_are_aliased(self, static_mode):
        """donate_argnums=(1,) must alias EVERY updated persistable
        (params + opt slots) into the outputs — no double-buffering."""
        pt.seed(0)
        prog, startup, loss = _build_mlp_program()
        exe = fluid.Executor()
        exe.run(startup)
        feed = _train_feed(prog)
        txt, compiled = _compiled_text(exe, prog, feed, [loss], False)
        assert "input_output_alias" in txt
        n_updated = len(compiled.updated)
        assert n_updated >= 4  # 2xW, 2xb at minimum
        assert txt.count("alias") - txt.count("input_output_alias") \
            >= n_updated or txt.count("may-alias") >= n_updated, \
            f"expected >= {n_updated} alias entries"


class TestTrainStepHLO:
    def _compiled_step(self):
        from paddle_tpu.framework.jit import TrainStep
        from paddle_tpu.core import random as prandom

        m = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
        opt = optim.Adam(parameters=m.parameters(), learning_rate=1e-3)

        def loss_fn(model, x, y):
            d = model(x) - y
            return (d * d).mean()

        step = TrainStep(m, opt, loss_fn)
        x = np.zeros((16, 32), np.float32)
        y = np.zeros((16, 8), np.float32)
        step(x, y)
        fn = next(iter(step._compiled.values()))
        opt_state = {p.name: opt._accumulators[p.name]
                     for p in step._trainable}
        lowered = fn.lower([p._data for p in step._trainable],
                           [b._data for b in step._buffers], opt_state,
                           jnp.float32(1e-3), prandom.next_key(),
                           [jnp.asarray(x), jnp.asarray(y)], {})
        comp = lowered.compile()
        n_leaves = len(step._trainable) + len(step._buffers) + sum(
            len(v) for v in opt_state.values())
        return comp, n_leaves

    def test_all_params_and_state_aliased(self):
        comp, n_leaves = self._compiled_step()
        txt = comp.as_text()
        assert txt.count("may-alias") == n_leaves, \
            f"{txt.count('may-alias')} aliased of {n_leaves} donated leaves"
        ma = comp.memory_analysis()
        # aliased bytes must cover the params+state (less scalar slack):
        # if donation regressed, alias_size collapses and the step
        # double-buffers every parameter in HBM
        assert ma.alias_size_in_bytes >= 0.9 * ma.output_size_in_bytes

    def test_no_fullsize_copies_of_optimizer_state(self):
        comp, _ = self._compiled_step()
        txt = comp.as_text()
        bad = [ln for ln in txt.splitlines()
               if re.search(r"\w+\[\d[0-9,]*\]\S* copy\(\S*opt_state", ln)]
        assert not bad, "moment buffers copied instead of updated " \
            f"in place:\n" + "\n".join(bad[:5])


class TestFusedDecodeHLO:
    def test_beam_xla_single_while_no_host_transfers(self):
        from paddle_tpu.inference.decoder import beam_search_xla

        V, B, K, L = 11, 2, 3, 8

        def run(table):
            def step_fn(cur, state, t):
                logits = pt.Tensor(
                    jnp.tile(table, (cur.shape[0], 1)), _internal=True)
                return logits, state

            toks, scores = beam_search_xla(step_fn, None, B, bos_id=0,
                                           eos_id=1, beam_size=K, max_len=L)
            return toks._data, scores._data

        table = jnp.linspace(0.0, 1.0, V)
        txt = jax.jit(run).lower(table).compile().as_text()
        # op defs look like `%while.2 = (<tuple shape>) while(%tuple.N)`;
        # metadata op_names only ever contain "/while/" so ' while(' is
        # unambiguous
        n_while = txt.count(" while(")
        assert n_while == 1, f"expected ONE fused decode loop, got {n_while}"
        for marker in ("infeed", "outfeed", " send(", " recv(",
                       "SendToHost", "RecvFromHost"):
            assert marker not in txt, f"host transfer {marker!r} in decode"
