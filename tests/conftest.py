"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware isn't available in CI; XLA's host platform can fake
N devices, which exercises the exact same SPMD partitioner + collective
lowering paths our Mesh code uses on a real pod.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np
import pytest

import jax

# The axon sitecustomize force-selects the tunneled TPU backend via
# jax.config; hard-override back to CPU *before* any backend client is
# created so the suite never depends on (or competes for) the TPU tunnel.
jax.config.update("jax_platforms", "cpu")

# test-only: exact f32 matmuls so numerical comparisons vs numpy are tight
# (the production TPU path keeps the fast default so the MXU runs bf16)
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
