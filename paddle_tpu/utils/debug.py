"""Debug dumps: program graphs and compiler IR.

Ref (capability target): python/paddle/fluid/graphviz.py (GraphPreviewGenerator),
debugger.py (draw_block_graphviz), and the reference's habit of printing
ProgramDesc text. TPU-native additions: jaxpr and XLA-HLO dumps of any
traceable callable — the IRs that actually matter on this backend.
"""
from __future__ import annotations

import os

__all__ = ["program_to_dot", "draw_program", "dump_jaxpr", "dump_hlo"]


def _esc(s):
    return str(s).replace('"', '\\"')


def program_to_dot(program, graph_name="program", max_label=40):
    """Render a static Program's op/var graph as graphviz dot text
    (ref: debugger.py draw_block_graphviz).

    Vars are ellipses (persistables shaded), ops are boxes; edges follow
    input/output names through the single global block.
    """
    lines = [f'digraph "{_esc(graph_name)}" {{',
             "  rankdir=TB;",
             '  node [fontsize=10, fontname="Helvetica"];']
    blk = program.global_block
    seen_vars = set()

    def var_node(name):
        if name in seen_vars or name is None:
            return
        seen_vars.add(name)
        style = ""
        if blk.has_var(name):
            v = blk.var(name)
            shape = getattr(v, "shape", None)
            label = f"{name}\\n{list(shape) if shape is not None else ''}"
            if getattr(v, "persistable", False):
                style = ', style=filled, fillcolor="lightsteelblue"'
        else:
            label = name
        lines.append(
            f'  "v_{_esc(name)}" [label="{_esc(label[:max_label])}", '
            f"shape=ellipse{style}];")

    for i, op in enumerate(blk.ops):
        label = op.type[:max_label]
        lines.append(
            f'  "op_{i}" [label="{_esc(label)}", shape=box, '
            'style=filled, fillcolor="honeydew"];')
        for n in op.input_names:
            if n is not None:
                var_node(n)
                lines.append(f'  "v_{_esc(n)}" -> "op_{i}";')
        for n in op.output_names:
            if n is not None:
                var_node(n)
                lines.append(f'  "op_{i}" -> "v_{_esc(n)}";')
    lines.append("}")
    return "\n".join(lines)


def draw_program(program, path, graph_name="program"):
    """Write <path> (.dot text); if graphviz's ``dot`` binary exists and
    path ends in .png/.pdf/.svg, also render it. Returns the dot path."""
    dot = program_to_dot(program, graph_name=graph_name)
    base, ext = os.path.splitext(path)
    dot_path = path if ext == ".dot" else base + ".dot"
    d = os.path.dirname(dot_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(dot_path, "w") as f:
        f.write(dot)
    if ext in (".png", ".pdf", ".svg"):
        import shutil
        import subprocess

        if shutil.which("dot"):
            subprocess.run(["dot", f"-T{ext[1:]}", dot_path, "-o", path],
                           check=False)
    return dot_path


def _purify(fn_or_layer):
    """A jax-traceable callable from a Layer (its forward with concrete
    params baked) or a plain function over Tensors/arrays."""
    from ..core import dispatch
    from ..core.tensor import Tensor
    from ..nn.layer import Layer

    if isinstance(fn_or_layer, Layer):
        layer = fn_or_layer

        def pure(*arrays):
            with dispatch.no_grad(), dispatch.fresh_tape():
                ts = [Tensor(a, _internal=True) for a in arrays]
                out = layer(*ts)
            return out._data if isinstance(out, Tensor) else out

        return pure

    def pure_fn(*arrays):
        with dispatch.no_grad(), dispatch.fresh_tape():
            ts = [Tensor(a, _internal=True) for a in arrays]
            out = fn_or_layer(*ts)
        return out._data if isinstance(out, Tensor) else out

    return pure_fn


def dump_jaxpr(fn_or_layer, *example_args, path=None):
    """The jaxpr of a Layer/function on example inputs — this backend's
    'program text' (analog of the reference's ProgramDesc dump)."""
    import jax
    import numpy as np
    import jax.numpy as jnp

    arrays = [a._data if hasattr(a, "_data") else jnp.asarray(np.asarray(a))
              for a in example_args]
    text = str(jax.make_jaxpr(_purify(fn_or_layer))(*arrays))
    if path:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(text)
    return text


def dump_hlo(fn_or_layer, *example_args, path=None, optimized=False):
    """XLA HLO for a Layer/function: what actually runs on the chip.
    ``optimized=True`` returns the post-fusion compiled module."""
    import jax
    import numpy as np
    import jax.numpy as jnp

    arrays = [a._data if hasattr(a, "_data") else jnp.asarray(np.asarray(a))
              for a in example_args]
    lowered = jax.jit(_purify(fn_or_layer)).lower(*arrays)
    if optimized:
        text = lowered.compile().as_text()
    else:
        text = lowered.as_text()
    if path:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(text)
    return text
