"""Training-curve plotting (ref: python/paddle/utils/plot.py Ploter).

Headless-first: points are recorded and savable as CSV; if matplotlib
is importable the classic .plot()/.savefig flow works too.
"""
from __future__ import annotations

__all__ = ["Ploter", "PlotData", "dump_config"]


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(float(value))

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    """ref: plot.py Ploter — named train/test curve recorder."""

    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {t: PlotData() for t in args}

    def append(self, title, step, value):
        assert title in self.__plot_data__, \
            f"{title} not in {self.__args__}"
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if path:
            self.savefig(path)
            return
        try:
            import matplotlib.pyplot as plt
        except ImportError:  # headless image: text fallback
            for title, data in self.__plot_data__.items():
                if data.value:
                    print(f"{title}: last={data.value[-1]:.6f} "
                          f"over {len(data.value)} points")
            return
        for title, data in self.__plot_data__.items():
            plt.plot(data.step, data.value, label=title)
        plt.legend()
        plt.show()

    def savefig(self, path):
        """Save curves; .csv always works, image formats need
        matplotlib."""
        if path.endswith(".csv"):
            with open(path, "w") as f:
                f.write("title,step,value\n")
                for title, data in self.__plot_data__.items():
                    for s, v in zip(data.step, data.value):
                        f.write(f"{title},{s},{v}\n")
            return path
        # object-oriented API: no global backend switch, no pyplot
        # figure registry to leak
        from matplotlib.backends.backend_agg import FigureCanvasAgg
        from matplotlib.figure import Figure

        fig = Figure()
        FigureCanvasAgg(fig)
        ax = fig.add_subplot(111)
        for title, data in self.__plot_data__.items():
            ax.plot(data.step, data.value, label=title)
        ax.legend()
        fig.savefig(path)
        return path

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()


def dump_config(obj, path=None, indent=2):
    """Serialize a config-ish object to readable text (ref:
    utils/__init__.py dump_config, protobuf-era)."""
    import json

    seen = set()

    def conv(o):
        if isinstance(o, (int, float, str, bool, type(None))):
            return o
        if id(o) in seen:  # cycle (e.g. child.parent back-references)
            return f"<cycle: {type(o).__name__}>"
        seen.add(id(o))
        try:
            if hasattr(o, "__dict__"):
                return {k: conv(v) for k, v in vars(o).items()
                        if not k.startswith("_")}
            if isinstance(o, (list, tuple)):
                return [conv(v) for v in o]
            if isinstance(o, dict):
                return {k: conv(v) for k, v in o.items()}
            return str(o)
        finally:
            seen.discard(id(o))

    text = json.dumps(conv(obj), indent=indent)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
