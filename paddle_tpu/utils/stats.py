"""Memory / model statistics from the compiled executable.

Capability refs:
- python/paddle/fluid/contrib/memory_usage_calc.py:46 ``memory_usage``
  (dtype-arithmetic estimate of a Program's memory)
- python/paddle/fluid/contrib/model_stat.py:40 ``summary`` (per-op
  param/flop table)
- python/paddle/fluid/contrib/op_frequence.py (op histogram — see
  fluid/contrib.py op_freq_statistic)

TPU-first twist: instead of re-deriving byte counts from var dtypes the
way the reference does, ``memory_usage`` compiles the program the same
way the Executor will run it and reads XLA's OWN memory analysis
(argument/output/temp/code bytes — the real HBM reservation), falling
back to the dtype estimate only when the backend doesn't expose it.
"""
from __future__ import annotations

import numpy as np

__all__ = ["compiled_stats", "memory_usage", "summary", "format_bytes"]


def format_bytes(n):
    """Human byte formatting shared by the CLI tools (``tools/`` is not
    a package, so the one copy lives here): ``None -> '?'``, exact
    integers under 1 KiB, one decimal above."""
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{int(n)}B" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}GiB"

_DTYPE_BYTES = {"float64": 8, "int64": 8, "float32": 4, "int32": 4,
                "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
                "int8": 1, "uint8": 1, "bool": 1}


def _analysis_dict(obj, keys):
    out = {}
    for k in keys:
        v = getattr(obj, k, None)
        if v is not None:
            try:
                out[k.replace("_in_bytes", "")] = int(v)
            except (TypeError, ValueError):
                pass  # backend reported a non-integral curiosity
    return out


def _scalar_value(v):
    """Best-effort float from one cost_analysis value. Backends are
    inconsistent here: TPU returns plain floats, CPU has been seen
    returning numpy scalars, 0-d arrays, and LIST-valued entries (one
    element per computation) — sum those, since per-computation costs
    add. Returns None for anything non-numeric."""
    if isinstance(v, (list, tuple)):
        parts = [f for f in (_scalar_value(x) for x in v) if f is not None]
        return sum(parts) if parts else None
    if isinstance(v, bool):
        return None
    try:
        if np.isscalar(v) or (hasattr(v, "shape") and np.asarray(v).size == 1):
            return float(np.asarray(v).reshape(()))
    except (TypeError, ValueError):
        return None
    return None


def _cost_dict(ca):
    """Normalize a ``compiled.cost_analysis()`` result to
    {str: float}. Tolerates None, a dict, a dict-like, a LIST of dicts
    (per-computation: summed key-wise), and junk values inside."""
    if ca is None:
        return {}
    if isinstance(ca, (list, tuple)):
        out = {}
        for d in ca:
            if not hasattr(d, "items"):
                continue
            for k, v in d.items():
                f = _scalar_value(v)
                if f is not None:
                    out[k] = out.get(k, 0.0) + f
        return out
    try:
        items = dict(ca).items()
    except (TypeError, ValueError):
        return {}
    out = {}
    for k, v in items:
        f = _scalar_value(v)
        if f is not None:
            out[k] = f
    return out


def compiled_stats(fn, *example_args):
    """Compile ``fn`` (a jax-traceable callable) for the current backend
    and return {"memory": {...bytes...}, "cost": {...}} from XLA's
    memory_analysis()/cost_analysis(). Values that the backend does not
    report are simply absent."""
    import jax

    lowered = jax.jit(fn).lower(*example_args)
    compiled = lowered.compile()
    out = {"memory": {}, "cost": {}}
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out["memory"] = _analysis_dict(ma, (
                "argument_size_in_bytes", "output_size_in_bytes",
                "temp_size_in_bytes", "alias_size_in_bytes",
                "generated_code_size_in_bytes"))
            if out["memory"]:
                out["memory"]["total"] = sum(
                    v for k, v in out["memory"].items()
                    if k != "generated_code_size")
    except Exception:
        pass
    try:
        out["cost"] = _cost_dict(compiled.cost_analysis())
    except Exception:
        pass
    return out


def _program_feed_zeros(program, batch_size):
    feed = {}
    for v in program.global_block.vars.values():
        if getattr(v, "is_data", False):
            # dynamic (-1/None) dims default to 1 when no batch_size is
            # given (the fluid reference requires batch_size; measuring
            # a 1-batch program is the graceful analog)
            shape = [(batch_size or 1) if s in (-1, None) else s
                     for s in v.shape]
            if batch_size and len(shape) >= 1:
                shape[0] = batch_size
            dt = str(getattr(v, "dtype", "float32"))
            feed[v.name] = np.zeros(shape, dt.replace("paddle.", ""))
    return feed


def memory_usage(program, batch_size=None, fetch_list=None):
    """Measured memory usage of a static Program (ref:
    memory_usage_calc.py:46 — there an estimate; here the compiled
    executable's real reservation). Returns (min_bytes, max_bytes,
    "B") where min==max when XLA reports exact numbers, or the
    reference-style dtype estimate (min = 0.8x, max = 1.2x) when it
    doesn't."""
    import jax

    from ..static_.executor import Executor
    from ..static_.program import global_scope

    feed = _program_feed_zeros(program, batch_size)
    fetch = fetch_list if fetch_list is not None else []
    if not fetch:  # fetch every non-persistable op output still alive
        names = [v.name for v in program.global_block.vars.values()
                 if not v.persistable and not getattr(v, "is_data", False)]
        fetch = names[-1:] if names else []
    exe = Executor()
    compiled = exe._compile(program, feed, fetch)
    scope = global_scope()

    def struct(name):
        arr = scope.find_var(name)
        return jax.ShapeDtypeStruct(tuple(np.asarray(arr).shape),
                                    np.asarray(arr).dtype)

    feeds = [jax.ShapeDtypeStruct(feed[n].shape, feed[n].dtype)
             for n in compiled.feed_names]
    upd = [struct(n) for n in compiled.updated]
    frz = [struct(n) for n in compiled.frozen]
    try:
        # AOT-hydrated entries (runtime.aot) hold the Compiled directly
        c = compiled.fn if not hasattr(compiled.fn, "lower") \
            else compiled.fn.lower(feeds, upd, frz).compile()
        ma = c.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        d = _analysis_dict(ma, ("argument_size_in_bytes",
                                "output_size_in_bytes",
                                "temp_size_in_bytes"))
        total = sum(d.values())
        if total:
            return total, total, "B"
    # estimate fallback: the reference's dtype arithmetic
    total = 0
    for v in program.global_block.vars.values():
        shape = [(batch_size or 1) if s in (-1, None) else s
                 for s in v.shape]
        n = int(np.prod([abs(int(s)) for s in shape])) if shape else 1
        total += n * _DTYPE_BYTES.get(str(v.dtype), 4)
    return int(total * 0.8), int(total * 1.2), "B"


def _layer_flops(layer, in_shape, out_shape, custom_ops=None):
    if custom_ops:
        for cls, fn in custom_ops.items():
            if isinstance(layer, cls):
                return int(fn(layer, in_shape, out_shape))
    name = type(layer).__name__
    if name in ("Conv2D", "Conv1D", "Conv3D"):
        k = int(np.prod(layer._kernel_size))
        cin = layer._in_channels // layer._groups
        return 2 * int(np.prod(out_shape)) * k * cin
    if name == "Linear":
        return 2 * int(np.prod(out_shape)) * int(layer.weight.shape[0])
    return 0


def summary(layer, input_shapes, dtypes="float32", print_table=True,
            custom_ops=None):
    """Per-layer param/FLOP table for an nn.Layer (ref: model_stat.py:40
    summary — there a Program walk; here forward hooks capture real
    shapes). ``input_shapes``: one shape tuple or a list of them;
    ``custom_ops``: {LayerClass: fn(layer, in_shape, out_shape) -> flops}
    for layers the built-in Conv/Linear rules don't cover.
    Returns {"total_params", "total_flops", "rows"}."""
    from ..core.tensor import Tensor

    # normalize 2.x dynamic-batch conventions: a lone shape whose first
    # dim is None/-1 (e.g. (None, 1, 28, 28)) is ONE shape, and dynamic
    # dims probe with batch=1 (ref: model_stat substitutes 1 likewise)
    if isinstance(input_shapes[0], int) or input_shapes[0] in (None, -1):
        input_shapes = [input_shapes]
    input_shapes = [tuple(1 if s in (None, -1) else int(s) for s in shp)
                    for shp in input_shapes]
    if isinstance(dtypes, str):
        dtypes = [dtypes] * len(input_shapes)
    rows = []
    handles = []
    counted = set()  # modules fired more than once (weight sharing)
    # count params only on their first firing

    def hook(sub):
        def fn(mod, inputs, output):
            ins = inputs[0].shape if inputs and hasattr(inputs[0], "shape") \
                else None
            outs = output.shape if hasattr(output, "shape") else None
            # own params only — composite layers can hold direct params
            # (e.g. a bias created on the model itself); sublayer params
            # are counted by the sublayer's own row
            n_params = 0
            if id(mod) not in counted:
                counted.add(id(mod))
                n_params = sum(
                    int(np.prod(p.shape)) if len(p.shape) else 1
                    for p in mod.parameters(include_sublayers=False))
            rows.append({"layer": type(mod).__name__,
                         "output_shape": tuple(outs) if outs else None,
                         "params": n_params,
                         "flops": _layer_flops(mod, ins, outs,
                                               custom_ops)})

        return fn

    for sub in [layer] + list(layer.sublayers(include_self=False)):
        handles.append(sub.register_forward_post_hook(hook(sub)))
    was_training = layer.training
    layer.eval()
    try:
        xs = [Tensor(np.zeros(s, d)) for s, d in zip(input_shapes, dtypes)]
        layer(*xs)
    finally:
        if was_training:
            layer.train()
        for h in handles:
            h.remove()
    total_p = sum(r["params"] for r in rows)
    total_f = sum(r["flops"] for r in rows)
    trainable = sum(
        int(np.prod(p.shape)) if len(p.shape) else 1
        for p in layer.parameters() if getattr(p, "trainable", True))
    if print_table:
        for r in rows:
            print(f"{r['layer']:<20} {str(r['output_shape']):<24} "
                  f"{r['params']:>12,} {r['flops']:>16,}")
        print(f"Total params: {total_p:,}  Total FLOPs/fwd: {total_f:,}")
    return {"total_params": total_p, "trainable_params": trainable,
            "total_flops": total_f, "rows": rows}
