"""Profiler (ref: python/paddle/fluid/profiler.py — profiler context,
start/stop, per-op timing report).

TPU-native: three layers.
- ``profiler()`` / start_profiler / stop_profiler wrap ``jax.profiler``
  traces (view in TensorBoard / xprof — this is where XLA fusion and MXU
  utilization actually show up; the reference's per-CUDA-kernel timers
  have no TPU analog because the whole step is one executable) AND turn
  on ``paddle_tpu.obs`` span tracing for the window, so the host-side
  timeline (compiles, runs, dataloader waits) records real spans —
  exportable via ``obs.export_chrome_trace``.
- ``span(...)`` re-exported from ``obs.trace`` for ad-hoc host ranges
  (the role nvprof ranges play in the reference).
- ``StepTimer`` / ``add_profiler_step`` give the host-side per-step
  wall-clock stats the reference prints (min/max/mean, imgs-per-sec),
  rebased on the ``obs.metrics`` registry: every step also lands in the
  process-wide ``step_timer.step_ms`` histogram.
"""
from __future__ import annotations

import contextlib
import os
import time

import numpy as np

from ..obs import journal as _journal
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs.trace import span  # noqa: F401  (re-export)

__all__ = ["profiler", "start_profiler", "stop_profiler",
           "add_profiler_step", "StepTimer", "cuda_profiler", "span"]

_trace_dir = None
_window = None  # (span-cm, tracing-was-enabled-before)


def start_profiler(state=None, tracer_option=None, log_dir="/tmp/pt_profile"):
    """ref: profiler.start_profiler. Starts a jax.profiler trace and
    enables obs span tracing for the window."""
    global _trace_dir, _window
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _trace_dir = log_dir
    was_on = _trace.tracing_enabled()
    _trace.enable_tracing()
    sp = _trace.span("profiler.window", log_dir=log_dir)
    sp.__enter__()
    _window = (sp, was_on)


def stop_profiler(sorted_key=None, profile_path=None):
    """ref: profiler.stop_profiler. Ends the trace; returns the dir.
    Span tracing reverts to its pre-window state (env ``PADDLE_TPU_TRACE``
    keeps it on)."""
    global _trace_dir, _window
    import jax

    jax.profiler.stop_trace()
    if _window is not None:
        sp, was_on = _window
        sp.__exit__(None, None, None)
        if not was_on:
            _trace.disable_tracing()
        _window = None
    d, _trace_dir = _trace_dir, None
    return d


@contextlib.contextmanager
def profiler(state=None, sorted_key=None, profile_path=None,
             log_dir="/tmp/pt_profile"):
    """ref: profiler.profiler context manager."""
    start_profiler(state, log_dir=log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """API-parity shim: there is no CUDA on TPU; this is a no-op trace."""
    yield


class StepTimer:
    """Host-side per-step timing (the reference's profiler report numbers).

    Exact wall-times stay local (so ``summary()`` percentiles are exact,
    not bucket-interpolated); each step is additionally observed into the
    shared ``obs.metrics`` histogram named ``<name>.step_ms`` so the
    process-wide report sees training cadence without a StepTimer
    reference.

    >>> t = StepTimer()
    >>> for batch in loader:
    ...     with t.step():
    ...         loss = train_step(*batch)
    >>> t.summary()   # {'steps': N, 'mean_ms': ..., 'p50_ms': ...}
    """

    def __init__(self, skip_first=1, name="step_timer"):
        self.skip_first = skip_first
        self.times = []
        self._seen = 0
        self._hist = _metrics.histogram(f"{name}.step_ms")

    @contextlib.contextmanager
    def step(self):
        t0 = time.perf_counter()
        yield
        dt = time.perf_counter() - t0
        self._seen += 1
        if self._seen > self.skip_first:
            self.times.append(dt)
            self._hist.observe(dt * 1e3)
            if _journal.ACTIVE is not None:  # feeds the next step record
                _journal.ACTIVE.note_step_ms(dt * 1e3)

    def summary(self):
        if not self.times:
            return {"steps": 0}
        a = np.asarray(self.times) * 1e3
        return {"steps": len(a), "mean_ms": float(a.mean()),
                "p50_ms": float(np.percentile(a, 50)),
                "p90_ms": float(np.percentile(a, 90)),
                "p99_ms": float(np.percentile(a, 99)),
                "max_ms": float(a.max())}

    def reset(self):
        self.times.clear()
        self._seen = 0


_step_timer = StepTimer()


def add_profiler_step(*a, **k):
    """ref: profiler.add_profiler_step hook for Executor loops."""
    return _step_timer


def reset_profiler():
    """ref: fluid/profiler.py reset_profiler: drop collected records.
    jax.profiler traces are per start/stop window, so this is a no-op
    between windows; StepTimer state resets explicitly via .reset()."""
    return None
