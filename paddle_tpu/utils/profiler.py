"""Profiler (ref: python/paddle/fluid/profiler.py — profiler context,
start/stop, per-op timing report).

TPU-native: two layers.
- ``profiler()`` / start_profiler / stop_profiler wrap ``jax.profiler``
  traces (view in TensorBoard / xprof — this is where XLA fusion and MXU
  utilization actually show up; the reference's per-CUDA-kernel timers
  have no TPU analog because the whole step is one executable).
- ``StepTimer`` / ``add_profiler_step`` give the host-side per-step
  wall-clock stats the reference prints (min/max/mean, imgs-per-sec).
"""
from __future__ import annotations

import contextlib
import os
import time

import numpy as np

__all__ = ["profiler", "start_profiler", "stop_profiler",
           "add_profiler_step", "StepTimer", "cuda_profiler"]

_trace_dir = None


def start_profiler(state=None, tracer_option=None, log_dir="/tmp/pt_profile"):
    """ref: profiler.start_profiler. Starts a jax.profiler trace."""
    global _trace_dir
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _trace_dir = log_dir


def stop_profiler(sorted_key=None, profile_path=None):
    """ref: profiler.stop_profiler. Ends the trace; returns the dir."""
    global _trace_dir
    import jax

    jax.profiler.stop_trace()
    d, _trace_dir = _trace_dir, None
    return d


@contextlib.contextmanager
def profiler(state=None, sorted_key=None, profile_path=None,
             log_dir="/tmp/pt_profile"):
    """ref: profiler.profiler context manager."""
    start_profiler(state, log_dir=log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """API-parity shim: there is no CUDA on TPU; this is a no-op trace."""
    yield


class StepTimer:
    """Host-side per-step timing (the reference's profiler report numbers).

    >>> t = StepTimer()
    >>> for batch in loader:
    ...     with t.step():
    ...         loss = train_step(*batch)
    >>> t.summary()   # {'steps': N, 'mean_ms': ..., 'p50_ms': ...}
    """

    def __init__(self, skip_first=1):
        self.skip_first = skip_first
        self.times = []
        self._seen = 0

    @contextlib.contextmanager
    def step(self):
        t0 = time.perf_counter()
        yield
        dt = time.perf_counter() - t0
        self._seen += 1
        if self._seen > self.skip_first:
            self.times.append(dt)

    def summary(self):
        if not self.times:
            return {"steps": 0}
        a = np.asarray(self.times) * 1e3
        return {"steps": len(a), "mean_ms": float(a.mean()),
                "p50_ms": float(np.percentile(a, 50)),
                "p90_ms": float(np.percentile(a, 90)),
                "max_ms": float(a.max())}

    def reset(self):
        self.times.clear()
        self._seen = 0


_step_timer = StepTimer()


def add_profiler_step(*a, **k):
    """ref: profiler.add_profiler_step hook for Executor loops."""
    return _step_timer


def reset_profiler():
    """ref: fluid/profiler.py reset_profiler: drop collected records.
    jax.profiler traces are per start/stop window, so this is a no-op
    between windows; StepTimer state resets explicitly via .reset()."""
    return None
