"""The shared env-spec grammar: ``"name:key=val,key=val;name2"``.

One parser for every env knob that configures a registry of named
things — chaos points (``PADDLE_TPU_CHAOS``), anomaly detectors
(``PADDLE_TPU_ANOMALY``). Values coerce int -> float -> str.
Stdlib-only: both ``resilience.inject`` and ``obs.anomaly`` import this
at module load, so it must never pull jax or another paddle_tpu
subsystem.
"""
from __future__ import annotations

__all__ = ["parse_scalar", "parse_spec"]


def parse_scalar(s):
    try:
        return int(s)
    except ValueError:
        try:
            return float(s)
        except ValueError:
            return s


def parse_spec(spec):
    """``"a:x=1,y=2;b"`` -> ``[("a", {"x": 1, "y": 2}), ("b", {})]``."""
    out = []
    for entry in filter(None, (e.strip() for e in (spec or "").split(";"))):
        name, _, rest = entry.partition(":")
        cfg = {}
        for kv in filter(None, (p.strip() for p in rest.split(","))):
            k, _, v = kv.partition("=")
            cfg[k.strip()] = parse_scalar(v.strip())
        out.append((name.strip(), cfg))
    return out
