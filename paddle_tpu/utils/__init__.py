"""Utility subpackage (ref: python/paddle/fluid/unique_name.py, utils/)."""
from . import unique_name  # noqa: F401
from .plot import Ploter, PlotData, dump_config  # noqa: F401
from . import stats  # noqa: F401
from .stats import compiled_stats, memory_usage, summary  # noqa: F401
