"""Utility subpackage (ref: python/paddle/fluid/unique_name.py, utils/)."""
from . import unique_name  # noqa: F401
