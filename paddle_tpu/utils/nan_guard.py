"""NaN/Inf detection (ref: FLAGS_check_nan_inf consumed in
paddle/fluid/framework/operator.cc:41 — after every op kernel the runtime
scans outputs and aborts naming the op).

Two modes, matching how TPU programs actually run:
- eager debug mode (``enable_check_nan()``): the dispatcher host-checks
  every op's outputs right after execution and raises with the op name —
  the direct analog of the reference flag. Forces a device sync per op, so
  debug-only.
- fused-step mode (``TrainStep(check_nan=True)``): the compiled step
  returns a found-nonfinite flag computed on-device (loss + grads); the
  host raises after the step. No per-op sync, usable in real training.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["enable_check_nan", "disable_check_nan", "check_nan_enabled",
           "check_numerics", "NanInfError", "nonfinite_summary"]

_ENABLED = False


class NanInfError(FloatingPointError):
    """Nonfinite value detected. ``summary`` (also attached as an
    attribute) carries the bounded postmortem record built by
    ``nonfinite_summary``: counts, first bad flat index, finite range —
    the producing values are gone by the time the error unwinds, so this
    is what makes the report actionable."""

    def __init__(self, msg, summary=None):
        super().__init__(msg)
        self.summary = dict(summary or {})


def nonfinite_summary(value):
    """Bounded description of the nonfinite content of one array: counts,
    the first bad flat index, and the finite min/max — O(n) scan, O(1)
    output, so it is safe to compute on any tensor at fault time."""
    a = np.asarray(value)  # ONE device->host transfer
    dtype = str(a.dtype)
    if a.dtype.kind != "f":
        a = a.astype(np.float64)
    bad = ~np.isfinite(a)
    n_bad = int(bad.sum())
    finite = a[~bad]
    return {
        "shape": tuple(a.shape),
        "dtype": dtype,
        "num_nan": int(np.isnan(a).sum()),
        "num_inf": int(np.isinf(a).sum()),
        "first_bad_index": int(np.argmax(bad.ravel())) if n_bad else -1,
        "finite_min": float(finite.min()) if finite.size else None,
        "finite_max": float(finite.max()) if finite.size else None,
    }


def _summary_text(s):
    return (f"nan={s['num_nan']} inf={s['num_inf']} "
            f"first_bad_flat_index={s['first_bad_index']} "
            f"finite_range=[{s['finite_min']}, {s['finite_max']}]")


def enable_check_nan():
    """Turn on per-op NaN/Inf checking in eager mode."""
    global _ENABLED
    _ENABLED = True


def disable_check_nan():
    global _ENABLED
    _ENABLED = False


def check_nan_enabled():
    return _ENABLED


def _bad(arr):
    return jnp.issubdtype(arr.dtype, jnp.inexact) and \
        bool(jnp.any(~jnp.isfinite(arr)))


def check_numerics(value, name="tensor"):
    """Raise NanInfError if any leaf of ``value`` holds NaN/Inf.

    Accepts arrays, Tensors, or nested lists/tuples/dicts of them.
    """
    from ..core.tensor import Tensor

    def walk(v, path):
        if isinstance(v, Tensor):
            v = v._data
        if isinstance(v, dict):
            for k, x in v.items():
                walk(x, f"{path}.{k}")
        elif isinstance(v, (list, tuple)):
            for i, x in enumerate(v):
                walk(x, f"{path}[{i}]")
        elif hasattr(v, "dtype"):
            if _bad(v):
                s = nonfinite_summary(v)
                raise NanInfError(
                    f"NaN/Inf found in {path}: shape={tuple(v.shape)} "
                    f"{_summary_text(s)}", summary=s)

    walk(value, name)
    return value


def check_op_outputs(name, outs):
    """Dispatcher hook: eager per-op check (debug flag on). Raises on the
    FIRST nonfinite op with a bounded summary of the producing values —
    they are freed once the error unwinds, so this is the postmortem."""
    for i, o in enumerate(outs):
        if hasattr(o, "dtype") and _bad(o):
            s = nonfinite_summary(o)
            raise NanInfError(
                f"op '{name}' produced NaN/Inf in output {i} "
                f"(shape={tuple(o.shape)}) {_summary_text(s)} — "
                f"reference analog: FLAGS_check_nan_inf", summary=s)
