"""NaN/Inf detection (ref: FLAGS_check_nan_inf consumed in
paddle/fluid/framework/operator.cc:41 — after every op kernel the runtime
scans outputs and aborts naming the op).

Two modes, matching how TPU programs actually run:
- eager debug mode (``enable_check_nan()``): the dispatcher host-checks
  every op's outputs right after execution and raises with the op name —
  the direct analog of the reference flag. Forces a device sync per op, so
  debug-only.
- fused-step mode (``TrainStep(check_nan=True)``): the compiled step
  returns a found-nonfinite flag computed on-device (loss + grads); the
  host raises after the step. No per-op sync, usable in real training.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["enable_check_nan", "disable_check_nan", "check_nan_enabled",
           "check_numerics", "NanInfError"]

_ENABLED = False


class NanInfError(FloatingPointError):
    pass


def enable_check_nan():
    """Turn on per-op NaN/Inf checking in eager mode."""
    global _ENABLED
    _ENABLED = True


def disable_check_nan():
    global _ENABLED
    _ENABLED = False


def check_nan_enabled():
    return _ENABLED


def _bad(arr):
    return jnp.issubdtype(arr.dtype, jnp.inexact) and \
        bool(jnp.any(~jnp.isfinite(arr)))


def check_numerics(value, name="tensor"):
    """Raise NanInfError if any leaf of ``value`` holds NaN/Inf.

    Accepts arrays, Tensors, or nested lists/tuples/dicts of them.
    """
    from ..core.tensor import Tensor

    def walk(v, path):
        if isinstance(v, Tensor):
            v = v._data
        if isinstance(v, dict):
            for k, x in v.items():
                walk(x, f"{path}.{k}")
        elif isinstance(v, (list, tuple)):
            for i, x in enumerate(v):
                walk(x, f"{path}[{i}]")
        elif hasattr(v, "dtype"):
            if _bad(v):
                n_nan = int(jnp.sum(jnp.isnan(v)))
                n_inf = int(jnp.sum(jnp.isinf(v)))
                raise NanInfError(
                    f"NaN/Inf found in {path}: shape={tuple(v.shape)} "
                    f"nan={n_nan} inf={n_inf}")

    walk(value, name)
    return value


def check_op_outputs(name, outs):
    """Dispatcher hook: eager per-op check (debug flag on)."""
    for i, o in enumerate(outs):
        if hasattr(o, "dtype") and _bad(o):
            raise NanInfError(
                f"op '{name}' produced NaN/Inf in output {i} "
                f"(shape={tuple(o.shape)}) — reference analog: "
                f"FLAGS_check_nan_inf")
