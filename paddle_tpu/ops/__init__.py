"""Op library: every op is a pure jax kernel in the OP_REGISTRY.

Analog of the reference's ``paddle/fluid/operators`` — but instead of ~500
hand-written CPU/CUDA kernels, ops are jnp/lax compositions that XLA fuses.
``monkey_patch_tensor()`` attaches the rich method/dunder API onto Tensor
(ref: python/paddle/fluid/dygraph/math_op_patch.py monkey_patch_math_varbase).
"""
from __future__ import annotations

from ._base import OP_REGISTRY, apply, register
from . import (  # noqa: F401
    math,
    creation,
    manipulation,
    reduction,
    compare,
    activation,
    linalg,
    conv,
    norm_ops,
    sequence,
    control_flow,
    random_ops,
    detection,
    rcnn,
    labeling,
    misc,
)
from ..core.tensor import Tensor


def _rops():
    from .math import (
        add, subtract, multiply, divide, floor_divide, remainder, pow as _pow,
        matmul,
    )
    from .compare import (
        equal, not_equal, less_than, less_equal, greater_than, greater_equal,
    )

    def _swap(fn):
        return lambda self, other: fn(other, self)

    Tensor.__add__ = add
    Tensor.__radd__ = _swap(add)
    Tensor.__sub__ = subtract
    Tensor.__rsub__ = _swap(subtract)
    Tensor.__mul__ = multiply
    Tensor.__rmul__ = _swap(multiply)
    Tensor.__truediv__ = divide
    Tensor.__rtruediv__ = _swap(divide)
    Tensor.__floordiv__ = floor_divide
    Tensor.__rfloordiv__ = _swap(floor_divide)
    Tensor.__mod__ = remainder
    Tensor.__rmod__ = _swap(remainder)
    Tensor.__pow__ = _pow
    Tensor.__rpow__ = _swap(_pow)
    Tensor.__matmul__ = matmul
    Tensor.__rmatmul__ = _swap(matmul)
    Tensor.__neg__ = lambda self: math.neg(self)
    Tensor.__abs__ = lambda self: math.abs(self)
    Tensor.__invert__ = lambda self: compare.logical_not(self)
    Tensor.__eq__ = equal
    Tensor.__ne__ = not_equal
    Tensor.__lt__ = less_than
    Tensor.__le__ = less_equal
    Tensor.__gt__ = greater_than
    Tensor.__ge__ = greater_equal
    Tensor.__and__ = compare.logical_and
    Tensor.__or__ = compare.logical_or
    Tensor.__xor__ = compare.logical_xor


# Flat namespace: every public op is reachable as ``ops.<name>`` (analog of
# the reference's single fluid.layers namespace). Submodule attributes and
# registry infrastructure keep precedence.
def _flatten_namespace():
    import types

    g = globals()
    skip = {"apply", "register", "Tensor", "unwrap", "convert_dtype",
            "OP_REGISTRY"}
    for mod in (math, creation, manipulation, reduction, compare, activation,
                linalg, conv, norm_ops, sequence, control_flow, random_ops,
                detection, rcnn, labeling, misc):
        public = getattr(mod, "__all__", None) or [
            n for n in dir(mod) if not n.startswith("_")]
        for n in public:
            v = getattr(mod, n)
            if n in skip or isinstance(v, types.ModuleType) or n in g:
                continue
            g[n] = v


_flatten_namespace()

_METHODS = {}


def monkey_patch_tensor():
    _rops()
    from . import math as m, reduction as r, manipulation as mp, activation as a
    from . import linalg as la, compare as cm, creation as cr

    methods = dict(
        # math
        add=m.add, subtract=m.subtract, multiply=m.multiply, divide=m.divide,
        matmul=m.matmul, mm=m.mm, bmm=m.bmm, dot=m.dot, pow=m.pow,
        exp=m.exp, log=m.log, log2=m.log2, log10=m.log10, log1p=m.log1p,
        sqrt=m.sqrt, rsqrt=m.rsqrt, abs=m.abs, floor=m.floor, ceil=m.ceil,
        round=m.round, trunc=m.trunc, sin=m.sin, cos=m.cos, tan=m.tan,
        sinh=m.sinh, cosh=m.cosh, asin=m.asin, acos=m.acos, atan=m.atan,
        erf=m.erf, sign=m.sign, reciprocal=m.reciprocal, square=m.square,
        scale=m.scale, clip=m.clip, cumsum=m.cumsum, cumprod=m.cumprod,
        maximum=m.maximum, minimum=m.minimum, remainder=m.remainder,
        mod=m.remainder, floor_divide=m.floor_divide, kron=m.kron,
        trace=m.trace, diagonal=m.diagonal, lerp=m.lerp,
        isnan=m.isnan, isinf=m.isinf, isfinite=m.isfinite,
        nan_to_num=m.nan_to_num, neg=m.neg,
        # reduction
        sum=r.sum, mean=r.mean, max=r.max, min=r.min, prod=r.prod,
        all=r.all, any=r.any, argmax=r.argmax, argmin=r.argmin,
        std=r.std, var=r.var, median=r.median, logsumexp=r.logsumexp,
        quantile=r.quantile, kthvalue=r.kthvalue, mode=r.mode,
        count_nonzero=r.count_nonzero, nansum=r.nansum, nanmean=r.nanmean,
        # manipulation
        reshape=mp.reshape, transpose=mp.transpose, flatten=mp.flatten,
        squeeze=mp.squeeze, unsqueeze=mp.unsqueeze, split=mp.split,
        chunk=mp.chunk, unbind=mp.unbind, gather=mp.gather,
        gather_nd=mp.gather_nd, scatter=mp.scatter, tile=mp.tile,
        expand=mp.expand, expand_as=mp.expand_as, broadcast_to=mp.broadcast_to,
        flip=mp.flip, roll=mp.roll, topk=mp.topk, sort=mp.sort,
        argsort=mp.argsort, index_select=mp.index_select,
        index_sample=mp.index_sample, masked_select=mp.masked_select,
        masked_fill=mp.masked_fill, where=mp.where, nonzero=mp.nonzero,
        unique=mp.unique, repeat_interleave=mp.repeat_interleave,
        moveaxis=mp.moveaxis, swapaxes=mp.swapaxes,
        take_along_axis=mp.take_along_axis, put_along_axis=mp.put_along_axis,
        # activation
        tanh=a.tanh, softmax=a.softmax, sigmoid=a.sigmoid, relu=a.relu,
        # linalg
        norm=la.norm, dist=la.dist, cholesky=la.cholesky, inverse=la.inverse,
        matrix_power=la.matrix_power, det=la.det, slogdet=la.slogdet,
        cross=la.cross, solve=la.solve, mv=la.mv, pinv=la.pinv,
        # compare
        equal=cm.equal, not_equal=cm.not_equal, less_than=cm.less_than,
        less_equal=cm.less_equal, greater_than=cm.greater_than,
        greater_equal=cm.greater_equal, logical_and=cm.logical_and,
        logical_or=cm.logical_or, logical_not=cm.logical_not,
        logical_xor=cm.logical_xor, isclose=cm.isclose, allclose=cm.allclose,
        equal_all=cm.equal_all, bitwise_and=cm.bitwise_and,
        bitwise_or=cm.bitwise_or, bitwise_xor=cm.bitwise_xor,
        bitwise_not=cm.bitwise_not,
        # creation-ish
        zeros_like=cr.zeros_like, ones_like=cr.ones_like, full_like=cr.full_like,
        tril=cr.tril, triu=cr.triu,
    )
    _METHODS.update(methods)
    for name, fn in methods.items():
        setattr(Tensor, name, fn)


monkey_patch_tensor()
