"""Linear-algebra ops.

Covers the reference's ``norm_op.cc`` (p-norm), ``cholesky_op.cc``,
``matrix_inverse``, ``svd``-family, ``dist_op.cc``, ``cross_op.cc``,
``triangular ops``, ``histogram_op.cc``.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._base import register, apply, unwrap


@register("p_norm")
def _p_norm(x, *, p=2.0, axis=None, keepdim=False):
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@register("frobenius_norm")
def _fro(x, *, axis=None, keepdim=False):
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    if p == "fro":
        return apply("frobenius_norm", x, axis=axis, keepdim=keepdim)
    return apply("p_norm", x, p=float(p), axis=axis, keepdim=keepdim)


def dist(x, y, p=2.0, name=None):
    from .math import subtract

    return norm(subtract(x, y), p=p)


@register("cholesky")
def _cholesky(x, *, upper=False):
    l = jnp.linalg.cholesky(x)
    return jnp.swapaxes(l, -1, -2) if upper else l


def cholesky(x, upper=False, name=None):
    return apply("cholesky", x, upper=upper)


@register("inverse")
def _inverse(x):
    return jnp.linalg.inv(x)


def inverse(x, name=None):
    return apply("inverse", x)


@register("matrix_power")
def _matrix_power(x, *, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_power(x, n, name=None):
    return apply("matrix_power", x, n=int(n))


@register("pinv")
def _pinv(x, *, rcond=1e-15):
    return jnp.linalg.pinv(x, rtol=rcond)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", x, rcond=rcond)


def svd(x, full_matrices=False, name=None):
    u, s, vh = jnp.linalg.svd(unwrap(x), full_matrices=full_matrices)
    return (Tensor(u, _internal=True), Tensor(s, _internal=True),
            Tensor(jnp.swapaxes(vh, -1, -2), _internal=True))


def qr(x, mode="reduced", name=None):
    q, r = jnp.linalg.qr(unwrap(x), mode=mode)
    return Tensor(q, _internal=True), Tensor(r, _internal=True)


def eig(x, name=None):
    w, v = jnp.linalg.eig(unwrap(x))
    return Tensor(w, _internal=True), Tensor(v, _internal=True)


def eigh(x, UPLO="L", name=None):
    w, v = jnp.linalg.eigh(unwrap(x), UPLO=UPLO)
    return Tensor(w, _internal=True), Tensor(v, _internal=True)


def eigvals(x, name=None):
    return Tensor(jnp.linalg.eigvals(unwrap(x)), _internal=True)


def eigvalsh(x, UPLO="L", name=None):
    return Tensor(jnp.linalg.eigvalsh(unwrap(x), UPLO=UPLO), _internal=True)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor(jnp.linalg.matrix_rank(unwrap(x), rtol=tol), _internal=True)


def det(x, name=None):
    return apply("det", x)


@register("det")
def _det(x):
    return jnp.linalg.det(x)


@register("slogdet")
def _slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return sign, logdet


def slogdet(x, name=None):
    return apply("slogdet", x)


@register("cross")
def _cross(x, y, *, axis=-1):
    return jnp.cross(x, y, axis=axis)


def cross(x, y, axis=-1, name=None):
    return apply("cross", x, y, axis=axis)


@register("triangular_solve")
def _triangular_solve(x, y, *, upper=True, transpose=False, unitriangular=False):
    a = jnp.swapaxes(x, -1, -2) if transpose else x
    return jax.scipy.linalg.solve_triangular(a, y, lower=not upper, unit_diagonal=unitriangular)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return apply("triangular_solve", x, y, upper=upper, transpose=transpose, unitriangular=unitriangular)


@register("cholesky_solve")
def _cholesky_solve(x, y, *, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


def cholesky_solve(x, y, upper=False, name=None):
    return apply("cholesky_solve", x, y, upper=upper)


@register("solve")
def _solve(x, y):
    return jnp.linalg.solve(x, y)


def solve(x, y, name=None):
    return apply("solve", x, y)


@register("lstsq_vals")
def _lstsq(x, y, *, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(unwrap(x), unwrap(y), rcond=rcond)
    return (Tensor(sol, _internal=True), Tensor(res, _internal=True),
            Tensor(rank, _internal=True), Tensor(sv, _internal=True))


def histogram(input, bins=100, min=0, max=0, name=None):
    arr = np.asarray(unwrap(input))
    if min == 0 and max == 0:
        min, max = float(arr.min()), float(arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(min, max))
    return Tensor(jnp.asarray(hist, dtype=jnp.int32), _internal=True)


@register("mv")
def _mv(x, vec):
    return jnp.matmul(x, vec)


def mv(x, vec, name=None):
    return apply("mv", x, vec)


@register("multi_dot_2")
def _multi_dot(*xs):
    return jnp.linalg.multi_dot(xs)


def multi_dot(x, name=None):
    return apply("multi_dot_2", *x)


@register("cov")
def _cov(x, *, rowvar=True, ddof=1):
    return jnp.cov(x, rowvar=rowvar, ddof=ddof)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply("cov", x, rowvar=rowvar, ddof=1 if ddof else 0)


@register("corrcoef")
def _corrcoef(x, *, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def corrcoef(x, rowvar=True, name=None):
    return apply("corrcoef", x, rowvar=rowvar)
