"""Detection op suite.

Ref (capability target): python/paddle/fluid/layers/detection.py —
iou_similarity (:657), box_coder (:711), yolov3_loss (:895), yolo_box
(:1022), prior_box (:1637), anchor_generator (:2260), box_clip (:2822),
multiclass_nms (:3020), sigmoid_focal_loss (:437) — and layers/nn.py
roi_pool (:6607) / roi_align (:6680).

TPU-native design: every op is dense and statically shaped. Where the
reference emits LoD/variable-length results (NMS output, matched boxes),
we emit fixed-capacity padded tensors plus valid counts — the XLA-correct
formulation (no dynamic shapes, no host sync). Suppression loops are
``lax.scan`` over a fixed candidate count; RoI ops vmap one pure-gather
kernel over the RoI axis so everything batches onto the MXU/VPU.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ._base import register, apply, unwrap

__all__ = [
    "iou_similarity", "box_coder", "prior_box", "anchor_generator",
    "box_clip", "multiclass_nms", "yolo_box", "yolov3_loss",
    "roi_align", "roi_pool", "sigmoid_focal_loss", "nms",
    "bipartite_match", "target_assign", "ssd_loss", "detection_output",
]


# ---------------------------------------------------------------------------
# IoU / coder
# ---------------------------------------------------------------------------


def _areas(b, norm):
    off = 0.0 if norm else 1.0
    return ((b[..., 2] - b[..., 0] + off)
            * (b[..., 3] - b[..., 1] + off))


def _pairwise_iou(x, y, norm=True):
    """x (..., N, 4), y (..., M, 4) -> (..., N, M)."""
    off = 0.0 if norm else 1.0
    xi = x[..., :, None, :]
    yi = y[..., None, :, :]
    iw = jnp.maximum(jnp.minimum(xi[..., 2], yi[..., 2])
                     - jnp.maximum(xi[..., 0], yi[..., 0]) + off, 0.0)
    ih = jnp.maximum(jnp.minimum(xi[..., 3], yi[..., 3])
                     - jnp.maximum(xi[..., 1], yi[..., 1]) + off, 0.0)
    inter = iw * ih
    union = (_areas(x, norm)[..., :, None] + _areas(y, norm)[..., None, :]
             - inter)
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register("iou_similarity")
def _iou_similarity(x, y, *, box_normalized=True):
    return _pairwise_iou(x, y, box_normalized)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU of two box sets (ref: detection.py:657).

    x: (N, 4), y: (M, 4) in [xmin, ymin, xmax, ymax] -> (N, M).
    """
    return apply("iou_similarity", x, y, box_normalized=box_normalized)


def _to_center(b, norm):
    off = 0.0 if norm else 1.0
    w = b[..., 2] - b[..., 0] + off
    h = b[..., 3] - b[..., 1] + off
    cx = b[..., 0] + w * 0.5 - (0.0 if norm else 0.5)
    cy = b[..., 1] + h * 0.5 - (0.0 if norm else 0.5)
    return cx, cy, w, h


def _box_coder(prior, pvar, target, *, code_type, box_normalized, axis):
    pcx, pcy, pw, ph = _to_center(prior, box_normalized)
    if pvar is None:
        pvar = jnp.ones((4,), prior.dtype)
    if pvar.ndim == 1:
        pvar = jnp.broadcast_to(pvar, prior.shape)
    if code_type == "encode_center_size":
        # target (N,4) vs priors (M,4) -> (N, M, 4)
        tcx, tcy, tw, th = _to_center(target, box_normalized)
        ox = (tcx[:, None] - pcx[None]) / pw[None] / pvar[None, :, 0]
        oy = (tcy[:, None] - pcy[None]) / ph[None] / pvar[None, :, 1]
        ow = jnp.log(tw[:, None] / pw[None]) / pvar[None, :, 2]
        oh = jnp.log(th[:, None] / ph[None]) / pvar[None, :, 3]
        return jnp.stack([ox, oy, ow, oh], axis=-1)
    # decode_center_size: target (N, M, 4) deltas (or (N,4) broadcast on
    # ``axis``) -> boxes
    if target.ndim == 2:
        target = target[:, None, :] if axis == 0 else target[None, :, :]
    if axis == 0:
        pcx_, pcy_, pw_, ph_ = (v[None, :] for v in (pcx, pcy, pw, ph))
        pvar_ = pvar[None, :, :]
    else:
        pcx_, pcy_, pw_, ph_ = (v[:, None] for v in (pcx, pcy, pw, ph))
        pvar_ = pvar[:, None, :]
    cx = target[..., 0] * pvar_[..., 0] * pw_ + pcx_
    cy = target[..., 1] * pvar_[..., 1] * ph_ + pcy_
    w = jnp.exp(target[..., 2] * pvar_[..., 2]) * pw_
    h = jnp.exp(target[..., 3] * pvar_[..., 3]) * ph_
    off = 0.0 if box_normalized else 1.0
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    """Encode/decode boxes against priors (ref: detection.py:711)."""
    if prior_box_var is None:
        return apply("box_coder", prior_box, target_box,
                     code_type=code_type, box_normalized=box_normalized,
                     axis=axis)
    if isinstance(prior_box_var, (list, tuple)):
        prior_box_var = Tensor(jnp.asarray(prior_box_var, jnp.float32),
                               _internal=True)
    return apply("box_coder3", prior_box, prior_box_var, target_box,
                 code_type=code_type, box_normalized=box_normalized,
                 axis=axis)


@register("box_coder3")
def _box_coder3(prior, pvar, target, *, code_type, box_normalized, axis):
    return _box_coder(prior, pvar, target, code_type=code_type,
                      box_normalized=box_normalized, axis=axis)


@register("box_coder")
def _box_coder_novar(prior, target, *, code_type, box_normalized, axis):
    return _box_coder(prior, None, target, code_type=code_type,
                      box_normalized=box_normalized, axis=axis)


# ---------------------------------------------------------------------------
# priors / anchors
# ---------------------------------------------------------------------------


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes for one feature map (ref: detection.py:1637).

    input: (B, C, H, W) feature map; image: (B, C, IH, IW).
    Returns (boxes (H, W, P, 4), variances (H, W, P, 4)), normalized.
    """
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    min_sizes = [float(m) for m in np.atleast_1d(min_sizes)]
    max_sizes = [float(m) for m in np.atleast_1d(max_sizes)] \
        if max_sizes else []
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh

    whs = []
    for k, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                big = np.sqrt(ms * max_sizes[k])
                whs.append((big, big))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                big = np.sqrt(ms * max_sizes[k])
                whs.append((big, big))
    whs = np.asarray(whs, np.float32)  # (P, 2) pixel units

    cx = (np.arange(fw, dtype=np.float32) + offset) * step_w
    cy = (np.arange(fh, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)  # (H, W)
    boxes = np.empty((fh, fw, len(whs), 4), np.float32)
    boxes[..., 0] = (cxg[..., None] - whs[None, None, :, 0] / 2) / iw
    boxes[..., 1] = (cyg[..., None] - whs[None, None, :, 1] / 2) / ih
    boxes[..., 2] = (cxg[..., None] + whs[None, None, :, 0] / 2) / iw
    boxes[..., 3] = (cyg[..., None] + whs[None, None, :, 1] / 2) / ih
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(np.asarray(variance, np.float32),
                            boxes.shape).copy()
    return (Tensor(jnp.asarray(boxes), _internal=True),
            Tensor(jnp.asarray(vars_), _internal=True))


def anchor_generator(input, anchor_sizes, aspect_ratios, variance=(
        0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0), offset=0.5, name=None):
    """RPN anchors for one feature map (ref: detection.py:2260).

    Returns (anchors (H, W, A, 4) in PIXEL coords, variances alike).
    """
    fh, fw = int(input.shape[2]), int(input.shape[3])
    whs = []
    for size in np.atleast_1d(anchor_sizes):
        area = float(size) ** 2
        for ar in np.atleast_1d(aspect_ratios):
            w = np.sqrt(area / ar)
            whs.append((w, w * ar))
    whs = np.asarray(whs, np.float32)
    cx = (np.arange(fw, dtype=np.float32) + offset) * stride[0]
    cy = (np.arange(fh, dtype=np.float32) + offset) * stride[1]
    cxg, cyg = np.meshgrid(cx, cy)
    anchors = np.empty((fh, fw, len(whs), 4), np.float32)
    anchors[..., 0] = cxg[..., None] - whs[None, None, :, 0] / 2
    anchors[..., 1] = cyg[..., None] - whs[None, None, :, 1] / 2
    anchors[..., 2] = cxg[..., None] + whs[None, None, :, 0] / 2
    anchors[..., 3] = cyg[..., None] + whs[None, None, :, 1] / 2
    vars_ = np.broadcast_to(np.asarray(variance, np.float32),
                            anchors.shape).copy()
    return (Tensor(jnp.asarray(anchors), _internal=True),
            Tensor(jnp.asarray(vars_), _internal=True))


@register("box_clip")
def _box_clip(boxes, im_info, *, _unused=None):
    # im_info rows: (height, width, scale); boxes clip to image-1 extents
    h = im_info[..., 0] / im_info[..., 2] - 1.0
    w = im_info[..., 1] / im_info[..., 2] - 1.0
    h = h.reshape((-1,) + (1,) * (boxes.ndim - 2))
    w = w.reshape((-1,) + (1,) * (boxes.ndim - 2))
    x1 = jnp.clip(boxes[..., 0], 0.0, None)
    y1 = jnp.clip(boxes[..., 1], 0.0, None)
    return jnp.stack([jnp.minimum(x1, w), jnp.minimum(y1, h),
                      jnp.minimum(jnp.clip(boxes[..., 2], 0.0, None), w),
                      jnp.minimum(jnp.clip(boxes[..., 3], 0.0, None), h)],
                     axis=-1)


def box_clip(input, im_info, name=None):
    """Clip boxes into the (possibly scaled) image extent
    (ref: detection.py:2822). input (..., 4); im_info (B, 3) [h, w, scale].
    """
    return apply("box_clip", input, im_info)


# ---------------------------------------------------------------------------
# NMS
# ---------------------------------------------------------------------------


def _greedy_nms_mask(boxes, scores, iou_threshold, normalized,
                     nms_eta=1.0):
    """Keep-mask (K,) bool of greedy NMS over score-sorted candidates.
    Static shapes: a lax.scan walks candidates best-first, suppressing by
    the IoU matrix. ``nms_eta < 1`` decays the threshold after each kept
    candidate while it exceeds 0.5 (the reference's adaptive NMS)."""
    K = boxes.shape[0]
    order = jnp.argsort(-scores)
    b_sorted = boxes[order]
    iou = _pairwise_iou(b_sorted, b_sorted, normalized)

    def body(carry, i):
        alive, thr = carry
        keep_i = alive[i]
        sup = (iou[i] > thr) & keep_i
        alive = alive & (~sup | (jnp.arange(K) <= i))
        thr = jnp.where(keep_i & (nms_eta < 1.0) & (thr > 0.5),
                        thr * nms_eta, thr)
        return (alive, thr), keep_i

    carry0 = (jnp.ones((K,), bool), jnp.float32(iou_threshold))
    _, kept_sorted = lax.scan(body, carry0, jnp.arange(K))
    # map back to original candidate order
    keep = jnp.zeros((K,), bool).at[order].set(kept_sorted)
    return keep


@register("nms")
def _nms(boxes, scores, *, iou_threshold, normalized=True):
    return _greedy_nms_mask(boxes, scores, iou_threshold, normalized)


def nms(boxes, scores, iou_threshold=0.3, normalized=True, name=None):
    """Single-class greedy NMS -> bool keep mask (N,) (static shape)."""
    return apply("nms", boxes, scores, iou_threshold=float(iou_threshold),
                 normalized=normalized)


@register("multiclass_nms")
def _multiclass_nms(bboxes, scores, *, score_threshold, nms_top_k,
                    keep_top_k, nms_threshold, normalized,
                    background_label, nms_eta=1.0):
    B, M = bboxes.shape[0], bboxes.shape[1]
    C = scores.shape[1]
    nms_top_k = min(nms_top_k, M) if nms_top_k > 0 else M
    cap = C * nms_top_k
    keep_top_k = min(keep_top_k, cap) if keep_top_k > 0 else cap

    def one_image(boxes_i, scores_i):
        # scores_i: (C, M)
        def one_class(c):
            s = scores_i[c]
            s = jnp.where(s >= score_threshold, s, -jnp.inf)
            top_s, top_i = lax.top_k(s, nms_top_k)
            cand = boxes_i[top_i]
            keep = _greedy_nms_mask(cand, top_s, nms_threshold, normalized,
                                    nms_eta)
            keep = keep & jnp.isfinite(top_s)
            if background_label >= 0:
                keep = keep & (c != background_label)
            return top_s, cand, keep, top_i

        cs = jnp.arange(C)
        top_s, cand, keep, orig = jax.vmap(one_class)(cs)
        # top_s/keep (C, K); cand (C, K, 4); orig (C, K) box index in M
        flat_s = jnp.where(keep.reshape(-1), top_s.reshape(-1), -jnp.inf)
        flat_b = cand.reshape(-1, 4)
        flat_c = jnp.repeat(cs, nms_top_k)
        # flat candidate id = class * M + original box index (the
        # reference multiclass_nms2 index contract)
        flat_id = (flat_c * M + orig.reshape(-1)).astype(jnp.int32)
        sel_s, sel_i = lax.top_k(flat_s, keep_top_k)
        valid = jnp.isfinite(sel_s)
        out = jnp.concatenate([
            jnp.where(valid, flat_c[sel_i], -1).astype(bboxes.dtype)[:, None],
            jnp.where(valid, sel_s, 0.0)[:, None],
            jnp.where(valid[:, None], flat_b[sel_i], 0.0)], axis=1)
        index = jnp.where(valid, flat_id[sel_i], -1)
        return out, index, valid.sum().astype(jnp.int32)

    out, index, counts = jax.vmap(one_image)(bboxes, scores)
    return out, index, counts


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    """Multi-class NMS (ref: detection.py:3020) — TPU-first output:
    fixed (B, keep_top_k, 6) [label, score, x1, y1, x2, y2] padded with
    label -1, plus valid counts (B,) (the reference emits LoD instead).
    """
    out, _, counts = apply(
        "multiclass_nms", bboxes, scores,
        score_threshold=float(score_threshold), nms_top_k=int(nms_top_k),
        keep_top_k=int(keep_top_k), nms_threshold=float(nms_threshold),
        normalized=normalized, background_label=int(background_label),
        nms_eta=float(nms_eta))
    return out, counts


def multiclass_nms_with_index(bboxes, scores, score_threshold, nms_top_k,
                              keep_top_k, nms_threshold=0.3,
                              normalized=True, nms_eta=1.0,
                              background_label=0, name=None):
    """multiclass_nms that also returns the flat candidate index
    (class * M + original box index), -1 padded (ref: multiclass_nms2)."""
    return apply(
        "multiclass_nms", bboxes, scores,
        score_threshold=float(score_threshold), nms_top_k=int(nms_top_k),
        keep_top_k=int(keep_top_k), nms_threshold=float(nms_threshold),
        normalized=normalized, background_label=int(background_label),
        nms_eta=float(nms_eta))


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------


@register("yolo_box")
def _yolo_box(x, img_size, *, anchors, class_num, conf_thresh,
              downsample_ratio, clip_bbox):
    B, _, H, W = x.shape
    A = len(anchors) // 2
    an = jnp.asarray(np.asarray(anchors, np.float32).reshape(A, 2))
    x = x.reshape(B, A, 5 + class_num, H, W)
    tx, ty = x[:, :, 0], x[:, :, 1]
    tw, th = x[:, :, 2], x[:, :, 3]
    tobj = jax.nn.sigmoid(x[:, :, 4])
    tcls = jax.nn.sigmoid(x[:, :, 5:])

    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    in_w = W * downsample_ratio
    in_h = H * downsample_ratio
    cx = (jax.nn.sigmoid(tx) + gx) / W
    cy = (jax.nn.sigmoid(ty) + gy) / H
    bw = jnp.exp(tw) * an[None, :, 0, None, None] / in_w
    bh = jnp.exp(th) * an[None, :, 1, None, None] / in_h

    imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (cx - bw / 2) * imw
    y1 = (cy - bh / 2) * imh
    x2 = (cx + bw / 2) * imw
    y2 = (cy + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, None)
        y1 = jnp.clip(y1, 0.0, None)
        x2 = jnp.minimum(x2, imw - 1.0)
        y2 = jnp.minimum(y2, imh - 1.0)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(B, -1, 4)
    conf = jnp.where(tobj >= conf_thresh, tobj, 0.0)
    scores = (tcls * conf[:, :, None]).transpose(0, 1, 3, 4, 2) \
        .reshape(B, -1, class_num)
    return boxes, scores


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    """Decode a YOLOv3 head (ref: detection.py:1022).

    x: (B, A*(5+C), H, W); img_size: (B, 2) [h, w].
    Returns boxes (B, A*H*W, 4) pixel coords, scores (B, A*H*W, C)
    (sub-threshold boxes get score 0 — dense masking, not pruning).
    """
    return apply("yolo_box", x, img_size, anchors=tuple(anchors),
                 class_num=int(class_num), conf_thresh=float(conf_thresh),
                 downsample_ratio=int(downsample_ratio),
                 clip_bbox=clip_bbox)


@register("yolov3_loss")
def _yolov3_loss(x, gt_box, gt_label, gt_score, *, anchors, anchor_mask,
                 class_num, ignore_thresh, downsample_ratio,
                 use_label_smooth):
    B, _, H, W = x.shape
    A = len(anchor_mask)
    an_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    an_np = an_all[list(anchor_mask)]  # (A, 2) masked anchors, HOST-side
    an = jnp.asarray(an_np)
    in_w, in_h = W * downsample_ratio, H * downsample_ratio
    x = x.reshape(B, A, 5 + class_num, H, W)
    px, py = x[:, :, 0], x[:, :, 1]
    pw, ph = x[:, :, 2], x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]  # (B, A, C, H, W)
    G = gt_box.shape[1]

    # -- target assignment: each gt goes to the best-IoU anchor (by shape)
    # at its center cell, if that anchor is in this head's mask
    gw = gt_box[..., 2] * in_w
    gh = gt_box[..., 3] * in_h
    inter = (jnp.minimum(gw[..., None], an_all[None, None, :, 0])
             * jnp.minimum(gh[..., None], an_all[None, None, :, 1]))
    union = (gw * gh)[..., None] + (an_all[:, 0] * an_all[:, 1])[None, None] \
        - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # (B, G)
    valid = (gt_box[..., 2] > 0) & (gt_box[..., 3] > 0)
    mask_arr = jnp.asarray(np.asarray(anchor_mask, np.int64))
    local_a = jnp.argmax(best[..., None] == mask_arr[None, None], axis=-1)
    in_head = (best[..., None] == mask_arr[None, None]).any(-1) & valid
    gi = jnp.clip((gt_box[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gt_box[..., 1] * H).astype(jnp.int32), 0, H - 1)

    # scatter gt targets into dense (B, A, H, W) maps; masked-out rows
    # (padding / other-head gts) are routed to the out-of-bounds anchor A
    # so mode="drop" discards them instead of clobbering cell (a, j, i)
    safe_a = jnp.where(in_head, local_a, A)
    obj_set = jax.vmap(
        lambda a_idx, j, i: jnp.zeros((A, H, W))
        .at[a_idx, j, i].max(1.0, mode="drop")
    )(safe_a, gj, gi)

    def dense(vals):
        return jax.vmap(
            lambda a_idx, j, i, v: jnp.zeros((A, H, W))
            .at[a_idx, j, i].set(v, mode="drop")
        )(safe_a, gj, gi, vals)

    t_x = dense(gt_box[..., 0] * W - gi.astype(jnp.float32))
    t_y = dense(gt_box[..., 1] * H - gj.astype(jnp.float32))
    t_w = dense(jnp.log(jnp.maximum(
        gw / jnp.maximum(an[:, 0][local_a], 1e-10), 1e-10)))
    t_h = dense(jnp.log(jnp.maximum(
        gh / jnp.maximum(an[:, 1][local_a], 1e-10), 1e-10)))
    # box-size weighting (small boxes matter more): 2 - w*h
    t_scale = dense(2.0 - gt_box[..., 2] * gt_box[..., 3])
    # mixup weighting: gt_score scales every positive term (ref: gt_score)
    t_score = dense(gt_score)

    # class one-hot targets
    smooth_lo = 1.0 / class_num if use_label_smooth else 0.0
    smooth_hi = 1.0 - smooth_lo if use_label_smooth else 1.0
    t_cls = jax.vmap(
        lambda a_idx, j, i, lab: jnp.full((A, class_num, H, W), smooth_lo)
        .at[a_idx, :, j, i].set(
            jax.nn.one_hot(lab, class_num) * (smooth_hi - smooth_lo)
            + smooth_lo, mode="drop")
    )(safe_a, gj, gi, gt_label)

    # ignore mask: predictions overlapping any gt above ignore_thresh are
    # not penalized as background
    pred_boxes, _ = _yolo_box(
        x.reshape(B, A * (5 + class_num), H, W),
        jnp.broadcast_to(jnp.asarray([[in_h, in_w]], jnp.float32),
                         (B, 2)).astype(jnp.int32),
        anchors=tuple(an_np.reshape(-1).tolist()),
        class_num=class_num, conf_thresh=-1.0,
        downsample_ratio=downsample_ratio, clip_bbox=False)
    gt_xyxy = jnp.stack([
        (gt_box[..., 0] - gt_box[..., 2] / 2) * in_w,
        (gt_box[..., 1] - gt_box[..., 3] / 2) * in_h,
        (gt_box[..., 0] + gt_box[..., 2] / 2) * in_w,
        (gt_box[..., 1] + gt_box[..., 3] / 2) * in_h], axis=-1)
    ious = _pairwise_iou(pred_boxes, gt_xyxy)  # (B, AHW, G)
    ious = jnp.where(valid[:, None, :], ious, 0.0)
    ignore = (ious.max(-1) > ignore_thresh).reshape(B, A, H, W)

    from ._base import bce_with_logits as bce

    obj = obj_set
    w_pos = t_scale * t_score * obj
    loss_xy = (w_pos * (bce(px, t_x) + bce(py, t_y))).sum(axis=(1, 2, 3))
    loss_wh = (w_pos * ((pw - t_w) ** 2 + (ph - t_h) ** 2) * 0.5) \
        .sum(axis=(1, 2, 3))
    loss_obj = (t_score * obj * bce(pobj, 1.0)
                + (1.0 - obj) * (~ignore) * bce(pobj, 0.0)) \
        .sum(axis=(1, 2, 3))
    loss_cls = ((t_score * obj)[:, :, None] * bce(pcls, t_cls)) \
        .sum(axis=(1, 2, 3, 4))
    return loss_xy + loss_wh + loss_obj + loss_cls


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """YOLOv3 training loss for one head (ref: detection.py:895).

    x: (B, A*(5+C), H, W) raw head; gt_box (B, G, 4) normalized
    [cx, cy, w, h]; gt_label (B, G) int; ``gt_score`` (B, G) mixup
    weights (default 1.0) scaling every positive-sample term.
    Returns per-image loss (B,). Dense target assignment — zero-area gt
    rows are padding.
    """
    if gt_score is None:
        shp = unwrap(gt_label).shape
        gt_score = Tensor(jnp.ones(shp, jnp.float32), _internal=True)
    return apply("yolov3_loss", x, gt_box, gt_label, gt_score,
                 anchors=tuple(anchors), anchor_mask=tuple(anchor_mask),
                 class_num=int(class_num),
                 ignore_thresh=float(ignore_thresh),
                 downsample_ratio=int(downsample_ratio),
                 use_label_smooth=bool(use_label_smooth))


# ---------------------------------------------------------------------------
# RoI ops
# ---------------------------------------------------------------------------


@register("roi_align")
def _roi_align(feat, rois, roi_batch_id, *, pooled_height, pooled_width,
               spatial_scale, sampling_ratio, aligned):
    C, H, W = feat.shape[1], feat.shape[2], feat.shape[3]
    sr = sampling_ratio if sampling_ratio > 0 else 2
    off = 0.5 if aligned else 0.0

    def one_roi(roi, bid):
        x1, y1, x2, y2 = (roi[i] * spatial_scale for i in range(4))
        x1, y1 = x1 - off, y1 - off
        x2, y2 = x2 - off, y2 - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w = rw / pooled_width
        bin_h = rh / pooled_height
        # sample grid: (ph*sr, pw*sr) bilinear taps, mean-pooled per bin
        ys = y1 + (jnp.arange(pooled_height * sr) + 0.5) * (bin_h / sr)
        xs = x1 + (jnp.arange(pooled_width * sr) + 0.5) * (bin_w / sr)

        def bilinear(img, yy, xx):
            # img (C, H, W); yy (Ny,), xx (Nx,) -> (C, Ny, Nx)
            yy = jnp.clip(yy, 0.0, H - 1.0)
            xx = jnp.clip(xx, 0.0, W - 1.0)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, H - 1)
            x1_ = jnp.minimum(x0 + 1, W - 1)
            wy = (yy - y0)[None, :, None]
            wx = (xx - x0)[None, None, :]
            v00 = img[:, y0][:, :, x0]
            v01 = img[:, y0][:, :, x1_]
            v10 = img[:, y1_][:, :, x0]
            v11 = img[:, y1_][:, :, x1_]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                    + v10 * wy * (1 - wx) + v11 * wy * wx)

        taps = bilinear(feat[bid], ys, xs)  # (C, ph*sr, pw*sr)
        taps = taps.reshape(C, pooled_height, sr, pooled_width, sr)
        return taps.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois, roi_batch_id)


def _roi_batch_ids(rois, rois_num):
    """fluid semantics: ``rois_num`` is the per-IMAGE roi count (the LoD
    replacement). Expand counts -> per-roi batch index host-side."""
    n = unwrap(rois).shape[0]
    if rois_num is None:
        return Tensor(jnp.zeros((n,), jnp.int32), _internal=True)
    counts = np.asarray(unwrap(rois_num)).astype(np.int64)
    if counts.sum() != n:
        raise ValueError(
            f"rois_num (per-image counts) sums to {counts.sum()} but "
            f"there are {n} rois")
    ids = np.repeat(np.arange(len(counts)), counts).astype(np.int32)
    return Tensor(jnp.asarray(ids), _internal=True)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              aligned=False, name=None):
    """RoIAlign (ref: layers/nn.py:6680). input (B, C, H, W); rois (N, 4)
    [x1, y1, x2, y2] in input-image coords; ``rois_num``: per-image roi
    counts summing to N, as in the reference (defaults to all batch 0).
    Returns (N, C, pooled_height, pooled_width)."""
    return apply("roi_align", input, rois, _roi_batch_ids(rois, rois_num),
                 pooled_height=int(pooled_height),
                 pooled_width=int(pooled_width),
                 spatial_scale=float(spatial_scale),
                 sampling_ratio=int(sampling_ratio), aligned=bool(aligned))


@register("roi_pool")
def _roi_pool(feat, rois, roi_batch_id, *, pooled_height, pooled_width,
              spatial_scale):
    C, H, W = feat.shape[1], feat.shape[2], feat.shape[3]
    ygrid = jnp.arange(H)[:, None]
    xgrid = jnp.arange(W)[None, :]

    def one_roi(roi, bid):
        x1 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)

        def one_bin(pj, pi):
            hs = y1 + jnp.floor(pj * rh / pooled_height).astype(jnp.int32)
            he = y1 + jnp.ceil((pj + 1) * rh / pooled_height) \
                .astype(jnp.int32)
            ws = x1 + jnp.floor(pi * rw / pooled_width).astype(jnp.int32)
            we = x1 + jnp.ceil((pi + 1) * rw / pooled_width) \
                .astype(jnp.int32)
            m = ((ygrid >= hs) & (ygrid < he) & (xgrid >= ws)
                 & (xgrid < we))[None]  # (1, H, W)
            empty = (he <= hs) | (we <= ws)
            val = jnp.where(m, feat[bid], -jnp.inf).max(axis=(1, 2))
            return jnp.where(empty, 0.0, val)

        pj = jnp.arange(pooled_height)
        pi = jnp.arange(pooled_width)
        out = jax.vmap(lambda j: jax.vmap(lambda i: one_bin(j, i))(pi))(pj)
        return out.transpose(2, 0, 1)  # (C, ph, pw)

    return jax.vmap(one_roi)(rois, roi_batch_id)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    """RoI max pooling (ref: layers/nn.py:6607); dense masked bins, static
    shapes. Same roi/rois_num convention as roi_align."""
    return apply("roi_pool", input, rois, _roi_batch_ids(rois, rois_num),
                 pooled_height=int(pooled_height),
                 pooled_width=int(pooled_width),
                 spatial_scale=float(spatial_scale))


# ---------------------------------------------------------------------------
# focal loss
# ---------------------------------------------------------------------------


# "sigmoid_focal_loss" in the registry is the 2.0-style op
# (nn/functional/loss.py, one-hot labels); this is the fluid detection
# variant (int labels, 0 = background, fg_num normalizer)
@register("sigmoid_focal_loss_fluid")
def _sigmoid_focal_loss(x, label, fg_num, *, gamma, alpha):
    # label (N,) int in [0, C]: 0 = background (ref one-based fg classes)
    from ._base import bce_with_logits

    C = x.shape[1]
    t = jax.nn.one_hot(label - 1, C, dtype=x.dtype)  # bg rows all-zero
    p = jax.nn.sigmoid(x)
    ce = bce_with_logits(x, t)
    w = (alpha * t + (1 - alpha) * (1 - t)) \
        * jnp.power(jnp.abs(t - p), gamma)
    return w * ce / jnp.maximum(fg_num.astype(x.dtype), 1.0)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25, name=None):
    """Focal loss (ref: detection.py:437). x (N, C) logits; label (N,)
    with 0 = background, 1..C = foreground classes; fg_num scalar."""
    return apply("sigmoid_focal_loss_fluid", x, label, fg_num,
                 gamma=float(gamma), alpha=float(alpha))


# ---------------------------------------------------------------------------
# SSD stack: matching, target assignment, loss, inference output
# ---------------------------------------------------------------------------


@register("bipartite_match")
def _bipartite_match(dist, *, match_type, overlap_threshold):
    B, G, P = dist.shape

    def one(d):
        # greedy bipartite: G rounds, each takes the global max over the
        # still-unmatched (gt, prior) pairs
        def body(carry, _):
            midx, mdist, avail = carry
            masked = jnp.where(avail, d, -1.0)
            flat = jnp.argmax(masked)
            g, p = flat // P, flat % P
            val = masked.reshape(-1)[flat]
            ok = val > 0
            midx = jnp.where(ok, midx.at[p].set(g.astype(jnp.int32)),
                             midx)
            mdist = mdist.at[p].set(jnp.where(ok, val, mdist[p]))
            kill = ((jnp.arange(G)[:, None] == g)
                    | (jnp.arange(P)[None, :] == p))
            avail = jnp.where(ok, avail & ~kill, avail)
            return (midx, mdist, avail), None

        init = (jnp.full((P,), -1, jnp.int32), jnp.zeros((P,), d.dtype),
                jnp.ones((G, P), bool))
        (midx, mdist, _), _ = lax.scan(body, init, jnp.arange(G))
        if match_type == "per_prediction":
            # unmatched priors also match their argmax gt above threshold
            best = jnp.argmax(d, axis=0).astype(jnp.int32)
            bestv = jnp.max(d, axis=0)
            extra = (midx < 0) & (bestv >= overlap_threshold)
            midx = jnp.where(extra, best, midx)
            mdist = jnp.where(extra, bestv, mdist)
        return midx, mdist

    return jax.vmap(one)(dist)


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """Greedy bipartite (optionally + per-prediction) matching
    (ref: detection.py:1198). dist_matrix (B, G, P) similarity ->
    (match_indices (B, P) int32 with -1 = unmatched, match_dist (B, P)).
    """
    return apply("bipartite_match", dist_matrix, match_type=match_type,
                 overlap_threshold=float(dist_threshold))


@register("target_assign")
def _target_assign(x, match, *, mismatch_value):
    # x (B, G, K) per-gt attributes; match (B, P) -> out (B, P, K)
    safe = jnp.maximum(match, 0)
    out = jnp.take_along_axis(
        x, safe[:, :, None].astype(jnp.int32), axis=1)
    neg = (match < 0)[:, :, None]
    out = jnp.where(neg, jnp.full((), mismatch_value, x.dtype), out)
    weight = (~neg).astype(jnp.float32)
    return out, weight


@register("target_assign_neg")
def _target_assign_neg(x, match, neg_idx, *, mismatch_value):
    out, weight = _target_assign(x, match, mismatch_value=mismatch_value)
    # listed negatives are REAL training targets: mismatch_value with
    # weight 1 (how SSD marks background conf rows trainable)
    B, P = match.shape
    # padding entries (negative indices) must DROP, not wrap: route them
    # to the explicit out-of-bounds index P
    neg_i = neg_idx.astype(jnp.int32)
    safe_i = jnp.where(neg_i < 0, P, neg_i)
    neg_mask = jnp.zeros((B, P), bool)
    neg_mask = jax.vmap(
        lambda m, idx: m.at[idx].set(True, mode="drop"))(neg_mask, safe_i)
    out = jnp.where(neg_mask[:, :, None],
                    jnp.full((), mismatch_value, x.dtype), out)
    weight = jnp.where(neg_mask[:, :, None], 1.0, weight)
    return out, weight


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """Gather per-gt rows onto priors by match index
    (ref: detection.py:1287). Unmatched priors get ``mismatch_value``
    with weight 0; priors listed in ``negative_indices`` (B, K) get
    ``mismatch_value`` with weight 1 (trainable background targets)."""
    if negative_indices is None:
        return apply("target_assign", input, matched_indices,
                     mismatch_value=mismatch_value)
    return apply("target_assign_neg", input, matched_indices,
                 negative_indices, mismatch_value=mismatch_value)


@register("ssd_loss")
def _ssd_loss(loc, conf, gt_box, gt_label, prior, pvar, *,
              background_label, overlap_threshold, neg_pos_ratio,
              neg_overlap, loc_loss_weight, conf_loss_weight,
              match_type="per_prediction"):
    B, P = loc.shape[0], loc.shape[1]
    G = gt_box.shape[1]
    C = conf.shape[-1]
    valid_gt = (gt_box[..., 2] > gt_box[..., 0]) \
        & (gt_box[..., 3] > gt_box[..., 1])

    iou = _pairwise_iou(gt_box, prior[None])  # (B, G, P)
    iou = jnp.where(valid_gt[:, :, None], iou, 0.0)
    midx, mdist = _bipartite_match(iou, match_type=match_type,
                                   overlap_threshold=overlap_threshold)
    pos = midx >= 0  # (B, P)
    npos = pos.sum(-1)

    # -- localization target: encode matched gt against its prior
    safe = jnp.maximum(midx, 0)
    gt_m = jnp.take_along_axis(gt_box, safe[:, :, None], axis=1)  # B,P,4

    def encode(gt_b):
        # per-prior single encode (diagonal of the pairwise box_coder)
        pcx, pcy, pw, ph = _to_center(prior, True)
        tcx, tcy, tw, th = _to_center(gt_b, True)
        ox = (tcx - pcx) / pw / pvar[:, 0]
        oy = (tcy - pcy) / ph / pvar[:, 1]
        ow = jnp.log(jnp.maximum(tw / pw, 1e-10)) / pvar[:, 2]
        oh = jnp.log(jnp.maximum(th / ph, 1e-10)) / pvar[:, 3]
        return jnp.stack([ox, oy, ow, oh], axis=-1)

    loc_t = jax.vmap(encode)(gt_m)  # (B, P, 4)
    diff = loc - loc_t
    ad = jnp.abs(diff)
    smooth_l1 = jnp.where(ad < 1.0, 0.5 * ad * ad, ad - 0.5).sum(-1)
    loc_loss = (smooth_l1 * pos).sum(-1)

    # -- confidence target + hard negative mining
    lab_m = jnp.take_along_axis(gt_label, safe, axis=1)  # (B, P)
    conf_t = jnp.where(pos, lab_m, background_label)
    logp = jax.nn.log_softmax(conf.astype(jnp.float32), axis=-1)
    ce = -jnp.take_along_axis(logp, conf_t[:, :, None].astype(jnp.int32),
                              axis=-1)[..., 0]  # (B, P)
    is_neg = (~pos) & (mdist < neg_overlap)
    neg_ce = jnp.where(is_neg, ce, -jnp.inf)
    order = jnp.argsort(-neg_ce, axis=-1)
    rank = jnp.zeros((B, P), jnp.int32)
    rank = jax.vmap(lambda r, o: r.at[o].set(jnp.arange(P,
                                                        dtype=jnp.int32))
                    )(rank, order)
    k = jnp.clip(neg_pos_ratio * npos, 0, P).astype(jnp.int32)
    sel_neg = is_neg & (rank < k[:, None])
    conf_loss = (ce * (pos | sel_neg)).sum(-1)

    denom = jnp.maximum(npos.astype(jnp.float32), 1.0)
    return (loc_loss_weight * loc_loss
            + conf_loss_weight * conf_loss) / denom


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0,
             overlap_threshold=0.5, neg_pos_ratio=3.0, neg_overlap=0.5,
             loc_loss_weight=1.0, conf_loss_weight=1.0, match_type=
             "per_prediction", mining_type="max_negative",
             sample_size=None, name=None):
    """SSD multibox loss (ref: detection.py:1390): per-prediction
    matching, smooth-L1 localization, softmax confidence with
    max-negative hard mining at ``neg_pos_ratio``.

    location (B, P, 4), confidence (B, P, C), gt_box (B, G, 4)
    normalized corners (degenerate rows = padding), gt_label (B, G) int,
    prior_box (P, 4) (+ optional (P, 4) variances). Returns per-image
    loss (B,).
    """
    if mining_type != "max_negative":
        raise NotImplementedError("only max_negative mining (the SSD "
                                  "paper recipe) is implemented")
    if match_type not in ("per_prediction", "bipartite"):
        raise ValueError(f"match_type {match_type!r} not recognized")
    if prior_box_var is None:
        pv = Tensor(jnp.ones((unwrap(prior_box).shape[0], 4),
                             jnp.float32), _internal=True)
    elif isinstance(prior_box_var, (list, tuple)):
        pv = Tensor(jnp.broadcast_to(
            jnp.asarray(prior_box_var, jnp.float32),
            (unwrap(prior_box).shape[0], 4)), _internal=True)
    else:
        pv = prior_box_var
    return apply("ssd_loss", location, confidence, gt_box, gt_label,
                 prior_box, pv, background_label=int(background_label),
                 overlap_threshold=float(overlap_threshold),
                 neg_pos_ratio=float(neg_pos_ratio),
                 neg_overlap=float(neg_overlap),
                 loc_loss_weight=float(loc_loss_weight),
                 conf_loss_weight=float(conf_loss_weight),
                 match_type=match_type)


def detection_output(loc, scores, prior_box, prior_box_var=None,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     name=None):
    """SSD inference head (ref: detection.py:518): decode loc deltas
    against priors, then multiclass NMS.

    loc (B, P, 4), scores (B, P, C) post-softmax, prior_box (P, 4).
    Returns (out (B, keep_top_k, 6), valid counts (B,)) like
    multiclass_nms.
    """
    if prior_box_var is None:
        prior_box_var = [1.0, 1.0, 1.0, 1.0]
    # loc (B, P, 4) deltas; priors align with axis 1, i.e. decoded[b, p]
    # decodes loc[b, p] against prior[p]
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size", axis=0)
    from .manipulation import transpose as _tr

    return multiclass_nms(decoded, _tr(scores, [0, 2, 1]),
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, nms_eta=nms_eta,
                          background_label=background_label)
