"""Elementwise + matmul math ops.

Covers the reference's ``paddle/fluid/operators/elementwise/*``,
``activation_op.cc`` (math portion), ``matmul_op.cc``, ``mul_op.cc``,
``sum_op.cc``, ``scale_op.cc``, ``clip_op.cc``, ``cumsum_op.cc`` etc.
All kernels are pure jnp — XLA fuses elementwise chains into matmul
epilogues on TPU, which is why there are no hand-fused variants here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._base import register, apply
from ..core.dtype import convert_dtype

# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


@register("add")
def _add(x, y):
    return jnp.add(x, y)


@register("subtract")
def _subtract(x, y):
    return jnp.subtract(x, y)


@register("multiply")
def _multiply(x, y):
    return jnp.multiply(x, y)


@register("divide")
def _divide(x, y):
    return jnp.divide(x, y)


@register("floor_divide")
def _floor_divide(x, y):
    return jnp.floor_divide(x, y)


@register("remainder")
def _remainder(x, y):
    return jnp.remainder(x, y)


@register("pow")
def _pow(x, y):
    return jnp.power(x, y)


@register("maximum")
def _maximum(x, y):
    return jnp.maximum(x, y)


@register("minimum")
def _minimum(x, y):
    return jnp.minimum(x, y)


@register("atan2")
def _atan2(x, y):
    return jnp.arctan2(x, y)


@register("matmul")
def _matmul(x, y, *, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim >= 2 else y
    return jnp.matmul(x, y)


@register("scale")
def _scale(x, *, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


@register("clip")
def _clip(x, *, min=None, max=None):
    return jnp.clip(x, min, max)


@register("add_n")
def _add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register("cumsum")
def _cumsum(x, *, axis=None):
    return jnp.cumsum(x, axis=axis)


@register("cumprod")
def _cumprod(x, *, axis=None):
    return jnp.cumprod(x, axis=axis)


@register("lerp")
def _lerp(x, y, w):
    return x + w * (y - x)


@register("outer")
def _outer(x, y):
    return jnp.outer(x, y)


@register("inner")
def _inner(x, y):
    return jnp.inner(x, y)


@register("dot")
def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


@register("logaddexp")
def _logaddexp(x, y):
    return jnp.logaddexp(x, y)


_UNARY = {
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "abs": jnp.abs,
    "neg": jnp.negative,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "trunc": jnp.trunc,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh_": jnp.tanh,
    "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh,
    "atanh": jnp.arctanh,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "sign": jnp.sign,
    "reciprocal": jnp.reciprocal,
    "square": jnp.square,
    "digamma": jax.scipy.special.digamma,
    "lgamma": jax.scipy.special.gammaln,
    "frac": lambda x: x - jnp.trunc(x),
    "angle": jnp.angle,
    "conj": jnp.conj,
    "deg2rad": jnp.deg2rad,
    "rad2deg": jnp.rad2deg,
}

for _name, _fn in _UNARY.items():
    register(_name)(_fn)


@register("isnan")
def _isnan(x):
    return jnp.isnan(x)


@register("isinf")
def _isinf(x):
    return jnp.isinf(x)


@register("isfinite")
def _isfinite(x):
    return jnp.isfinite(x)


@register("nan_to_num")
def _nan_to_num(x, *, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@register("stanh")
def _stanh(x, *, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@register("einsum")
def _einsum(*xs, equation):
    return jnp.einsum(equation, *xs)


@register("kron")
def _kron(x, y):
    return jnp.kron(x, y)


@register("trace_op")
def _trace(x, *, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@register("diag")
def _diag(x, *, offset=0):
    return jnp.diag(x, k=offset)


@register("diagonal")
def _diagonal(x, *, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _binop(name):
    def op(x, y, name_=None, **kw):
        from ..core.tensor import Tensor

        if not isinstance(x, Tensor):
            x = Tensor(x)
        if not isinstance(y, Tensor):
            if isinstance(y, (bool, int, float)):
                # python scalars adopt the tensor dtype (paddle semantics)
                y = Tensor(jnp.asarray(y, dtype=x._data.dtype), _internal=True)
            else:
                y = Tensor(y)
        return apply(name, x, y, **kw)

    op.__name__ = name
    return op


add = _binop("add")
subtract = _binop("subtract")
multiply = _binop("multiply")
divide = _binop("divide")
floor_divide = _binop("floor_divide")
remainder = _binop("remainder")
mod = remainder
floor_mod = remainder
maximum = _binop("maximum")
minimum = _binop("minimum")
atan2 = _binop("atan2")
logaddexp = _binop("logaddexp")
elementwise_add = add
elementwise_sub = subtract
elementwise_mul = multiply
elementwise_div = divide


def pow(x, y, name=None):
    return _binop("pow")(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return apply("matmul", x, y, transpose_x=transpose_x, transpose_y=transpose_y)


def mm(x, y, name=None):
    return apply("matmul", x, y)


def bmm(x, y, name=None):
    return apply("matmul", x, y)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """Ref: mul_op.cc — flatten then matmul."""
    from .manipulation import reshape

    xs, ys = x.shape, y.shape
    x2 = reshape(x, [int(jnp.prod(jnp.array(xs[:x_num_col_dims]))), -1])
    y2 = reshape(y, [int(jnp.prod(jnp.array(ys[:y_num_col_dims]))), -1])
    out = apply("matmul", x2, y2)
    return reshape(out, list(xs[:x_num_col_dims]) + list(ys[y_num_col_dims:]))


def dot(x, y, name=None):
    return apply("dot", x, y)


def outer(x, y, name=None):
    return apply("outer", x, y)


def inner(x, y, name=None):
    return apply("inner", x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = apply("scale", x, scale=float(scale), bias=float(bias), bias_after_scale=bias_after_scale)
    if act:
        from . import activation

        out = getattr(activation, act)(out)
    return out


def clip(x, min=None, max=None, name=None):
    from ..core.tensor import Tensor

    if isinstance(min, Tensor):
        min = float(min.item())
    if isinstance(max, Tensor):
        max = float(max.item())
    return apply("clip", x, min=min, max=max)


def add_n(inputs, name=None):
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    return apply("add_n", *inputs)


sums = add_n


def cumsum(x, axis=None, dtype=None, name=None):
    out = apply("cumsum", x, axis=axis)
    return out.astype(dtype) if dtype is not None else out


def cumprod(x, dim=None, dtype=None, name=None):
    out = apply("cumprod", x, axis=dim)
    return out.astype(dtype) if dtype is not None else out


def lerp(x, y, weight, name=None):
    from ..core.tensor import Tensor

    if not isinstance(weight, Tensor):
        weight = Tensor(float(weight))
    return apply("lerp", x, y, weight)


def einsum(equation, *operands):
    return apply("einsum", *operands, equation=equation)


def kron(x, y, name=None):
    return apply("kron", x, y)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace_op", x, offset=offset, axis1=axis1, axis2=axis2)


def diag(x, offset=0, name=None):
    return apply("diag", x, offset=offset)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal", x, offset=offset, axis1=axis1, axis2=axis2)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply("nan_to_num", x, nan=nan, posinf=posinf, neginf=neginf)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", x, scale_a=scale_a, scale_b=scale_b)


def _make_unary(name, opname=None):
    opname = opname or name

    def op(x, name_=None):
        from ..core.tensor import Tensor

        if not isinstance(x, Tensor):
            x = Tensor(x)
        return apply(opname, x)

    op.__name__ = name
    return op


exp = _make_unary("exp")
expm1 = _make_unary("expm1")
log = _make_unary("log")
log2 = _make_unary("log2")
log10 = _make_unary("log10")
log1p = _make_unary("log1p")
sqrt = _make_unary("sqrt")
rsqrt = _make_unary("rsqrt")
abs = _make_unary("abs")
neg = _make_unary("neg")
floor = _make_unary("floor")
ceil = _make_unary("ceil")
round = _make_unary("round")
trunc = _make_unary("trunc")
sin = _make_unary("sin")
cos = _make_unary("cos")
tan = _make_unary("tan")
asin = _make_unary("asin")
acos = _make_unary("acos")
atan = _make_unary("atan")
sinh = _make_unary("sinh")
cosh = _make_unary("cosh")
asinh = _make_unary("asinh")
acosh = _make_unary("acosh")
atanh = _make_unary("atanh")
erf = _make_unary("erf")
erfinv = _make_unary("erfinv")
sign = _make_unary("sign")
reciprocal = _make_unary("reciprocal")
square = _make_unary("square")
digamma = _make_unary("digamma")
lgamma = _make_unary("lgamma")
frac = _make_unary("frac")
angle = _make_unary("angle")
conj = _make_unary("conj")
deg2rad = _make_unary("deg2rad")
rad2deg = _make_unary("rad2deg")
isnan = _make_unary("isnan")
isinf = _make_unary("isinf")
isfinite = _make_unary("isfinite")


def increment(x, value=1.0, name=None):
    return apply("scale", x, scale=1.0, bias=float(value))
