"""Activation ops.

Covers the reference's ``activation_op.cc``/``softmax_op.cc``/``maxout_op.cc``.
Pure jnp — XLA fuses these into surrounding matmuls on TPU.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._base import register, apply

_ACTS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softplus_": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "silu": jax.nn.silu,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "tanhshrink": lambda x: x - jnp.tanh(x),
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "hardsigmoid": lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0),
    "hardswish": lambda x: x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0),
    "log_sigmoid": jax.nn.log_sigmoid,
}
for _n, _f in _ACTS.items():
    register(_n)(_f)


def _unary(opname):
    def op(x, name=None):
        if not isinstance(x, Tensor):
            x = Tensor(np.asarray(x))
        return apply(opname, x)

    op.__name__ = opname
    return op


relu = _unary("relu")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
softsign = _unary("softsign")
silu = _unary("silu")
swish = silu
mish = _unary("mish")
tanhshrink = _unary("tanhshrink")
relu6 = _unary("relu6")
hardsigmoid = _unary("hardsigmoid")
hardswish = _unary("hardswish")
log_sigmoid = _unary("log_sigmoid")
logsigmoid = log_sigmoid


@register("softplus")
def _softplus(x, *, beta=1.0, threshold=20.0):
    return jnp.where(x * beta > threshold, x, jax.nn.softplus(x * beta) / beta)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply("softplus", x, beta=beta, threshold=threshold)


@register("gelu")
def _gelu(x, *, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def gelu(x, approximate=False, name=None):
    return apply("gelu", x, approximate=approximate)


@register("leaky_relu")
def _leaky_relu(x, *, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu", x, negative_slope=negative_slope)


@register("elu")
def _elu(x, *, alpha=1.0):
    return jnp.where(x > 0, x, alpha * jnp.expm1(x))


def elu(x, alpha=1.0, name=None):
    return apply("elu", x, alpha=alpha)


@register("celu")
def _celu(x, *, alpha=1.0):
    return jnp.maximum(x, 0) + jnp.minimum(0, alpha * jnp.expm1(x / alpha))


def celu(x, alpha=1.0, name=None):
    return apply("celu", x, alpha=alpha)


@register("selu")
def _selu(x, *, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply("selu", x, scale=scale, alpha=alpha)


@register("prelu")
def _prelu(x, weight):
    return jnp.where(x >= 0, x, weight * x)


def prelu(x, weight, data_format="NCHW", name=None):
    w = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    if w.ndim == 1 and w.shape[0] > 1 and x._data.ndim > 1:
        # per-channel: broadcast along channel dim
        ch_axis = 1 if data_format == "NCHW" else x._data.ndim - 1
        shape = [1] * x._data.ndim
        shape[ch_axis] = w.shape[0]
        weight = Tensor(w.reshape(shape), _internal=True) if not isinstance(weight, Tensor) else weight.reshape(shape)
    elif not isinstance(weight, Tensor):
        weight = Tensor(w, _internal=True)
    return apply("prelu", x, weight)


@register("hardtanh")
def _hardtanh(x, *, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("hardtanh", x, min=min, max=max)


@register("hardshrink")
def _hardshrink(x, *, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink", x, threshold=threshold)


@register("softshrink")
def _softshrink(x, *, threshold=0.5):
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0))


def softshrink(x, threshold=0.5, name=None):
    return apply("softshrink", x, threshold=threshold)


@register("thresholded_relu")
def _thresholded_relu(x, *, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply("thresholded_relu", x, threshold=threshold)


@register("softmax")
def _softmax(x, *, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype)
    return apply("softmax", x, axis=axis)


@register("log_softmax")
def _log_softmax(x, *, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype)
    return apply("log_softmax", x, axis=axis)


@register("gumbel_softmax_det")
def _gumbel_softmax_det(x, g, *, temperature=1.0, hard=False, axis=-1):
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:  # straight-through estimator
        y_hard = (y == jnp.max(y, axis=axis, keepdims=True)).astype(y.dtype)
        y = y_hard - jax.lax.stop_gradient(y) + y
    return y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ..core import random as _random

    g = jax.random.gumbel(_random.next_key(), tuple(x.shape), dtype=x._data.dtype)
    return apply("gumbel_softmax_det", x, Tensor(g, _internal=True),
                 temperature=temperature, hard=hard, axis=axis)


@register("maxout")
def _maxout(x, *, groups, axis=1):
    shp = list(x.shape)
    c = shp[axis]
    shp[axis:axis + 1] = [c // groups, groups]
    return jnp.max(jnp.reshape(x, shp), axis=axis + 1)


def maxout(x, groups, axis=1, name=None):
    return apply("maxout", x, groups=groups, axis=axis)


@register("glu")
def _glu(x, *, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def glu(x, axis=-1, name=None):
    return apply("glu", x, axis=axis)
