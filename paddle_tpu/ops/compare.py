"""Comparison and logical ops.

Covers the reference's ``controlflow/compare_op.cc``, ``logical_op.cc``,
``isclose/allclose`` and ``is_empty``.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._base import register, apply


def _coerce(x, other=None):
    if isinstance(x, Tensor):
        return x
    if isinstance(x, (bool, int, float)) and isinstance(other, Tensor):
        return Tensor(jnp.asarray(x, dtype=other._data.dtype), _internal=True)
    return Tensor(np.asarray(x))


def _cmp(name, jfn):
    register(name)(jfn)

    def op(x, y, name_=None):
        x_t = _coerce(x, y if isinstance(y, Tensor) else None)
        y_t = _coerce(y, x_t)
        return apply(name, x_t, y_t)

    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)


def _logical(name, jfn):
    @register(name)
    def _k(x, y=None):
        return jfn(x) if y is None else jfn(x, y)

    def op(x, y=None, out=None, name_=None):
        x_t = _coerce(x)
        res = apply(name, x_t) if y is None else apply(name, x_t, _coerce(y))
        if out is not None:
            out.set_value(res)
            return out
        return res

    op.__name__ = name
    return op


logical_and = _logical("logical_and", jnp.logical_and)
logical_or = _logical("logical_or", jnp.logical_or)
logical_xor = _logical("logical_xor", jnp.logical_xor)
logical_not = _logical("logical_not", jnp.logical_not)


@register("bitwise_and")
def _bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@register("bitwise_or")
def _bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@register("bitwise_xor")
def _bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@register("bitwise_not")
def _bitwise_not(x):
    return jnp.bitwise_not(x)


def bitwise_and(x, y, out=None, name=None):
    return apply("bitwise_and", _coerce(x), _coerce(y))


def bitwise_or(x, y, out=None, name=None):
    return apply("bitwise_or", _coerce(x), _coerce(y))


def bitwise_xor(x, y, out=None, name=None):
    return apply("bitwise_xor", _coerce(x), _coerce(y))


def bitwise_not(x, out=None, name=None):
    return apply("bitwise_not", _coerce(x))


@register("isclose")
def _isclose(x, y, *, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    return apply("isclose", _coerce(x), _coerce(y), rtol=float(rtol), atol=float(atol), equal_nan=equal_nan)


@register("allclose_op")
def _allclose(x, y, *, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    return apply("allclose_op", _coerce(x), _coerce(y), rtol=float(rtol), atol=float(atol), equal_nan=equal_nan)


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(_coerce(x)._data, _coerce(y)._data), _internal=True)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_coerce(x).size == 0), _internal=True)


def is_tensor(x):
    return isinstance(x, Tensor)
