"""Sequence-labeling op family: CRF, chunk eval, edit distance, and the
large-vocab sampled losses (NCE / hsigmoid / sampled softmax).

Ref (capability target): python/paddle/fluid/layers/nn.py —
linear_chain_crf (:695), crf_decoding (:772), chunk_eval (:820 area),
nce (:5213 area), hsigmoid; layers/loss.py sampled_softmax_with_
cross_entropy, edit_distance; exercised by the reference book chapter
tests/book/test_label_semantic_roles.py.

TPU-native design: everything is dense (B, L) padded + lengths — no LoD.
The CRF forward/viterbi recursions are lax.scan over time (one compiled
loop, grads by autodiff through the scan); edit distance is a scan over
DP rows; sampled losses take an explicit PRNG key input so the kernels
stay pure under jit.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core import random as _random
from ..core.tensor import Tensor
from ._base import register, apply, unwrap

__all__ = [
    "linear_chain_crf", "crf_decoding", "chunk_eval", "edit_distance",
    "nce", "hsigmoid", "sampled_softmax_with_cross_entropy",
]


# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------


def _split_transition(transition):
    """fluid layout: (T+2, T) — row 0 start, row 1 stop, rows 2.. pairwise."""
    return transition[0], transition[1], transition[2:]


@register("linear_chain_crf")
def _linear_chain_crf(emission, label, length, transition):
    B, L, T = emission.shape
    start, stop, trans = _split_transition(transition)
    t_idx = jnp.arange(L)
    mask = (t_idx[None, :] < length[:, None]).astype(emission.dtype)

    # -- partition function: alpha recursion in log space
    def alpha_step(alpha, inp):
        emit_t, m_t = inp  # (B, T), (B,)
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) \
            + emit_t
        return jnp.where(m_t[:, None] > 0, nxt, alpha), None

    alpha0 = start[None] + emission[:, 0]
    alphaL, _ = lax.scan(
        alpha_step, alpha0,
        (emission.transpose(1, 0, 2)[1:], mask.T[1:]))
    log_z = jax.nn.logsumexp(alphaL + stop[None], axis=-1)

    # -- gold path score
    lab = label.astype(jnp.int32)
    emit_score = jnp.take_along_axis(emission, lab[:, :, None],
                                     axis=-1)[..., 0]  # (B, L)
    emit_score = (emit_score * mask).sum(-1)
    pair = trans[lab[:, :-1], lab[:, 1:]]  # (B, L-1)
    pair = (pair * mask[:, 1:]).sum(-1)
    first = start[lab[:, 0]]
    last_idx = jnp.clip(length - 1, 0, L - 1)
    last_lab = jnp.take_along_axis(lab, last_idx[:, None], axis=1)[:, 0]
    gold = first + emit_score + pair + stop[last_lab]
    return log_z - gold  # negative log-likelihood per sequence


def linear_chain_crf(input, label, param_attr=None, length=None,
                     transition=None, name=None):
    """CRF negative log-likelihood (ref: layers/nn.py:695).

    input: (B, L, T) emissions; label (B, L) int; transition (T+2, T)
    (row 0 start, row 1 stop); length (B,) valid lengths (defaults to
    full L). Returns nll (B,) — minimize its mean.
    """
    if transition is None:
        raise ValueError("pass the transition parameter "
                         "(Tensor of shape (num_tags + 2, num_tags))")
    if length is None:
        B, L = unwrap(input).shape[:2]
        length = Tensor(jnp.full((B,), L, jnp.int32), _internal=True)
    return apply("linear_chain_crf", input, label, length, transition)


@register("crf_decoding")
def _crf_decoding(emission, length, transition):
    B, L, T = emission.shape
    start, stop, trans = _split_transition(transition)
    mask = (jnp.arange(L)[None, :] < length[:, None])

    def vit_step(state, inp):
        score = state  # (B, T)
        emit_t, m_t = inp
        cand = score[:, :, None] + trans[None]  # (B, T, T)
        best_prev = jnp.argmax(cand, axis=1)  # (B, T)
        nxt = jnp.max(cand, axis=1) + emit_t
        nxt = jnp.where(m_t[:, None], nxt, score)
        bp = jnp.where(m_t[:, None], best_prev,
                       jnp.arange(T)[None].astype(best_prev.dtype))
        return nxt, bp

    score0 = start[None] + emission[:, 0]
    scoreL, bps = lax.scan(vit_step, score0,
                           (emission.transpose(1, 0, 2)[1:],
                            mask.T[1:]))  # bps: (L-1, B, T)
    final = scoreL + stop[None]
    last = jnp.argmax(final, axis=-1)  # (B,)
    best_score = jnp.max(final, axis=-1)

    def back_step(tag, bp_t):
        # bp_t[b, tag_{t+1}] = best tag at time t; emit it at position t
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = lax.scan(back_step, last, bps, reverse=True)
    path = jnp.concatenate([path_rev, last[None]], axis=0).T  # (B, L)
    path = jnp.where(mask, path, 0)
    return path.astype(jnp.int64), best_score


def crf_decoding(input, param_attr=None, length=None, transition=None,
                 name=None):
    """Viterbi decode (ref: layers/nn.py:772). Returns (path (B, L) int64
    zero-padded, best score (B,))."""
    if transition is None:
        raise ValueError("pass the transition parameter")
    if length is None:
        B, L = unwrap(input).shape[:2]
        length = Tensor(jnp.full((B,), L, jnp.int32), _internal=True)
    return apply("crf_decoding", input, length, transition)


# ---------------------------------------------------------------------------
# chunk eval (host-side metric, IOB/IOE/IOBES)
# ---------------------------------------------------------------------------


def _extract_chunks(tags, length, scheme, num_types):
    """-> set of (type, start, end) chunks from a dense tag row."""
    n_states = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
    chunks = set()
    start = None
    ctype = None
    for i in range(length):
        t = int(tags[i])
        if t == n_states * num_types:  # the "O" tag
            if start is not None:
                chunks.add((ctype, start, i))
                start, ctype = None, None
            continue
        ty, st = divmod(t, n_states)
        if scheme == "plain":
            begin = ctype != ty or start is None
        elif scheme == "IOB":
            begin = st == 0 or ctype != ty
        elif scheme == "IOE":
            begin = start is None or ctype != ty
        else:  # IOBES: B=0, I=1, E=2, S=3
            begin = st in (0, 3) or start is None or ctype != ty
        if begin:
            if start is not None:
                chunks.add((ctype, start, i))
            start, ctype = i, ty
        if scheme == "IOE" and st == 1:  # E tag closes
            chunks.add((ctype, start, i + 1))
            start, ctype = None, None
        if scheme == "IOBES" and st in (2, 3):
            chunks.add((ctype, start, i + 1))
            start, ctype = None, None
    if start is not None:
        chunks.add((ctype, start, length))
    return chunks


def chunk_eval(input, label, chunk_scheme, num_chunk_types, seq_length=None,
               excluded_chunk_types=None, name=None):
    """Chunk-level P/R/F1 (ref: chunk_eval op; CoNLL NER convention).

    input/label: (B, L) int tag ids; tag = type * n_states + state,
    with the single "O" tag = num_chunk_types * n_states.
    Returns (precision, recall, f1, n_infer, n_label, n_correct) floats —
    host-side metric (not jit-traceable), like the reference's C++ op
    output fetched to host.
    """
    pred = np.asarray(unwrap(input))
    lab = np.asarray(unwrap(label))
    B, L = pred.shape
    lens = np.full((B,), L, np.int64) if seq_length is None \
        else np.asarray(unwrap(seq_length))
    excl = set(excluded_chunk_types or [])
    n_inf = n_lab = n_cor = 0
    for b in range(B):
        pc = {c for c in _extract_chunks(pred[b], lens[b], chunk_scheme,
                                         num_chunk_types)
              if c[0] not in excl}
        lc = {c for c in _extract_chunks(lab[b], lens[b], chunk_scheme,
                                         num_chunk_types)
              if c[0] not in excl}
        n_inf += len(pc)
        n_lab += len(lc)
        n_cor += len(pc & lc)
    p = n_cor / n_inf if n_inf else 0.0
    r = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return p, r, f1, n_inf, n_lab, n_cor


# ---------------------------------------------------------------------------
# edit distance
# ---------------------------------------------------------------------------


@register("edit_distance")
def _edit_distance(hyp, ref, hyp_len, ref_len, *, normalized):
    B, Lh = hyp.shape
    Lr = ref.shape[1]

    def one(h, r, hl, rl):
        # DP over ref positions; rows scanned over hyp tokens
        row0 = jnp.arange(Lr + 1, dtype=jnp.float32)

        def step(prev_row, inp):
            i, tok = inp  # 1-based hyp position
            in_h = i <= hl

            def row_fn(carry, inp2):
                j, up, diag = inp2  # prev_row[j], prev_row[j-1]
                left = carry
                sub = diag + jnp.where(
                    (tok == r[j - 1]) | (j > rl), 0.0, 1.0)
                # positions beyond ref length replicate the j=rl column
                val = jnp.minimum(jnp.minimum(up + 1.0, left + 1.0), sub)
                val = jnp.where(j <= rl, val, carry)
                return val, val

            first = prev_row[0] + 1.0
            _, rest = lax.scan(
                row_fn, first,
                (jnp.arange(1, Lr + 1), prev_row[1:], prev_row[:-1]))
            new_row = jnp.concatenate([first[None], rest])
            return jnp.where(in_h, new_row, prev_row), None

        rowL, _ = lax.scan(step, row0,
                           (jnp.arange(1, Lh + 1), h))
        d = rowL[jnp.clip(rl, 0, Lr)]
        return jnp.where(normalized, d / jnp.maximum(rl, 1), d)

    return jax.vmap(one)(hyp, ref, hyp_len.astype(jnp.int32),
                         ref_len.astype(jnp.int32))


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance per pair (ref: layers/loss.py edit_distance).

    input (B, Lh), label (B, Lr) int token ids, with lengths; returns
    (distances (B,), sequence_num scalar). ``ignored_tokens`` are removed
    host-side first (mirrors the reference's preprocessing).
    """
    hyp = np.asarray(unwrap(input))
    ref = np.asarray(unwrap(label))
    B = hyp.shape[0]
    hl = np.full((B,), hyp.shape[1], np.int32) if input_length is None \
        else np.asarray(unwrap(input_length)).astype(np.int32)
    rl = np.full((B,), ref.shape[1], np.int32) if label_length is None \
        else np.asarray(unwrap(label_length)).astype(np.int32)
    if ignored_tokens:
        def strip(arr, lens):
            out = np.zeros_like(arr)
            new_lens = np.zeros_like(lens)
            for b in range(B):
                row = [t for t in arr[b, :lens[b]]
                       if t not in ignored_tokens]
                out[b, :len(row)] = row
                new_lens[b] = len(row)
            return out, new_lens

        hyp, hl = strip(hyp, hl)
        ref, rl = strip(ref, rl)
    d = apply("edit_distance", Tensor(jnp.asarray(hyp), _internal=True),
              Tensor(jnp.asarray(ref), _internal=True),
              Tensor(jnp.asarray(hl), _internal=True),
              Tensor(jnp.asarray(rl), _internal=True),
              normalized=bool(normalized))
    return d, Tensor(jnp.asarray(B, jnp.int64), _internal=True)


# ---------------------------------------------------------------------------
# sampled large-vocab losses
# ---------------------------------------------------------------------------


@register("nce")
def _nce(x, label, weight, bias, key, *, num_neg, vocab):
    B = x.shape[0]
    neg = jax.random.randint(key, (B, num_neg), 0, vocab)  # uniform sampler
    pos_w = weight[label]  # (B, D)
    pos_b = bias[label]
    pos_logit = (x * pos_w).sum(-1) + pos_b
    neg_w = weight[neg]  # (B, K, D)
    neg_b = bias[neg]
    neg_logit = jnp.einsum("bd,bkd->bk", x, neg_w) + neg_b
    # NCE with uniform noise: P_n = 1/vocab; logit correction log(k*Pn)
    corr = jnp.log(num_neg / vocab)
    pos_loss = -jax.nn.log_sigmoid(pos_logit - corr)
    neg_loss = -jax.nn.log_sigmoid(-(neg_logit - corr)).sum(-1)
    return pos_loss + neg_loss


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10,
        name=None, sampler="uniform", custom_dist=None, seed=0,
        is_sparse=False, weight=None, bias=None, key=None):
    """NCE loss (ref: layers/nn.py nce): per-example loss (B,).

    Functional form: pass ``weight (V, D)`` and ``bias (V,)`` explicitly.
    """
    if sampler != "uniform" or custom_dist is not None \
            or sample_weight is not None:
        raise NotImplementedError(
            "only sampler='uniform' is implemented; log_uniform/custom "
            "samplers would bias the NCE correction term silently")
    if weight is None:
        raise ValueError("pass weight=(V, D) (and optionally bias=(V,))")
    if bias is None:
        V = unwrap(weight).shape[0]
        bias = Tensor(jnp.zeros((V,), unwrap(weight).dtype), _internal=True)
    if key is None:
        key = _random.next_key()
    lab = label.reshape([-1]) if hasattr(label, "reshape") else label
    return apply("nce", input, lab, weight, bias,
                 Tensor(key, _internal=True),
                 num_neg=int(num_neg_samples),
                 vocab=int(num_total_classes))


@register("hsigmoid")
def _hsigmoid(x, label, weight, bias, *, num_classes):
    # default complete binary tree over num_classes leaves; internal nodes
    # are num_classes-1 rows of weight. Path of leaf l: bits of (l + C)
    # from the root (MSB after the implicit 1) down.
    C = num_classes
    depth = max(int(np.ceil(np.log2(C))), 1)
    lab = label.astype(jnp.int32)
    node = lab + C  # heap index of the leaf

    # walk root->leaf: bit i of the heap index selects left/right
    losses = jnp.zeros(x.shape[0], x.dtype)
    codes = []
    nodes = []
    cur = node
    for _ in range(depth):
        codes.append((cur & 1).astype(x.dtype))  # this level's branch bit
        cur = cur >> 1
        nodes.append(jnp.clip(cur - 1, 0, C - 2))  # parent internal node
    # nodes[i] is the parent at height i+1; valid while parent index >= 1
    from ._base import bce_with_logits

    for code, nidx, lvl in zip(codes, nodes, range(depth)):
        valid = ((node >> (lvl + 1)) >= 1).astype(x.dtype)
        logit = (x * weight[nidx]).sum(-1) + bias[nidx]
        # code 1 -> right child: target sigmoid(logit) = 1
        losses = losses + bce_with_logits(logit, code) * valid
    return losses


def hsigmoid(input, label, num_classes, weight=None, bias=None,
             param_attr=None, bias_attr=None, name=None,
             path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid loss over a complete binary tree
    (ref: layers/nn.py hsigmoid). weight: (num_classes - 1, D) internal
    node vectors; returns per-example loss (B,)."""
    if weight is None:
        raise ValueError("pass weight=(num_classes - 1, D)")
    if bias is None:
        C = int(num_classes)
        bias = Tensor(jnp.zeros((C - 1,), unwrap(weight).dtype),
                      _internal=True)
    lab = label.reshape([-1]) if hasattr(label, "reshape") else label
    return apply("hsigmoid", input, lab, weight, bias,
                 num_classes=int(num_classes))


@register("sampled_softmax")
def _sampled_softmax(x, label, weight, bias, key, *, num_samples, vocab):
    B = x.shape[0]
    neg = jax.random.randint(key, (B, num_samples), 0, vocab)
    # candidate set = [true, negatives]; logQ correction for uniform
    # sampling, true class gets -inf correction removal (it is always in)
    cand = jnp.concatenate([label[:, None], neg], axis=1)  # (B, 1+K)
    w = weight[cand]  # (B, 1+K, D)
    b = bias[cand]
    logits = jnp.einsum("bd,bkd->bk", x, w) + b
    # importance-weight the sampled denominator: each negative stands in
    # for expected-count num_samples*q of the full vocab (q uniform), so
    # subtract log(k*q) from negatives only — sum_j exp(s_j - log(k q))
    # is then an unbiased estimate of the full softmax denominator
    log_kq = jnp.log(num_samples / vocab)
    # mask accidental hits (a negative equal to the true class)
    hit = cand[:, 1:] == label[:, None]
    logits = logits.at[:, 1:].set(
        jnp.where(hit, -1e30, logits[:, 1:] - log_kq))
    return -jax.nn.log_softmax(logits, axis=-1)[:, 0]


def sampled_softmax_with_cross_entropy(logits=None, label=None,
                                       num_samples=100, *, input=None,
                                       weight=None, bias=None,
                                       num_classes=None, key=None,
                                       name=None, **kwargs):
    """Sampled-softmax CE (ref: layers/loss.py sampled_softmax_with_
    cross_entropy): softmax over [true class + sampled negatives] only.
    Functional form: input (B, D) hidden, weight (V, D), bias (V,),
    label (B,). Returns per-example loss (B,)."""
    x = input if input is not None else logits
    if weight is None:
        raise ValueError("pass weight=(V, D)")
    if num_classes is None:
        num_classes = unwrap(weight).shape[0]
    if bias is None:
        bias = Tensor(jnp.zeros((int(num_classes),),
                                unwrap(weight).dtype), _internal=True)
    if key is None:
        key = _random.next_key()
    lab = label.reshape([-1]) if hasattr(label, "reshape") else label
    return apply("sampled_softmax", x, lab, weight, bias,
                 Tensor(key, _internal=True),
                 num_samples=int(num_samples), vocab=int(num_classes))
