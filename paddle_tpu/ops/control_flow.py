"""Control-flow ops.

Covers the reference's ``layers/control_flow.py`` (cond, while_loop, case,
switch_case) and the C++ ``conditional_block_op`` / ``while_op``. On TPU these
map directly onto ``lax.cond`` / ``lax.while_loop`` / ``lax.switch`` so the
loop body compiles once — no Python-side unrolling of dynamic trip counts.
In eager mode with concrete predicates we just run Python, matching dygraph.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dispatch


def _unwrap_tree(x):
    return jax.tree_util.tree_map(
        lambda v: v._data if isinstance(v, Tensor) else v, x,
        is_leaf=lambda v: isinstance(v, Tensor))


def _wrap_tree(x):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v, _internal=True) if isinstance(v, jax.Array) else v, x)


def _is_concrete(v):
    if isinstance(v, Tensor):
        v = v._data
    return not isinstance(v, jax.core.Tracer)


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Ref: layers/control_flow.py cond()."""
    if _is_concrete(pred) and dispatch.current_tracer() is None:
        p = bool(pred.item() if isinstance(pred, Tensor) else pred)
        return true_fn() if p else (false_fn() if false_fn is not None else None)
    p = pred._data if isinstance(pred, Tensor) else jnp.asarray(pred)
    out = jax.lax.cond(
        p,
        lambda _: _unwrap_tree(true_fn()),
        lambda _: _unwrap_tree(false_fn()),
        operand=None,
    )
    return _wrap_tree(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Ref: layers/control_flow.py while_loop()."""
    concrete = _is_concrete(cond_fn(*loop_vars)) and dispatch.current_tracer() is None
    if concrete:
        vars_ = list(loop_vars)
        while bool(_as_bool(cond_fn(*vars_))):
            out = body_fn(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_

    def c(state):
        return _as_bool_arr(cond_fn(*_wrap_tree(state)))

    def b(state):
        out = body_fn(*_wrap_tree(state))
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return _unwrap_tree(out)

    final = jax.lax.while_loop(c, b, _unwrap_tree(list(loop_vars)))
    return _wrap_tree(final)


def _as_bool(v):
    if isinstance(v, Tensor):
        return bool(v.item())
    return bool(v)


def _as_bool_arr(v):
    if isinstance(v, Tensor):
        return v._data.reshape(())
    return jnp.asarray(v).reshape(())


def case(pred_fn_pairs, default=None, name=None):
    """Ref: layers/control_flow.py case()."""
    for pred, fn in pred_fn_pairs:
        if _is_concrete(pred):
            if _as_bool(pred):
                return fn()
        else:
            # build nested lax.cond chain
            rest = pred_fn_pairs[pred_fn_pairs.index((pred, fn)) + 1:]
            return cond(pred, fn, lambda: case(rest, default))
    if default is not None:
        return default()
    raise ValueError("no branch taken in case() and no default provided")


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Ref: layers/control_flow.py switch_case()."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        if _is_concrete(branch_index):
            i = int(branch_index.item() if isinstance(branch_index, Tensor) else branch_index)
            if i in branch_fns:
                return branch_fns[i]()
            return default() if default is not None else fns[-1]()
        # map arbitrary keys onto dense switch
        idx = branch_index._data if isinstance(branch_index, Tensor) else jnp.asarray(branch_index)
        dense = jnp.zeros((), jnp.int32) + len(fns)  # default slot
        for pos, k in enumerate(keys):
            dense = jnp.where(idx == k, pos, dense)
        all_fns = [lambda f=f: _unwrap_tree(f()) for f in fns]
        all_fns.append(lambda: _unwrap_tree((default or fns[-1])()))
        return _wrap_tree(jax.lax.switch(dense, all_fns))
    fns = list(branch_fns)
    if _is_concrete(branch_index):
        i = int(branch_index.item() if isinstance(branch_index, Tensor) else branch_index)
        if 0 <= i < len(fns):
            return fns[i]()
        return default() if default is not None else fns[-1]()
    idx = branch_index._data if isinstance(branch_index, Tensor) else jnp.asarray(branch_index)
    return _wrap_tree(jax.lax.switch(idx, [lambda f=f: _unwrap_tree(f()) for f in fns]))


def scan(f, init, xs, length=None, reverse=False, unroll=1):
    """TPU-native sequential loop (lax.scan passthrough with Tensor wrapping)."""
    def body(carry, x):
        c, y = f(_wrap_tree(carry), _wrap_tree(x))
        return _unwrap_tree(c), _unwrap_tree(y)

    carry, ys = jax.lax.scan(body, _unwrap_tree(init), _unwrap_tree(xs),
                             length=length, reverse=reverse, unroll=unroll)
    return _wrap_tree(carry), _wrap_tree(ys)
