"""Op plumbing shared by all op modules.

Kernels are module-level pure functions registered by name
(ref: ``paddle/fluid/framework/op_registry.h`` REGISTER_OP_KERNEL). The
registry lets the static-graph serializer reconstruct an op from
``(name, attrs)`` alone.
"""
from __future__ import annotations

from ..core import dispatch
from ..core.tensor import Tensor

OP_REGISTRY: dict[str, callable] = {}


def register(name):
    def deco(fn):
        OP_REGISTRY[name] = fn
        fn._op_name = name
        return fn

    return deco


def apply(name, *tensor_args, **attrs):
    """Dispatch a registered op."""
    return dispatch.apply(name, OP_REGISTRY[name], *tensor_args, **attrs)


def unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def bce_with_logits(logit, target):
    """Numerically stable sigmoid cross-entropy on raw logits
    (shared by the yolo/focal/hsigmoid kernels)."""
    import jax.numpy as jnp

    return (jnp.maximum(logit, 0) - logit * target
            + jnp.log1p(jnp.exp(-jnp.abs(logit))))


def wrap(arr, stop_gradient=True):
    return Tensor(arr, stop_gradient=stop_gradient, _internal=True)
