"""Convolution / pooling / resize ops.

Covers the reference's ``conv_op.cc``, ``conv_transpose_op.cc``,
``pool_op.cc``, ``adaptive pooling``, ``interpolate_op.cc``,
``pixel_shuffle_op.cc``, ``unfold_op.cc``.

All convs lower to ``lax.conv_general_dilated`` which XLA maps onto the MXU;
NCHW in/out is accepted for API parity but XLA freely relayouts internally.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ._base import register, apply, unwrap


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# Low-precision convs run in the input dtype end to end: the TPU MXU
# accumulates bf16 convs in float32 internally, so no explicit
# preferred_element_type is needed — and requesting one breaks the vjp
# (an f32 cotangent meets bf16 operands in the transpose conv).


def _conv_padding(padding, nsp, stride=None, ksize=None, dilation=None):
    """Normalize paddle padding spec -> lax padding."""
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * nsp
    padding = list(padding)
    if len(padding) == nsp and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nsp:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nsp)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # [[0,0],[0,0],[t,b],[l,r]] NCHW form: keep trailing spatial entries
        return [tuple(p) for p in padding[-nsp:]]
    raise ValueError(f"bad padding {padding}")


@register("conv2d")
def _conv2d(x, w, *, stride, padding, dilation, groups, data_format="NCHW"):
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """Ref: paddle/fluid/operators/conv_op.cc (Conv2D forward).

    weight layout OIHW (paddle convention); NHWC supported via data_format.
    """
    stride = _pair(stride, 2)
    dilation = _pair(dilation, 2)
    pad = _conv_padding(padding, 2)
    out = apply("conv2d", x, weight, stride=stride, padding=pad,
                dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        from .math import add

        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = add(out, bias.reshape(list(shape)))
    return out


@register("conv1d")
def _conv1d(x, w, *, stride, padding, dilation, groups, data_format="NCL"):
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "HIO", "NHC"))
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    stride = _pair(stride, 1)
    dilation = _pair(dilation, 1)
    pad = _conv_padding(padding, 1)
    out = apply("conv1d", x, weight, stride=stride, padding=pad,
                dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        from .math import add

        shape = (1, -1, 1) if data_format == "NCL" else (1, 1, -1)
        out = add(out, bias.reshape(list(shape)))
    return out


@register("conv3d")
def _conv3d(x, w, *, stride, padding, dilation, groups, data_format="NCDHW"):
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW" else ("NDHWC", "DHWIO", "NDHWC"))
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    pad = _conv_padding(padding, 3)
    out = apply("conv3d", x, weight, stride=stride, padding=pad,
                dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        from .math import add

        shape = (1, -1, 1, 1, 1) if data_format == "NCDHW" else (1, 1, 1, 1, -1)
        out = add(out, bias.reshape(list(shape)))
    return out


@register("conv2d_transpose")
def _conv2d_transpose(x, w, *, stride, padding, dilation, groups, output_padding):
    # w layout IOHW (paddle transpose-conv convention: [in, out/groups, kh, kw]).
    # Implemented as a fractionally-strided conv: lhs_dilation=stride with a
    # flipped kernel; out = (in-1)*s - 2p + d*(k-1) + op + 1 (paddle formula).
    # Shared math lives in _convnd_transpose (also serves the 1-D/3-D ops).
    return _convnd_transpose(x, w, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_padding=output_padding, nsp=2)


def _output_padding_for(output_size, x_spatial, stride, padding, dilation,
                        ksize):
    """Derive output_padding from a requested output_size (paddle lets the
    user disambiguate the transposed-conv output shape either way)."""
    ops = []
    for out, inp, s, (p0, p1), d, k in zip(output_size, x_spatial, stride,
                                           padding, dilation, ksize):
        base = (inp - 1) * s - (p0 + p1) + d * (k - 1) + 1
        op = int(out) - base
        if op < 0 or op >= s:
            raise ValueError(
                f"output_size {output_size} unreachable: needs output_padding"
                f" {op} for stride {s}")
        ops.append(op)
    return tuple(ops)


def _conv_transpose_wrapper(opname, nsp, x, weight, bias, stride, padding,
                            output_padding, dilation, groups, output_size,
                            data_format):
    channel_last = data_format in ("NLC", "NHWC", "NDHWC")
    stride = _pair(stride, nsp)
    dilation = _pair(dilation, nsp)
    pad = _conv_padding(padding, nsp)
    from .manipulation import transpose as _tr

    if channel_last:
        perm_in = [0, nsp + 1] + list(range(1, nsp + 1))
        x = _tr(x, perm_in)
    if output_size is not None:
        if isinstance(pad, str):
            raise ValueError("output_size with SAME/VALID padding is ambiguous")
        if isinstance(output_size, int):
            output_size = (output_size,) * nsp
        xs = unwrap(x).shape[2:]
        output_padding = _output_padding_for(output_size, xs, stride, pad,
                                             dilation, unwrap(weight).shape[2:])
    else:
        output_padding = _pair(output_padding, nsp)
    out = apply(opname, x, weight, stride=stride, padding=pad,
                dilation=dilation, groups=groups, output_padding=output_padding)
    if bias is not None:
        from .math import add

        out = add(out, bias.reshape([1, -1] + [1] * nsp))
    if channel_last:
        perm_out = [0] + list(range(2, nsp + 2)) + [1]
        out = _tr(out, perm_out)
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose_wrapper("conv2d_transpose", 2, x, weight, bias,
                                   stride, padding, output_padding, dilation,
                                   groups, output_size, data_format)


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def _pool(x, init, op, ksize, stride, padding, nsp, count_include_pad=True, avg=False):
    window = (1, 1) + ksize
    strides = (1, 1) + stride
    if isinstance(padding, str):
        pad = padding
    else:
        pad = ((0, 0), (0, 0)) + tuple(padding)
    out = lax.reduce_window(x, init, op, window, strides, pad)
    if avg:
        if count_include_pad or (isinstance(pad, str) and pad == "VALID"):
            out = out / float(np.prod(ksize))
        else:
            ones = jnp.ones(x.shape[2:], x.dtype)[None, None]
            counts = lax.reduce_window(jnp.broadcast_to(ones, x.shape), 0.0, lax.add, window, strides, pad)
            out = out / counts
    return out


@register("max_pool2d")
def _max_pool2d(x, *, ksize, stride, padding):
    return _pool(x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
                 lax.max, ksize, stride, padding, 2)


@register("avg_pool2d")
def _avg_pool2d(x, *, ksize, stride, padding, count_include_pad=True):
    return _pool(x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x, 0.0, lax.add,
                 ksize, stride, padding, 2, count_include_pad, avg=True).astype(x.dtype)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    ksize = _pair(kernel_size, 2)
    stride = ksize if stride is None else _pair(stride, 2)
    pad = _conv_padding(padding, 2)
    if data_format != "NCHW":
        from .manipulation import transpose

        x = transpose(x, [0, 3, 1, 2])
        out = apply("max_pool2d", x, ksize=ksize, stride=stride, padding=pad)
        return transpose(out, [0, 2, 3, 1])
    return apply("max_pool2d", x, ksize=ksize, stride=stride, padding=pad)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               count_include_pad=True, divisor_override=None,
               data_format="NCHW", name=None):
    ksize = _pair(kernel_size, 2)
    stride = ksize if stride is None else _pair(stride, 2)
    pad = _conv_padding(padding, 2)
    return apply("avg_pool2d", x, ksize=ksize, stride=stride, padding=pad,
                 count_include_pad=count_include_pad)


def pool2d(x, pool_size, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, exclusive=True, data_format="NCHW"):
    """Ref: layers/nn.py pool2d (fluid API)."""
    if global_pooling:
        pool_size = tuple(unwrap(x).shape[2:])
        pool_padding = 0
        pool_stride = 1
    if pool_type == "max":
        return max_pool2d(x, pool_size, pool_stride, pool_padding)
    return avg_pool2d(x, pool_size, pool_stride, pool_padding, count_include_pad=not exclusive)


@register("max_pool1d")
def _max_pool1d(x, *, ksize, stride, padding):
    x4 = x[:, :, None, :]
    out = _pool(x4, -jnp.inf, lax.max, (1,) + ksize, (1,) + stride,
                ((0, 0),) + tuple(padding) if not isinstance(padding, str) else padding, 2)
    return out[:, :, 0, :]


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    ksize = _pair(kernel_size, 1)
    stride = ksize if stride is None else _pair(stride, 1)
    pad = _conv_padding(padding, 1)
    return apply("max_pool1d", x, ksize=ksize, stride=stride, padding=pad)


@register("avg_pool1d")
def _avg_pool1d(x, *, ksize, stride, padding, count_include_pad=True):
    x4 = x[:, :, None, :]
    out = _pool(x4, 0.0, lax.add, (1,) + ksize, (1,) + stride,
                ((0, 0),) + tuple(padding) if not isinstance(padding, str) else padding, 2,
                count_include_pad, avg=True)
    return out[:, :, 0, :]


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    ksize = _pair(kernel_size, 1)
    stride = ksize if stride is None else _pair(stride, 1)
    pad = _conv_padding(padding, 1)
    return apply("avg_pool1d", x, ksize=ksize, stride=stride, padding=pad,
                 count_include_pad=not exclusive)


@register("max_pool3d")
def _max_pool3d(x, *, ksize, stride, padding):
    window = (1, 1) + ksize
    strides = (1, 1) + stride
    pad = padding if isinstance(padding, str) else ((0, 0), (0, 0)) + tuple(padding)
    return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    ksize = _pair(kernel_size, 3)
    stride = ksize if stride is None else _pair(stride, 3)
    pad = _conv_padding(padding, 3)
    return apply("max_pool3d", x, ksize=ksize, stride=stride, padding=pad)


@register("avg_pool3d")
def _avg_pool3d(x, *, ksize, stride, padding, count_include_pad=True):
    return _pool(x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x,
                 0.0, lax.add, ksize, stride, padding, 3,
                 count_include_pad, avg=True).astype(x.dtype)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCDHW", name=None):
    ksize = _pair(kernel_size, 3)
    stride = ksize if stride is None else _pair(stride, 3)
    pad = _conv_padding(padding, 3)
    return apply("avg_pool3d", x, ksize=ksize, stride=stride, padding=pad,
                 count_include_pad=not exclusive)


@register("adaptive_avg_pool2d")
def _adaptive_avg_pool2d(x, *, output_size):
    n, c, h, w = x.shape
    oh, ow = output_size
    # When input divides evenly this is a plain reshape-mean (the common case:
    # global pooling oh=ow=1); otherwise fall back to per-window mean.
    if h % oh == 0 and w % ow == 0:
        return jnp.mean(jnp.reshape(x, (n, c, oh, h // oh, ow, w // ow)), axis=(3, 5))
    ys = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh))) for i in range(oh)]
    xs = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow))) for j in range(ow)]
    rows = [jnp.stack([jnp.mean(x[:, :, y0:y1, x0:x1], axis=(2, 3)) for (x0, x1) in xs], axis=-1)
            for (y0, y1) in ys]
    return jnp.stack(rows, axis=-2)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return apply("adaptive_avg_pool2d", x, output_size=_pair(output_size, 2))


@register("adaptive_max_pool2d")
def _adaptive_max_pool2d(x, *, output_size):
    n, c, h, w = x.shape
    oh, ow = output_size
    if h % oh == 0 and w % ow == 0:
        return jnp.max(jnp.reshape(x, (n, c, oh, h // oh, ow, w // ow)), axis=(3, 5))
    ys = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh))) for i in range(oh)]
    xs = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow))) for j in range(ow)]
    rows = [jnp.stack([jnp.max(x[:, :, y0:y1, x0:x1], axis=(2, 3)) for (x0, x1) in xs], axis=-1)
            for (y0, y1) in ys]
    return jnp.stack(rows, axis=-2)


@register("adaptive_max_pool2d_mask")
def _adaptive_max_pool2d_mask(x, *, output_size):
    # (out, mask): mask holds the flattened h*w argmax per output cell
    # (ref: max_pool_with_index_op.cc contract used by adaptive_pool2d).
    n, c, h, w = x.shape
    oh, ow = output_size
    ys = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh)))
          for i in range(oh)]
    xs = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow)))
          for j in range(ow)]
    outs, idxs = [], []
    for (y0, y1) in ys:
        row_o, row_i = [], []
        for (x0, x1) in xs:
            cell = x[:, :, y0:y1, x0:x1].reshape(n, c, -1)
            flat = jnp.argmax(cell, axis=-1)
            cw = x1 - x0
            gy = y0 + flat // cw
            gx = x0 + flat % cw
            row_o.append(jnp.max(cell, axis=-1))
            row_i.append(gy * w + gx)
        outs.append(jnp.stack(row_o, axis=-1))
        idxs.append(jnp.stack(row_i, axis=-1))
    return jnp.stack(outs, axis=-2), jnp.stack(idxs, axis=-2)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return apply("adaptive_max_pool2d_mask", x,
                     output_size=_pair(output_size, 2))
    return apply("adaptive_max_pool2d", x, output_size=_pair(output_size, 2))


def adaptive_avg_pool1d(x, output_size, name=None):
    out = adaptive_avg_pool2d(x[:, :, None, :] if isinstance(x, jnp.ndarray) else _unsq(x),
                              (1, int(output_size) if not isinstance(output_size, (list, tuple)) else int(output_size[0])))
    from .manipulation import squeeze

    return squeeze(out, 2)


def _unsq(x):
    from .manipulation import unsqueeze

    return unsqueeze(x, 2)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = adaptive_max_pool2d(_unsq(x), (1, int(output_size)))
    from .manipulation import squeeze

    return squeeze(out, 2)


# ---------------------------------------------------------------------------
# resize / shuffle / unfold
# ---------------------------------------------------------------------------


def _src_positions(n_in, n_out, align_corners, align_mode):
    """Output-pixel -> source coordinate (ref interpolate_op.h:118,572):
    out==1 -> ratio 0, i.e. src row 0; align_corners -> src =
    dst*(in-1)/(out-1); else align_mode==0 -> half-pixel centers
    src = (dst+0.5)*in/out - 0.5 (clamped at 0), align_mode==1 ->
    src = dst*in/out."""
    dst = jnp.arange(n_out, dtype=jnp.float32)
    if n_out <= 1:
        pos = jnp.zeros((n_out,), jnp.float32)
    elif align_corners:
        pos = dst * ((n_in - 1) / (n_out - 1))
    elif align_mode == 0:
        pos = (dst + 0.5) * (n_in / n_out) - 0.5
    else:
        pos = dst * (n_in / n_out)
    return pos


def _clamped_positions(n_in, n_out, align_corners, align_mode):
    return jnp.clip(
        _src_positions(n_in, n_out, align_corners, align_mode),
        0.0, n_in - 1)


def _cubic_contrib(t, a=-0.75):
    """Keys cubic convolution kernel (the 2.x bicubic convention)."""
    t = jnp.abs(t)
    w1 = ((a + 2.0) * t - (a + 3.0)) * t * t + 1.0       # |t| <= 1
    w2 = a * (((t - 5.0) * t + 8.0) * t - 4.0)           # 1 < |t| < 2
    return jnp.where(t <= 1.0, w1, jnp.where(t < 2.0, w2, 0.0))


def _resize_weights(n_in, n_out, align_corners, align_mode, mode="linear"):
    """(n_out, n_in) interpolation matrix for one spatial axis; resize
    becomes a per-axis matmul — the MXU-native formulation (vs gathers).
    Edge handling matches the reference kernels: positions clamp into
    [0, in-1] and out-of-range taps accumulate at the clamped index."""
    rows = jnp.arange(n_out)
    if mode == "nearest":
        # ref interpolate_op.h:88: nearest ignores align_mode —
        # floor(ratio*dst + 0.5) when align_corners else floor(ratio*dst)
        pos = _clamped_positions(n_in, n_out, align_corners, 1)
        idx = jnp.floor(pos + (0.5 if align_corners else 0.0))
        idx = jnp.clip(idx.astype(jnp.int32), 0, n_in - 1)
        return jax.nn.one_hot(idx, n_in, dtype=jnp.float32)
    if mode == "cubic":
        # bicubic (a 2.x-surface extension; no 1.x kernel): half-pixel
        # unless align_corners; weights come from the UNCLAMPED source
        # position (only tap indices clamp — the cubic kernel's border
        # convention), 4 taps accumulated at clamped indices
        pos = _src_positions(n_in, n_out, align_corners, 0)
        lo = jnp.floor(pos).astype(jnp.int32)
        W = jnp.zeros((n_out, n_in), jnp.float32)
        for tap in (-1, 0, 1, 2):
            i = lo + tap
            wgt = _cubic_contrib(pos - i.astype(jnp.float32))
            W = W.at[rows, jnp.clip(i, 0, n_in - 1)].add(wgt)
        return W
    pos = _clamped_positions(n_in, n_out, align_corners, align_mode)
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, n_in - 1)
    frac = pos - lo
    W = jnp.zeros((n_out, n_in), jnp.float32)
    W = W.at[rows, lo].add(1.0 - frac)
    W = W.at[rows, hi].add(frac)
    return W


@register("interpolate")
def _interpolate(x, *, size, mode, align_corners, align_mode=1):
    """N-spatial-dim resize as one interpolation matmul per axis —
    NCL linear, NCHW bilinear/bicubic/nearest, NCDHW trilinear all
    share the same per-axis weights."""
    axis_mode = {"nearest": "nearest", "linear": "linear",
                 "bilinear": "linear", "trilinear": "linear",
                 "bicubic": "cubic", "area": "linear"}[mode]
    spatial = x.shape[2:]
    if len(size) != len(spatial):
        raise ValueError(f"size {size} does not match the "
                         f"{len(spatial)} spatial dims of {x.shape}")
    dt = x.dtype
    out = x.astype(jnp.float32)
    for ax, (n_in, n_out) in enumerate(zip(spatial, size)):
        W = _resize_weights(n_in, n_out, align_corners, align_mode,
                            mode=axis_mode)
        out = jnp.moveaxis(
            jnp.tensordot(jnp.moveaxis(out, 2 + ax, -1), W.T, axes=1),
            -1, 2 + ax)
    return out.astype(dt)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    shp = unwrap(x).shape
    if size is None:
        nsp = len(shp) - 2
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else (scale_factor,) * nsp
        size = tuple(int(shp[2 + i] * sf[i]) for i in range(nsp))
    else:
        if isinstance(size, Tensor):
            size = [int(v) for v in np.asarray(size._data)]
        size = tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in size)
    return apply("interpolate", x, size=tuple(size), mode=mode,
                 align_corners=bool(align_corners),
                 align_mode=int(align_mode))


upsample = interpolate


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    """ref: layers/nn.py resize_bilinear — fluid defaults are
    align_corners=True, align_mode=1."""
    return interpolate(input, size=out_shape, scale_factor=scale,
                       mode="bilinear", align_corners=align_corners,
                       align_mode=align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True,
                   data_format="NCHW"):
    """ref: layers/nn.py resize_nearest."""
    return interpolate(input, size=out_shape, scale_factor=scale,
                       mode="nearest", align_corners=align_corners,
                       align_mode=1)


@register("pixel_shuffle")
def _pixel_shuffle(x, *, upscale_factor):
    n, c, h, w = x.shape
    r = upscale_factor
    x = jnp.reshape(x, (n, c // (r * r), r, r, h, w))
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return jnp.reshape(x, (n, c // (r * r), h * r, w * r))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return apply("pixel_shuffle", x, upscale_factor=int(upscale_factor))


@register("pixel_unshuffle")
def _pixel_unshuffle(x, *, downscale_factor):
    n, c, h, w = x.shape
    r = downscale_factor
    x = jnp.reshape(x, (n, c, h // r, r, w // r, r))
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return jnp.reshape(x, (n, c * r * r, h // r, w // r))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    """Inverse of pixel_shuffle (ref: pixel_shuffle_op.cc reverse)."""
    return apply("pixel_unshuffle", x,
                 downscale_factor=int(downscale_factor))


@register("space_to_depth")
def _space_to_depth(x, *, blocksize):
    # Reference layout: block offset is the HIGH-order part of the output
    # channel ((by*bs + bx)*C + c) — NOT pixel_unshuffle's channel-major
    # (c*bs*bs + offset); they only coincide for C == 1.
    n, c, h, w = x.shape
    r = blocksize
    x = jnp.reshape(x, (n, c, h // r, r, w // r, r))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))  # (n, by, bx, c, h', w')
    return jnp.reshape(x, (n, r * r * c, h // r, w // r))


def space_to_depth(x, blocksize, name=None):
    """ref: space_to_depth_op.cc — rearrange (B, C, H, W) spatial blocks
    into channels: (B, bs*bs*C, H/bs, W/bs), block-offset-major."""
    return apply("space_to_depth", x, blocksize=int(blocksize))


@register("affine_grid")
def _affine_grid(theta, *, out_h, out_w, align_corners):
    n = theta.shape[0]
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, out_h)
        xs = jnp.linspace(-1.0, 1.0, out_w)
    else:
        ys = (jnp.arange(out_h) * 2.0 + 1.0) / out_h - 1.0
        xs = (jnp.arange(out_w) * 2.0 + 1.0) / out_w - 1.0
    xg, yg = jnp.meshgrid(xs, ys)  # (H, W)
    ones = jnp.ones_like(xg)
    base = jnp.stack([xg, yg, ones], axis=-1)  # (H, W, 3)
    # grid = base @ theta^T per batch: (N, H, W, 2)
    return jnp.einsum("hwk,nik->nhwi", base, theta)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D affine sampling grid (ref: layers/nn.py affine_grid).

    theta: (N, 2, 3); out_shape [N, C, H, W] -> grid (N, H, W, 2) in
    normalized [-1, 1] xy coords (grid_sample convention)."""
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in np.asarray(unwrap(out_shape))]
    return apply("affine_grid", theta, out_h=int(out_shape[2]),
                 out_w=int(out_shape[3]), align_corners=bool(align_corners))


@register("grid_sample")
def _grid_sample(x, grid, *, mode, padding_mode, align_corners):
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]  # (N, H', W') in [-1, 1]
    if align_corners:
        fx = (gx + 1.0) * 0.5 * (w - 1)
        fy = (gy + 1.0) * 0.5 * (h - 1)
    else:
        fx = ((gx + 1.0) * w - 1.0) * 0.5
        fy = ((gy + 1.0) * h - 1.0) * 0.5

    def reflect(v, lo, hi):
        """Triangular-wave reflection into [lo, hi]: in-range values
        come back unchanged, out-of-range fold back from the nearer
        edge (rng - |mod(v-lo, 2rng) - rng| + lo; the |...| alone
        would MIRROR in-range values across the interval)."""
        rng = hi - lo
        return rng - jnp.abs(jnp.mod(v - lo, 2 * rng + 1e-12) - rng) + lo

    if padding_mode == "border":
        fx = jnp.clip(fx, 0.0, w - 1.0)
        fy = jnp.clip(fy, 0.0, h - 1.0)
    elif padding_mode == "reflection":
        if align_corners:
            # reflect around pixel CENTERS (interval [0, size-1])
            fx = reflect(fx, 0.0, w - 1.0)
            fy = reflect(fy, 0.0, h - 1.0)
        else:
            # reflect around pixel EDGES ([-0.5, size-0.5]), as torch
            # and the reference kernel do for unaligned corners
            fx = reflect(fx, -0.5, w - 0.5)
            fy = reflect(fy, -0.5, h - 0.5)
        fx = jnp.clip(fx, 0.0, w - 1.0)
        fy = jnp.clip(fy, 0.0, h - 1.0)

    def tap(ix, iy):
        """x[n, :, iy, ix] with zero padding OOB -> (N, H', W', C)."""
        inb = ((ix >= 0) & (ix <= w - 1) & (iy >= 0)
               & (iy <= h - 1))
        ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
        v = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, iyc, ixc)
        # v: (N, C, H', W')
        return v * inb[:, None].astype(x.dtype)

    if mode == "nearest":
        return tap(jnp.round(fx), jnp.round(fy))
    x0 = jnp.floor(fx)
    y0 = jnp.floor(fy)
    wx = (fx - x0).astype(x.dtype)[:, None]
    wy = (fy - y0).astype(x.dtype)[:, None]
    v00 = tap(x0, y0)
    v01 = tap(x0 + 1, y0)
    v10 = tap(x0, y0 + 1)
    v11 = tap(x0 + 1, y0 + 1)
    return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
            + v10 * (1 - wx) * wy + v11 * wx * wy)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x at normalized grid locations (ref: layers/nn.py:12182
    grid_sampler). x (N, C, H, W); grid (N, H', W', 2) xy in [-1, 1].
    Returns (N, C, H', W')."""
    return apply("grid_sample", x, grid, mode=mode,
                 padding_mode=padding_mode,
                 align_corners=bool(align_corners))


grid_sampler = grid_sample


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None,
                 align_corners=True, align_mode=1, data_format="NCHW"):
    """ref: layers/nn.py image_resize — thin front over interpolate.
    align_corners and align_mode 0/1 follow the fluid interpolate_op
    conventions (weight-matrix resize, see _resize_weights)."""
    modes = {"BILINEAR": "bilinear", "NEAREST": "nearest",
             "BICUBIC": "bicubic"}
    key = str(resample).upper()
    if key == "TRILINEAR":
        return resize_trilinear(input, out_shape=out_shape, scale=scale,
                                actual_shape=actual_shape,
                                align_corners=align_corners,
                                align_mode=align_mode)
    if key not in modes:
        raise ValueError(
            f"resample={resample!r} not supported (have "
            f"{sorted(modes) + ['TRILINEAR']})")
    return interpolate(input, size=out_shape, scale_factor=scale,
                       mode=modes[key], align_corners=align_corners,
                       align_mode=align_mode)


@register("unfold")
def _unfold(x, *, ksize, stride, padding, dilation):
    n, c, h, w = x.shape
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=ksize, window_strides=stride,
        padding=[(padding[0], padding[0]), (padding[1], padding[1])] if isinstance(padding[0], int) else padding,
        rhs_dilation=dilation)
    # patches: (N, C*kh*kw, OH, OW) -> (N, C*kh*kw, OH*OW)
    return jnp.reshape(patches, (n, patches.shape[1], -1))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    return apply("unfold", x, ksize=_pair(kernel_sizes, 2), stride=_pair(strides, 2),
                 padding=_pair(paddings, 2), dilation=_pair(dilations, 2))


# ---------------------------------------------------------------------------
# 1-D / 3-D transposed conv (ref: conv_transpose_op.cc covers 1/2/3-D)
# ---------------------------------------------------------------------------


def _convnd_transpose(x, w, *, stride, padding, dilation, groups,
                      output_padding, nsp):
    # Same fractionally-strided formulation as conv2d_transpose, generalized
    # over nsp spatial dims. w layout: [in, out/groups, *k].
    spatial = tuple(range(-nsp, 0))
    if groups > 1:
        i, o = w.shape[0], w.shape[1]
        w_t = jnp.reshape(w, (groups, i // groups, o, *w.shape[2:]))
        w_t = jnp.swapaxes(w_t, 1, 2)
        w_t = jnp.reshape(w_t, (groups * o, i // groups, *w.shape[2:]))
    else:
        w_t = jnp.swapaxes(w, 0, 1)
    w_t = jnp.flip(w_t, axis=spatial)
    chars = "DHW"[-nsp:]
    fmt = ("NC" + chars, "OI" + chars, "NC" + chars)
    dn = lax.conv_dimension_numbers(x.shape, w_t.shape, fmt)
    if isinstance(padding, str):
        pad = padding
    else:
        pad = [(d * (k - 1) - p0, d * (k - 1) - p1 + op)
               for (p0, p1), k, d, op in zip(padding, w.shape[2:], dilation,
                                             output_padding)]
    return lax.conv_general_dilated(
        x, w_t, window_strides=(1,) * nsp, padding=pad, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups)


@register("conv1d_transpose")
def _conv1d_transpose(x, w, *, stride, padding, dilation, groups, output_padding):
    return _convnd_transpose(x, w, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_padding=output_padding, nsp=1)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv_transpose_wrapper("conv1d_transpose", 1, x, weight, bias,
                                   stride, padding, output_padding, dilation,
                                   groups, output_size, data_format)


@register("conv3d_transpose")
def _conv3d_transpose(x, w, *, stride, padding, dilation, groups, output_padding):
    return _convnd_transpose(x, w, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_padding=output_padding, nsp=3)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_wrapper("conv3d_transpose", 3, x, weight, bias,
                                   stride, padding, output_padding, dilation,
                                   groups, output_size, data_format)


@register("shuffle_channel")
def _shuffle_channel(x, *, group):
    n, c, h, w = x.shape
    x = jnp.reshape(x, (n, group, c // group, h, w))
    x = jnp.transpose(x, (0, 2, 1, 3, 4))
    return jnp.reshape(x, (n, c, h, w))


def shuffle_channel(x, group, name=None):
    """ShuffleNet channel shuffle (ref: shuffle_channel_op.cc)."""
    if unwrap(x).shape[1] % group:
        raise ValueError(
            f"channels {unwrap(x).shape[1]} not divisible by {group}")
    return apply("shuffle_channel", x, group=int(group))


def im2sequence(input, filter_size=1, stride=1, padding=0,
                input_image_size=None, out_stride=1, name=None):
    """Unfold image patches into a sequence (ref: im2sequence_op.cc):
    (N, C, H, W) -> (N, OH*OW, C*kh*kw), row-major patch order."""
    if input_image_size is not None or out_stride != 1:
        raise NotImplementedError(
            "per-sample real-size patch grids (input_image_size/"
            "out_stride) are not implemented; patches come from the "
            "padded static H/W")
    ks = _pair(filter_size, 2)
    st = _pair(stride, 2)
    out = unfold(input, ks, strides=st, paddings=padding)
    # unfold gives (N, C*kh*kw, OH*OW); sequence layout wants time first
    from .manipulation import transpose as _tr

    return _tr(out, [0, 2, 1])


@register("resize_trilinear_op")
def _resize_trilinear(x, *, size, align_corners=True, align_mode=1):
    # attr defaults match the fluid signature so programs saved before
    # these attrs existed still replay; the math is the shared N-d
    # per-axis kernel (one implementation to keep in sync)
    return _interpolate(x, size=tuple(size), mode="trilinear",
                        align_corners=align_corners,
                        align_mode=align_mode)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    """Trilinear resize of NCDHW volumes (ref: nn.py resize_trilinear).
    Honors align_corners (corner-aligned src = dst*(in-1)/(out-1), the
    fluid default) and align_mode 0/1; ``actual_shape`` — the
    reference's runtime-tensor output shape — supplies out_shape when
    given (static ints here)."""
    shp = unwrap(input).shape
    if actual_shape is not None:
        out_shape = [int(v) for v in np.asarray(
            actual_shape._data if isinstance(actual_shape, Tensor)
            else actual_shape)][-3:]
    if out_shape is None:
        out_shape = [int(shp[2] * scale), int(shp[3] * scale),
                     int(shp[4] * scale)]
    out_shape = tuple(int(v) for v in out_shape)
    return apply("resize_trilinear_op", input, size=out_shape,
                 align_corners=bool(align_corners),
                 align_mode=int(align_mode))


@register("adaptive_pool3d_op")
def _adaptive_pool3d(x, *, output_size, pool_type):
    n, c, d, h, w = x.shape
    od, oh, ow = output_size
    red = jnp.max if pool_type == "max" else jnp.mean
    # static per-cell bucket loops (output sizes are small Python ints)
    rows = []
    for i in range(od):
        d0, d1 = (i * d) // od, max(((i + 1) * d + od - 1) // od, (i * d) // od + 1)
        plane = []
        for j in range(oh):
            h0, h1 = (j * h) // oh, max(((j + 1) * h + oh - 1) // oh, (j * h) // oh + 1)
            cells = []
            for k in range(ow):
                w0, w1 = (k * w) // ow, max(((k + 1) * w + ow - 1) // ow, (k * w) // ow + 1)
                cells.append(red(x[:, :, d0:d1, h0:h1, w0:w1], axis=(2, 3, 4)))
            plane.append(jnp.stack(cells, axis=-1))
        rows.append(jnp.stack(plane, axis=-2))
    return jnp.stack(rows, axis=-3)


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """Adaptive 3-D pooling (ref: nn.py adaptive_pool3d)."""
    if isinstance(pool_size, int):
        pool_size = (pool_size,) * 3
    return apply("adaptive_pool3d_op", input,
                 output_size=tuple(int(s) for s in pool_size),
                 pool_type=pool_type)


@register("deformable_conv_op")
def _deformable_conv(x, offset, mask, weight, bias, *, stride, padding,
                     dilation, groups, deformable_groups=1):
    # ref: layers/nn.py deformable_conv (deformable_conv_op.cu). v1/v2
    # via bilinear sampling: for each kernel tap (r, s) the input is
    # sampled at p0 + (r,s)*dilation + learned offset, optionally scaled
    # by a modulation mask (v2), then the taps contract with the weight
    # as a dense matmul (MXU) — the XLA-native layout of the CUDA
    # im2col+gemm kernel. Each of the ``deformable_groups`` channel
    # groups (C/dg channels) has its own offset/mask planes.
    B, C, H, W = x.shape
    O, Cg, KH, KW = weight.shape
    dg = deformable_groups
    sh, sw = stride
    ph, pw = padding
    dh, dw = dilation
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    Hp, Wp = xp.shape[2], xp.shape[3]
    OH = (H + 2 * ph - (dh * (KH - 1) + 1)) // sh + 1
    OW = (W + 2 * pw - (dw * (KW - 1) + 1)) // sw + 1
    oy = jnp.arange(OH) * sh
    ox = jnp.arange(OW) * sw
    Cd = C // dg
    cols = []
    for r in range(KH):
        for s in range(KW):
            k = r * KW + s
            vals = []
            for d in range(dg):
                base = d * 2 * KH * KW
                dy = offset[:, base + 2 * k, :OH, :OW]     # (B, OH, OW)
                dx = offset[:, base + 2 * k + 1, :OH, :OW]
                yy = oy[None, :, None] + r * dh + dy
                xx = ox[None, None, :] + s * dw + dx
                y0 = jnp.floor(yy)
                x0 = jnp.floor(xx)
                wy = yy - y0
                wx = xx - x0
                xg = xp[:, d * Cd:(d + 1) * Cd]

                def gather(yi, xi):
                    yi = jnp.clip(yi.astype(jnp.int32), 0, Hp - 1)
                    xi = jnp.clip(xi.astype(jnp.int32), 0, Wp - 1)
                    flat = yi * Wp + xi                    # (B, OH, OW)
                    xf = xg.reshape(B, Cd, Hp * Wp)
                    return jnp.take_along_axis(
                        xf, flat.reshape(B, 1, OH * OW).astype(jnp.int32),
                        axis=2).reshape(B, Cd, OH, OW)

                inb = ((yy >= 0) & (yy <= Hp - 1) &
                       (xx >= 0) & (xx <= Wp - 1))
                val = (gather(y0, x0) * ((1 - wy) * (1 - wx))[:, None] +
                       gather(y0, x0 + 1) * ((1 - wy) * wx)[:, None] +
                       gather(y0 + 1, x0) * (wy * (1 - wx))[:, None] +
                       gather(y0 + 1, x0 + 1) * (wy * wx)[:, None])
                val = jnp.where(inb[:, None], val, 0.0)
                if mask is not None:                       # v2 modulation
                    val = val * mask[:, d * KH * KW + k, :OH, :OW][:, None]
                vals.append(val)
            cols.append(vals[0] if dg == 1 else jnp.concatenate(vals, axis=1))
    col = jnp.stack(cols, axis=2)                     # (B, C, KH*KW, OH, OW)
    col = col.reshape(B, groups, (C // groups) * KH * KW, OH * OW)
    wr = weight.reshape(groups, O // groups, Cg * KH * KW)
    out = jnp.einsum("bgkp,gok->bgop", col, wr)
    out = out.reshape(B, O, OH, OW)
    if bias is not None:
        out = out + bias.reshape(1, O, 1, 1)
    return out


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, weight=None,
                    bias=None, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    """Deformable convolution v1/v2 (ref: nn.py deformable_conv).
    Functional form: pass ``weight (O, C/groups, KH, KW)`` (and optional
    ``bias``); ``mask=None`` selects v1."""
    pair = lambda v: (v, v) if isinstance(v, int) else tuple(v)
    if weight is None:
        raise ValueError("pass weight=(num_filters, C//groups, KH, KW)")
    if mask is None:
        return apply("deformable_conv_v1_op", input, offset, weight, bias,
                     stride=pair(stride), padding=pair(padding),
                     dilation=pair(dilation), groups=int(groups),
                     deformable_groups=int(deformable_groups))
    return apply("deformable_conv_op", input, offset, mask, weight, bias,
                 stride=pair(stride), padding=pair(padding),
                 dilation=pair(dilation), groups=int(groups),
                 deformable_groups=int(deformable_groups))


@register("deformable_conv_v1_op")
def _deformable_conv_v1(x, offset, weight, bias, *, stride, padding,
                        dilation, groups, deformable_groups=1):
    return _deformable_conv(x, offset, None, weight, bias, stride=stride,
                            padding=padding, dilation=dilation,
                            groups=groups,
                            deformable_groups=deformable_groups)
