"""Tensor creation ops.

Covers the reference's ``fill_constant_op.cc``, ``range_op.cc``,
``eye_op.cc``, ``linspace_op.cc``, ``uniform_random_op.cc``,
``gaussian_random_op.cc``, ``randint_op.cc``, ``randperm_op.cc``,
``bernoulli``/``multinomial`` samplers and ``assign_value_op.cc``.
Random ops draw from the global PRNG (core/random.py) in eager mode.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as _random
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ._base import register, apply, unwrap

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "tril", "triu", "meshgrid", "diagflat", "assign", "clone",
    "rand", "randn", "randint", "randperm", "uniform", "normal", "bernoulli",
    "multinomial", "standard_normal", "fill_constant",
]


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    del place
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in np.asarray(shape._data)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_list(shape), convert_dtype(dtype)), _internal=True)


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_list(shape), convert_dtype(dtype)), _internal=True)


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape_list(shape), fill_value, convert_dtype(dtype)), _internal=True)


def fill_constant(shape, dtype, value, name=None, out=None):
    t = full(shape, value, dtype)
    if out is not None:
        out.set_value(t)
        return out
    return t


empty = zeros  # deterministic "empty" — uninitialized memory is a CUDA-ism


@register("zeros_like")
def _zeros_like(x, *, dtype=None):
    return jnp.zeros_like(x, dtype=dtype)


@register("ones_like")
def _ones_like(x, *, dtype=None):
    return jnp.full_like(x, 1, dtype=dtype)


@register("full_like")
def _full_like(x, *, value, dtype=None):
    return jnp.full_like(x, value, dtype=dtype)


def zeros_like(x, dtype=None, name=None):
    return apply("zeros_like", x, dtype=None if dtype is None else convert_dtype(dtype))


def ones_like(x, dtype=None, name=None):
    return apply("ones_like", x, dtype=None if dtype is None else convert_dtype(dtype))


def full_like(x, fill_value, dtype=None, name=None):
    return apply("full_like", x, value=fill_value, dtype=None if dtype is None else convert_dtype(dtype))


empty_like = zeros_like


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if all(isinstance(v, (int, np.integer)) for v in (start, end, step)) else "float32"
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)), _internal=True)


range_ = arange


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    num = int(num.item()) if isinstance(num, Tensor) else int(num)
    return Tensor(jnp.linspace(start, stop, num, dtype=convert_dtype(dtype or "float32")), _internal=True)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=convert_dtype(dtype or "float32")), _internal=True)


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns),
                          dtype=convert_dtype(dtype)), _internal=True)


@register("tril")
def _tril(x, *, diagonal=0):
    return jnp.tril(x, k=diagonal)


@register("triu")
def _triu(x, *, diagonal=0):
    return jnp.triu(x, k=diagonal)


def tril(x, diagonal=0, name=None):
    return apply("tril", x, diagonal=diagonal)


def triu(x, diagonal=0, name=None):
    return apply("triu", x, diagonal=diagonal)


def diagflat(x, offset=0, name=None):
    return Tensor(jnp.diagflat(unwrap(x), k=offset), _internal=True)


def meshgrid(*args, name=None):
    arrays = [unwrap(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return [Tensor(g, _internal=True) for g in jnp.meshgrid(*arrays, indexing="ij")]


@register("assign")
def _assign(x):
    return x + jnp.zeros((), x.dtype)


def assign(x, output=None):
    if not isinstance(x, Tensor):
        x = Tensor(np.asarray(x))
    out = apply("assign", x)
    if output is not None:
        output.set_value(out)
        return output
    return out


def clone(x, name=None):
    return apply("assign", x)


# ---------------------------------------------------------------------------
# random creation (eager: stateful global key; traced code threads keys)
# ---------------------------------------------------------------------------


def _key():
    return _random.next_key()


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = convert_dtype(dtype)
    if not jnp.issubdtype(d, jnp.floating):
        d = jnp.float32
    k = jax.random.PRNGKey(seed) if seed else _key()
    return Tensor(jax.random.uniform(k, _shape_list(shape), dtype=d, minval=min, maxval=max), _internal=True)


uniform_random = uniform


def randn(shape, dtype=None, name=None):
    d = convert_dtype(dtype)
    if not jnp.issubdtype(d, jnp.floating):
        d = jnp.float32
    return Tensor(jax.random.normal(_key(), _shape_list(shape), dtype=d), _internal=True)


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = unwrap(mean) if isinstance(mean, Tensor) else mean
        s = unwrap(std) if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s)) if shape is None else tuple(_shape_list(shape))
        return Tensor(m + s * jax.random.normal(_key(), shp, dtype=jnp.float32), _internal=True)
    shp = _shape_list(shape) if shape is not None else []
    return Tensor(mean + std * jax.random.normal(_key(), shp, dtype=jnp.float32), _internal=True)


gaussian = normal
gaussian_random = normal


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(), _shape_list(shape), low, high, dtype=convert_dtype(dtype)), _internal=True)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_key(), int(n)).astype(convert_dtype(dtype)), _internal=True)


def bernoulli(x, name=None):
    p = unwrap(x)
    return Tensor(jax.random.bernoulli(_key(), p).astype(p.dtype), _internal=True)


def multinomial(x, num_samples=1, replacement=False, name=None):
    p = unwrap(x)
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if replacement:
        out = jax.random.categorical(_key(), logits, axis=-1, shape=(*p.shape[:-1], num_samples))
    else:
        k = _key()
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(k, p.shape)
        out = jnp.argsort(-(logits + g), axis=-1)[..., :num_samples]
    return Tensor(out.astype(jnp.int32), _internal=True)
