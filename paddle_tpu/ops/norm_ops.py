"""Normalization ops (functional).

Covers the reference's ``batch_norm_op.cc``, ``layer_norm_op.cc``,
``group_norm_op.cc``, ``instance_norm_op.cc``, ``norm_op.cc`` (l2_normalize),
``lrn_op.cc``. Running-stat updates are returned functionally; the Layer
wrappers own the mutable state (XLA-friendly: no in-place buffers).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._base import register, apply, unwrap


@register("batch_norm_infer")
def _bn_infer(x, mean, var, weight, bias, *, epsilon, axis):
    shape = [1] * x.ndim
    shape[axis] = -1
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + epsilon).astype(x.dtype)
    out = (x - mean.reshape(shape)) * inv.reshape(shape)
    return out * weight.reshape(shape) + bias.reshape(shape)


@register("batch_norm_train")
def _bn_train(x, weight, bias, *, epsilon, axis):
    axes = tuple(i for i in range(x.ndim) if i != axis)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    shape = [1] * x.ndim
    shape[axis] = -1
    inv = jax.lax.rsqrt(var + epsilon)
    out = (xf - mean.reshape(shape)) * inv.reshape(shape)
    out = out * weight.astype(jnp.float32).reshape(shape) + bias.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype), mean, var


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None):
    axis = 1 if data_format.startswith("NC") else unwrap(x).ndim - 1
    if use_global_stats is None:
        use_global_stats = not training
    if use_global_stats:
        return apply("batch_norm_infer", x, running_mean, running_var, weight, bias,
                     epsilon=float(epsilon), axis=axis)
    out, mean, var = apply("batch_norm_train", x, weight, bias,
                           epsilon=float(epsilon), axis=axis)
    # functional running-stat update (ref: batch_norm_op.cc MomentumUpdate)
    n = 1
    for i, s in enumerate(unwrap(x).shape):
        if i != axis:
            n *= s
    unbiased = var * (n / max(n - 1, 1))
    new_mean = running_mean * momentum + mean.astype(running_mean.dtype) * (1 - momentum)
    new_var = running_var * momentum + unbiased.astype(running_var.dtype) * (1 - momentum)
    running_mean.set_value(new_mean)
    running_var.set_value(new_var)
    return out


@register("layer_norm")
def _layer_norm(x, weight, bias, *, epsilon, begin_norm_axis):
    # Pallas fused path for the common last-axis case with 1D scale/shift
    # (ref: the hand-fused layer_norm_op.cu) — one VMEM pass + fused bwd.
    if begin_norm_axis == x.ndim - 1 and weight.ndim == 1 and \
            bias.ndim == 1:
        from . import pallas as pk

        D = x.shape[-1]
        N = 1
        for s in x.shape[:-1]:
            N *= s
        if pk.enabled() and D % 128 == 0 and N % 8 == 0:
            out = pk.fused_layer_norm(x.reshape(N, D), weight, bias,
                                      float(epsilon), pk.auto_interpret())
            return out.reshape(x.shape)
    axes = tuple(range(begin_norm_axis, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    shape = [1] * begin_norm_axis + list(x.shape[begin_norm_axis:])
    out = out * weight.astype(jnp.float32).reshape(shape) + bias.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype)


@register("layer_norm_noaffine")
def _layer_norm_noaffine(x, *, epsilon, begin_norm_axis):
    axes = tuple(range(begin_norm_axis, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    return ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)


def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5, name=None):
    nd = unwrap(x).ndim
    if normalized_shape is None:
        begin = nd - 1
    else:
        ns = [normalized_shape] if isinstance(normalized_shape, int) else list(normalized_shape)
        begin = nd - len(ns)
    if weight is None:
        return apply("layer_norm_noaffine", x, epsilon=float(epsilon), begin_norm_axis=begin)
    return apply("layer_norm", x, weight, bias, epsilon=float(epsilon), begin_norm_axis=begin)


@register("group_norm")
def _group_norm(x, weight, bias, *, num_groups, epsilon, channel_axis):
    # NCHW path: reshape channels into groups
    n = x.shape[0]
    c = x.shape[channel_axis]
    if channel_axis == 1:
        xg = jnp.reshape(x, (n, num_groups, c // num_groups, *x.shape[2:]))
        axes = tuple(range(2, xg.ndim))
    else:
        xg = jnp.reshape(x, (*x.shape[:-1], num_groups, c // num_groups))
        axes = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
    xf = xg.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = [1] * x.ndim
    shape[channel_axis] = -1
    out = out * weight.astype(jnp.float32).reshape(shape) + bias.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else unwrap(x).ndim - 1
    c = unwrap(x).shape[ch_axis]
    if weight is None:
        weight = Tensor(jnp.ones((c,), unwrap(x).dtype), _internal=True)
    if bias is None:
        bias = Tensor(jnp.zeros((c,), unwrap(x).dtype), _internal=True)
    return apply("group_norm", x, weight, bias, num_groups=int(num_groups),
                 epsilon=float(epsilon), channel_axis=ch_axis)


@register("instance_norm")
def _instance_norm(x, weight, bias, *, epsilon):
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    shape = [1, -1] + [1] * (x.ndim - 2)
    out = out * weight.astype(jnp.float32).reshape(shape) + bias.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    c = unwrap(x).shape[1]
    if weight is None:
        weight = Tensor(jnp.ones((c,), unwrap(x).dtype), _internal=True)
    if bias is None:
        bias = Tensor(jnp.zeros((c,), unwrap(x).dtype), _internal=True)
    return apply("instance_norm", x, weight, bias, epsilon=float(eps))


@register("l2_normalize")
def _l2_normalize(x, *, axis, epsilon):
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    return x / jnp.maximum(norm, epsilon)


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    return apply("l2_normalize", x, axis=axis, epsilon=float(epsilon))


@register("p_normalize")
def _p_normalize(x, *, p, axis, epsilon):
    n = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(n, epsilon)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return apply("p_normalize", x, p=float(p), axis=axis, epsilon=float(epsilon))


@register("local_response_norm")
def _lrn(x, *, size, alpha, beta, k):
    # NCHW cross-channel LRN (ref: lrn_op.cc)
    sq = jnp.square(x)
    half = size // 2
    pad = jnp.pad(sq, ((0, 0), (half, size - half - 1), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(size))
    return x / jnp.power(k + alpha * acc, beta)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    return apply("local_response_norm", x, size=int(size), alpha=float(alpha),
                 beta=float(beta), k=float(k))


lrn = local_response_norm


@register("spectral_norm_op")
def _spectral_norm(w, *, dim, power_iters, eps):
    # ref: nn.py spectral_norm (spectral_norm_op.cc): normalize a weight
    # by its largest singular value, estimated with power iteration.
    perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)
    wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
    u = jnp.ones((wm.shape[0],), jnp.float32)
    v = jnp.ones((wm.shape[1],), jnp.float32)

    def it(_, uv):
        u, v = uv
        v = wm.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wm @ v
        u = u / (jnp.linalg.norm(u) + eps)
        return u, v

    u, v = jax.lax.fori_loop(0, power_iters, it, (u, v))
    sigma = u @ wm @ v
    return w / sigma


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    return apply("spectral_norm_op", weight, dim=int(dim),
                 power_iters=int(power_iters), eps=float(eps))


@register("data_norm_op")
def _data_norm(x, batch_size, batch_sum, batch_square_sum, *, epsilon):
    # ref: nn.py data_norm (data_norm_op.cc): normalize with accumulated
    # batch statistics (a CTR-model staple; stats updated by the caller).
    # Stats are per-channel (C,); broadcast along axis 1 for NC* layouts.
    shape = (1, -1) + (1,) * (x.ndim - 2)
    mean = (batch_sum / batch_size).reshape(shape)
    var = (batch_square_sum / batch_size).reshape(shape) - mean * mean
    return (x - mean) / jnp.sqrt(var + epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False, slot_dim=-1,
              summary_decay_rate=0.9999999, stats=None):
    """Accumulated-stats normalization (ref: nn.py data_norm). Pass
    ``stats=(batch_size, batch_sum, batch_square_sum)`` (each (C,)); when
    omitted, per-feature batch statistics of ``input`` are used."""
    if stats is None:
        xv = unwrap(input)
        n = float(np.prod([s for i, s in enumerate(xv.shape) if i != 1]))
        axes = tuple(i for i in range(xv.ndim) if i != 1)
        bsize = Tensor(jnp.full((xv.shape[1],), n, jnp.float32), _internal=True)
        bsum = apply("_dn_sum", input, axes=axes)
        bsq = apply("_dn_sqsum", input, axes=axes)
        stats = (bsize, bsum, bsq)
    out = apply("data_norm_op", input, *stats, epsilon=float(epsilon))
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


@register("_dn_sum")
def _dn_sum(x, *, axes):
    return jnp.sum(x.astype(jnp.float32), axis=axes)


@register("_dn_sqsum")
def _dn_sqsum(x, *, axes):
    return jnp.sum(x.astype(jnp.float32) ** 2, axis=axes)
