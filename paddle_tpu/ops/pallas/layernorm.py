"""Fused LayerNorm as a pallas TPU kernel (fwd + custom_vjp bwd).

TPU-native analog of the reference's hand-fused CUDA layer_norm kernel
(paddle/fluid/operators/layer_norm_op.cu): one VMEM pass computes the
moments, normalizes, and applies scale/shift; the backward kernel fuses
the three-term gradient in a single pass. Stats are f32 even for bf16
activations.

Layout: (N, D) rows; callers flatten leading dims.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    y = xhat * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean[:, 0]
    rstd_ref[:] = rstd[:, 0]


def _bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref, dx_ref, dg_ref,
                db_ref):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    mean = mean_ref[:][:, None]
    rstd = rstd_ref[:][:, None]
    xhat = (x - mean) * rstd
    wdy = dy * g
    c1 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy, axis=-1, keepdims=True)
    dx = (wdy - xhat * c1 - c2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # per-block partial reductions; caller sums the grid axis
    dg_ref[:] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[:] = jnp.sum(dy, axis=0, keepdims=True)


def _pick_rows(N, want=256):
    b = min(want, N)
    while N % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_layer_norm(x, gamma, beta, eps=1e-5, interpret=False):
    """x: (N, D); gamma/beta: (D,) -> (N, D)."""
    y, _, _ = _ln_call(x, gamma, beta, eps, interpret)
    return y


def _ln_call(x, gamma, beta, eps, interpret):
    N, D = x.shape
    bn = _pick_rows(N)
    kern = functools.partial(_fwd_kernel, eps=float(eps))
    y, mean, rstd = pl.pallas_call(
        kern,
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((bn,), lambda i: (i,)),
            pl.BlockSpec((bn,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D), x.dtype),
            jax.ShapeDtypeStruct((N,), jnp.float32),
            jax.ShapeDtypeStruct((N,), jnp.float32),
        ],
        interpret=interpret,
    )(x, gamma, beta)
    return y, mean, rstd


def _ln_fwd(x, gamma, beta, eps, interpret):
    y, mean, rstd = _ln_call(x, gamma, beta, eps, interpret)
    return y, (x, gamma, mean, rstd)


def _ln_bwd(eps, interpret, res, dy):
    x, gamma, mean, rstd = res
    N, D = x.shape
    bn = _pick_rows(N)
    nblocks = N // bn
    dx, dg_part, db_part = pl.pallas_call(
        _bwd_kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((bn,), lambda i: (i,)),
            pl.BlockSpec((bn,), lambda i: (i,)),
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D), x.dtype),
            jax.ShapeDtypeStruct((nblocks, D), jnp.float32),
            jax.ShapeDtypeStruct((nblocks, D), jnp.float32),
        ],
        interpret=interpret,
    )(x, gamma, mean, rstd, dy)
    dgamma = jnp.sum(dg_part, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(db_part, axis=0).astype(gamma.dtype)
    return dx, dgamma, dbeta


fused_layer_norm.defvjp(_ln_fwd, _ln_bwd)
