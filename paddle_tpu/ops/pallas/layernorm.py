"""Fused LayerNorm as a pallas TPU kernel (fwd + custom_vjp bwd).

TPU-native analog of the reference's hand-fused CUDA layer_norm kernel
(paddle/fluid/operators/layer_norm_op.cu): one VMEM pass computes the
moments, normalizes, and applies scale/shift; the backward kernel fuses
the three-term gradient in a single pass. Stats are f32 even for bf16
activations.

Layout: (N, D) rows; callers flatten leading dims.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    y = xhat * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean      # (bn, 1): 2-D so the block is TPU-tileable
    rstd_ref[:] = rstd


def _bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref, dx_ref, dg_ref,
                db_ref):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd
    wdy = dy * g
    c1 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy, axis=-1, keepdims=True)
    dx = (wdy - xhat * c1 - c2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # dgamma/dbeta: accumulate into one (D,) block revisited across the
    # sequential TPU grid (a (1, D) partial-per-block output would violate
    # the (8, 128) min-tile rule)
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    dg_ref[:] += jnp.sum(dy * xhat, axis=0)
    db_ref[:] += jnp.sum(dy, axis=0)


def _pick_rows(N, want=256):
    b = min(want, N)
    while N % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_layer_norm(x, gamma, beta, eps=1e-5, interpret=False):
    """x: (N, D); gamma/beta: (D,) -> (N, D)."""
    y, _, _ = _ln_call(x, gamma, beta, eps, interpret)
    return y


def _ln_call(x, gamma, beta, eps, interpret):
    N, D = x.shape
    bn = _pick_rows(N)
    kern = functools.partial(_fwd_kernel, eps=float(eps))
    y, mean, rstd = pl.pallas_call(
        kern,
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D), x.dtype),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, gamma, beta)
    return y, mean, rstd


def _ln_fwd(x, gamma, beta, eps, interpret):
    y, mean, rstd = _ln_call(x, gamma, beta, eps, interpret)
    return y, (x, gamma, mean, rstd)


def _ln_bwd(eps, interpret, res, dy):
    x, gamma, mean, rstd = res
    N, D = x.shape
    bn = _pick_rows(N)
    nblocks = N // bn
    dx, dg, db = pl.pallas_call(
        _bwd_kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D), x.dtype),
            jax.ShapeDtypeStruct((D,), jnp.float32),
            jax.ShapeDtypeStruct((D,), jnp.float32),
        ],
        interpret=interpret,
    )(x, gamma, mean, rstd, dy)
    return dx, dg.astype(gamma.dtype), db.astype(gamma.dtype)


fused_layer_norm.defvjp(_ln_fwd, _ln_bwd)
