"""Flash attention: blocked online-softmax attention as a pallas TPU kernel.

TPU-native replacement for the reference's dense-score attention graphs
(the reference has no fused attention kernel — its transformers build
softmax(QK^T)V from primitive CUDA ops; this kernel is the TPU design
point the hand-fused CUDA kernels in paddle/fluid/operators aspire to).

Design:
- O(L) memory: scores never materialize; K/V stream through VMEM blocks
  while a running (max, sumexp) pair rescales the accumulator.
- fwd saves only the logsumexp row stats; bwd recomputes probabilities
  blockwise (two kernels: dq over q-blocks, dk/dv over k-blocks).
- f32 accumulation regardless of input dtype (bf16 in, f32 softmax).
- `interpret=True` runs the same kernels on CPU for tests.

Layout: (B, H, L, D) — collapsed to (BH, L, D) for the grid.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _causal_mask(qi, ki, block_q, block_k, offset):
    """Additive mask block (block_q, block_k) for q-block qi / k-block ki.

    offset = Lk - Lq aligns the last query with the last key (standard
    causal convention for cached decode)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    q_pos = qi * block_q + rows + offset
    k_pos = ki * block_k + cols
    return jnp.where(q_pos >= k_pos, 0.0, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k, Lk, offset):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (Bq, D)
    block_q, D = q.shape
    nk = Lk // block_k

    acc = jnp.zeros((block_q, D), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)

    def body(ki, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = s + _causal_mask(qi, ki, block_q, block_k, offset)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p, v,
                                        preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    if causal:
        # skip fully-masked k-blocks beyond the diagonal
        last = jnp.minimum(
            nk, ((qi + 1) * block_q + offset + block_k - 1) // block_k)
        acc, m, l = jax.lax.fori_loop(0, last, body, (acc, m, l))
    else:
        acc, m, l = jax.lax.fori_loop(0, nk, body, (acc, m, l))

    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)   # (bq, 1) — trailing unit dim keeps the
    # block 2-D-tileable on TPU ((1, bq) row blocks violate the min tile)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_k, Lk, offset):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]        # (bq, 1)
    delta = delta_ref[0]    # (bq, 1)
    block_q, D = q.shape
    nk = Lk // block_k
    dq = jnp.zeros((block_q, D), jnp.float32)

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = s + _causal_mask(qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    if causal:
        last = jnp.minimum(
            nk, ((qi + 1) * block_q + offset + block_k - 1) // block_k)
        dq = jax.lax.fori_loop(0, last, body, dq)
    else:
        dq = jax.lax.fori_loop(0, nk, body, dq)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, Lq, offset):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    block_k, D = k.shape
    nq = Lq // block_q
    dk = jnp.zeros((block_k, D), jnp.float32)
    dv = jnp.zeros((block_k, D), jnp.float32)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32) \
            * scale
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q)]      # (bq, 1)
        delta = delta_ref[0, pl.ds(qi * block_q, block_q)]  # (bq, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = s + _causal_mask(qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse)
        dv_new = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    if causal:
        # q-blocks before the diagonal never attend to this k-block
        first = jnp.maximum(0, (ki * block_k - offset) // block_q)
        dk, dv = jax.lax.fori_loop(first, nq, body, (dk, dv))
    else:
        dk, dv = jax.lax.fori_loop(0, nq, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pick_block(L, want):
    b = min(want, L)
    while L % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    interpret=False):
    """q: (B, H, Lq, D); k/v: (B, H, Lk, D) -> (B, H, Lq, D)."""
    o, _ = _flash_fwd(q, k, v, causal, scale, block_q, interpret)
    return o


def _flash_call(q, k, v, causal, scale, block_q, interpret):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    bq = _pick_block(Lq, block_q)
    bk = _pick_block(Lk, max(128, bq))
    qr = q.reshape(B * H, Lq, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    grid = (B * H, Lq // bq)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_k=bk, Lk=Lk, offset=Lk - Lq)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Lq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return o.reshape(B, H, Lq, D), lse   # lse stays (BH, Lq, 1) for bwd


def _flash_fwd(q, k, v, causal, scale, block_q, interpret):
    o, lse = _flash_call(q, k, v, causal, scale, block_q, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, interpret, res, do):
    q, k, v, o, lse = res
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    bq = _pick_block(Lq, block_q)
    bk = _pick_block(Lk, max(128, bq))
    qr = q.reshape(B * H, Lq, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    dor = do.reshape(B * H, Lq, D)
    lser = lse                                   # (BH, Lq, 1)
    # delta_i = rowsum(dO * O) — the softmax-jacobian diagonal term
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(B * H, Lq, 1)

    dq_kern = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                                block_k=bk, Lk=Lk, offset=Lk - Lq)
    dq = pl.pallas_call(
        dq_kern,
        grid=(B * H, Lq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    dkv_kern = functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                                 block_q=bq, Lq=Lq, offset=Lk - Lq)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(B * H, Lk // bk),
        in_specs=[
            pl.BlockSpec((1, Lq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Lq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lq, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lq, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Lk, D), v.dtype),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)
    return (dq.reshape(B, H, Lq, D), dk.reshape(B, H, Lk, D),
            dv.reshape(B, H, Lk, D))


flash_attention.defvjp(_flash_fwd, _flash_bwd)
