"""Pallas TPU kernels (SURVEY §2.39).

The reference ships ~500 hand-written CUDA kernels under
paddle/fluid/operators; on TPU, XLA fusion covers most of them, and these
pallas kernels cover the rest — the memory-bound fusions XLA can't do:

- flash_attention: O(L)-memory blocked attention (fwd + custom_vjp bwd)
- fused_layer_norm: one-pass moments+normalize (+ fused bwd)
- softmax_cross_entropy: LM-head CE without materializing softmax
- paged_decode_attention: ragged paged decode attention for the
  serving path (K/V gathered through per-sequence page tables via
  scalar prefetch — see paddle_tpu.serving)

``enabled()`` gates use: on by default on TPU backends, off elsewhere
(the dense jnp paths remain the reference implementations and the CPU
test oracle; interpret=True runs these same kernels on CPU for parity
tests).
"""
from __future__ import annotations

import os

import jax

from .flash_attention import flash_attention
from .layernorm import fused_layer_norm
from .softmax_ce import softmax_cross_entropy
from .paged_attention import dense_decode_reference, paged_decode_attention

__all__ = ["flash_attention", "fused_layer_norm", "softmax_cross_entropy",
           "paged_decode_attention", "dense_decode_reference",
           "enabled", "set_enabled"]

_FORCED = None  # None: auto (TPU only); True/False: explicit override


def set_enabled(value):
    """Force pallas kernels on/off (None restores platform auto-detect)."""
    global _FORCED
    _FORCED = value


def enabled():
    if _FORCED is not None:
        return _FORCED
    env = os.environ.get("PADDLE_TPU_PALLAS")
    if env is not None:
        return env not in ("0", "false", "off")
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def auto_interpret():
    """Interpret-mode fallback so force-enabled kernels still run off-TPU
    (the CPU test oracle for the wired call sites)."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True
