"""Ragged paged decode attention as a pallas TPU kernel.

The serving path (``paddle_tpu.serving``) keeps every sequence's KV
history in fixed-size pages scattered across one preallocated pool
(``serving.kv_cache.PagedKVCache``), so a decode step cannot use the
dense ``flash_attention`` layout — each query must *gather* its K/V
through a per-sequence page table, and the batch is ragged (every
sequence has its own context length). This is the TPU-native kernel
shape of Ragged Paged Attention (arXiv 2604.15464): one kernel serves
the whole mixed batch, no per-sequence padding to the longest context.

Design:
- grid ``(B, max_pages)``: the page axis iterates sequentially per
  sequence, so one VMEM-resident (m, l, acc) online-softmax carry in
  scratch accumulates across a sequence's pages — O(page) memory.
- the page table and context lengths ride scalar prefetch
  (``PrefetchScalarGridSpec``): the K/V BlockSpec index map reads
  ``page_table[b, p]`` *before* the body runs, so the pool pages DMA
  straight from HBM into VMEM blocks — the gather never materializes.
- pages past a sequence's last (``p >= ceil(len/page)``) are skipped
  with ``pl.when``; inside the last live page, positions ``>= len``
  are masked to -inf, which is what makes ragged lengths exact.
- f32 softmax/accumulation regardless of pool dtype.
- ``interpret=True`` runs the identical kernel on CPU — the tier-1
  numerics gate pins it against ``dense_decode_reference`` below.

Layouts: q ``(B, H, D)`` (one decode token per sequence);
k/v pools ``(P, page_size, H, D)``; page_table ``(B, max_pages)``
int32; lengths ``(B,)`` int32 (tokens already *in* the cache that this
query attends over, query included).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_decode_attention", "dense_decode_reference"]

NEG_INF = -1e30


def _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, page_size, scale):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    seq_len = len_ref[b]
    n_pages = (seq_len + page_size - 1) // page_size

    @pl.when(p < n_pages)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale          # (H, D)
        k = k_ref[0].astype(jnp.float32)                  # (T, H, D)
        v = v_ref[0].astype(jnp.float32)
        # per-head scores q·k over the page: (H, T). An MXU dot would
        # contract D but cross the head axes (HxH); heads are few and
        # D small for decode, so the VPU elementwise-sum is the shape
        s = jnp.sum(q[:, None, :] * jnp.swapaxes(k, 0, 1), axis=-1)
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)                 # (1, T)
        s = jnp.where(pos < seq_len, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        pexp = jnp.exp(s - m_new)                         # (H, T)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(pexp, axis=-1,
                                              keepdims=True)
        # (H, T) @ (T, D) per head: contract T with v (T, H, D)
        pv = jnp.sum(pexp[:, :, None] * jnp.swapaxes(v, 0, 1), axis=1)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = m_new

    @pl.when(p == pl.num_programs(1) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           scale=None, interpret=False):
    """Decode attention for ragged sequences through a paged KV pool.

    q: ``(B, H, D)`` — the current token's query per sequence;
    k_pages/v_pages: ``(P, page_size, H, D)`` pools;
    page_table: ``(B, max_pages)`` page ids per sequence (entries past
    a sequence's last live page are ignored — any in-range id is safe,
    the pool's null page included);
    lengths: ``(B,)`` context length per sequence (the query's own
    position is ``lengths - 1``).

    Returns ``(B, H, D)`` in q's dtype. ``interpret=True`` runs on CPU.
    """
    B, H, D = q.shape
    P, page_size = k_pages.shape[0], k_pages.shape[1]
    max_pages = page_table.shape[1]
    scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    # clamp so even garbage tail entries DMA a real page (masked anyway)
    page_table = jnp.clip(page_table.astype(jnp.int32), 0, P - 1)
    lengths = lengths.astype(jnp.int32)

    kern = functools.partial(_decode_kernel, page_size=page_size,
                             scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, p, pt, ln: (b, 0, 0)),
            pl.BlockSpec((1, page_size, H, D),
                         lambda b, p, pt, ln: (pt[b, p], 0, 0, 0)),
            pl.BlockSpec((1, page_size, H, D),
                         lambda b, p, pt, ln: (pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, p, pt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),   # acc
            pltpu.VMEM((H, 1), jnp.float32),   # running max
            pltpu.VMEM((H, 1), jnp.float32),   # running sumexp
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(page_table, lengths, q, k_pages, v_pages)


def dense_decode_reference(q, k, v, lengths=None):
    """The CPU oracle the kernel is pinned against: masked dense decode
    attention in f32. ``k``/``v`` are ``(B, L, H, D)`` contiguous
    histories (L >= every length); ``lengths (B,)`` masks the ragged
    tails (None = all L live)."""
    B, H, D = q.shape
    L = k.shape[1]
    qf = q.astype(jnp.float32) / (D ** 0.5)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # (B, H, L): per-head scores against every cached position
    s = jnp.einsum("bhd,blhd->bhl", qf, kf)
    if lengths is not None:
        mask = jnp.arange(L)[None, None, :] < lengths[:, None, None]
        s = jnp.where(mask, s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhl,blhd->bhd", w, vf)
    return out.astype(q.dtype)
