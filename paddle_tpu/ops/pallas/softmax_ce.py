"""Fused softmax + cross-entropy as a pallas TPU kernel.

TPU-native analog of the reference's fused CUDA kernel
(paddle/fluid/operators/softmax_with_cross_entropy_op.cu): the (N, V)
logits never materialize a softmax — a single blocked pass over the vocab
keeps a running (max, sumexp, label-logit) triple, so memory is O(N) and
the V-dim stays resident in VMEM one block at a time (the win at LM-head
vocab sizes, V ≈ 50k). Backward fuses softmax-minus-onehot.

ignore_index rows contribute 0 loss and 0 gradient.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref, *, block_v, V,
                ignore_index):
    lab = lab_ref[:]                 # (bn,)
    bn = lab.shape[0]
    nv = V // block_v

    m = jnp.full((bn, 1), NEG_INF, jnp.float32)
    s = jnp.zeros((bn, 1), jnp.float32)
    t = jnp.zeros((bn, 1), jnp.float32)  # label logit

    def body(vi, carry):
        m, s, t = carry
        blk = x_ref[:, pl.ds(vi * block_v, block_v)].astype(jnp.float32)
        cols = jax.lax.broadcasted_iota(jnp.int32, (bn, block_v), 1) \
            + vi * block_v
        m_new = jnp.maximum(m, jnp.max(blk, axis=-1, keepdims=True))
        s_new = s * jnp.exp(m - m_new) + \
            jnp.sum(jnp.exp(blk - m_new), axis=-1, keepdims=True)
        hit = (cols == lab[:, None]).astype(jnp.float32)
        t_new = t + jnp.sum(blk * hit, axis=-1, keepdims=True)
        return m_new, s_new, t_new

    m, s, t = jax.lax.fori_loop(0, nv, body, (m, s, t))
    lse = (m + jnp.log(jnp.maximum(s, 1e-30)))[:, 0]
    valid = (lab != ignore_index)
    loss_ref[:] = jnp.where(valid, lse - t[:, 0], 0.0)
    lse_ref[:] = lse


def _bwd_kernel(x_ref, lab_ref, lse_ref, g_ref, dx_ref, *, ignore_index):
    x = x_ref[:].astype(jnp.float32)         # (bn, bv)
    lab = lab_ref[:]
    lse = lse_ref[:][:, None]
    g = g_ref[:][:, None]
    bn, bv = x.shape
    vi = pl.program_id(1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1) + vi * bv
    p = jnp.exp(x - lse)
    onehot = (cols == lab[:, None]).astype(jnp.float32)
    valid = (lab != ignore_index)[:, None].astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * g * valid).astype(dx_ref.dtype)


def _pick(n, want):
    b = min(want, n)
    while n % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy(logits, labels, ignore_index=-100,
                          interpret=False):
    """logits: (N, V), labels: (N,) int32 -> per-row loss (N,) f32."""
    loss, _ = _ce_fwd(logits, labels, ignore_index, interpret)
    return loss


def _ce_call(logits, labels, ignore_index, interpret):
    N, V = logits.shape
    bn = _pick(N, 128)
    bv = _pick(V, 2048)
    labels = labels.astype(jnp.int32)
    kern = functools.partial(_fwd_kernel, block_v=bv, V=V,
                             ignore_index=ignore_index)
    loss, lse = pl.pallas_call(
        kern,
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, V), lambda i: (i, 0)),
            pl.BlockSpec((bn,), lambda i: (i,)),
        ],
        out_specs=[
            pl.BlockSpec((bn,), lambda i: (i,)),
            pl.BlockSpec((bn,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N,), jnp.float32),
            jax.ShapeDtypeStruct((N,), jnp.float32),
        ],
        interpret=interpret,
    )(logits, labels)
    return loss, lse


def _ce_fwd(logits, labels, ignore_index, interpret):
    loss, lse = _ce_call(logits, labels, ignore_index, interpret)
    return loss, (logits, labels, lse)


def _ce_bwd(ignore_index, interpret, res, g):
    logits, labels, lse = res
    N, V = logits.shape
    bn = _pick(N, 128)
    bv = _pick(V, 2048)
    labels = labels.astype(jnp.int32)
    kern = functools.partial(_bwd_kernel, ignore_index=ignore_index)
    dx = pl.pallas_call(
        kern,
        grid=(N // bn, V // bv),
        in_specs=[
            pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            pl.BlockSpec((bn,), lambda i, j: (i,)),
            pl.BlockSpec((bn,), lambda i, j: (i,)),
            pl.BlockSpec((bn,), lambda i, j: (i,)),
        ],
        out_specs=pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((N, V), logits.dtype),
        interpret=interpret,
    )(logits, labels, lse, g.astype(jnp.float32))
    return dx, None


softmax_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
