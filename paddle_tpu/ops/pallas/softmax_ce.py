"""Fused softmax + cross-entropy as a pallas TPU kernel.

TPU-native analog of the reference's fused CUDA kernel
(paddle/fluid/operators/softmax_with_cross_entropy_op.cu): the (N, V)
logits never materialize a softmax — a blocked pass over the vocab axis
keeps a running (max, sumexp, label-logit) triple in VMEM scratch, so
memory is O(N) and each grid step touches one (bn, bv) logits tile (a
full (bn, V) row block at V ≈ 50k would blow the ~16 MB VMEM budget).
Backward fuses softmax-minus-onehot.

ignore_index rows contribute 0 loss and 0 gradient.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref, m_ref, s_ref, t_ref, *,
                nv, block_v, ignore_index):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        s_ref[:] = jnp.zeros_like(s_ref)
        t_ref[:] = jnp.zeros_like(t_ref)

    blk = x_ref[:].astype(jnp.float32)            # (bn, bv)
    lab = lab_ref[:]                              # (bn, 1) int32
    bn, bv = blk.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1) + vi * block_v
    m = m_ref[:]
    m_new = jnp.maximum(m, jnp.max(blk, axis=-1, keepdims=True))
    s_ref[:] = s_ref[:] * jnp.exp(m - m_new) + \
        jnp.sum(jnp.exp(blk - m_new), axis=-1, keepdims=True)
    hit = (cols == lab).astype(jnp.float32)
    t_ref[:] += jnp.sum(blk * hit, axis=-1, keepdims=True)
    m_ref[:] = m_new

    @pl.when(vi == nv - 1)
    def _finish():
        lse = m_ref[:] + jnp.log(jnp.maximum(s_ref[:], 1e-30))
        valid = (lab != ignore_index).astype(jnp.float32)
        loss_ref[:] = (lse - t_ref[:]) * valid
        lse_ref[:] = lse


def _bwd_kernel(x_ref, lab_ref, lse_ref, g_ref, dx_ref, *, ignore_index):
    x = x_ref[:].astype(jnp.float32)              # (bn, bv)
    lab = lab_ref[:]                              # (bn, 1)
    lse = lse_ref[:]                              # (bn, 1)
    g = g_ref[:]                                  # (bn, 1)
    bn, bv = x.shape
    vi = pl.program_id(1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1) + vi * bv
    p = jnp.exp(x - lse)
    onehot = (cols == lab).astype(jnp.float32)
    valid = (lab != ignore_index).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * g * valid).astype(dx_ref.dtype)


def _pick(n, want):
    b = min(want, n)
    while n % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy(logits, labels, ignore_index=-100,
                          interpret=False):
    """logits: (N, V), labels: (N,) int32 -> per-row loss (N,) f32."""
    loss, _ = _ce_fwd(logits, labels, ignore_index, interpret)
    return loss


def _ce_call(logits, labels, ignore_index, interpret):
    N, V = logits.shape
    bn = _pick(N, 256)
    bv = _pick(V, 2048)
    nv = V // bv
    lab2 = labels.astype(jnp.int32).reshape(N, 1)
    kern = functools.partial(_fwd_kernel, nv=nv, block_v=bv,
                             ignore_index=ignore_index)
    loss, lse = pl.pallas_call(
        kern,
        grid=(N // bn, nv),        # vocab axis iterates fastest (sequential)
        in_specs=[
            pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
        ],
        interpret=interpret,
    )(logits, lab2)
    return loss[:, 0], lse


def _ce_fwd(logits, labels, ignore_index, interpret):
    loss, lse = _ce_call(logits, labels, ignore_index, interpret)
    return loss, (logits, labels, lse)


def _ce_bwd(ignore_index, interpret, res, g):
    logits, labels, lse = res
    N, V = logits.shape
    bn = _pick(N, 256)
    bv = _pick(V, 2048)
    lab2 = labels.astype(jnp.int32).reshape(N, 1)
    kern = functools.partial(_bwd_kernel, ignore_index=ignore_index)
    dx = pl.pallas_call(
        kern,
        grid=(N // bn, V // bv),
        in_specs=[
            pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((N, V), logits.dtype),
        interpret=interpret,
    )(logits, lab2, lse, g.astype(jnp.float32).reshape(N, 1))
    return dx, None


softmax_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
