"""Long-tail fluid ops: similarity/ranking/distillation losses, tensor
utilities, decode helpers.

Ref (capability target): python/paddle/fluid/layers/nn.py and loss.py —
cos_sim, dice_loss, huber_loss, rank_loss, margin_rank_loss, bpr_loss,
center_loss, teacher_student_sigmoid_loss, mean_iou, multiplex,
crop_tensor, unstack, bilinear_tensor_product, add_position_encoding,
temporal_shift, affine_channel, gather_tree, sampling_id,
ctc_greedy_decoder, fsp_matrix, clip_by_norm, brelu, soft_relu.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core import random as _random
from ..core.tensor import Tensor
from ._base import register, apply, unwrap, bce_with_logits

__all__ = [
    "cos_sim", "dice_loss", "huber_loss", "rank_loss",
    "margin_rank_loss", "bpr_loss", "center_loss",
    "teacher_student_sigmoid_loss", "mean_iou", "multiplex",
    "crop_tensor", "unstack", "bilinear_tensor_product",
    "add_position_encoding", "temporal_shift", "affine_channel",
    "gather_tree", "sampling_id", "ctc_greedy_decoder", "fsp_matrix",
    "clip_by_norm", "brelu", "soft_relu",
    "unique_with_counts", "hash", "similarity_focus",
    "polygon_box_transform", "tree_conv",
]


# -- similarity / ranking / distillation losses -----------------------------


@register("cos_sim")
def _cos_sim(x, y):
    xn = jnp.sqrt(jnp.sum(x * x, -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, -1, keepdims=True))
    dot = jnp.sum(x * y, -1, keepdims=True)
    return dot / jnp.maximum(xn * yn, 1e-12)


def cos_sim(X, Y, name=None):
    """Row-wise cosine similarity -> (N, 1) (ref: nn.py cos_sim)."""
    return apply("cos_sim", X, Y)


@register("dice_loss")
def _dice_loss(x, label, *, epsilon):
    # x (N, ..., C) probabilities; label (N, ..., 1) int
    lab = jax.nn.one_hot(label[..., 0], x.shape[-1], dtype=x.dtype)
    red = tuple(range(1, x.ndim))
    inter = jnp.sum(x * lab, red)
    union = jnp.sum(x, red) + jnp.sum(lab, red)
    return 1.0 - (2.0 * inter + epsilon) / (union + epsilon)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice loss for segmentation (ref: loss.py dice_loss)."""
    return apply("dice_loss", input, label, epsilon=float(epsilon))


@register("huber_loss")
def _huber_loss(x, y, *, delta):
    r = jnp.abs(x - y)
    return jnp.where(r <= delta, 0.5 * r * r,
                     delta * (r - 0.5 * delta))


def huber_loss(input, label, delta=1.0, name=None):
    """Huber loss (ref: loss.py huber_loss)."""
    return apply("huber_loss", input, label, delta=float(delta))


@register("rank_loss")
def _rank_loss(label, left, right):
    # pairwise logistic ranking (RankNet): P(left > right)
    return bce_with_logits(left - right, label)


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (ref: loss.py rank_loss)."""
    return apply("rank_loss", label, left, right)


@register("margin_rank_loss")
def _margin_rank_loss(label, left, right, *, margin):
    return jnp.maximum(0.0, -label * (left - right) + margin)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """Margin ranking loss; label in {1, -1} (ref: loss.py
    margin_rank_loss)."""
    return apply("margin_rank_loss", label, left, right,
                 margin=float(margin))


@register("bpr_loss")
def _bpr_loss(x, label):
    # Bayesian personalized ranking over softmax-free logits:
    # -mean_j log(sigmoid(x[label] - x[j])), j != label
    N, C = x.shape
    pos = jnp.take_along_axis(x, label.astype(jnp.int32), axis=1)
    diff = pos - x  # (N, C)
    lsm = jax.nn.log_sigmoid(diff)
    mask = jax.nn.one_hot(label[:, 0], C, dtype=x.dtype)
    return -(lsm * (1 - mask)).sum(-1, keepdims=True) / (C - 1)


def bpr_loss(input, label, name=None):
    """BPR pairwise loss (ref: loss.py bpr_loss). input (N, C) logits,
    label (N, 1)."""
    return apply("bpr_loss", input, label)


@register("center_loss")
def _center_loss(x, label, centers, *, alpha, update_center):
    lab = label.reshape(-1).astype(jnp.int32)
    c = centers[lab]  # (N, D)
    loss = 0.5 * jnp.sum((x - c) ** 2, -1, keepdims=True)
    if not update_center:
        return loss, centers
    # class-wise center EMA toward the batch mean (ref center update)
    diff = c - x
    counts = jnp.zeros((centers.shape[0],), x.dtype) \
        .at[lab].add(1.0)
    delta = jnp.zeros_like(centers).at[lab].add(diff)
    new_centers = centers - alpha * delta / (counts[:, None] + 1.0)
    return loss, new_centers


def center_loss(input, label, num_classes=None, alpha=0.5, centers=None,
                update_center=True, param_attr=None, name=None):
    """Center loss (ref: loss.py center_loss). Functional: pass
    ``centers`` (num_classes, D); returns (loss (N, 1), new_centers)."""
    if centers is None:
        raise ValueError("pass centers=(num_classes, D)")
    return apply("center_loss", input, label, centers,
                 alpha=float(alpha), update_center=bool(update_center))


@register("ts_sigmoid_loss")
def _ts_sigmoid_loss(x, label, *, soft_max_up_bound, soft_max_lower_bound):
    # teacher (soft) vs student (hard) combined sigmoid loss
    z = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
    hard = (label > 0.5).astype(x.dtype)
    return bce_with_logits(z, hard) + bce_with_logits(z, label)


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0, name=None):
    """ref: loss.py teacher_student_sigmoid_loss."""
    return apply("ts_sigmoid_loss", input, label,
                 soft_max_up_bound=float(soft_max_up_bound),
                 soft_max_lower_bound=float(soft_max_lower_bound))


# -- metrics-ish ------------------------------------------------------------


@register("mean_iou")
def _mean_iou(pred, label, *, num_classes):
    p = pred.reshape(-1).astype(jnp.int32)
    l = label.reshape(-1).astype(jnp.int32)
    conf = jnp.zeros((num_classes, num_classes)) \
        .at[l, p].add(1.0, mode="drop")
    inter = jnp.diagonal(conf)
    union = conf.sum(0) + conf.sum(1) - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
    miou = iou.sum() / jnp.maximum(present.sum(), 1)
    correct = inter.astype(jnp.int64)
    wrong = (conf.sum(1) - inter).astype(jnp.int64)
    return miou, wrong, correct


def mean_iou(input, label, num_classes, name=None):
    """Mean IoU over predicted segmentation ids (ref: nn.py mean_iou).
    Returns (mean_iou scalar, out_wrong (C,), out_correct (C,))."""
    return apply("mean_iou", input, label, num_classes=int(num_classes))


# -- tensor utilities -------------------------------------------------------


@register("multiplex")
def _multiplex(index, *xs):
    stacked = jnp.stack(xs, 0)  # (K, N, ...)
    idx = index.reshape(-1).astype(jnp.int32)
    return stacked[idx, jnp.arange(stacked.shape[1])]


def multiplex(inputs, index, name=None):
    """Row-wise select among K same-shape inputs by index (N, 1)
    (ref: nn.py multiplex)."""
    return apply("multiplex", index, *inputs)


@register("crop_tensor")
def _crop_tensor(x, *, offsets, shape):
    return lax.dynamic_slice(x, offsets, shape)


def crop_tensor(x, shape=None, offsets=None, name=None):
    """Static crop at offsets (ref: nn.py crop_tensor). shape entries of
    -1/None mean "to the end" (dim - offset), like the reference."""
    xs = unwrap(x).shape
    if offsets is None:
        offsets = [0] * len(xs)
    shape = [xs[i] - int(offsets[i]) if s in (-1, None) else int(s)
             for i, s in enumerate(shape)]
    return apply("crop_tensor", x, offsets=tuple(int(o) for o in offsets),
                 shape=tuple(shape))


def unstack(x, axis=0, num=None, name=None):
    """Split along axis into unit slices (ref: nn.py unstack)."""
    from .manipulation import squeeze, split

    n = unwrap(x).shape[axis]
    if num is not None and num != n:
        raise ValueError(f"num={num} != dim size {n}")
    return [squeeze(p, axis=axis) for p in split(x, n, axis=axis)]


@register("bilinear_tensor_product")
def _bilinear_tensor_product(x, y, w, b):
    # w (size, dx, dy): out[n, k] = x[n] @ w[k] @ y[n]
    out = jnp.einsum("nd,kde,ne->nk", x, w, y)
    return out if b is None else out + b


def bilinear_tensor_product(x, y, size=None, weight=None, bias=None,
                            act=None, name=None, param_attr=None,
                            bias_attr=None):
    """x^T W y bilinear form (ref: nn.py bilinear_tensor_product).
    Functional: pass weight (size, dx, dy) (+ optional bias (size,))."""
    if weight is None:
        raise ValueError("pass weight=(size, x_dim, y_dim)")
    out = apply("bilinear_tensor_product", x, y, weight, bias)
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


@register("add_position_encoding")
def _add_position_encoding(x, *, alpha, beta):
    B, L, D = x.shape
    half = D // 2
    pos = jnp.arange(L, dtype=jnp.float32)[:, None]
    inv = jnp.power(10000.0, -jnp.arange(half, dtype=jnp.float32)
                    / max(half, 1))
    angles = pos * inv[None, :]
    enc = jnp.concatenate([jnp.sin(angles), jnp.cos(angles)], axis=1)
    if enc.shape[1] < D:  # odd D: pad
        enc = jnp.pad(enc, ((0, 0), (0, D - enc.shape[1])))
    return alpha * x + beta * enc[None].astype(x.dtype)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """Sinusoidal position encoding mixed in (ref: nn.py
    add_position_encoding): alpha*x + beta*PE."""
    return apply("add_position_encoding", input, alpha=float(alpha),
                 beta=float(beta))


@register("temporal_shift")
def _temporal_shift(x, *, seg_num, shift_ratio):
    NT, C, H, W = x.shape
    N = NT // seg_num
    v = x.reshape(N, seg_num, C, H, W)
    fold = int(C * shift_ratio)
    back = jnp.roll(v[:, :, :fold], 1, axis=1) \
        .at[:, 0, :].set(0.0)
    fwd = jnp.roll(v[:, :, fold:2 * fold], -1, axis=1) \
        .at[:, -1, :].set(0.0)
    rest = v[:, :, 2 * fold:]
    return jnp.concatenate([back, fwd, rest], axis=2) \
        .reshape(NT, C, H, W)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    """TSM temporal channel shift (ref: nn.py temporal_shift)."""
    return apply("temporal_shift", x, seg_num=int(seg_num),
                 shift_ratio=float(shift_ratio))


@register("affine_channel")
def _affine_channel(x, scale, bias):
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return x * scale.reshape(shape) + bias.reshape(shape)


def affine_channel(x, scale=None, bias=None, data_layout="NCHW",
                   name=None, act=None):
    """Per-channel affine (frozen-BN form; ref: nn.py affine_channel)."""
    out = apply("affine_channel", x, scale, bias)
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


# -- decode helpers ---------------------------------------------------------


@register("gather_tree")
def _gather_tree(ids, parents):
    # ids, parents: (T, B, K) — backtrace beams into full sequences
    T = ids.shape[0]
    K = ids.shape[2]

    def step(beam, t):
        tok = jnp.take_along_axis(ids[t], beam, axis=1)
        beam = jnp.take_along_axis(parents[t], beam, axis=1)
        return beam, tok

    beam0 = jnp.broadcast_to(jnp.arange(K, dtype=ids.dtype)[None],
                             ids.shape[1:])
    _, toks = lax.scan(step, beam0, jnp.arange(T - 1, -1, -1))
    return toks[::-1]


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (ref: rnn.py gather_tree): follow parent
    pointers from the last step so every (b, k) column holds a complete
    sequence."""
    return apply("gather_tree", ids, parents)


@register("sampling_id")
def _sampling_id(probs, key):
    return jax.random.categorical(key, jnp.log(jnp.maximum(probs, 1e-20)),
                                  axis=-1)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64", name=None):
    """Sample one id per row from probabilities (ref: nn.py
    sampling_id)."""
    key = _random.next_key() if seed == 0 else jax.random.PRNGKey(seed)
    return apply("sampling_id", x, Tensor(key, _internal=True))


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """Greedy CTC decode: argmax -> merge repeats -> drop blanks
    (ref: nn.py ctc_greedy_decoder). input (B, T, C) probs/logits.
    Returns (decoded (B, T) padded with ``padding_value``, lengths (B,)).
    Host-side (decode output feeds metrics, not the graph)."""
    arr = np.asarray(unwrap(input))
    B, T = arr.shape[0], arr.shape[1]
    lens = np.full((B,), T) if input_length is None \
        else np.asarray(unwrap(input_length)).reshape(-1)
    out = np.full((B, T), padding_value, np.int64)
    out_lens = np.zeros((B,), np.int64)
    for b in range(B):
        path = arr[b, :lens[b]].argmax(-1)
        prev = -1
        k = 0
        for t in path:
            if t != prev and t != blank:
                out[b, k] = t
                k += 1
            prev = t
        out_lens[b] = k
    return (Tensor(jnp.asarray(out), _internal=True),
            Tensor(jnp.asarray(out_lens), _internal=True))


@register("fsp_matrix")
def _fsp_matrix(x, y):
    # flow-of-solution-procedure: (B, Cx, H, W) x (B, Cy, H, W)
    B, Cx, H, W = x.shape
    return jnp.einsum("bchw,bdhw->bcd", x, y) / (H * W)


def fsp_matrix(x, y, name=None):
    """FSP distillation matrix (ref: loss.py fsp_matrix)."""
    return apply("fsp_matrix", x, y)


@register("clip_by_norm")
def _clip_by_norm(x, *, max_norm):
    norm = jnp.sqrt(jnp.sum(x * x))
    return jnp.where(norm > max_norm, x * (max_norm / norm), x)


def clip_by_norm(x, max_norm, name=None):
    """Scale down to L2 norm <= max_norm (ref: nn.py clip_by_norm)."""
    return apply("clip_by_norm", x, max_norm=float(max_norm))


@register("brelu")
def _brelu(x, *, t_min, t_max):
    return jnp.clip(x, t_min, t_max)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    """Bounded relu (ref: nn.py brelu)."""
    return apply("brelu", x, t_min=float(t_min), t_max=float(t_max))


@register("soft_relu")
def _soft_relu(x, *, threshold):
    return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))


def soft_relu(x, threshold=40.0, name=None):
    """log(1 + exp(clip(x))) (ref: nn.py soft_relu)."""
    return apply("soft_relu", x, threshold=float(threshold))


def unique_with_counts(x, dtype="int32", name=None):
    """Unique values + index map + counts (ref: nn.py unique_with_counts;
    host-side like ``unique`` — dynamic output shape can't live under jit)."""
    arr = np.asarray(unwrap(x)).reshape(-1)
    vals, inverse, counts = np.unique(arr, return_inverse=True,
                                      return_counts=True)
    return (Tensor(jnp.asarray(vals), _internal=True),
            Tensor(jnp.asarray(inverse.astype(dtype)), _internal=True),
            Tensor(jnp.asarray(counts.astype(dtype)), _internal=True))


@register("hash_op")
def _hash(x, *, num_hash, mod_by):
    # Deterministic multiplicative hashing (XLA-friendly stand-in for the
    # reference's xxhash kernel, pyramid_hash/hash_op.cc). Each of the
    # ``num_hash`` slots uses a distinct odd multiplier.
    xi = x.astype(jnp.uint32)
    muls = (jnp.arange(num_hash, dtype=jnp.uint32) * jnp.uint32(2654435761)
            | jnp.uint32(1))
    flat = xi.reshape(-1, xi.shape[-1])
    key = jnp.zeros((flat.shape[0],), jnp.uint32)
    for c in range(flat.shape[-1]):  # combine the row of ids into one key
        key = key * jnp.uint32(1000003) + flat[:, c]
    acc = (key[:, None] * muls[None, :]) % jnp.uint32(mod_by)
    return acc.astype(jnp.int64).reshape(x.shape[:-1] + (num_hash,))


def hash(input, hash_size, num_hash=1, name=None):  # noqa: A001 (fluid name)
    """Bucketed id hashing (ref: nn.py hash): maps each row of int ids to
    ``num_hash`` bucket ids in [0, hash_size)."""
    return apply("hash_op", input, num_hash=int(num_hash),
                 mod_by=int(hash_size))


@register("similarity_focus_op")
def _similarity_focus(x, *, axis, indices):
    # ref: nn.py similarity_focus (similarity_focus_op.cc): for each
    # selected channel along ``axis``, mark the argmax position of every
    # other (depth) slice; output is a {0,1} mask of x's shape.
    B = x.shape[0]
    mask = jnp.zeros_like(x, dtype=jnp.float32)
    if axis == 1:
        C, H, W = x.shape[1], x.shape[2], x.shape[3]
        for ind in indices:
            sl = x[:, ind]                      # (B, H, W)
            flat = sl.reshape(B, -1)
            top = jnp.argmax(flat, axis=-1)
            hi, wi = top // W, top % W
            row_mask = jnp.zeros((B, H, W), jnp.float32)
            row_mask = row_mask.at[jnp.arange(B), hi, :].set(1.0)
            col_mask = jnp.zeros((B, H, W), jnp.float32)
            col_mask = col_mask.at[jnp.arange(B), :, wi].set(1.0)
            m = jnp.maximum(row_mask, col_mask)[:, None, :, :]
            mask = jnp.maximum(mask, jnp.broadcast_to(m, mask.shape))
    else:
        raise NotImplementedError("similarity_focus: axis must be 1 (NCHW)")
    return mask.astype(x.dtype)


def similarity_focus(input, axis, indexes, name=None):
    return apply("similarity_focus_op", input, axis=int(axis),
                 indices=tuple(int(i) for i in indexes))


@register("polygon_box_transform_op")
def _polygon_box_transform(x):
    # ref: detection.py polygon_box_transform (polygon_box_transform_op.cc):
    # converts per-pixel quad offsets to absolute coordinates. EAST geo
    # maps are 1/4-resolution, so the kernel uses 4*col - in for channel
    # 2k (x-offset) and 4*row - in for channel 2k+1 (y-offset).
    B, C, H, W = x.shape
    cols = jnp.arange(W, dtype=x.dtype)[None, None, None, :]
    rows = jnp.arange(H, dtype=x.dtype)[None, None, :, None]
    is_x = (jnp.arange(C) % 2 == 0)[None, :, None, None]
    base = jnp.where(is_x, jnp.broadcast_to(cols, x.shape),
                     jnp.broadcast_to(rows, x.shape))
    return 4.0 * base - x


def polygon_box_transform(input, name=None):
    return apply("polygon_box_transform_op", input)


@register("tree_conv_op")
def _tree_conv(nodes, edges, W, *, max_depth):
    # ref: contrib/layers/nn.py:376 tree_conv (tree_conv_op.cc), the
    # TBCNN continuous binary tree convolution (Mou et al.): each node's
    # window is its subtree to ``max_depth``; every window member mixes
    # three filter banks W[:, (t, l, r)] by coefficients from its
    # relative depth and sibling position. Dense adjacency keeps it XLA
    # (matmul powers for depth-d reachability), O(N^2 * depth).
    B, N, F = nodes.shape
    Fs, three, O, M = W.shape

    def one(x, e):
        # adjacency: edge rows are (parent, child); zero rows are pads
        p = e[:, 0].astype(jnp.int32)
        c = e[:, 1].astype(jnp.int32)
        real = (p != c)                    # pad rows repeat a node id
        adj = jnp.zeros((N, N))
        adj = adj.at[p, c].add(jnp.where(real, 1.0, 0.0))
        adj = jnp.minimum(adj, 1.0)
        parent_of = jnp.argmax(adj, axis=0)            # (N,)
        has_parent = adj.max(axis=0) > 0
        # sibling rank/count by node-id order
        sib_cnt = adj.sum(axis=1)[parent_of]           # siblings incl self
        # rank of node i among its siblings = earlier children of parent
        par_rows = adj[parent_of]                      # (N, N)
        earlier = jnp.arange(N)[None, :] < jnp.arange(N)[:, None]
        rank = (par_rows * earlier).sum(axis=1)
        out = jnp.zeros((N, O, M))
        reach = jnp.eye(N)
        for d in range(max_depth):
            # window coefficients for members at relative depth d
            denom = max(max_depth - 1, 1)
            eta_t = (max_depth - 1 - d) / denom
            div = jnp.maximum(sib_cnt - 1.0, 1.0)
            frac = jnp.where(sib_cnt > 1, rank / div, 0.5)
            eta_r = (1.0 - eta_t) * frac
            eta_l = (1.0 - eta_t) * (1.0 - frac)
            if d == 0:                      # window root: all weight on t
                eta = jnp.stack([jnp.ones((N,)), jnp.zeros((N,)),
                                 jnp.zeros((N,))], axis=1)
            else:
                eta = jnp.stack([jnp.full((N,), eta_t), eta_l, eta_r],
                                axis=1)
            # mixed per-member features: (N, O, M)
            mixed = jnp.einsum("nf,fkom,nk->nom", x, W, eta)
            out = out + jnp.einsum("rn,nom->rom", reach, mixed)
            reach = reach @ adj
        return out

    return jax.vmap(one)(nodes, edges)


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None, weight=None):
    """Tree-based convolution (ref: contrib/layers/nn.py:376).
    nodes_vector (B, N, F); edge_set (B, E, 2) directed (parent, child)
    pairs (pad rows repeat one id). Returns (B, N, output_size,
    num_filters). Functional form takes ``weight (F, 3, O, M)``; without
    it a fresh parameter is created (fluid convention)."""
    F_dim = unwrap(nodes_vector).shape[2]
    if weight is None:
        # A fresh throwaway parameter would be untrainable and re-drawn
        # every eager call; require the owned weight (the TreeConv Layer
        # in fluid.dygraph holds one).
        raise ValueError(
            f"pass weight=({F_dim}, 3, {output_size}, {num_filters}) — "
            "use fluid.dygraph.TreeConv for a parameter-owning layer")
    out = apply("tree_conv_op", nodes_vector, edge_set, weight,
                max_depth=int(max_depth))
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


@register("dequantize_weight")
def _dequantize_weight(q, s, *, dtype="float32"):
    """Graph-pass dequant for int8-stored weights (TPU analog of the
    reference's quant_dequant ops, quantization_pass.py:703): the int8
    array is the HBM-resident copy passed as a jit argument; this op
    runs inside the compiled program so XLA fuses the multiply into the
    consuming matmul/conv — weight memory traffic shrinks 4x."""
    return q.astype(dtype) * s.astype(dtype)
