"""Sequence (LoD) ops on dense + offsets layout.

The reference stores variable-length sequences as LoDTensor
(``paddle/fluid/framework/lod_tensor.h``) and provides
``sequence_pool/pad/unpad/expand/mask`` ops. Dynamic shapes don't compile on
TPU, so our layout is the XLA-native one: dense padded data + an int32
``length`` (or offsets) array, with masking everywhere. segment_* ops use
``jax.ops.segment_sum``-style reductions which XLA lowers efficiently.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._base import register, apply, unwrap


@register("sequence_mask")
def _sequence_mask(lengths, *, maxlen, dtype):
    row = jnp.arange(maxlen)
    return (row[None, :] < lengths[:, None]).astype(dtype)


def sequence_mask(x, maxlen=None, dtype="float32", name=None):
    from ..core.dtype import convert_dtype

    if maxlen is None:
        maxlen = int(np.asarray(unwrap(x)).max())
    elif isinstance(maxlen, Tensor):
        maxlen = int(maxlen.item())
    return apply("sequence_mask", x, maxlen=int(maxlen), dtype=convert_dtype(dtype))


@register("sequence_pool_op")
def _sequence_pool(x, lengths, *, pool_type):
    # x: (B, T, ...) padded; lengths: (B,)
    t = x.shape[1]
    mask = (jnp.arange(t)[None, :] < lengths[:, None])
    mshape = mask.shape + (1,) * (x.ndim - 2)
    m = mask.reshape(mshape).astype(x.dtype)
    if pool_type == "sum":
        return jnp.sum(x * m, axis=1)
    if pool_type == "average":
        denom = jnp.maximum(lengths.astype(x.dtype), 1).reshape((-1,) + (1,) * (x.ndim - 2))
        return jnp.sum(x * m, axis=1) / denom
    if pool_type == "sqrt":
        denom = jnp.sqrt(jnp.maximum(lengths.astype(x.dtype), 1)).reshape((-1,) + (1,) * (x.ndim - 2))
        return jnp.sum(x * m, axis=1) / denom
    if pool_type == "max":
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return jnp.max(jnp.where(m > 0, x, neg), axis=1)
    if pool_type == "first":
        return x[:, 0]
    if pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
    raise ValueError(pool_type)


def sequence_pool(input, pool_type="sum", lengths=None, name=None):
    """Padded-batch analog of fluid sequence_pool (ref: sequence_pool_op.cc)."""
    if lengths is None:
        lengths = Tensor(jnp.full((unwrap(input).shape[0],), unwrap(input).shape[1], jnp.int32), _internal=True)
    return apply("sequence_pool_op", input, lengths, pool_type=pool_type.lower())


@register("sequence_pad_op")
def _sequence_pad(x, offsets, *, maxlen, pad_value):
    # x: (total, ...) flat concatenated; offsets: (B+1,)
    b = offsets.shape[0] - 1
    starts = offsets[:-1]
    lengths = offsets[1:] - offsets[:-1]
    idx = starts[:, None] + jnp.arange(maxlen)[None, :]
    idx = jnp.clip(idx, 0, x.shape[0] - 1)
    out = x[idx]  # (B, maxlen, ...)
    mask = jnp.arange(maxlen)[None, :] < lengths[:, None]
    mshape = mask.shape + (1,) * (x.ndim - 1)
    return jnp.where(mask.reshape(mshape), out, pad_value), lengths


def sequence_pad(x, pad_value=0.0, maxlen=None, offsets=None, name=None):
    if offsets is None:
        raise ValueError("sequence_pad requires offsets (LoD) tensor")
    if maxlen is None:
        off = np.asarray(unwrap(offsets))
        maxlen = int((off[1:] - off[:-1]).max())
    if isinstance(pad_value, Tensor):
        pad_value = float(pad_value.item())
    return apply("sequence_pad_op", x, offsets, maxlen=int(maxlen), pad_value=pad_value)


@register("sequence_unpad_op")
def _sequence_unpad(x, lengths, *, total):
    # x: (B, T, ...) -> (total, ...): gather valid positions
    b, t = x.shape[0], x.shape[1]
    starts = jnp.concatenate([jnp.zeros((1,), lengths.dtype), jnp.cumsum(lengths)[:-1]])
    flat = jnp.reshape(x, (b * t,) + x.shape[2:])
    pos = jnp.arange(b * t)
    row = pos // t
    col = pos % t
    dest = jnp.where(col < lengths[row], starts[row] + col, total)
    out = jnp.zeros((total + 1,) + x.shape[2:], x.dtype).at[dest].set(flat)
    return out[:total]


def sequence_unpad(x, length, name=None):
    total = int(np.asarray(unwrap(length)).sum())
    return apply("sequence_unpad_op", x, length, total=total)


@register("sequence_expand_op")
def _sequence_expand(x, repeats, *, total):
    idx = jnp.repeat(jnp.arange(x.shape[0]), repeats, total_repeat_length=total)
    return x[idx]


def sequence_expand(x, repeats, name=None):
    r = np.asarray(unwrap(repeats))
    return apply("sequence_expand_op", x, Tensor(jnp.asarray(r), _internal=True), total=int(r.sum()))


@register("sequence_reverse_op")
def _sequence_reverse(x, lengths):
    t = x.shape[1]
    idx = lengths[:, None] - 1 - jnp.arange(t)[None, :]
    valid = idx >= 0
    idx = jnp.where(valid, idx, jnp.arange(t)[None, :])
    return jnp.take_along_axis(x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)


def sequence_reverse(x, lengths=None, name=None):
    if lengths is None:
        lengths = Tensor(jnp.full((unwrap(x).shape[0],), unwrap(x).shape[1], jnp.int32), _internal=True)
    return apply("sequence_reverse_op", x, lengths)


@register("segment_sum")
def _segment_sum(x, ids, *, num_segments):
    return jax.ops.segment_sum(x, ids, num_segments=num_segments)


def segment_sum(data, segment_ids, num_segments=None, name=None):
    if num_segments is None:
        num_segments = int(np.asarray(unwrap(segment_ids)).max()) + 1
    return apply("segment_sum", data, segment_ids, num_segments=num_segments)


@register("segment_mean")
def _segment_mean(x, ids, *, num_segments):
    s = jax.ops.segment_sum(x, ids, num_segments=num_segments)
    c = jax.ops.segment_sum(jnp.ones_like(x[..., :1] if x.ndim > 1 else x), ids, num_segments=num_segments)
    return s / jnp.maximum(c, 1)


def segment_mean(data, segment_ids, num_segments=None, name=None):
    if num_segments is None:
        num_segments = int(np.asarray(unwrap(segment_ids)).max()) + 1
    return apply("segment_mean", data, segment_ids, num_segments=num_segments)


@register("segment_max")
def _segment_max(x, ids, *, num_segments):
    return jax.ops.segment_max(x, ids, num_segments=num_segments)


def segment_max(data, segment_ids, num_segments=None, name=None):
    if num_segments is None:
        num_segments = int(np.asarray(unwrap(segment_ids)).max()) + 1
    return apply("segment_max", data, segment_ids, num_segments=num_segments)


@register("segment_min")
def _segment_min(x, ids, *, num_segments):
    return jax.ops.segment_min(x, ids, num_segments=num_segments)


def segment_min(data, segment_ids, num_segments=None, name=None):
    if num_segments is None:
        num_segments = int(np.asarray(unwrap(segment_ids)).max()) + 1
    return apply("segment_min", data, segment_ids, num_segments=num_segments)


# ---------------------------------------------------------------------------
# sequence_* breadth (ref: python/paddle/fluid/layers/sequence_lod.py)
# ---------------------------------------------------------------------------


@register("sequence_first_step")
def _sequence_first_step(x, lengths):
    return x[:, 0]


def sequence_first_step(input, lengths=None, name=None):
    """First timestep of each sequence (ref: sequence_lod.py
    sequence_first_step). input (B, L, ...) -> (B, ...)."""
    if lengths is None:
        B = unwrap(input).shape[0]
        lengths = Tensor(jnp.full((B,), unwrap(input).shape[1],
                                  jnp.int32), _internal=True)
    return apply("sequence_first_step", input, lengths)


@register("sequence_last_step")
def _sequence_last_step(x, lengths):
    idx = jnp.clip(lengths.astype(jnp.int32) - 1, 0, x.shape[1] - 1)
    return jnp.take_along_axis(
        x, idx.reshape((-1,) + (1,) * (x.ndim - 1)), axis=1)[:, 0]


def sequence_last_step(input, lengths=None, name=None):
    """Last VALID timestep per sequence (ref: sequence_last_step)."""
    if lengths is None:
        B = unwrap(input).shape[0]
        lengths = Tensor(jnp.full((B,), unwrap(input).shape[1],
                                  jnp.int32), _internal=True)
    return apply("sequence_last_step", input, lengths)


@register("sequence_softmax")
def _sequence_softmax(x, lengths):
    mask = jnp.arange(x.shape[1])[None, :] < lengths[:, None]
    z = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(z.astype(jnp.float32), axis=-1).astype(x.dtype)
    return jnp.where(mask, out, jnp.zeros((), x.dtype))


def sequence_softmax(input, lengths=None, use_cudnn=False, name=None):
    """Per-sequence masked softmax over the time axis
    (ref: sequence_lod.py sequence_softmax). input (B, L)."""
    if lengths is None:
        B = unwrap(input).shape[0]
        lengths = Tensor(jnp.full((B,), unwrap(input).shape[1],
                                  jnp.int32), _internal=True)
    return apply("sequence_softmax", input, lengths)


def _context_gather(x, offsets, lengths=None):
    """Gather per-position context frames: x (B, L, D) + relative
    ``offsets`` (ctx,) -> (B, L, ctx, D), with out-of-bounds (and, when
    ``lengths`` is given, beyond-length) frames zeroed. Shared by
    sequence_conv and row_conv."""
    B, L, D = x.shape
    pos = jnp.arange(L)[:, None] + offsets[None, :]  # (L, ctx)
    mask = ((pos >= 0) & (pos < L))[None, :, :]  # (1, L, ctx)
    if lengths is not None:
        mask = mask & (pos[None] < lengths[:, None, None])
    posc = jnp.clip(pos, 0, L - 1)
    return x[:, posc] * mask[..., None].astype(x.dtype)


@register("sequence_conv")
def _sequence_conv(x, w, lengths, *, context_start, context_length):
    B, L, D = x.shape
    offs = jnp.arange(context_length) + context_start
    ctx = _context_gather(x, offs, lengths)  # (B, L, ctx, D)
    flat = ctx.reshape(B, L, context_length * D)
    return jnp.einsum("bld,do->blo", flat, w)


def sequence_conv(input, num_filters=None, filter_size=3, stride=1,
                  padding=True, padding_start=None, weight=None,
                  lengths=None, bias=None, name=None, **kw):
    """Context-window sequence convolution (ref: sequence_lod.py
    sequence_conv): each position sees [t + padding_start,
    t + padding_start + filter_size) frames, flattened and projected.

    Functional form: pass ``weight`` (filter_size * D, num_filters).
    input (B, L, D) dense + lengths.
    """
    if weight is None:
        raise ValueError("pass weight=(filter_size * D, num_filters)")
    if padding_start is None:
        padding_start = -(filter_size // 2)
    if lengths is None:
        B = unwrap(input).shape[0]
        lengths = Tensor(jnp.full((B,), unwrap(input).shape[1],
                                  jnp.int32), _internal=True)
    out = apply("sequence_conv", input, weight, lengths,
                context_start=int(padding_start),
                context_length=int(filter_size))
    if bias is not None:
        from .math import add

        out = add(out, bias)
    return out


@register("sequence_reshape")
def _sequence_reshape(x, *, new_dim):
    B, L, D = x.shape
    return x.reshape(B, L * D // new_dim, new_dim)


def sequence_reshape(input, new_dim, name=None):
    """Re-chunk each sequence's flattened payload into new_dim columns
    (ref: sequence_lod.py sequence_reshape). (B, L, D) ->
    (B, L*D/new_dim, new_dim); lengths scale by D/new_dim."""
    D = unwrap(input).shape[-1]
    L = unwrap(input).shape[1]
    if (L * D) % new_dim != 0:
        raise ValueError(f"L*D = {L * D} not divisible by {new_dim}")
    return apply("sequence_reshape", input, new_dim=int(new_dim))


@register("sequence_scatter")
def _sequence_scatter(x, index, updates, lengths, *, overwrite):
    # x (B, N, ...); index (B, K) positions; updates (B, K, ...)
    valid = index < lengths[:, None]
    safe = jnp.where(valid, index, x.shape[1]).astype(jnp.int32)

    def one(row, idx, upd):
        if overwrite:
            return row.at[idx].set(upd, mode="drop")
        return row.at[idx].add(upd, mode="drop")

    vshape = (valid.shape + (1,) * (updates.ndim - 2))
    upd = updates * valid.reshape(vshape).astype(updates.dtype) \
        if not overwrite else updates
    return jax.vmap(one)(x, safe, upd)


def sequence_scatter(input, index, updates, lengths=None, overwrite=False,
                     name=None):
    """Scatter updates into per-sequence positions (ref: sequence_lod.py
    sequence_scatter; add-semantics by default like the reference).
    input (B, N, ...), index (B, K) int, updates (B, K, ...)."""
    if lengths is None:
        B = unwrap(input).shape[0]
        lengths = Tensor(jnp.full((B,), unwrap(input).shape[1],
                                  jnp.int32), _internal=True)
    return apply("sequence_scatter", input, index, updates, lengths,
                 overwrite=bool(overwrite))


@register("sequence_enumerate")
def _sequence_enumerate(x, lengths, *, win_size, pad_value):
    B, L = x.shape
    pos = jnp.arange(L)[:, None] + jnp.arange(win_size)[None, :]
    inb = (pos[None] < lengths[:, None, None])  # within this row's length
    posc = jnp.clip(pos, 0, L - 1)
    win = x[:, posc]  # (B, L, win)
    return jnp.where(inb, win, jnp.full((), pad_value, x.dtype))


def sequence_enumerate(input, win_size, pad_value=0, lengths=None,
                       name=None):
    """All length-win_size subsequences per position, padded past the
    sequence end (ref: sequence_lod.py sequence_enumerate).
    input (B, L) int -> (B, L, win_size)."""
    if lengths is None:
        B = unwrap(input).shape[0]
        lengths = Tensor(jnp.full((B,), unwrap(input).shape[1],
                                  jnp.int32), _internal=True)
    return apply("sequence_enumerate", input, lengths,
                 win_size=int(win_size), pad_value=int(pad_value))


@register("sequence_slice")
def _sequence_slice(x, offset, length, *, maxlen):
    B, L = x.shape[0], x.shape[1]
    pos = offset[:, None].astype(jnp.int32) + jnp.arange(maxlen)[None, :]
    valid = jnp.arange(maxlen)[None, :] < length[:, None]
    posc = jnp.clip(pos, 0, L - 1)
    out = jnp.take_along_axis(
        x, posc.reshape(pos.shape + (1,) * (x.ndim - 2)), axis=1)
    return jnp.where(valid.reshape(valid.shape + (1,) * (x.ndim - 2)),
                     out, jnp.zeros((), x.dtype))


def sequence_slice(input, offset, length, maxlen=None, name=None):
    """Per-sequence slice [offset, offset+length) (ref: sequence_lod.py
    sequence_slice). Dense output padded to ``maxlen`` (defaults to the
    host max of ``length``); returns (sliced (B, maxlen, ...), length)."""
    ln = unwrap(length)
    if maxlen is None:
        maxlen = int(np.asarray(ln).max())
    out = apply("sequence_slice", input, offset, length,
                maxlen=int(maxlen))
    return out, length


@register("row_conv")
def _row_conv(x, w):
    # x (B, L, D); w (ctx, D): look-ahead conv (DeepSpeech2)
    gathered = _context_gather(x, jnp.arange(w.shape[0]))
    return jnp.einsum("blcd,cd->bld", gathered, w)


def row_conv(input, future_context_size=None, weight=None, param_attr=None,
             act=None, name=None):
    """Look-ahead row convolution (ref: row_conv_op.cc, DeepSpeech2):
    out[t] = sum_i w[i] * x[t+i] over the next ``ctx`` frames.

    Functional form: pass weight (future_context_size + 1, D)."""
    if weight is None:
        raise ValueError("pass weight=(future_context_size + 1, D)")
    out = apply("row_conv", input, weight)
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


@register("sequence_concat_op")
def _sequence_concat(*xs_and_lens):
    # xs: dense (B, T_i, ...) padded; lens: (B,) each. Rows are packed
    # back-to-back per batch item into a (B, sum(T_i), ...) buffer.
    n = len(xs_and_lens) // 2
    xs, lens = xs_and_lens[:n], xs_and_lens[n:]
    B = xs[0].shape[0]
    T_out = sum(x.shape[1] for x in xs)
    out = jnp.zeros((B, T_out) + xs[0].shape[2:], xs[0].dtype)
    offset = jnp.zeros((B,), jnp.int32)
    t_idx = jnp.arange(T_out)
    for x, ln in zip(xs, lens):
        T = x.shape[1]
        # scatter rows [0, ln) of x at [offset, offset+ln) of out
        src_pos = t_idx[None, :] - offset[:, None]          # (B, T_out)
        valid = (src_pos >= 0) & (src_pos < ln[:, None])
        src = jnp.take_along_axis(
            x, jnp.clip(src_pos, 0, T - 1).reshape(
                (B, T_out) + (1,) * (x.ndim - 2)), axis=1)
        out = jnp.where(valid.reshape((B, T_out) + (1,) * (x.ndim - 2)),
                        src, out)
        offset = offset + ln.astype(jnp.int32)
    return out


def sequence_concat(input, lengths=None, name=None):
    """Per-row concatenation of padded sequences (ref:
    sequence_lod.py sequence_concat): row b of the result is
    x1[b,:len1] ++ x2[b,:len2] ++ ..., zero-padded. ``lengths`` is a
    list of (B,) arrays (defaults to full rows). Returns (out, lengths)."""
    if lengths is None:
        lengths = [Tensor(jnp.full((unwrap(x).shape[0],), unwrap(x).shape[1],
                                   jnp.int32), _internal=True) for x in input]
    out = apply("sequence_concat_op", *input, *lengths)
    total = lengths[0]
    for ln in lengths[1:]:
        total = Tensor(unwrap(total) + unwrap(ln).astype(unwrap(total).dtype),
                       _internal=True)
    return out, total


def sequence_expand_as(x, y_lengths, name=None):
    """Expand each row of ``x (N, ...)`` ``y_lengths[i]`` times (ref:
    sequence_lod.py sequence_expand_as)."""
    return sequence_expand(x, y_lengths)
