"""Reduction ops.

Covers the reference's ``reduce_ops/*`` (reduce_sum/mean/max/min/prod/all/any),
``arg_max_op.cc``/``arg_min_op.cc``, ``mean_op.cc``, ``norm`` reductions,
``logsumexp``, ``kthvalue``/``mode`` and moment ops.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._base import register, apply, unwrap


def _norm_axis(axis):
    if isinstance(axis, Tensor):
        axis = [int(v) for v in np.atleast_1d(np.asarray(axis._data))]
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


def _reduce(name, jfn):
    @register(name)
    def _kernel(x, *, axis=None, keepdim=False):
        return jfn(x, axis=axis, keepdims=keepdim)

    def op(x, axis=None, keepdim=False, name_=None, dtype=None):
        if not isinstance(x, Tensor):
            x = Tensor(np.asarray(x))
        out = apply(name, x, axis=_norm_axis(axis), keepdim=keepdim)
        if dtype is not None:
            out = out.astype(dtype)
        return out

    op.__name__ = name
    return op


sum = _reduce("reduce_sum", jnp.sum)
mean = _reduce("reduce_mean", jnp.mean)
max = _reduce("reduce_max", jnp.max)
min = _reduce("reduce_min", jnp.min)
prod = _reduce("reduce_prod", jnp.prod)
amax = max
amin = min


@register("reduce_all")
def _all(x, *, axis=None, keepdim=False):
    return jnp.all(x, axis=axis, keepdims=keepdim)


@register("reduce_any")
def _any(x, *, axis=None, keepdim=False):
    return jnp.any(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False, name=None):
    return apply("reduce_all", x, axis=_norm_axis(axis), keepdim=keepdim)


def any(x, axis=None, keepdim=False, name=None):
    return apply("reduce_any", x, axis=_norm_axis(axis), keepdim=keepdim)


@register("logsumexp")
def _logsumexp(x, *, axis=None, keepdim=False):
    from jax.scipy.special import logsumexp as lse

    return lse(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply("logsumexp", x, axis=_norm_axis(axis), keepdim=keepdim)


@register("argmax")
def _argmax(x, *, axis=None, keepdim=False):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(jnp.int32)


@register("argmin")
def _argmin(x, *, axis=None, keepdim=False):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(jnp.int32)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply("argmax", x, axis=_norm_axis(axis), keepdim=keepdim).astype(dtype)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply("argmin", x, axis=_norm_axis(axis), keepdim=keepdim).astype(dtype)


@register("std")
def _std(x, *, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


@register("var")
def _var(x, *, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("std", x, axis=_norm_axis(axis), unbiased=unbiased, keepdim=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("var", x, axis=_norm_axis(axis), unbiased=unbiased, keepdim=keepdim)


@register("median")
def _median(x, *, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False, name=None):
    return apply("median", x, axis=_norm_axis(axis), keepdim=keepdim)


@register("quantile")
def _quantile(x, *, q, axis=None, keepdim=False):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply("quantile", x, q=q, axis=_norm_axis(axis), keepdim=keepdim)


@register("kthvalue")
def _kthvalue(x, *, k, axis=-1, keepdim=False):
    vals = jnp.sort(x, axis=axis)
    idx = jnp.argsort(x, axis=axis)
    taken_v = jnp.take(vals, k - 1, axis=axis)
    taken_i = jnp.take(idx, k - 1, axis=axis)
    if keepdim:
        taken_v = jnp.expand_dims(taken_v, axis)
        taken_i = jnp.expand_dims(taken_i, axis)
    return taken_v, taken_i.astype(jnp.int32)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    return apply("kthvalue", x, k=int(k), axis=axis, keepdim=keepdim)


@register("mode")
def _mode(x, *, axis=-1, keepdim=False):
    # O(n^2) pairwise count along the axis — fine for the modest n this op
    # sees; keeps shapes static for XLA.
    ax = axis % x.ndim
    eq = jnp.expand_dims(x, ax) == jnp.expand_dims(x, ax + 1)
    counts = jnp.sum(eq, axis=ax + 1)
    idx = jnp.argmax(counts, axis=ax)
    val = jnp.take_along_axis(x, jnp.expand_dims(idx, ax), axis=ax)
    if not keepdim:
        return jnp.squeeze(val, ax), idx.astype(jnp.int32)
    return val, jnp.expand_dims(idx, ax).astype(jnp.int32)


def mode(x, axis=-1, keepdim=False, name=None):
    return apply("mode", x, axis=axis, keepdim=keepdim)


@register("count_nonzero")
def _count_nonzero(x, *, axis=None, keepdim=False):
    return jnp.sum((x != 0).astype(jnp.int32), axis=axis, keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply("count_nonzero", x, axis=_norm_axis(axis), keepdim=keepdim)


@register("nansum")
def _nansum(x, *, axis=None, keepdim=False):
    return jnp.nansum(x, axis=axis, keepdims=keepdim)


def nansum(x, axis=None, keepdim=False, name=None):
    return apply("nansum", x, axis=_norm_axis(axis), keepdim=keepdim)


@register("nanmean")
def _nanmean(x, *, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply("nanmean", x, axis=_norm_axis(axis), keepdim=keepdim)
