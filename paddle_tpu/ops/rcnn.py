"""Two-stage (RPN / R-CNN / FPN / RetinaNet) detection ops.

Refs (capability targets):
- generate_proposals: python/paddle/fluid/layers/detection.py:2646
- rpn_target_assign: detection.py:157; retinanet_target_assign: :370
- retinanet_detection_output: detection.py:735
- distribute_fpn_proposals / collect_fpn_proposals:
  python/paddle/fluid/layers/detection.py:3838,3914
- psroi_pool / prroi_pool: layers/nn.py:13439,13504
- density_prior_box: detection.py:1800
- box_decoder_and_assign: detection.py:3770
- locality_aware_nms: detection.py:3327
- roi_perspective_transform: detection.py:1931
- generate_proposal_labels / generate_mask_labels: detection.py:2308,2440
- deformable_roi_pooling: layers/nn.py:14038

TPU-first conventions (same as ops/detection.py): everything is static
shape — variable-size results come back as fixed-size buffers padded
with sentinels plus valid counts; per-image structure replaces LoD.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ._base import register, apply, unwrap
from .detection import _pairwise_iou, _greedy_nms_mask, _roi_batch_ids

__all__ = [
    "generate_proposals", "rpn_target_assign", "retinanet_target_assign",
    "retinanet_detection_output", "distribute_fpn_proposals",
    "collect_fpn_proposals", "psroi_pool", "prroi_pool",
    "density_prior_box", "box_decoder_and_assign", "locality_aware_nms",
    "roi_perspective_transform", "generate_proposal_labels",
    "generate_mask_labels", "deformable_roi_pooling",
]


def _encode_deltas(anchors, gts):
    """Elementwise (A, 4) box -> delta encoding (inverse of
    _decode_deltas); the per-anchor regression target."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + aw * 0.5
    ay = anchors[:, 1] + ah * 0.5
    gw = jnp.maximum(gts[:, 2] - gts[:, 0] + 1.0, 1e-3)
    gh = jnp.maximum(gts[:, 3] - gts[:, 1] + 1.0, 1e-3)
    gx = gts[:, 0] + gw * 0.5
    gy = gts[:, 1] + gh * 0.5
    return jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                      jnp.log(gw / aw), jnp.log(gh / ah)], axis=1)


def _decode_deltas(anchors, deltas, variances=None):
    """Anchor + (dx, dy, dw, dh) -> box, the RPN decode_bbox_target."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + aw * 0.5
    ay = anchors[:, 1] + ah * 0.5
    if variances is not None:
        deltas = deltas * variances
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    cx = dx * aw + ax
    cy = dy * ah + ay
    w = jnp.exp(jnp.minimum(dw, 10.0)) * aw
    h = jnp.exp(jnp.minimum(dh, 10.0)) * ah
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - 1.0, cy + h * 0.5 - 1.0], axis=1)


@register("generate_proposals_op")
def _generate_proposals(scores, deltas, im_info, anchors, variances, *,
                        pre_nms_top_n, post_nms_top_n, nms_thresh,
                        min_size):
    # scores (B, A, H, W); deltas (B, A*4, H, W); anchors (H, W, A, 4)
    B = scores.shape[0]
    A, H, W = scores.shape[1], scores.shape[2], scores.shape[3]
    anc = anchors.reshape(-1, 4)
    var = variances.reshape(-1, 4) if variances is not None else None
    pre_n = min(pre_nms_top_n, A * H * W)

    def one(scores_i, deltas_i, info_i):
        s = jnp.transpose(scores_i, (1, 2, 0)).reshape(-1)       # HWA
        d = deltas_i.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        top_s, top_i = lax.top_k(s, pre_n)
        boxes = _decode_deltas(anc[top_i], d[top_i],
                               None if var is None else var[top_i])
        ih, iw = info_i[0], info_i[1]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0.0, iw - 1.0),
            jnp.clip(boxes[:, 1], 0.0, ih - 1.0),
            jnp.clip(boxes[:, 2], 0.0, iw - 1.0),
            jnp.clip(boxes[:, 3], 0.0, ih - 1.0)], axis=1)
        ws = boxes[:, 2] - boxes[:, 0] + 1.0
        hs = boxes[:, 3] - boxes[:, 1] + 1.0
        ms = min_size * info_i[2]
        ok = (ws >= ms) & (hs >= ms)
        top_s = jnp.where(ok, top_s, -jnp.inf)
        keep = _greedy_nms_mask(boxes, top_s, nms_thresh, False)
        keep = keep & jnp.isfinite(top_s)
        sel_s, sel_i = lax.top_k(jnp.where(keep, top_s, -jnp.inf),
                                 min(post_nms_top_n, pre_n))
        valid = jnp.isfinite(sel_s)
        out = jnp.where(valid[:, None], boxes[sel_i], 0.0)
        return out, jnp.where(valid, sel_s, 0.0), \
            valid.sum().astype(jnp.int32)

    return jax.vmap(one)(scores, deltas, im_info)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=True):
    """RPN proposal generation (ref: detection.py:2646). Fixed-shape:
    (B, post_nms_top_n, 4) boxes + (B, post_nms_top_n) scores, zero-padded,
    plus per-image valid counts (the LoD replacement)."""
    rois, roi_probs, counts = apply(
        "generate_proposals_op", scores, bbox_deltas, im_info, anchors,
        variances, pre_nms_top_n=int(pre_nms_top_n),
        post_nms_top_n=int(post_nms_top_n), nms_thresh=float(nms_thresh),
        min_size=float(min_size))
    if return_rois_num:
        return rois, roi_probs, counts
    return rois, roi_probs


def _subsample_mask(rng_scores, eligible, num):
    """Pick up to ``num`` of ``eligible`` with highest rng_scores (the
    random-subsample stand-in — static shape)."""
    masked = jnp.where(eligible, rng_scores, -jnp.inf)
    k = min(num, int(masked.shape[0]))
    top_v, top_i = lax.top_k(masked, k)
    sel = jnp.zeros_like(eligible).at[top_i].set(jnp.isfinite(top_v))
    return sel & eligible


@register("rpn_target_assign_op")
def _rpn_target_assign(anchors, gt_boxes, gt_valid, seed_scores, *,
                       rpn_batch_size_per_im, fg_fraction, positive_overlap,
                       negative_overlap):
    # anchors (A, 4); gt_boxes (G, 4); gt_valid (G,) bool
    iou = _pairwise_iou(anchors, gt_boxes, False)           # (A, G)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    # force-positive: best anchor per gt
    best_anchor = jnp.argmax(iou, axis=0)                   # (G,)
    forced = jnp.zeros((anchors.shape[0],), bool).at[best_anchor].set(
        gt_valid)
    pos = (best_iou >= positive_overlap) | forced
    neg = (best_iou < negative_overlap) & (best_iou >= 0.0) & ~pos
    n_fg = int(rpn_batch_size_per_im * fg_fraction)
    pos_sel = _subsample_mask(seed_scores, pos, n_fg)
    n_bg = rpn_batch_size_per_im - n_fg
    neg_sel = _subsample_mask(-seed_scores, neg, n_bg)
    labels = jnp.where(pos_sel, 1, jnp.where(neg_sel, 0, -1))
    tgt = _encode_deltas(anchors, gt_boxes[best_gt])
    return labels.astype(jnp.int32), tgt, pos_sel, neg_sel


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True,
                      gt_valid=None, name=None):
    """RPN anchor sampling (ref: detection.py:157). TPU-first output:
    dense per-anchor ``labels`` (A,) in {1 fg, 0 bg, -1 ignore}, encoded
    ``bbox_targets`` (A, 4), and fg/bg selection masks — in place of the
    reference's dynamic gathered index lists."""
    A = unwrap(anchor_box).reshape(-1, 4).shape[0]
    anchors = Tensor(unwrap(anchor_box).reshape(-1, 4), _internal=True)
    gts = Tensor(unwrap(gt_boxes).reshape(-1, 4), _internal=True)
    G = unwrap(gts).shape[0]
    if gt_valid is None:
        gt_valid = Tensor(jnp.ones((G,), bool), _internal=True)
    from ..core import random as prandom

    seed = Tensor(
        jax.random.uniform(prandom.next_key(), (A,), jnp.float32)
        if use_random else jnp.arange(A, 0, -1, dtype=jnp.float32) / A,
        _internal=True)
    return apply("rpn_target_assign_op", anchors, gts, gt_valid, seed,
                 rpn_batch_size_per_im=int(rpn_batch_size_per_im),
                 fg_fraction=float(rpn_fg_fraction),
                 positive_overlap=float(rpn_positive_overlap),
                 negative_overlap=float(rpn_negative_overlap))


@register("retinanet_target_assign_op")
def _retina_target_assign(anchors, gt_boxes, gt_labels, gt_valid, *,
                          positive_overlap, negative_overlap):
    iou = _pairwise_iou(anchors, gt_boxes, False)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    best_anchor = jnp.argmax(iou, axis=0)
    forced = jnp.zeros((anchors.shape[0],), bool).at[best_anchor].set(
        gt_valid)
    pos = (best_iou >= positive_overlap) | forced
    neg = (best_iou < negative_overlap) & ~pos
    cls = jnp.where(pos, gt_labels[best_gt], jnp.where(neg, 0, -1))
    tgt = _encode_deltas(anchors, gt_boxes[best_gt])
    fg_num = pos.sum().astype(jnp.int32)
    return cls.astype(jnp.int32), tgt, pos, neg, fg_num


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4,
                            gt_valid=None, name=None):
    """RetinaNet dense assignment (ref: detection.py:370): every anchor
    labeled {class fg, 0 bg, -1 ignore}; returns (labels (A,),
    bbox_targets (A, 4), fg_mask, bg_mask, fg_num)."""
    anchors = Tensor(unwrap(anchor_box).reshape(-1, 4), _internal=True)
    gts = Tensor(unwrap(gt_boxes).reshape(-1, 4), _internal=True)
    G = unwrap(gts).shape[0]
    labels = Tensor(unwrap(gt_labels).reshape(-1), _internal=True)
    if gt_valid is None:
        gt_valid = Tensor(jnp.ones((G,), bool), _internal=True)
    return apply("retinanet_target_assign_op", anchors, gts, labels,
                 gt_valid, positive_overlap=float(positive_overlap),
                 negative_overlap=float(negative_overlap))


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """RetinaNet inference head (ref: detection.py:735): decode per-level
    deltas onto anchors, then class-wise NMS. ``bboxes``/``scores`` are
    lists per FPN level; anchors likewise. Returns (B, keep_top_k, 6)
    + counts, as multiclass_nms."""
    from .detection import multiclass_nms

    info = unwrap(im_info)                               # (B, 3) h, w, scale
    decoded = []
    for dlt, anc in zip(bboxes, anchors):
        d = unwrap(dlt)                                  # (B, A_l, 4)
        a = unwrap(anc).reshape(-1, 4)

        def dec(di, inf):
            b = _decode_deltas(a, di)
            # clip to image bounds per the reference op
            return jnp.stack([
                jnp.clip(b[:, 0], 0.0, inf[1] - 1.0),
                jnp.clip(b[:, 1], 0.0, inf[0] - 1.0),
                jnp.clip(b[:, 2], 0.0, inf[1] - 1.0),
                jnp.clip(b[:, 3], 0.0, inf[0] - 1.0)], axis=1)

        decoded.append(Tensor(jax.vmap(dec)(d, info), _internal=True))
    from .manipulation import concat

    all_boxes = concat(decoded, axis=1)                  # (B, A, 4)
    all_scores = concat(list(scores), axis=2) if len(scores) > 1 \
        else scores[0]                                   # (B, C, A)
    return multiclass_nms(all_boxes, all_scores, score_threshold,
                          nms_top_k, keep_top_k, nms_threshold,
                          normalized=False, nms_eta=nms_eta,
                          background_label=-1)


@register("distribute_fpn_op")
def _distribute_fpn(rois, *, min_level, max_level, refer_level,
                    refer_scale):
    w = jnp.maximum(rois[:, 2] - rois[:, 0], 0.0)
    h = jnp.maximum(rois[:, 3] - rois[:, 1], 0.0)
    scale = jnp.sqrt(w * h)
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
    return jnp.clip(lvl, min_level, max_level).astype(jnp.int32)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """FPN level routing (ref: detection.py:3838). TPU-first: returns
    the per-roi target level (N,), per-level boolean masks, and the
    restore order (argsort by level, stable) instead of dynamically
    sized per-level tensors."""
    lvl = apply("distribute_fpn_op", fpn_rois, min_level=int(min_level),
                max_level=int(max_level), refer_level=int(refer_level),
                refer_scale=int(refer_scale))
    lv = unwrap(lvl)
    masks = [Tensor(lv == l, _internal=True)
             for l in range(int(min_level), int(max_level) + 1)]
    order = jnp.argsort(lv, stable=True)
    restore = jnp.argsort(order, stable=True)
    return lvl, masks, Tensor(restore.astype(jnp.int32), _internal=True)


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Merge per-level proposals, keep global top-k by score (ref:
    detection.py:3914). Inputs: lists of (N_l, 4) rois and (N_l,) scores,
    zero-padded the way generate_proposals emits them, plus
    ``rois_num_per_level`` — per-level valid counts. Pad rows are masked
    to -inf before the top-k so padding never competes, and the returned
    count reflects real proposals. Returns (post_nms_top_n, 4) boxes
    (zero-padded) + valid count."""
    from .manipulation import concat

    rois = concat(list(multi_rois), axis=0)
    scores = concat(list(multi_scores), axis=0)
    r, s = unwrap(rois), unwrap(scores).reshape(-1)
    if rois_num_per_level is not None:
        # mask per-level pad rows: row i of level l is valid iff
        # i < rois_num_per_level[l]
        valid_rows = []
        for lvl_scores, n in zip(multi_scores, rois_num_per_level):
            n_l = n if isinstance(n, (int, np.integer)) else unwrap(n)
            n_l = jnp.asarray(n_l).reshape(()).astype(jnp.int32)
            size = int(np.prod(unwrap(lvl_scores).shape))
            valid_rows.append(jnp.arange(size) < n_l)
        row_valid = jnp.concatenate(valid_rows)
        s = jnp.where(row_valid, s, -jnp.inf)
    k = min(int(post_nms_top_n), r.shape[0])
    top_s, top_i = lax.top_k(s, k)
    valid = jnp.isfinite(top_s)
    out = jnp.where(valid[:, None], r[top_i], 0.0)
    return Tensor(out, _internal=True), \
        Tensor(valid.sum().astype(jnp.int32), _internal=True)


@register("psroi_pool_op")
def _psroi_pool(feat, rois, bids, *, out_channels, spatial_scale, ph, pw):
    # position-sensitive: output channel c at bin (i, j) pools input
    # channel c*ph*pw + i*pw + j (ref: psroi_pool_op.cc).
    H, W = feat.shape[2], feat.shape[3]

    def one(roi, bid):
        x1, y1, x2, y2 = (roi[k] * spatial_scale for k in range(4))
        bw = jnp.maximum(x2 - x1, 0.1) / pw
        bh = jnp.maximum(y2 - y1, 0.1) / ph
        img = feat[bid]                                   # (C, H, W)
        outs = []
        for i in range(ph):
            row = []
            for j in range(pw):
                ys = jnp.clip(y1 + i * bh, 0, H - 1)
                ye = jnp.clip(y1 + (i + 1) * bh, 0, H)
                xs = jnp.clip(x1 + j * bw, 0, W - 1)
                xe = jnp.clip(x1 + (j + 1) * bw, 0, W)
                yy = jnp.arange(H, dtype=jnp.float32)
                xx = jnp.arange(W, dtype=jnp.float32)
                my = ((yy >= jnp.floor(ys)) & (yy < jnp.ceil(ye)))
                mx = ((xx >= jnp.floor(xs)) & (xx < jnp.ceil(xe)))
                m = (my[:, None] & mx[None, :]).astype(feat.dtype)
                cnt = jnp.maximum(m.sum(), 1.0)
                chans = jnp.arange(out_channels) * (ph * pw) + i * pw + j
                sel = img[chans]                          # (Co, H, W)
                row.append((sel * m[None]).sum(axis=(1, 2)) / cnt)
            outs.append(jnp.stack(row, axis=-1))
        return jnp.stack(outs, axis=-2)                   # (Co, ph, pw)

    return jax.vmap(one)(rois, bids)


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    """Position-sensitive RoI pooling (ref: nn.py:13439). input channels
    must equal output_channels * ph * pw."""
    C = unwrap(input).shape[1]
    assert C == output_channels * pooled_height * pooled_width, \
        f"C={C} != {output_channels}*{pooled_height}*{pooled_width}"
    return apply("psroi_pool_op", input, rois,
                 _roi_batch_ids(rois, rois_num),
                 out_channels=int(output_channels),
                 spatial_scale=float(spatial_scale),
                 ph=int(pooled_height), pw=int(pooled_width))


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    """Precise RoI pooling (ref: nn.py:13504). The exact op integrates
    the bilinear surface over each bin; a dense 4x4-tap average per bin
    converges to the same value and stays MXU-friendly."""
    from .detection import roi_align

    return roi_align(input, rois, pooled_height, pooled_width,
                     spatial_scale, sampling_ratio=4,
                     rois_num=batch_roi_nums, aligned=True)


@register("density_prior_box_op")
def _density_prior_box(fm, im, *, densities, fixed_sizes, fixed_ratios,
                       variance, step, offset, clip):
    H, W = fm.shape[2], fm.shape[3]
    IH, IW = im.shape[2], im.shape[3]
    sh = step[1] if step[1] > 0 else IH / H
    sw = step[0] if step[0] > 0 else IW / W
    cy = (jnp.arange(H) + offset) * sh
    cx = (jnp.arange(W) + offset) * sw
    boxes = []
    for density, fsize in zip(densities, fixed_sizes):
        for ratio in fixed_ratios:
            bw = fsize * np.sqrt(ratio)
            bh = fsize / np.sqrt(ratio)
            shift = fsize / density
            for di in range(density):
                for dj in range(density):
                    oy = -fsize / 2.0 + shift / 2.0 + di * shift
                    ox = -fsize / 2.0 + shift / 2.0 + dj * shift
                    ccy = cy[:, None] + oy
                    ccx = cx[None, :] + ox
                    b = jnp.stack([
                        jnp.broadcast_to((ccx - bw / 2.0) / IW, (H, W)),
                        jnp.broadcast_to((ccy - bh / 2.0) / IH, (H, W)),
                        jnp.broadcast_to((ccx + bw / 2.0) / IW, (H, W)),
                        jnp.broadcast_to((ccy + bh / 2.0) / IH, (H, W)),
                    ], axis=-1)
                    boxes.append(b)
    out = jnp.stack(boxes, axis=2)                        # (H, W, P, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, out.dtype), out.shape)
    return out, var


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """Density prior boxes (ref: detection.py:1800): per-cell anchors on
    a density x density sub-grid per fixed size/ratio. Returns
    (boxes (H, W, P, 4), variances) normalized, or flattened (HWP, 4)."""
    out, var = apply("density_prior_box_op", input, image,
                     densities=tuple(int(d) for d in densities),
                     fixed_sizes=tuple(float(s) for s in fixed_sizes),
                     fixed_ratios=tuple(float(r) for r in fixed_ratios),
                     variance=tuple(float(v) for v in variance),
                     step=tuple(float(s) for s in steps),
                     offset=float(offset), clip=bool(clip))
    if flatten_to_2d:
        from .manipulation import reshape

        return reshape(out, [-1, 4]), reshape(var, [-1, 4])
    return out, var


@register("box_decoder_and_assign_op")
def _box_decoder_and_assign(prior, pvar, deltas, scores, *, box_clip):
    # deltas (N, C*4), scores (N, C): decode every class, then assign the
    # argmax class's box (ref: box_decoder_and_assign_op.cc).
    N, C = scores.shape
    d = deltas.reshape(N, C, 4)
    var = pvar if pvar is not None else jnp.ones((N, 4), deltas.dtype)

    def dec(cls_deltas):
        dd = cls_deltas * var
        # box_clip upper-bounds only the log-scale dw/dh columns (ref:
        # box_decoder_and_assign_op.h:53 std::min(dw, clip) — caps exp()
        # growth); dx/dy pass through unclipped, no lower bound
        dd = jnp.concatenate(
            [dd[:, :2], jnp.minimum(dd[:, 2:4], box_clip)], axis=1)
        return _decode_deltas(prior, dd)

    all_boxes = jax.vmap(dec, in_axes=1, out_axes=1)(d)   # (N, C, 4)
    best = jnp.argmax(scores, axis=1)
    assigned = jnp.take_along_axis(
        all_boxes, best[:, None, None].repeat(4, 2), axis=1)[:, 0]
    return all_boxes.reshape(N, C * 4), assigned


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=4.135, name=None):
    """Per-class decode + best-class assignment (ref: detection.py:3770).
    Returns (decoded (N, C*4), assigned (N, 4))."""
    return apply("box_decoder_and_assign_op", prior_box, prior_box_var,
                 target_box, box_score, box_clip=float(box_clip))


@register("locality_aware_nms_op")
def _locality_aware_nms(boxes, scores, *, iou_threshold, keep_top_k):
    # EAST-style: first weighted-merge consecutive overlapping boxes
    # (score-weighted coordinates), then standard greedy NMS. Boxes with
    # score <= 0 (filtered by the threshold) are ineligible: they never
    # merge, never emit, and flush any open accumulator.
    N = boxes.shape[0]
    iou_next = jnp.concatenate([
        jax.vmap(lambda a, b: _pairwise_iou(a[None], b[None], False)[0, 0])(
            boxes[:-1], boxes[1:]), jnp.zeros((1,))])

    def body(carry, i):
        acc_box, acc_s, out_b, out_s, n = carry
        si = scores[i]
        eligible = si > 0.0
        w = acc_s + jnp.where(eligible, si, 0.0)
        merged = jnp.where(
            w > 0.0,
            (acc_box * acc_s + boxes[i] * jnp.where(eligible, si, 0.0))
            / jnp.maximum(w, 1e-8), acc_box)
        cont = eligible & (iou_next[i] > iou_threshold)  # keep accumulating
        emit = (w > 0.0) & ~cont
        out_b = jnp.where(emit, out_b.at[n].set(merged), out_b)
        out_s = jnp.where(emit, out_s.at[n].set(w), out_s)
        n = jnp.where(emit, n + 1, n)
        nb = jnp.where(cont, merged, jnp.zeros((4,)))
        ns = jnp.where(cont, w, 0.0)
        return (nb, ns, out_b, out_s, n), None

    init = (jnp.zeros((4,)), jnp.zeros(()), jnp.zeros((N, 4)),
            jnp.full((N,), -jnp.inf), jnp.int32(0))
    (nb, ns, mb, ms, n), _ = lax.scan(body, init, jnp.arange(N))
    # flush a still-open accumulator from the final step
    mb = jnp.where(ns > 0.0, mb.at[n].set(nb), mb)
    ms = jnp.where(ns > 0.0, ms.at[n].set(ns), ms)
    keep = _greedy_nms_mask(mb, ms, iou_threshold, False)
    keep = keep & jnp.isfinite(ms) & (ms > 0.0)
    k = min(keep_top_k, N) if keep_top_k > 0 else N
    sel_s, sel_i = lax.top_k(jnp.where(keep, ms, -jnp.inf), k)
    valid = jnp.isfinite(sel_s)
    return (jnp.where(valid[:, None], mb[sel_i], 0.0),
            jnp.where(valid, sel_s, 0.0),
            valid.sum().astype(jnp.int32))


def locality_aware_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                       keep_top_k=-1, nms_threshold=0.3, normalized=False,
                       nms_eta=1.0, background_label=-1, name=None):
    """Locality-aware NMS (ref: detection.py:3327, EAST): consecutive
    overlapping boxes are score-weighted-merged before standard NMS.
    bboxes (N, 4) sorted in reading order; scores (N,).
    Returns (boxes, scores, count) fixed-shape."""
    s = unwrap(scores).reshape(-1)
    s = jnp.where(s >= score_threshold, s, 0.0)  # 0 marks ineligible
    return apply("locality_aware_nms_op", bboxes,
                 Tensor(s, _internal=True),
                 iou_threshold=float(nms_threshold),
                 keep_top_k=int(keep_top_k))


@register("roi_perspective_op")
def _roi_perspective(feat, rois, bids, *, th, tw, spatial_scale):
    # rois: (N, 8) quad corners (x1..y4, clockwise from top-left).
    # Solve the 3x3 homography mapping the output rectangle onto the
    # quad, then bilinear-sample (ref: roi_perspective_transform_op.cc).
    H, W = feat.shape[2], feat.shape[3]

    def one(quad, bid):
        q = quad.reshape(4, 2) * spatial_scale
        src = jnp.asarray([[0.0, 0.0], [tw - 1.0, 0.0],
                           [tw - 1.0, th - 1.0], [0.0, th - 1.0]])
        # DLT: build the 8x8 system A h = b
        rows = []
        bvec = []
        for k in range(4):
            x, y = src[k, 0], src[k, 1]
            u, v = q[k, 0], q[k, 1]
            rows.append(jnp.asarray(
                [x, y, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0]).at[6].set(-x * u)
                .at[7].set(-y * u))
            bvec.append(u)
            rows.append(jnp.asarray(
                [0.0, 0.0, 0.0, x, y, 1.0, 0.0, 0.0]).at[6].set(-x * v)
                .at[7].set(-y * v))
            bvec.append(v)
        A = jnp.stack(rows)
        b = jnp.asarray(bvec)
        h8 = jnp.linalg.solve(A + 1e-8 * jnp.eye(8), b)
        Hm = jnp.concatenate([h8, jnp.ones((1,))]).reshape(3, 3)
        ys, xs = jnp.meshgrid(jnp.arange(th, dtype=jnp.float32),
                              jnp.arange(tw, dtype=jnp.float32),
                              indexing="ij")
        ones = jnp.ones_like(xs)
        pts = jnp.stack([xs, ys, ones], axis=0).reshape(3, -1)
        mapped = Hm @ pts
        mx = mapped[0] / jnp.maximum(mapped[2], 1e-8)
        my = mapped[1] / jnp.maximum(mapped[2], 1e-8)
        mx = jnp.clip(mx, 0.0, W - 1.0)
        my = jnp.clip(my, 0.0, H - 1.0)
        x0 = jnp.floor(mx).astype(jnp.int32)
        y0 = jnp.floor(my).astype(jnp.int32)
        x1 = jnp.minimum(x0 + 1, W - 1)
        y1 = jnp.minimum(y0 + 1, H - 1)
        wx = mx - x0
        wy = my - y0
        img = feat[bid].reshape(feat.shape[1], -1)        # (C, H*W)

        def g(yi, xi):
            return img[:, yi * W + xi]

        val = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1) * (1 - wy) * wx +
               g(y1, x0) * wy * (1 - wx) + g(y1, x1) * wy * wx)
        return val.reshape(feat.shape[1], th, tw)

    return jax.vmap(one)(rois, bids)


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_num=None, name=None):
    """Perspective-warp quad rois to a fixed size (ref: detection.py:1931,
    OCR east). rois: (N, 8) quads. Returns (N, C, th, tw)."""
    return apply("roi_perspective_op", input, rois,
                 _roi_batch_ids(rois, rois_num),
                 th=int(transformed_height), tw=int(transformed_width),
                 spatial_scale=float(spatial_scale))


@register("proposal_labels_op")
def _proposal_labels(rois, gt_boxes, gt_classes, gt_valid, seed, *,
                     batch_size_per_im, fg_fraction, fg_thresh,
                     bg_thresh_hi, bg_thresh_lo, num_classes):
    iou = _pairwise_iou(rois, gt_boxes, False)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    fg = best_iou >= fg_thresh
    bg = (best_iou < bg_thresh_hi) & (best_iou >= bg_thresh_lo)
    n_fg = int(batch_size_per_im * fg_fraction)
    fg_sel = _subsample_mask(seed, fg, n_fg)
    bg_sel = _subsample_mask(-seed, bg, batch_size_per_im - n_fg)
    labels = jnp.where(fg_sel, gt_classes[best_gt],
                       jnp.where(bg_sel, 0, -1))
    tgt = _encode_deltas(rois, gt_boxes[best_gt])
    w = (fg_sel)[:, None].astype(jnp.float32) * jnp.ones((1, 4))
    return labels.astype(jnp.int32), tgt, w, fg_sel, bg_sel, best_gt


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info=None, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True,
                             gt_valid=None, name=None):
    """Second-stage sampling (ref: detection.py:2308). TPU-first dense
    output per roi: labels {cls, 0, -1}, encoded bbox targets, bbox
    inside-weights, fg/bg masks, and the matched gt index."""
    R = unwrap(rpn_rois).reshape(-1, 4).shape[0]
    rois = Tensor(unwrap(rpn_rois).reshape(-1, 4), _internal=True)
    gts = Tensor(unwrap(gt_boxes).reshape(-1, 4), _internal=True)
    G = unwrap(gts).shape[0]
    cls = Tensor(unwrap(gt_classes).reshape(-1), _internal=True)
    if gt_valid is None:
        gt_valid = Tensor(jnp.ones((G,), bool), _internal=True)
    from ..core import random as prandom

    seed = Tensor(
        jax.random.uniform(prandom.next_key(), (R,), jnp.float32)
        if use_random else jnp.arange(R, 0, -1, dtype=jnp.float32) / R,
        _internal=True)
    return apply("proposal_labels_op", rois, gts, cls, gt_valid, seed,
                 batch_size_per_im=int(batch_size_per_im),
                 fg_fraction=float(fg_fraction),
                 fg_thresh=float(fg_thresh),
                 bg_thresh_hi=float(bg_thresh_hi),
                 bg_thresh_lo=float(bg_thresh_lo),
                 num_classes=int(class_nums))


@register("mask_labels_op")
def _mask_labels(gt_masks, rois, matched_gt, fg_mask, *, resolution):
    # Crop each fg roi out of its matched dense gt mask and resize to
    # (resolution, resolution) with bilinear sampling.
    H, W = gt_masks.shape[1], gt_masks.shape[2]

    def one(roi, g, keep):
        x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
        ys = y1 + (jnp.arange(resolution) + 0.5) * \
            jnp.maximum(y2 - y1, 1e-3) / resolution
        xs = x1 + (jnp.arange(resolution) + 0.5) * \
            jnp.maximum(x2 - x1, 1e-3) / resolution
        yi = jnp.clip(ys, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xs, 0, W - 1).astype(jnp.int32)
        m = gt_masks[g][yi][:, xi]
        return jnp.where(keep, (m > 0.5).astype(jnp.float32),
                         jnp.zeros((resolution, resolution)))

    return jax.vmap(one)(rois, matched_gt, fg_mask)


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32=None, num_classes=81, resolution=14,
                         matched_gt=None, fg_mask=None, name=None):
    """Mask R-CNN mask targets (ref: detection.py:2440). The reference
    rasterizes COCO polygons; the dense+offsets design takes dense gt
    masks ``gt_segms (G, H, W)`` and crops/resizes per sampled fg roi
    (pass ``matched_gt``/``fg_mask`` from generate_proposal_labels)."""
    R = unwrap(rois).reshape(-1, 4).shape[0]
    if matched_gt is None:
        matched_gt = Tensor(jnp.zeros((R,), jnp.int32), _internal=True)
    if fg_mask is None:
        fg_mask = Tensor(jnp.ones((R,), bool), _internal=True)
    return apply("mask_labels_op", gt_segms, rois, matched_gt, fg_mask,
                 resolution=int(resolution))


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=1,
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           rois_num=None, name=None):
    """Deformable RoI pooling (ref: nn.py:14038): shift each bin by the
    learned normalized offsets in ``trans (N, 2, ph, pw)`` then average
    (position-sensitive variant routes to psroi channels)."""
    r = unwrap(rois).reshape(-1, 4)
    t = unwrap(trans)
    if no_trans or t is None:
        if position_sensitive:
            C = unwrap(input).shape[1]
            co = C // (pooled_height * pooled_width)
            return psroi_pool(input, rois, co, spatial_scale,
                              pooled_height, pooled_width,
                              rois_num=rois_num)
        from .detection import roi_align

        return roi_align(input, rois, pooled_height, pooled_width,
                         spatial_scale, sampling_ratio=sample_per_part,
                         rois_num=rois_num)
    # offset each roi bin: shift the whole roi by the mean offset (dense
    # per-bin shifting reuses the roi_align sampler per bin)
    w = (r[:, 2] - r[:, 0])[:, None]
    h = (r[:, 3] - r[:, 1])[:, None]
    mean_dx = t[:, 0].reshape(t.shape[0], -1).mean(axis=1)[:, None]
    mean_dy = t[:, 1].reshape(t.shape[0], -1).mean(axis=1)[:, None]
    shifted = jnp.concatenate([
        r[:, 0:1] + mean_dx * trans_std * w,
        r[:, 1:2] + mean_dy * trans_std * h,
        r[:, 2:3] + mean_dx * trans_std * w,
        r[:, 3:4] + mean_dy * trans_std * h], axis=1)
    from .detection import roi_align

    return roi_align(Tensor(unwrap(input), _internal=True),
                     Tensor(shifted, _internal=True), pooled_height,
                     pooled_width, spatial_scale,
                     sampling_ratio=sample_per_part, rois_num=rois_num)
