"""Shape / layout / gather-scatter ops.

Covers the reference's ``reshape_op.cc``, ``transpose_op.cc``,
``concat_op.cc``, ``split_op.cc``, ``gather(_nd)_op.cc``,
``scatter(_nd_add)_op.cc``, ``squeeze/unsqueeze``, ``expand/tile``,
``flip/roll``, ``top_k/argsort``, ``where/one_hot`` etc.

Dynamic-output-shape ops (nonzero, masked_select, unique) exist but return
host-materialised results in eager mode only — data-dependent shapes do not
compile on TPU, matching XLA's static-shape model.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dtype import convert_dtype
from ._base import register, apply, unwrap


@register("reshape")
def _reshape(x, *, shape):
    return jnp.reshape(x, shape)


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(s) for s in np.asarray(shape._data)]
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    return apply("reshape", x, shape=tuple(shape))


reshape_ = reshape


@register("transpose")
def _transpose(x, *, perm):
    return jnp.transpose(x, perm)


def transpose(x, perm=None, name=None):
    if perm is None:
        perm = list(range(unwrap(x).ndim))[::-1]
    return apply("transpose", x, perm=tuple(int(p) for p in perm))


def t(x, name=None):
    if unwrap(x).ndim < 2:
        return x
    return apply("transpose", x, perm=(1, 0))


@register("flatten")
def _flatten(x, *, start_axis, stop_axis):
    shp = x.shape
    nd = len(shp)
    start = start_axis % nd
    stop = stop_axis % nd
    new = shp[:start] + (int(np.prod(shp[start:stop + 1] or (1,))),) + shp[stop + 1:]
    return jnp.reshape(x, new)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return apply("flatten", x, start_axis=start_axis, stop_axis=stop_axis)


@register("squeeze")
def _squeeze(x, *, axis=None):
    return jnp.squeeze(x, axis=axis)


def squeeze(x, axis=None, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if unwrap(x).shape[a] == 1) or None
    elif axis is not None and unwrap(x).shape[axis] != 1:
        return x
    return apply("squeeze", x, axis=axis)


@register("unsqueeze")
def _unsqueeze(x, *, axis):
    return jnp.expand_dims(x, axis)


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply("unsqueeze", x, axis=axis)


@register("concat")
def _concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply("concat", *x, axis=int(axis))


@register("stack")
def _stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return apply("stack", *x, axis=int(axis))


@register("split")
def _split(x, *, sections, axis):
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis=axis))
    idx = np.cumsum(sections)[:-1].tolist()
    return tuple(jnp.split(x, idx, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if isinstance(num_or_sections, (list, tuple)):
        total = unwrap(x).shape[axis]
        secs = [int(s) for s in num_or_sections]
        if any(s == -1 for s in secs):
            rem = total - sum(s for s in secs if s != -1)
            secs = [rem if s == -1 else s for s in secs]
        out = apply("split", x, sections=tuple(secs), axis=int(axis))
    else:
        out = apply("split", x, sections=int(num_or_sections), axis=int(axis))
    return list(out)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = unwrap(x).shape[axis]
    parts = split(x, n, axis)
    return [squeeze(p, axis) for p in parts]


@register("slice_op")
def _slice_op(x, *, axes, starts, ends):
    idx = [slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = slice(s, e)
    return x[tuple(idx)]


def slice(x, axes, starts, ends):
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]
    return apply("slice_op", x, axes=tuple(axes), starts=tuple(starts), ends=tuple(ends))


@register("strided_slice")
def _strided_slice(x, *, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = slice(s, e, st)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    return apply("strided_slice", x, axes=tuple(axes), starts=tuple(starts),
                 ends=tuple(ends), strides=tuple(strides))


@register("gather")
def _gather(x, index, *, axis=0):
    if index.ndim == 0:
        index = index[None]
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if not isinstance(index, Tensor):
        index = Tensor(np.asarray(index))
    return apply("gather", x, index, axis=int(axis))


@register("gather_nd")
def _gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def gather_nd(x, index, name=None):
    return apply("gather_nd", x, index)


@register("take_along_axis")
def _take_along_axis(x, index, *, axis):
    return jnp.take_along_axis(x, index, axis=axis)


def take_along_axis(x, indices, axis, name=None):
    return apply("take_along_axis", x, indices, axis=axis)


@register("index_select")
def _index_select(x, index, *, axis=0):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0, name=None):
    return apply("index_select", x, index, axis=axis)


@register("index_sample")
def _index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_sample(x, index, name=None):
    return apply("index_sample", x, index)


@register("scatter")
def _scatter(x, index, updates, *, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return apply("scatter", x, index, updates, overwrite=overwrite)


@register("scatter_nd_add")
def _scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    return apply("scatter_nd_add", x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    zeros = Tensor(jnp.zeros(shape, unwrap(updates).dtype), _internal=True)
    return scatter_nd_add(zeros, index, updates)


def _along(index, axis, ndim):
    idx = []
    for d in range(ndim):
        if d == axis:
            idx.append(index)
        else:
            shape = [1] * ndim
            shape[d] = -1
            idx.append(jnp.reshape(jnp.arange(index.shape[d]), shape))
    return tuple(idx)


@register("put_along_axis")
def _put_along_axis(x, index, value, *, axis, reduce="assign"):
    value = jnp.broadcast_to(value, index.shape)
    full = _along(index, axis, x.ndim)
    if reduce == "add":
        return x.at[full].add(value)
    if reduce == "multiply":
        return x.at[full].multiply(value)
    return x.at[full].set(value)


def put_along_axis(x, index, value, axis, reduce="assign", name=None):
    if not isinstance(value, Tensor):
        value = Tensor(np.asarray(value))
    return apply("put_along_axis", x, index, value, axis=axis, reduce=reduce)


@register("tile")
def _tile(x, *, repeat_times):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = [int(v) for v in np.asarray(repeat_times._data)]
    return apply("tile", x, repeat_times=tuple(int(r) for r in repeat_times))


@register("expand")
def _expand(x, *, shape):
    lead = len(shape) - x.ndim
    shape = tuple(
        x.shape[i - lead] if s in (-1, None) and i >= lead else s
        for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x, shape)


def expand(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(v) for v in np.asarray(shape._data)]
    return apply("expand", x, shape=tuple(int(s) for s in shape))


broadcast_to = expand


def expand_as(x, y, name=None):
    return expand(x, unwrap(y).shape)


@register("repeat_interleave")
def _repeat_interleave(x, *, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    return apply("repeat_interleave", x, repeats=int(repeats), axis=axis)


@register("flip")
def _flip(x, *, axis):
    return jnp.flip(x, axis=axis)


def flip(x, axis, name=None):
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply("flip", x, axis=axis)


reverse = flip


@register("roll")
def _roll(x, *, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def roll(x, shifts, axis=None, name=None):
    shifts = tuple(shifts) if isinstance(shifts, (list, tuple)) else int(shifts)
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return apply("roll", x, shifts=shifts, axis=axis)


@register("pad")
def _pad(x, *, paddings, mode="constant", value=0.0):
    if mode == "constant":
        return jnp.pad(x, paddings, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, paddings, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """Paddle pad: flat list [l, r] per-dim from last dims (NCHW aware for len-4)."""
    nd = unwrap(x).ndim
    if isinstance(pad, Tensor):
        pad = [int(v) for v in np.asarray(pad._data)]
    pad = [int(p) for p in pad]
    if len(pad) == 2 * nd:
        # paddle "2*ndim" form: [[d0_l, d0_r], ...] flattened
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # spatial form: pairs assign from the LAST spatial dim backwards
        # (paddle/torch convention: [left, right, top, bottom, ...] —
        # left/right pad the W axis, i.e. the innermost dim)
        nspatial = len(pad) // 2
        widths = [(0, 0)] * nd
        if data_format.upper().endswith("C"):  # NHWC/NLC/NDHWC: spatial before C
            spatial_dims = list(range(1, 1 + nspatial))
        else:  # NCHW/NCL/NCDHW
            spatial_dims = list(range(nd - nspatial, nd))
        for i, d in enumerate(reversed(spatial_dims)):
            widths[d] = (pad[2 * i], pad[2 * i + 1])
    return apply("pad", x, paddings=tuple(widths), mode=mode, value=value)


@register("where")
def _where(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    if not isinstance(x, Tensor):
        x = Tensor(np.asarray(x))
    if not isinstance(y, Tensor):
        y = Tensor(np.asarray(y))
    return apply("where", condition, x, y)


@register("topk")
def _topk(x, *, k, axis=-1, largest=True):
    if not largest:
        vals, idx = jax.lax.top_k(jnp.moveaxis(-x, axis, -1), k)
        vals = -vals
    else:
        vals, idx = jax.lax.top_k(jnp.moveaxis(x, axis, -1), k)
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis).astype(jnp.int32)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    vals, idx = apply("topk", x, k=int(k), axis=axis, largest=largest)
    return vals, idx


top_k = topk


@register("sort")
def _sort(x, *, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


def sort(x, axis=-1, descending=False, name=None):
    return apply("sort", x, axis=axis, descending=descending)


@register("argsort")
def _argsort(x, *, axis=-1, descending=False):
    idx = jnp.argsort(x, axis=axis)
    return (jnp.flip(idx, axis=axis) if descending else idx).astype(jnp.int32)


def argsort(x, axis=-1, descending=False, name=None):
    return apply("argsort", x, axis=axis, descending=descending)


@register("one_hot")
def _one_hot(x, *, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def one_hot(x, num_classes, name=None):
    if isinstance(num_classes, Tensor):
        num_classes = int(num_classes.item())
    return apply("one_hot", x, num_classes=int(num_classes))


@register("cast")
def _cast(x, *, dtype):
    return x.astype(dtype)


def cast(x, dtype):
    return apply("cast", x, dtype=convert_dtype(dtype))


@register("shard_index")
def _shard_index(x, *, index_num, nshards, shard_id, ignore_value):
    size = index_num // nshards
    in_shard = (x // size) == shard_id
    return jnp.where(in_shard, x % size, ignore_value)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return apply("shard_index", input, index_num=index_num, nshards=nshards,
                 shard_id=shard_id, ignore_value=ignore_value)


# --- dynamic-shape ops: eager only (host materialisation) -------------------


def nonzero(x, as_tuple=False):
    arr = np.asarray(unwrap(x))
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i, dtype=jnp.int32), _internal=True) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), dtype=jnp.int32), _internal=True)


def masked_select(x, mask, name=None):
    arr = np.asarray(unwrap(x))
    m = np.asarray(unwrap(mask)).astype(bool)
    return Tensor(jnp.asarray(arr[np.broadcast_to(m, arr.shape)]), _internal=True)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    arr = np.asarray(unwrap(x))
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res), _internal=True)
    return tuple(Tensor(jnp.asarray(r), _internal=True) for r in res)


@register("masked_fill")
def _masked_fill(x, mask, *, value):
    return jnp.where(mask, value, x)


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        value = value.item()
    return apply("masked_fill", x, mask, value=value)


@register("number_count")
def _number_count(x, *, upper_range):
    return jnp.bincount(x.reshape(-1), length=upper_range).astype(jnp.int32)


def bincount(x, weights=None, minlength=0, name=None):
    arr = unwrap(x).reshape(-1)
    w = unwrap(weights).reshape(-1) if weights is not None else None
    length = max(int(np.asarray(arr).max(initial=0)) + 1, minlength)
    return Tensor(jnp.bincount(arr, weights=w, length=length), _internal=True)


@register("as_real")
def _as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@register("as_complex")
def _as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_real(x, name=None):
    return apply("as_real", x)


def as_complex(x, name=None):
    return apply("as_complex", x)


@register("moveaxis")
def _moveaxis(x, *, source, destination):
    return jnp.moveaxis(x, source, destination)


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis", x, source=source, destination=destination)


@register("swapaxes")
def _swapaxes(x, *, axis1, axis2):
    return jnp.swapaxes(x, axis1, axis2)


def swapaxes(x, axis1, axis2, name=None):
    return apply("swapaxes", x, axis1=axis1, axis2=axis2)


transpose_ = swapaxes


@register("rot90")
def _rot90(x, *, k, axes):
    return jnp.rot90(x, k=k, axes=axes)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", x, k=k, axes=tuple(axes))
