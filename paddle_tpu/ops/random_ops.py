"""Stateful random ops that consume tensors (dropout etc.).

Covers the reference's ``dropout_op.cc``, ``shuffle_channel``, and
rrelu-style stochastic ops. Keys come from the global generator in eager
mode; kernels take the key as an explicit input so they stay pure.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as _random
from ..core.tensor import Tensor
from ._base import register, apply


@register("dropout")
def _dropout(x, key, *, p, mode):
    if p == 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))
    return jnp.where(mask, x, jnp.zeros((), x.dtype))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None, key=None):
    """Ref: dropout_op.cc. In eval mode: identity (upscale) or scale by 1-p."""
    if not training:
        if mode == "upscale_in_train":
            return x
        from .math import scale as _scale

        return _scale(x, scale=1.0 - p)
    if p == 0.0:
        return x
    if key is None:
        key = _random.next_key()
    key_t = Tensor(key, _internal=True)
    if axis is not None:
        # structured dropout along axis: broadcast the mask
        shape = list(x.shape)
        axes = [axis] if isinstance(axis, int) else list(axis)
        for i in range(len(shape)):
            if i not in axes:
                shape[i] = 1
        return apply("dropout_axes", x, key_t, p=float(p), mode=mode, mask_shape=tuple(shape))
    return apply("dropout", x, key_t, p=float(p), mode=mode)


@register("dropout_axes")
def _dropout_axes(x, key, *, p, mode, mask_shape):
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, mask_shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))
    return jnp.where(mask, x, jnp.zeros((), x.dtype))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p=p, axis=list(axis), training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p=p, axis=list(axis), training=training)


@register("alpha_dropout")
def _alpha_dropout(x, key, *, p):
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    return a * jnp.where(mask, x, jnp.full((), alpha_p, x.dtype)) + b


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    return apply("alpha_dropout", x, Tensor(_random.next_key(), _internal=True), p=float(p))


@register("shuffle_channel")
def _shuffle_channel(x, *, group):
    n, c, h, w = x.shape
    return jnp.reshape(jnp.swapaxes(jnp.reshape(x, (n, group, c // group, h, w)), 1, 2), (n, c, h, w))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    return apply("shuffle_channel", x, group=int(groups))
