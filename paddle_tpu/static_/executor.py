"""Executor: compile + run a Program.

TPU-native analog of ``python/paddle/fluid/executor.py`` +
``paddle/fluid/framework/executor.cc``. The reference walks the program and
launches one kernel per op; here the whole program is replayed into a single
pure jax function and compiled ONCE per (program version, feed shapes) with
``jax.jit`` — persistable buffers are donated so parameter updates happen
in-place in HBM.
"""
from __future__ import annotations

import dataclasses
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..obs import journal as _journal
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..resilience import inject as _chaos
from .program import (Program, default_main_program, global_scope)

__all__ = ["Executor", "CacheKey"]

# interned once: the run/compile paths tick these without touching the
# registry dict (obs.metrics.reset() zeroes in place, so the references
# stay live forever)
_M_CACHE_HITS = _metrics.counter("executor.jit_cache.hits")
_M_CACHE_MISSES = _metrics.counter("executor.jit_cache.misses")
_M_DISPATCHES = _metrics.counter("executor.dispatches")
_M_COMPILE_MS = _metrics.histogram("executor.compile_ms")
_M_RUN_MS = _metrics.histogram("executor.run_ms")
_M_FETCH_MS = _metrics.histogram("executor.fetch_ms")


@dataclasses.dataclass(frozen=True)
class CacheKey:
    """Named executor jit-cache key.

    Replaces the old positional tuple, whose layout was an append-order
    trap: every new axis (optimize level, data parallelism, now fused
    step count) had to slot in at exactly the right position or silently
    alias unrelated entries — and tests pinned magic indices like
    ``k[-2]``. Fields are named; add new axes as new fields.

    ``steps`` is ``None`` for the single-step path and the microbatch
    count K for fused ``lax.scan`` entries (``Executor.run_steps``) —
    the same program at the same feed shapes compiles to a different
    executable per K, so K is a genuine cache axis.

    ``comm`` is ``None`` for the implicit-GSPMD data-parallel path and
    ``CommOptions.cache_axis()`` for comm-efficient entries
    (``dist.gradcomm``): bucket layout / accumulation / quantization
    each change the compiled exchange, so they key distinct
    executables.

    ``plan`` is ``None`` for hand-specified parallelism and
    ``ShardingPlan.cache_axis()`` for ``fleet.auto_parallel`` entries:
    the plan's mesh layout and per-variable PartitionSpecs are baked
    into the executable's shardings, so two different plans over the
    same program/feeds are genuinely different executables.
    """

    program_uid: int
    program_version: int
    feed_names: tuple
    feed_shapes: tuple
    fetch_names: tuple
    optimize_level: int
    steps: int | None
    data_parallel: bool
    allow_replicated_fallback: bool
    comm: tuple | None = None
    plan: tuple | None = None


class _Compiled:
    def __init__(self, fn, feed_names, persist_in, persist_out, fetch_names):
        self.fn = fn
        self.feed_names = feed_names
        self.persist_in = persist_in
        self.persist_out = persist_out
        self.fetch_names = fetch_names


class Executor:
    def __init__(self, place=None, optimize_level=None):
        import os

        self.place = place
        self._cache: dict = {}
        # default pass pipeline level (see analysis.default_optimize_passes):
        # 0 = verify only, 1 = identity forwarding + DCE, 2 = + CSE.
        # Overridable per run() call and via PADDLE_TPU_OPT_LEVEL.
        if optimize_level is None:
            optimize_level = int(os.environ.get("PADDLE_TPU_OPT_LEVEL", "1"))
        self.optimize_level = int(optimize_level)
        self.last_diagnostics = None  # DiagnosticReport of the last compile
        self._cache_hits = 0    # this executor's share of the global
        self._cache_misses = 0  # executor.jit_cache.* counters
        self._dispatches = 0    # compiled-fn calls (run + run_steps);
        # process-wide mirror: obs.metrics executor.dispatches. The
        # perf gates (tools/perf_gate.py) read this to assert "1 compile
        # + 1 dispatch per K fused steps".

    def close(self):
        self._cache.clear()

    @property
    def dispatches(self):
        """Compiled-fn invocations so far (run + run_steps) — the cheap
        public read for compiled-call-count gates; pairs with
        ``cache_stats()['misses']`` (= compiles). Kept OUT of the
        default ``cache_stats()`` dict (its {hits,misses,size} shape is
        a pinned contract) and cheap unlike ``per_entry=True`` (which
        pays the lazy per-entry analysis)."""
        return self._dispatches

    # -- program -> pure function ------------------------------------------
    @staticmethod
    def _run_ops(env, ops, amp_cast):
        """Replay one op list over a name->array environment (the core
        interpreter loop, shared by the whole-program replay and the
        comm-efficient split replay)."""
        for op in ops:
            args = [env[n] if n is not None else None
                    for n in op.input_names]
            if amp_cast is not None:
                args = amp_cast(op.type, args)
            out = op.fn(*args, **op.attrs)
            if isinstance(out, tuple):
                for name, o in zip(op.output_names, out):
                    env[name] = o
            else:
                env[op.output_names[0]] = out
        return env

    @staticmethod
    def _replay_fn(program, ops, feed_names, updated_names, frozen_names,
                   fetch_names):
        ops = list(ops)
        consts = dict(program._constants)
        amp_cast = _amp_cast_fn(getattr(program, "_amp_cfg", None))

        def fn(feeds, updated, frozen):
            env = dict(consts)
            env.update(zip(feed_names, feeds))
            env.update(zip(updated_names, updated))
            env.update(zip(frozen_names, frozen))
            Executor._run_ops(env, ops, amp_cast)
            return ([env[n] for n in fetch_names],
                    [env[n] for n in updated_names])

        return fn

    def _comm_raw(self, program, ops, feed_names, fetch_names, shapes,
                  updated, frozen, steps, comm, mesh, scope, blk):
        """Comm-efficient data-parallel replay (``dist.gradcomm``).

        Instead of replaying the whole program under implicit GSPMD
        (one all-reduce per parameter gradient, placed by the
        partitioner), the op list is split at the backward/update
        boundary: the forward+backward segment runs under ``jax.vmap``
        over an explicit device-major batch axis — embarrassingly
        parallel, zero collectives — producing every gradient as an
        ``(ndev, ...)`` tensor of per-device partial sums; the exchange
        (bucketed / accumulated / int8-quantized all-reduce) is then
        explicit jax code; the update segment runs once on the reduced
        global gradients. Returns ``(raw_fn, state_var_names, plan,
        handles_steps)`` — ``handles_steps`` means the fn already
        consumes the whole stacked ``(K, ...)`` window (the
        accumulate_steps > 1 nested-scan form) and must not be wrapped
        in the generic single-level scan.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..dist import gradcomm as gc

        ndev = int(np.prod(mesh.devices.shape))
        N = int(comm.accumulate_steps)
        if N > 1:
            if not steps:
                raise ValueError(
                    f"accumulate_steps={N} needs the fused path: drive "
                    "the program through Executor.run_steps(steps=K) so "
                    "accumulation lives inside the scan body")
            if int(steps) % N:
                raise ValueError(
                    f"accumulate_steps={N} must divide the fused window "
                    f"(steps={steps}): partial accumulation windows "
                    "would silently change the effective batch")
        persist_set = set(updated) | set(frozen)
        comp_ops, update_ops, cross = gc.split_update_segment(ops)
        if comm.quantize and any(op.type.startswith("amp_")
                                 for op in update_ops):
            raise ValueError(
                "quantize='int8' cannot compose with AMP dynamic loss "
                "scaling: the exchange runs on SCALED gradients, so "
                "error-feedback residuals would live in loss-scale "
                "units and an overflow step would quantize inf into "
                "the persistent residual")
        cross = [n for n in cross if n not in persist_set
                 and n not in program._constants]
        if not cross:
            raise ValueError(
                "comm-efficient DP found no gradients crossing the "
                "backward/update boundary — nothing to exchange")
        grad_dtypes = {n: blk.var(n)._data.dtype for n in cross}
        plan = gc.plan_buckets(
            [(n, tuple(blk.var(n)._data.shape), np.dtype(grad_dtypes[n]))
             for n in cross], comm, ndev)

        # which feeds carry the batch axis (shapes are per-step even on
        # the fused path — same rule as feed_sharding below)
        vmap_feed = [len(s) >= 1 and s[0] > 0 and s[0] % ndev == 0
                     for s, _ in shapes]
        if shapes and not any(vmap_feed):
            dims = {n: s for (s, _), n in zip(shapes, feed_names)}
            raise ValueError(
                f"comm-efficient DP needs a feed whose leading dim "
                f"divides the {ndev}-device data mesh (feed shapes: "
                f"{dims}); there is no gradient exchange to optimize on "
                "a fully replicated step")

        comp_written = set()
        for op in comp_ops:
            comp_written.update(op.output_names)
        comp_persist = [n for n in updated if n in comp_written]
        comp_fetches = [n for n in fetch_names if n in comp_written]
        if N > 1:
            bad = [n for n in fetch_names if n not in comp_written]
            if bad:
                raise ValueError(
                    f"accumulate_steps={N} needs per-microbatch fetches, "
                    f"but {bad} come from the once-per-window update "
                    "segment (fetch forward/backward values instead)")

        consts = dict(program._constants)
        amp_cast = _amp_cast_fn(getattr(program, "_amp_cfg", None))
        need = list(dict.fromkeys(cross + comp_fetches + comp_persist))

        # -- exchange state (quantized path): per-bucket error-feedback
        # residuals + the stochastic-rounding counter, as @comm@*
        # persistables so they ride the donated carry, checkpoints, and
        # the elastic ProgramStateAdapter like any other training state
        state_names = []
        if comm.quantize:
            for i, b in enumerate(plan.buckets):
                name = gc.EF_PREFIX + str(i)
                ex = blk.vars.get(name)
                if ex is None or tuple(ex._data.shape) != (ndev, b.padded):
                    blk.vars.pop(name, None)
                    blk.create_var(name=name, shape=(ndev, b.padded),
                                   dtype="float32", persistable=True)
                    scope.set(name, jax.device_put(
                        jnp.zeros((ndev, b.padded), jnp.float32),
                        NamedSharding(mesh, P("data", None))))
                elif scope.find_var(name) is None:
                    scope.set(name, jax.device_put(
                        jnp.zeros((ndev, b.padded), jnp.float32),
                        NamedSharding(mesh, P("data", None))))
                state_names.append(name)
            # drop leftovers from a previously different bucket layout
            j = plan.n_buckets
            while blk.vars.pop(gc.EF_PREFIX + str(j), None) is not None:
                j += 1
            if not blk.has_var(gc.STEP_VAR):
                blk.create_var(name=gc.STEP_VAR, shape=(), dtype="int32",
                               persistable=True)
            if scope.find_var(gc.STEP_VAR) is None:
                scope.set(gc.STEP_VAR, jnp.int32(0))
            state_names.append(gc.STEP_VAR)
        n_base = len(updated)

        def comp_shard(feed_vals, upd_vals, frz_vals):
            env = dict(consts)
            env.update(zip(feed_names, feed_vals))
            env.update(zip(updated, upd_vals))
            env.update(zip(frozen, frz_vals))
            Executor._run_ops(env, comp_ops, amp_cast)
            return [env[n] for n in need]

        def vm_comp(feed_vals, upd_vals, frz_vals):
            """Reshape batch feeds device-major and vmap the
            forward+backward over the device axis."""
            batched, axes = gc.device_major(feed_vals, ndev, mesh,
                                            batch_flags=vmap_feed)
            outs = jax.vmap(
                lambda fv: comp_shard(fv, upd_vals, frz_vals),
                in_axes=(axes,))(batched)
            return dict(zip(need, outs))

        def aggregate(name, val):
            """Per-shard (ndev, ...) value -> global value: batch-shaped
            vars concatenate back to the full batch (exact); batch-
            reduced floats average across shards (the loss under a
            mean-type loss; rank-local-BN-style stats), integers sum."""
            lshape = tuple(blk.var(name)._data.shape)
            if val.ndim >= 2 and \
                    (val.shape[1] * ndev,) + tuple(val.shape[2:]) == lshape:
                return jnp.reshape(
                    val, (val.shape[1] * ndev,) + tuple(val.shape[2:]))
            red = val.sum(0)
            if jnp.issubdtype(val.dtype, jnp.floating) and \
                    comm.gradient_scale == "mean":
                red = red / ndev
            return red

        def flatten_cross(pershard):
            return plan.flatten_local(
                {n: pershard[n].astype(jnp.float32) for n in cross})

        def run_update(env, reduced, state, pershard_persist,
                       pershard_fetches):
            """The once-per-exchange tail: install aggregated comp
            values + reduced global grads, replay the update segment,
            advance the exchange state."""
            globals_ = plan.unflatten(reduced, dtypes=grad_dtypes)
            env.update(pershard_persist)
            env.update(pershard_fetches)
            env.update(globals_)
            Executor._run_ops(env, update_ops, amp_cast)
            if comm.quantize:
                new_resid, step_ctr = state
                new_state = list(new_resid) + [step_ctr + 1]
            else:
                new_state = []
            return env, new_state

        if N == 1:
            def raw(feeds, upd_all, frz_vals):
                upd_vals = list(upd_all[:n_base])
                state = list(upd_all[n_base:])
                residuals = state[:-1] if comm.quantize else None
                salt = state[-1] if comm.quantize else None
                pershard = vm_comp(feeds, upd_vals, frz_vals)
                reduced, new_resid = gc.exchange_bucketed(
                    plan, flatten_cross(pershard), mesh,
                    residuals=residuals, salt=salt)
                env = dict(consts)
                env.update(zip(feed_names, feeds))
                env.update(zip(updated, upd_vals))
                env.update(zip(frozen, frz_vals))
                env, new_state = run_update(
                    env, reduced, (new_resid, salt),
                    {n: aggregate(n, pershard[n]) for n in comp_persist},
                    {n: aggregate(n, pershard[n]) for n in comp_fetches})
                return ([env[n] for n in fetch_names],
                        [env[n] for n in updated] + new_state)

            return raw, tuple(state_names), plan, False

        # -- accumulate_steps > 1: nested scan over (K/N, N) windows.
        # The inner scan accumulates LOCAL per-device bucket partials
        # (zero communication); the exchange + update segment run once
        # per window, so the all-reduce fires once per N microbatches.
        K, W = int(steps), int(steps) // N

        def raw(stacked_feeds, upd_all, frz_vals):
            resh = [jnp.reshape(f, (W, N) + tuple(f.shape[1:]))
                    for f in stacked_feeds]

            def outer(carry, feeds_w):
                base, state = carry
                residuals = state[:-1] if comm.quantize else None
                salt = state[-1] if comm.quantize else None

                def inner(ic, feeds_k):
                    accs, pvals = ic
                    upd_cur = list(base)
                    for idx, n in enumerate(updated):
                        if n in comp_persist:
                            upd_cur[idx] = pvals[comp_persist.index(n)]
                    pershard = vm_comp(list(feeds_k), upd_cur, frz_vals)
                    accs = [a + f for a, f in
                            zip(accs, flatten_cross(pershard))]
                    new_pvals = [aggregate(n, pershard[n])
                                 for n in comp_persist]
                    fvals = [aggregate(n, pershard[n])
                             for n in fetch_names]
                    return (accs, new_pvals), fvals

                accs0 = [jax.lax.with_sharding_constraint(
                    jnp.zeros((ndev, b.padded), jnp.float32),
                    NamedSharding(mesh, P("data", None)))
                    for b in plan.buckets]
                pvals0 = [base[list(updated).index(n)]
                          for n in comp_persist]
                (accs, pvalsN), fetch_ys = jax.lax.scan(
                    inner, (accs0, pvals0), list(feeds_w))
                reduced, new_resid = gc.exchange_bucketed(
                    plan, accs, mesh, residuals=residuals, salt=salt)
                env = dict(consts)
                # update-segment feeds (e.g. @lr) take the window's last
                # microbatch row — the executor broadcast them over K
                env.update(zip(feed_names, [f[-1] for f in feeds_w]))
                env.update(zip(updated, base))
                env.update(zip(frozen, frz_vals))
                env, new_state = run_update(
                    env, reduced, (new_resid, salt),
                    dict(zip(comp_persist, pvalsN)), {})
                return ([env[n] for n in updated], new_state), fetch_ys

            upd_vals = list(upd_all[:n_base])
            state0 = list(upd_all[n_base:])
            (base_f, state_f), ys = jax.lax.scan(
                outer, (upd_vals, state0), resh)
            fetches = [jnp.reshape(y, (K,) + tuple(y.shape[2:]))
                       for y in ys]
            return fetches, list(base_f) + list(state_f)

        return raw, tuple(state_names), plan, True

    @staticmethod
    def _data_mesh():
        """One-axis ('data',) mesh over every local device. The reference's
        ParallelExecutor replicates the graph per GPU and all-reduces grads
        over NCCL (python/paddle/fluid/parallel_executor.py:28); here the
        same program is compiled ONCE as SPMD over this mesh and XLA
        inserts the ICI collectives. Local devices only: the Executor
        feeds host-local numpy arrays (multi-host DP goes through
        dist/parallel.py, which builds process-spanning arrays)."""
        from jax.sharding import Mesh

        return Mesh(np.asarray(jax.local_devices()), ("data",))

    def _compile(self, program, feed, fetch_list, data_parallel=False,
                 allow_replicated_fallback=False, optimize_level=None,
                 steps=None, comm_options=None, plan=None):
        from ..analysis import normalize_fetch

        if optimize_level is None:
            optimize_level = self.optimize_level
        if plan is not None:
            # an auto-parallel plan IS a data-parallel layout (its data
            # axis may be the whole mesh); the plan decides shardings
            data_parallel = True
        if _chaos.ACTIVE:  # chaos points: transient / optimized-only failure
            _chaos.fire("transient_compile")
            _chaos.fire("opt_compile_fail", optimize_level=optimize_level)
        feed_names = tuple(sorted(feed))
        fetch_names, _ = normalize_fetch(fetch_list)
        # per-STEP shapes even on the fused path (run_steps hands the
        # first microbatch here): the key describes the step body, and
        # `steps` carries the fusion axis. Metadata-only reads: a feed
        # value that is already a (possibly sharded, still-computing)
        # jax array must not be gathered to host just to learn its shape
        shapes = tuple(self._feed_shape_dtype(feed[n]) for n in feed_names)
        # program._uid is monotonic and never recycled (unlike id(program),
        # which the allocator can hand to a NEW Program after the old one
        # is GC'd — a stale-cache hit that replays the wrong executable)
        key = CacheKey(
            program_uid=program._uid, program_version=program._version,
            feed_names=feed_names, feed_shapes=shapes,
            fetch_names=fetch_names, optimize_level=int(optimize_level),
            steps=None if steps is None else int(steps),
            data_parallel=bool(data_parallel),
            allow_replicated_fallback=bool(allow_replicated_fallback),
            comm=None if comm_options is None else comm_options.cache_axis(),
            plan=None if plan is None else plan.cache_axis())
        if key in self._cache:
            compiled = self._cache[key]
            # coherence: uid+version are in the key, so a hit is the right
            # program UNLESS someone mutated Block.ops without bump() —
            # the one desync the key cannot see
            assert compiled.op_count == len(program.global_block.ops), \
                "executor cache incoherent: Block.ops changed without " \
                "Program.bump()"
            self.last_diagnostics = compiled.diagnostics
            self._cache_hits += 1
            _M_CACHE_HITS.inc()
            return compiled

        self._cache_misses += 1
        _M_CACHE_MISSES.inc()
        t0 = time.perf_counter()
        with _trace.span("executor.compile", uid=program._uid,
                         version=program._version,
                         optimize_level=int(optimize_level),
                         data_parallel=bool(data_parallel),
                         steps=steps):
            compiled = self._build(program, feed_names, fetch_names, shapes,
                                   fetch_list, data_parallel,
                                   allow_replicated_fallback, optimize_level,
                                   steps=steps, comm_options=comm_options,
                                   plan=plan)
        # NOTE: jax.jit is lazy — this times trace-side work (analysis
        # passes + jit wrapper construction); XLA's own compile lands in
        # the first executor.run_ms sample for this key
        compile_ms = (time.perf_counter() - t0) * 1e3
        _M_COMPILE_MS.observe(compile_ms)
        if _journal.ACTIVE is not None:
            # provenance: "xla" = compiled in this process (the lazy-jit
            # default), "aot_disk" = hydrated from the AOT executable
            # cache (runtime.aot) — zero XLA compile paid here. `via`
            # carries the same value on every site's compile events
            # (predictor/serving pin `source` to their site tag), so
            # run_report's cold-start summary reads one field.
            from ..runtime import aot as _aot

            prov = _aot.provenance_fields(
                getattr(compiled, "aot_info", None))
            prov.setdefault("via", "xla")
            extra = {"steps_fused": int(steps)} if steps else {}
            _journal.ACTIVE.event(
                "compile", uid=program._uid, version=program._version,
                optimize_level=int(optimize_level), ms=compile_ms,
                source=prov["via"], **prov, **extra)
            # one sharding event per compiled entry: feed/persistable
            # placement + footprints (metadata only — obs.spmd reads the
            # structs captured above, no device or XLA work)
            from ..obs import spmd as _spmd

            _journal.ACTIVE.event("sharding",
                                  **_spmd.sharding_summary(compiled))
            # one memory event per compiled entry: the static peak-HBM
            # prediction now; the measured memory_analysis() side is
            # re-journaled when the entry's lazy analysis lands
            _journal.ACTIVE.record_memory(compiled)
            if plan is not None:
                # one plan event per auto-parallel compile: the layout
                # the planner chose and its predicted-vs-measured wire
                # bytes (measured filled by fleet.verify_plan)
                _journal.ACTIVE.record_plan(plan, uid=program._uid,
                                            version=program._version)
        self._cache[key] = compiled
        return compiled

    def _build(self, program, feed_names, fetch_names, shapes, fetch_list,
               data_parallel, allow_replicated_fallback, optimize_level,
               steps=None, comm_options=None, plan=None):
        from ..analysis import run_compile_passes

        if plan is not None and comm_options is not None and \
                not plan.is_pure_dp:
            raise ValueError(
                "comm_options (dist.gradcomm) composes only with a "
                "pure data-parallel plan: the explicit exchange vmaps "
                f"over a single 'data' axis, but the plan spans "
                f"{plan.axes}")

        scope = global_scope()
        blk = program.global_block
        persist_in = tuple(
            v.name for v in blk.vars.values()
            if v.persistable and scope.find_var(v.name) is not None
            and not v.name.startswith("@comm@"))
        # @comm@* exchange state (dist.gradcomm error-feedback residuals
        # + rounding counter) is managed below: it must never ride the
        # generic persistable lists (a second compile would list it as
        # frozen AND updated)

        # -- analysis: verify always, optimize behind optimize_level --------
        # (raises ProgramVerificationError with coded, op-anchored
        # diagnostics instead of letting jax.jit fail mid-trace)
        ops, report = run_compile_passes(
            program, fetch_list=fetch_list,
            feed_shapes=dict(zip(feed_names, shapes)),
            scope_names=set(persist_in), optimize_level=optimize_level)
        self.last_diagnostics = report

        written = set()
        for op in ops:
            written.update(op.output_names)
        # only buffers the program re-emits may be donated; donating a
        # frozen (read-only) persistable would delete it from the scope
        updated = tuple(n for n in persist_in if n in written)
        frozen = tuple(n for n in persist_in if n not in written)

        # -- Executor-side verifier checks (need the live Scope / the
        # installed plan, which the pure-Program passes never see):
        # PTA011 use-after-donate buffer aliasing, PTA012 feed/fetch
        # specs inconsistent with the plan (analysis.dataflow)
        from ..analysis import dataflow as _ana_dataflow

        _ana_dataflow.check_donation_races(report, scope, updated, frozen)
        if plan is not None:
            _ana_dataflow.check_plan_consistency(
                report, plan, feed_names, shapes, fetch_names, scope)
        report.raise_if_errors()

        comm_state = ()
        comm_handles_steps = False
        if comm_options is not None:
            if not data_parallel:
                raise ValueError(
                    "comm_options requires a data-parallel program "
                    "(CompiledProgram.with_data_parallel)")
            raw, comm_state, comm_plan, comm_handles_steps = self._comm_raw(
                program, ops, feed_names, fetch_names, shapes, updated,
                frozen, steps, comm_options, self._data_mesh(), scope, blk)
            updated = updated + comm_state
        else:
            raw = self._replay_fn(program, ops, feed_names, updated,
                                  frozen, fetch_names)
        if steps and not comm_handles_steps:
            # fused multi-step path: drive K microbatches through ONE
            # lax.scan — the step body lowers once, the persistables ride
            # as the (donated) carry, stacked feeds are the scan xs, and
            # per-step fetches come back stacked as ys. One compile and
            # one dispatch per K steps instead of K Python dispatches —
            # the ParallelExecutor-era per-op dispatch amortization,
            # rebuilt on XLA's loop fusion.
            raw_step, K = raw, int(steps)

            def raw(stacked_feeds, updated_arrs, frozen_arrs):
                def body(carry, feeds_k):
                    fetches, new_updated = raw_step(list(feeds_k), carry,
                                                    frozen_arrs)
                    return new_updated, fetches

                new_updated, ys = jax.lax.scan(
                    body, list(updated_arrs), list(stacked_feeds), length=K)
                return ys, new_updated

        if data_parallel and plan is not None and not plan.is_pure_dp:
            # fleet.auto_parallel: the plan owns the layout — a multi-
            # axis mesh with per-variable PartitionSpecs (batch feeds
            # over the data axes, TP-paired weights over the model axis)
            # instead of the one-axis shard-the-batch default below.
            # GSPMD still inserts every collective; the plan just sets
            # the shardings it partitions around.
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = plan.build_mesh()
            rep = NamedSharding(mesh, P())

            def feed_sharding(name, shape):
                spec = plan.feed_spec_for(name, shape)
                if not spec:
                    return rep
                # fused entries carry a leading K scan axis every device
                # walks identically — the plan's specs shift right
                return NamedSharding(
                    mesh, P(*(((None,) + tuple(spec)) if steps
                              else spec)))

            feed_sh = [feed_sharding(n, s)
                       for n, (s, _) in zip(feed_names, shapes)]

            def persist_sharding(name):
                a = scope.find_var(name)
                shape = tuple(a.shape) if a is not None else None
                spec = plan.spec_for(name, shape)
                return NamedSharding(mesh, P(*spec)) if spec else rep

            upd_sh = [persist_sharding(n) for n in updated]
            frz_sh = [persist_sharding(n) for n in frozen]
            in_sh = (feed_sh, upd_sh, frz_sh)
            out_sh = ([rep] * len(fetch_names), upd_sh)
            jit_fn = jax.jit(raw, donate_argnums=(1,), in_shardings=in_sh,
                             out_shardings=out_sh)
        elif data_parallel:
            # Shard the feed batch axis over the data mesh; persistables
            # stay replicated. XLA partitions the one program and inserts
            # the grad all-reduce itself (GSPMD) — the TPU analog of the
            # reference's per-device graph replication + NCCL all_reduce.
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self._data_mesh()
            ndev = int(np.prod(mesh.devices.shape))
            rep = NamedSharding(mesh, P())

            def feed_sharding(shape):
                # `shape` is always the per-STEP shape; on the fused path
                # the actual jit argument carries a leading scan axis of
                # K microbatches, which must stay unsharded (every device
                # walks the same K steps) — the batch axis moves to dim 1
                if len(shape) >= 1 and shape[0] > 0 and shape[0] % ndev == 0:
                    return NamedSharding(
                        mesh, P(None, "data") if steps else P("data"))
                return rep  # non-batched / indivisible feeds replicate

            feed_sh = [feed_sharding(s) for s, _ in shapes]
            if shapes and not any(sh is not rep for sh in feed_sh):
                # NOTHING sharded: the "data-parallel" step would run
                # fully replicated — reference ParallelExecutor errors on
                # unsplittable batches (parallel_executor.py:28), so
                # refuse unless the user opted into the fallback. (An
                # indivisible AUXILIARY feed next to properly-sharded
                # batch feeds replicates quietly — that is correct, not
                # a degraded run.)
                dims = {n: s for (s, _), n in zip(shapes, feed_names)}
                if not allow_replicated_fallback:
                    raise ValueError(
                        f"data-parallel run but no feed's leading dim "
                        f"divides the {ndev} devices of the data mesh "
                        f"(feed shapes: {dims}): the step would execute "
                        "fully replicated with 0% DP speedup. Pad or "
                        "rebatch the feed, or opt in with "
                        "ExecutionStrategy.allow_replicated_fallback"
                        "=True")
                import warnings

                warnings.warn(
                    f"data-parallel feeds {dims} have no leading dim "
                    f"divisible by {ndev} devices: running fully "
                    "replicated (no DP speedup)", RuntimeWarning)

            def persist_sharding(name):
                # comm-exchange residuals are PER-DEVICE state: row d is
                # device d's error feedback — replicating them would
                # both waste HBM and gather what is semantically local
                from ..dist.gradcomm import EF_PREFIX

                if name.startswith(EF_PREFIX):
                    return NamedSharding(mesh, P("data", None))
                return rep

            upd_sh = [persist_sharding(n) for n in updated]
            in_sh = (feed_sh, upd_sh, [rep] * len(frozen))
            out_sh = ([rep] * len(fetch_names), upd_sh)
            jit_fn = jax.jit(raw, donate_argnums=(1,), in_shardings=in_sh,
                             out_shardings=out_sh)
        else:
            jit_fn = jax.jit(raw, donate_argnums=(1,))
        compiled = _Compiled(jit_fn, feed_names, updated + frozen, updated,
                             fetch_names)
        compiled.feed_shardings = in_sh[0] if data_parallel else None
        # persistable in_shardings, kept so the run path can re-place a
        # scope array a DIFFERENT entry committed to another mesh (two
        # plans over one program, or plan vs plain-DP): pjit refuses to
        # silently reshard committed args across meshes
        compiled.persist_shardings = (in_sh[1], in_sh[2]) \
            if data_parallel else None
        if data_parallel:
            # mesh identity for collective attribution + sharding
            # reports (obs.spmd): axis sizes and the device-id layout
            # the HLO replica groups refer to
            compiled.mesh_axes = dict(mesh.shape)
            compiled.mesh_device_ids = np.vectorize(
                lambda d: int(d.id))(mesh.devices)
        else:
            compiled.mesh_axes = None
            compiled.mesh_device_ids = None
        compiled.updated = updated
        compiled.frozen = frozen
        compiled.program_uid = program._uid
        compiled.program_version = program._version
        compiled.op_count = len(blk.ops)  # pre-optimization: mirrors _version
        compiled.diagnostics = report
        compiled.optimize_level = int(optimize_level)
        compiled.steps = None if steps is None else int(steps)
        compiled.comm_options = comm_options
        compiled.comm_plan = comm_plan if comm_options is not None else None
        compiled.plan = plan  # fleet.auto_parallel ShardingPlan (or None)
        # shape/dtype-only arg structs (no device data): what the lazy
        # per-entry memory/FLOP attribution (obs.mfu.entry_analysis) and
        # the journal's MFU accounting re-lower against on demand. Fused
        # entries record the STACKED feed shapes — the shapes the
        # executable actually takes — so a re-lower reproduces the scan.
        def _struct(name):
            a = scope.find_var(name)  # .shape/.dtype are metadata reads:
            return jax.ShapeDtypeStruct(  # no host transfer of the array
                tuple(a.shape), np.dtype(a.dtype))

        def _feed_struct(s, dt):
            s = (int(steps),) + tuple(s) if steps else tuple(s)
            return jax.ShapeDtypeStruct(s, np.dtype(dt))

        compiled.arg_structs = (
            [_feed_struct(s, dt) for s, dt in shapes],
            [_struct(n) for n in updated],
            [_struct(n) for n in frozen])
        # examples/step hint for throughput accounting: the largest
        # leading feed dim (the batch axis in every workload here)
        lead = [s[0] for s, _ in shapes if len(s) >= 1 and s[0] > 0]
        compiled.examples_hint = max(lead) if lead else None
        # static peak-HBM prediction for this entry (analysis.memory
        # liveness walk): journaled as a `memory` event and validated
        # against the executable's memory_analysis() once the lazy
        # entry analysis lands (obs.journal.record_memory)
        from ..analysis import memory as _ana_memory

        try:
            est = _ana_memory.estimate_entry(
                program, ops=ops, fetch_list=fetch_list,
                feed_shapes=dict(zip(feed_names, shapes)),
                scope_names=set(persist_in), steps=steps, plan=plan,
                data_devices=(len(jax.local_devices())
                              if data_parallel and plan is None else 1))
            compiled.memory_estimate = est
            compiled.predicted_memory = est.as_event()
        except Exception:  # an estimate failure must never cost a run
            compiled.memory_estimate = None
            compiled.predicted_memory = None
        # -- AOT executable cache (runtime.aot): with a cache active the
        # entry compiles EAGERLY — hydrated from disk when the content
        # digest (fingerprint + lowered StableHLO) matches, else
        # lowered.compile() + published — and compiled.fn becomes the
        # jax.stages.Compiled (same calling convention, donation and
        # shardings baked in, outputs bitwise what the lazy jit would
        # produce). No cache -> lazy jit, exactly as before.
        compiled.aot_info = None
        from ..runtime import aot as _aot

        cache = _aot.active_cache()
        if cache is not None:
            label = f"uid{program._uid}v{program._version}" + \
                (f"/steps{steps}" if steps else "")
            exe, info = _aot.load_or_compile(
                jit_fn, compiled.arg_structs, kind="executor",
                cache=cache, label=label)
            if exe is not None:
                compiled.fn = exe
                compiled.aot_info = info
        return compiled

    def cache_stats(self, per_entry=False):
        """Hit/miss/size of this executor's jit cache (the process-wide
        view lives in ``obs.metrics`` under ``executor.jit_cache.*``).
        Read-only: the cache-key layout is pinned by tests — never use
        this to re-key or evict.

        ``per_entry=True`` adds an ``entries`` list attributing cache
        growth: program uid/version/optimize_level plus bytes, FLOPs,
        and the ``collectives`` CollectiveProfile (per-kind counts/byte
        volumes, mesh-axis attribution — ``obs.spmd``) from the
        compiled executable — lazily computed on first request (one
        re-lower+compile per entry, cached), ``None`` where the backend
        doesn't report."""
        out = {"hits": self._cache_hits, "misses": self._cache_misses,
               "size": len(self._cache)}
        if per_entry:
            from ..obs.mfu import entry_analysis

            # dispatches only rides the opt-in shape: the default dict
            # {hits,misses,size} is pinned by tests
            out["dispatches"] = self._dispatches
            entries = []
            for compiled in self._cache.values():
                a = entry_analysis(compiled)
                mem = a["memory"]
                entries.append({
                    "program_uid": compiled.program_uid,
                    "program_version": compiled.program_version,
                    "optimize_level": getattr(compiled, "optimize_level",
                                              None),
                    "feed_names": list(compiled.feed_names),
                    "memory_bytes": (sum(v for k, v in mem.items()
                                         if k != "generated_code_size")
                                     if mem else None),
                    "memory": mem,
                    "flops": (a["cost"] or {}).get("flops"),
                    "collectives": a.get("collectives"),
                    "mesh": getattr(compiled, "mesh_axes", None),
                    "steps_fused": getattr(compiled, "steps", None),
                })
            out["entries"] = entries
        return out

    @staticmethod
    def _feed_shape_dtype(v):
        """(shape, dtype-str) of one feed value WITHOUT materializing
        it: jax arrays / Tensors / numpy answer from metadata (no
        device->host gather); only raw Python containers pay an
        np.asarray."""
        v = getattr(v, "_data", v)
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            return tuple(v.shape), str(np.dtype(v.dtype))
        a = np.asarray(v)
        return a.shape, str(a.dtype)

    @staticmethod
    def _align_persistables(compiled, updated, frozen):
        """Re-place scope persistables whose COMMITTED sharding no
        longer matches this entry's in_shardings (the array was last
        touched by an entry over a different mesh — e.g. two
        auto-parallel plans over one program). pjit would reject the
        mismatch instead of resharding; an explicit device_put is the
        sanctioned cross-mesh move. Metadata-only when nothing moved:
        one sharding equality check per persistable."""
        shs = getattr(compiled, "persist_shardings", None)
        if shs is None:
            return updated, frozen

        def fix(vals, shardings):
            out = []
            for v, sh in zip(vals, shardings):
                if isinstance(v, jax.Array) and \
                        getattr(v, "committed", False) and \
                        v.sharding != sh:
                    v = jax.device_put(v, sh)
                out.append(v)
            return out

        return fix(updated, shs[0]), fix(frozen, shs[1])

    @staticmethod
    def _as_device(v):
        """Feed value -> jax array via the canonical
        ``core.tensor.as_device_array`` (already-device arrays pass
        through untouched — see its docstring)."""
        from ..core.tensor import as_device_array

        return as_device_array(v)

    @staticmethod
    def _unwrap_program(program):
        """CompiledProgram / transpiled-DP normalization shared by run
        and run_steps: returns (program, data_parallel,
        allow_replicated_fallback, comm_options, plan)."""
        from .compiler import CompiledProgram

        if program is None:
            program = default_main_program()
        data_parallel = False
        allow_replicated_fallback = False
        comm_options = None
        plan = None
        if isinstance(program, CompiledProgram):
            data_parallel = program._data_parallel
            allow_replicated_fallback = getattr(
                program._exec_strategy, "allow_replicated_fallback", False)
            comm_options = getattr(program._build_strategy, "comm_options",
                                   None)
            # fleet.auto_parallel attaches its ShardingPlan here; the
            # plan then rides _compile as a genuine CacheKey axis
            plan = getattr(program, "_plan", None)
            program = program._program
        if getattr(program, "_transpiled_dp", False):
            # fluid.transpiler.collective.GradAllReduce marked this
            # program: run it data-parallel (same SPMD path as
            # CompiledProgram.with_data_parallel)
            data_parallel = True
        return program, data_parallel, allow_replicated_fallback, \
            comm_options, plan

    @staticmethod
    def _materialize_fetches(fetches, return_numpy, fetch_async):
        """The step's host-sync policy, in one place. ``return_numpy``
        blocks on every fetch (np.asarray is the sync point);
        ``fetch_async`` hands back the raw jax arrays — the device may
        still be computing, and the caller syncs when (if) it reads
        them; the lazy-Tensor default in between wraps without forcing
        numpy."""
        tf = time.perf_counter()
        if fetch_async:  # no wrapper, no sync: overlap-friendly fetches
            out = list(fetches)
        elif return_numpy:  # np.asarray is the step's host sync point:
            out = [np.asarray(f) for f in fetches]  # fetch latency
        else:  # lazy Tensors: fetch_ms records only wrapper cost
            out = [Tensor(f, _internal=True) for f in fetches]
        _M_FETCH_MS.observe((time.perf_counter() - tf) * 1e3)
        return out

    # -- public API ---------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, feed_var_name=None,
            fetch_var_name=None, scope=None, return_numpy=True,
            use_program_cache=True, optimize_level=None, fetch_async=False):
        """Run ``program`` (ref: executor.py Executor.run). New vs the
        reference: ``optimize_level`` selects the ``paddle_tpu.analysis``
        pass pipeline applied before compilation — 0 verify-only,
        1 (default) identity-forwarding + dead-op elimination,
        2 additionally CSE. The verifier always runs; a malformed Program
        raises ``analysis.ProgramVerificationError`` with coded
        diagnostics. ``None`` inherits the Executor-level default
        (``Executor(optimize_level=...)`` / env ``PADDLE_TPU_OPT_LEVEL``).

        ``fetch_async=True`` returns the raw jax arrays with NO host
        sync: the dispatch is asynchronous, so the Python loop can feed
        the next batch while the device still computes this one. The
        caller pays the sync when it first reads a value (or via
        ``jax.block_until_ready``). Overrides ``return_numpy``.
        """
        program, data_parallel, allow_replicated_fallback, comm_options, \
            plan = self._unwrap_program(program)
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        if not program.global_block.ops:  # startup program: params already
            return []  # materialized eagerly at build time

        # schedulers: refresh host-side lr into the feed each run
        if program._lr_getter is not None:
            feed = dict(feed)
            feed["@lr"] = np.asarray(program._lr_getter(), np.float32)

        t0 = time.perf_counter()
        with _trace.span("executor.run", uid=program._uid,
                         n_fetch=len(fetch_list)):
            compiled = self._compile(
                program, feed, fetch_list, data_parallel=data_parallel,
                allow_replicated_fallback=allow_replicated_fallback,
                optimize_level=optimize_level, comm_options=comm_options,
                plan=plan)
            if _chaos.ACTIVE:  # disabled => one empty-dict test, no host sync
                _chaos.fire("transient_execute")
                feed = _chaos.fire("nan_feed", feed)
            feeds = [self._as_device(feed[n]) for n in compiled.feed_names]
            updated = [scope.find_var(n) for n in compiled.updated]
            frozen = [scope.find_var(n) for n in compiled.frozen]
            updated, frozen = self._align_persistables(compiled, updated,
                                                       frozen)
            self._dispatches += 1
            _M_DISPATCHES.inc()
            fetches, new_persist = compiled.fn(feeds, updated, frozen)
            for name, arr in zip(compiled.persist_out, new_persist):
                scope.set(name, arr)
            out = self._materialize_fetches(fetches, return_numpy,
                                            fetch_async)
        run_ms = (time.perf_counter() - t0) * 1e3
        _M_RUN_MS.observe(run_ms)
        if _journal.ACTIVE is not None:  # flight recorder: one None check
            # synced=False keeps the flight recorder off the device: a
            # lazy/async fetch must not pay a hidden per-step host sync
            # just to log a scalar
            _journal.ACTIVE.record_executor_run(
                compiled, out, run_ms,
                synced=bool(return_numpy) and not fetch_async)
        return out

    def run_steps(self, program=None, feeds=None, fetch_list=None,
                  steps=None, scope=None, return_numpy=True,
                  fetch_async=False, optimize_level=None):
        """Run K microbatches through ONE fused ``lax.scan`` executable.

        ``feeds`` is either a sequence of K per-step feed dicts (uniform
        shapes/dtypes) or a single dict of pre-stacked arrays with a
        leading axis of length ``steps``. The step body is lowered once,
        persistable buffers ride the scan as a DONATED carry (parameter
        updates stay in HBM across all K steps), and each fetch comes
        back stacked with a leading K axis — element ``[k]`` is bitwise
        what the k-th sequential ``run()`` call would have fetched.

        vs K ``run()`` calls: one compile + one dispatch per window
        instead of K Python dispatches, K feed transfers issued as one
        stacked transfer, and zero intermediate host syncs. Host-side
        per-step work (LR scheduler reads, chaos hooks) necessarily
        happens once per WINDOW, not once per step: the learning rate is
        sampled once and applied to all K microbatches.

        Returns a list parallel to ``fetch_list`` of stacked values
        (numpy by default; lazy/async under ``return_numpy=False`` /
        ``fetch_async=True`` as in ``run``).
        """
        program, data_parallel, allow_replicated_fallback, comm_options, \
            plan = self._unwrap_program(program)
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        # normalize to {name: stacked (K, ...) array}. Device arrays
        # stay device-side (jnp.stack), host values stack in numpy —
        # prefetched batches must not be gathered back to host here
        def _stackable(v):
            # host values stay numpy (np.stack below); device values
            # keep the canonical pass-through (same invariant as
            # core.tensor.as_device_array, minus the host->device move,
            # which is deferred to the single stacked transfer)
            v = getattr(v, "_data", v)
            return v if isinstance(v, jax.Array) else np.asarray(v)

        if isinstance(feeds, dict):
            if not steps:
                raise ValueError(
                    "run_steps with a pre-stacked feed dict needs an "
                    "explicit steps=K (the leading axis length)")
            K = int(steps)
            stacked = {n: _stackable(v) for n, v in feeds.items()}
            for n, v in stacked.items():
                if v.ndim < 1 or v.shape[0] != K:
                    raise ValueError(
                        f"pre-stacked feed {n!r} has shape {v.shape}; "
                        f"expected a leading microbatch axis of {K}")
        else:
            feeds = list(feeds or ())
            if not feeds:
                raise ValueError("run_steps needs at least one feed dict")
            K = int(steps) if steps else len(feeds)
            if K != len(feeds):
                raise ValueError(
                    f"steps={K} but {len(feeds)} feed dicts were given")
            names = sorted(feeds[0])
            for f in feeds[1:]:
                if sorted(f) != names:
                    raise ValueError(
                        "every microbatch must feed the same variables; "
                        f"got {sorted(f)} vs {names}")

            def _stack(vals):
                vals = [_stackable(v) for v in vals]
                if any(isinstance(v, jax.Array) for v in vals):
                    return jnp.stack([jnp.asarray(v) for v in vals])
                return np.stack(vals)

            stacked = {n: _stack([f[n] for f in feeds]) for n in names}
        if K <= 0:
            raise ValueError(f"steps must be >= 1, got {K}")

        if not program.global_block.ops:
            return []

        # LR schedulers are host-side state: fused windows sample once
        # per dispatch (documented above), exactly like the compiled
        # multi-step loops the scheduler API was designed around
        if program._lr_getter is not None:
            lr = np.asarray(program._lr_getter(), np.float32)
            stacked = dict(stacked)
            stacked["@lr"] = np.broadcast_to(lr, (K,) + lr.shape).copy()

        # shape/dtype probes for the cache key — structs, not slices, so
        # no device work happens before the dispatch
        per_step = {n: jax.ShapeDtypeStruct(tuple(v.shape[1:]),
                                            np.dtype(v.dtype))
                    for n, v in stacked.items()}
        t0 = time.perf_counter()
        with _trace.span("executor.run_steps", uid=program._uid,
                         steps=K, n_fetch=len(fetch_list)):
            compiled = self._compile(
                program, per_step, fetch_list, data_parallel=data_parallel,
                allow_replicated_fallback=allow_replicated_fallback,
                optimize_level=optimize_level, steps=K,
                comm_options=comm_options, plan=plan)
            if _chaos.ACTIVE:  # window-granularity chaos (one fused step)
                _chaos.fire("transient_execute")
                stacked = _chaos.fire("nan_feed", stacked)
            feed_arrs = [self._as_device(stacked[n])
                         for n in compiled.feed_names]
            updated = [scope.find_var(n) for n in compiled.updated]
            frozen = [scope.find_var(n) for n in compiled.frozen]
            updated, frozen = self._align_persistables(compiled, updated,
                                                       frozen)
            self._dispatches += 1
            _M_DISPATCHES.inc()
            fetches, new_persist = compiled.fn(feed_arrs, updated, frozen)
            for name, arr in zip(compiled.persist_out, new_persist):
                scope.set(name, arr)
            out = self._materialize_fetches(fetches, return_numpy,
                                            fetch_async)
        run_ms = (time.perf_counter() - t0) * 1e3
        _M_RUN_MS.observe(run_ms)
        if _journal.ACTIVE is not None:
            _journal.ACTIVE.record_fused_run(
                compiled, out, run_ms, steps=K,
                synced=bool(return_numpy) and not fetch_async)
        return out

    # -- dataset-driven loops (ref: executor.py:1436 train_from_dataset /
    # :1369 infer_from_dataset). The reference hands the dataset to the
    # C++ device-worker thread pool; here the dataset yields batches of
    # the program's exact feed shapes and ONE compiled executable
    # consumes them (thread/debug accepted for source compat).
    def _run_from_dataset(self, program, dataset, scope, fetch_list,
                          fetch_info, print_period, fetch_handler,
                          steps_per_dispatch=None):
        if dataset is None:
            raise ValueError("dataset is required (build one with "
                             "fluid.DatasetFactory().create_dataset())")
        fetch_list = list(fetch_list or [])
        if fetch_info is not None and len(fetch_info) != len(fetch_list):
            raise ValueError(
                f"fetch_info has {len(fetch_info)} entries for "
                f"{len(fetch_list)} fetch_list variables (reference "
                "asserts equal lengths)")
        names = list(fetch_info) if fetch_info else [
            getattr(v, "name", str(v)) for v in fetch_list]
        K = int(steps_per_dispatch or 0)
        if K > 1:
            return self._run_from_dataset_fused(
                program, dataset, scope, fetch_list, names, K,
                print_period, fetch_handler)
        last = None
        for step, feed in enumerate(dataset.iter_batches()):
            last = self.run(program, feed=feed, fetch_list=fetch_list,
                            scope=scope)
            if fetch_list and print_period and \
                    (step + 1) % print_period == 0:
                msg = ", ".join(f"{n}={np.asarray(v).ravel()[:4]}"
                                for n, v in zip(names, last))
                print(f"[step {step + 1}] {msg}")
            if fetch_handler is not None and last is not None:
                fetch_handler.handler(dict(zip(names, last)))
        self._warn_dropped(dataset)
        return last

    @staticmethod
    def _warn_dropped(dataset):
        dropped = getattr(dataset, "last_dropped", 0)
        if dropped:
            import warnings

            warnings.warn(
                f"train/infer_from_dataset dropped the final partial "
                f"batch ({dropped} samples): static programs bake "
                f"concrete feed shapes. Pad the data to a multiple of "
                f"batch_size={dataset.batch_size} to consume every "
                "sample", RuntimeWarning)

    def _run_from_dataset_fused(self, program, dataset, scope, fetch_list,
                                names, K, print_period, fetch_handler):
        """``steps_per_dispatch=K``: drive fused ``run_steps`` windows
        straight from the data pipeline — the reachable-from-the-loader
        form of the fused path (no hand-stacked feeds). The FIRST window
        runs from host batches and compiles the fused entry; every later
        batch then streams through a ``DevicePrefetcher`` seeded with
        that entry's committed feed shardings
        (``executor_feed_shardings``), so host->device transfers overlap
        the previous window's compute and DP batches land pre-sharded. A
        tail of fewer than K batches falls back to per-step ``run()``
        (one extra compile, every sample consumed). ``fetch_handler``
        and the ``print_period`` log fire once per WINDOW on the stacked
        fetches (last microbatch shown), matching run_steps' fetch
        shape; returns the last window's stacked fetches."""
        import itertools

        from ..io_.dataloader import (DevicePrefetcher,
                                      executor_feed_shardings)

        prog, _, _, comm_options, _plan = self._unwrap_program(program)
        accum = int(getattr(comm_options, "accumulate_steps", 1) or 1)
        it = iter(dataset.iter_batches())
        last = None
        step = 0

        def run_window(window):
            nonlocal last, step
            last = self.run_steps(program, feeds=window,
                                  fetch_list=fetch_list, scope=scope)
            step += len(window)
            if fetch_list and print_period and \
                    step // print_period > (step - len(window)) \
                    // print_period:
                msg = ", ".join(
                    f"{n}={np.asarray(v)[-1].ravel()[:4]}"
                    for n, v in zip(names, last))
                print(f"[step {step}] {msg}")
            if fetch_handler is not None and last is not None:
                fetch_handler.handler(dict(zip(names, last)))

        def run_tail(feeds):
            nonlocal last, step
            if accum > 1:
                # the per-step run() rejects accumulation by design, so
                # a ragged tail runs as one SMALLER fused window (extra
                # compile) covering the whole accumulation multiples;
                # the remainder is dropped with a warning — exchanging
                # a partial window would silently change the effective
                # batch
                usable = len(feeds) - len(feeds) % accum
                if usable:
                    last = self.run_steps(program, feeds=feeds[:usable],
                                          fetch_list=fetch_list,
                                          scope=scope)
                    step += usable
                if len(feeds) - usable:
                    import warnings

                    warnings.warn(
                        f"train_from_dataset dropped {len(feeds) - usable}"
                        f" tail batch(es): accumulate_steps={accum} "
                        "exchanges whole N-microbatch windows only",
                        RuntimeWarning)
                return
            for feed in feeds:
                last = self.run(program, feed=feed,
                                fetch_list=fetch_list, scope=scope)
                step += 1

        first = list(itertools.islice(it, K))
        if len(first) < K:
            run_tail(first)
            self._warn_dropped(dataset)
            return last
        run_window(first)
        entry = None
        for key, compiled in self._cache.items():
            if key.program_uid == prog._uid and key.steps == K:
                entry = compiled  # newest matching fused entry wins
        pf = DevicePrefetcher(
            it, shardings=(executor_feed_shardings(entry)
                           if entry is not None else None),
            depth=K + 1)
        try:
            while True:
                window = list(itertools.islice(pf, K))
                if len(window) < K:
                    run_tail(window)
                    break
                run_window(window)
        finally:
            pf.shutdown()
        self._warn_dropped(dataset)
        return last

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None, steps_per_dispatch=None):
        """Run ``dataset`` through ``program`` batch by batch
        (ref executor.py:1436); a ragged final batch is dropped WITH a
        RuntimeWarning (static feed shapes are concrete). Returns the
        last fetch values (the reference returns None; returning the
        fetches is strictly more useful and costs nothing).

        ``steps_per_dispatch=K`` (no reference analog) switches the loop
        onto the fused multi-step path: K dataset batches per compiled
        ``lax.scan`` dispatch (``run_steps``), with batches prefetched
        to the device — pre-sharded for DP programs — while the previous
        window computes. With a comm-efficient DP program
        (``with_data_parallel(comm_options=...)``), an
        ``accumulate_steps=N`` exchange fires once per N microbatches
        INSIDE these windows (K must be a multiple of N)."""
        return self._run_from_dataset(program, dataset, scope, fetch_list,
                                      fetch_info, print_period,
                                      fetch_handler,
                                      steps_per_dispatch=steps_per_dispatch)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """ref executor.py:1369 — identical loop; the program simply has
        no optimizer ops."""
        return self._run_from_dataset(program, dataset, scope, fetch_list,
                                      fetch_info, print_period,
                                      fetch_handler)


class FetchHandler:
    """ref: executor.py:429 — user callback fed periodic var snapshots
    during train_from_dataset (and by FetchHandlerMonitor's polling
    thread). Subclass and override ``handler``."""

    def __init__(self, var_dict=None, period_secs=60):
        assert var_dict is not None
        self.var_dict = var_dict
        self.period_secs = period_secs

    def handler(self, res_dict):
        import sys

        for key, val in res_dict.items():
            if isinstance(val, np.ndarray):
                sys.stdout.write(f"{key}[0]: {val.flat[0]} ")
        sys.stdout.write("\n")

    @staticmethod
    def help():
        print("Subclass FetchHandler({'name': var}) and override "
              "handler(res_dict) to consume periodic var snapshots.")


def _amp_cast_fn(amp_cfg):
    """List-driven dtype policy for program interpretation — the
    one-executable analog of the reference's rewrite_program cast-op
    insertion (fluid/contrib/mixed_precision/fp16_utils.py): white-list
    op inputs go to the half dtype, black-list inputs back to f32, and
    XLA fuses the casts into the ops. Grad ops (``<type>@grad``) follow
    their forward op's list entry, which keeps the vjp's internal
    forward identical to the casted forward (CSE'd by XLA)."""
    if not amp_cfg:
        return None
    wl = amp_cfg["lists"].white_list
    bl = amp_cfg["lists"].black_list
    half = jnp.bfloat16 if amp_cfg["dtype"] == "bfloat16" else jnp.float16

    def amp_cast(op_type, args):
        base = op_type[:-5] if op_type.endswith("@grad") else op_type
        if base in wl:
            dt = half
        elif base in bl:
            dt = jnp.float32
        else:
            return args
        return [a.astype(dt)
                if a is not None and hasattr(a, "dtype")
                and jnp.issubdtype(a.dtype, jnp.floating) else a
                for a in args]

    return amp_cast


def append_amp_backward(amp_decorator, loss, parameter_list=None):
    """AMP backward phase (ref: mixed_precision/decorator.py backward +
    amp_nn.py check_finite_and_unscale): create the persistable scaling
    state, scale the loss, append grad ops, then one op that both
    checks every grad for inf/nan and unscales to f32 master grads.
    Returns (params_grads_on_unscaled, found_inf_var_name)."""
    from .backward import append_backward
    from .program import Operator, default_main_program

    program = default_main_program()
    blk = program.global_block
    scope = global_scope()
    program._amp_cfg = {"dtype": amp_decorator._dtype,
                        "lists": amp_decorator._amp_lists}

    if not blk.has_var("@amp@scale"):
        blk.create_var(name="@amp@scale", shape=(), dtype="float32",
                       persistable=True)
        blk.create_var(name="@amp@good", shape=(), dtype="int32",
                       persistable=True)
        blk.create_var(name="@amp@bad", shape=(), dtype="int32",
                       persistable=True)
        scope.set("@amp@scale",
                  jnp.float32(amp_decorator._init_loss_scaling))
        scope.set("@amp@good", jnp.int32(0))
        scope.set("@amp@bad", jnp.int32(0))

    sname = loss.name + "@SCALED"
    sv = blk.create_var(name=sname, shape=loss.shape,
                        dtype=loss._data.dtype, stop_gradient=False)
    blk.append_op(Operator(
        "amp_scale_loss", lambda l, s: l * s.astype(l.dtype),
        [loss.name, "@amp@scale"], [sname], {}))
    amp_decorator._scaled_loss = sv

    params_grads = append_backward(sv, parameter_list=parameter_list)

    gnames = [g.name for _, g in params_grads]
    fi = "@amp@found_inf"
    if not blk.has_var(fi):
        blk.create_var(name=fi, shape=(), dtype="bool")
    out_names = [n + "@UNSCALED" for n in gnames]
    for (_, g), on in zip(params_grads, out_names):
        blk.create_var(name=on, shape=g.shape, dtype="float32")
    blk.append_op(Operator(
        "amp_check_finite_and_unscale",
        amp_decorator.check_and_unscale_rule,
        ["@amp@scale"] + gnames, [fi] + out_names, {}))
    program.bump()
    return ([(p, blk.var(on)) for (p, _), on in
             zip(params_grads, out_names)], fi)


def append_update_ops(optimizer, params_grads, amp_decorator=None,
                      found_inf_name=None):
    """Append clip + per-param optimizer-update ops (the update phase of
    the reference's Optimizer.minimize / apply_gradients). With an AMP
    decorator, every update is guarded on the found-inf flag and the
    dynamic loss-scaling state is advanced in the same executable."""
    from .program import default_main_program

    program = default_main_program()
    blk = program.global_block
    scope = global_scope()

    if optimizer._grad_clip is not None:
        clip = optimizer._grad_clip
        grads = [g for _, g in params_grads]
        gnames = [g.name for g in grads]

        def clip_fn(*gs):
            pairs = clip([(p, g) for (p, _), g in zip(params_grads, gs)])
            return tuple(g for _, g in pairs)

        out_names = [n + "@CLIPPED" for n in gnames]
        from .program import Operator

        for (p, g), on in zip(params_grads, out_names):
            blk.create_var(name=on, shape=g.shape, dtype=g._data.dtype)
        blk.append_op(Operator("grad_clip", clip_fn, gnames, out_names, {}))
        params_grads = [(p, blk.var(on)) for (p, _), on in
                        zip(params_grads, out_names)]

    # lr enters as a fed scalar so schedulers never retrigger compilation
    if not blk.has_var("@lr"):
        blk.create_var(name="@lr", shape=(), dtype="float32", is_data=True)
    program._lr_getter = optimizer.get_lr

    from .program import Operator

    for p, g in params_grads:
        reg = getattr(p, "regularizer", None) or optimizer._regularization
        state = optimizer._init_state(
            jax.ShapeDtypeStruct(tuple(p._data.shape), p._data.dtype))
        skeys = sorted(state)
        sname = {k: f"{p.name}@OPT@{k}" for k in skeys}
        for k in skeys:
            blk.create_var(name=sname[k], shape=state[k].shape,
                           dtype=state[k].dtype, persistable=True)
            scope.set(sname[k], jnp.asarray(state[k]))

        def upd_fn(pa, ga, lr, *rest, _opt=optimizer, _reg=reg, _skeys=skeys,
                   _pvar=p, _amp=amp_decorator is not None):
            from ..optim.optimizer import AdamW

            if _amp:
                found_inf, svals = rest[0], rest[1:]
            else:
                found_inf, svals = None, rest
            if _reg is not None and not isinstance(_opt, AdamW):
                ga = _reg(pa, ga)
            s = dict(zip(_skeys, svals))
            _opt._current_param = _pvar  # AdamW decay exclusion / lr_ratio
            new_p, new_s = _opt._update(pa, ga.astype(pa.dtype), s, lr)
            if found_inf is not None:
                # inf/nan step: freeze param AND slot state (ref:
                # update_loss_scaling's skip semantics)
                new_p = jnp.where(found_inf, pa, new_p)
                new_s = {k: jnp.where(found_inf, s[k], new_s[k])
                         for k in _skeys}
            return (new_p, *[new_s[k] for k in _skeys])

        amp_in = [found_inf_name] if amp_decorator is not None else []
        blk.append_op(Operator(
            "optimize_" + type(optimizer).__name__.lower(), upd_fn,
            [p.name, g.name, "@lr"] + amp_in + [sname[k] for k in skeys],
            [p.name] + [sname[k] for k in skeys], {}))

    if amp_decorator is not None and amp_decorator._use_dynamic:
        blk.append_op(Operator(
            "amp_update_loss_scaling", amp_decorator.update_scaling_rule,
            ["@amp@scale", "@amp@good", "@amp@bad", found_inf_name],
            ["@amp@scale", "@amp@good", "@amp@bad"], {}))
    program.bump()


def build_optimize_ops(optimizer, loss, parameter_list=None):
    """Append backward + optimizer-update ops to the current program
    (ref: Optimizer.minimize static path in fluid/optimizer.py)."""
    from .backward import append_backward

    params_grads = append_backward(loss, parameter_list=parameter_list)
    append_update_ops(optimizer, params_grads)
    return None, params_grads
