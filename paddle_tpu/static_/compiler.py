"""CompiledProgram / BuildStrategy / ExecutionStrategy / ParallelExecutor.

Ref: python/paddle/fluid/compiler.py + parallel_executor.py. The reference's
ParallelExecutor replicates the graph per GPU and all-reduces grads over
NCCL; on TPU the same thing is a sharding annotation: ``with_data_parallel``
makes the Executor jit the one program over a ``Mesh(('data',))`` with the
feed batch axis sharded and persistables replicated
(``Executor._compile(data_parallel=True)``), so XLA partitions the program
and inserts the ICI grad all-reduces itself (GSPMD).
"""
from __future__ import annotations

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy",
           "ParallelExecutor"]


class BuildStrategy:
    """Knob container for API parity. Every flag below is accepted and
    INERT: the optimization it tuned in the reference's SSA-graph build
    is owned by XLA here (fusion passes, buffer assignment/donation,
    GSPMD all-reduce combining) and happens unconditionally — there is
    nothing to toggle. Setting a flag never changes behavior."""

    def __init__(self):
        self.reduce_strategy = "all_reduce"        # inert: GSPMD decides
        self.gradient_scale_strategy = "coeff_num_device"  # inert
        self.memory_optimize = None        # inert: XLA buffer assignment
        self.enable_inplace = None         # inert: donation covers it
        self.fuse_all_optimizer_ops = True     # inert: one fused step
        self.fuse_all_reduce_ops = True        # inert: XLA combiner
        self.fuse_elewise_add_act_ops = True   # inert: XLA fusion
        self.sync_batch_norm = False  # inert: BN stats ride the program
        self.num_trainers = 1
        self.trainer_id = 0
        # LIVE (the one exception to the inert rule above): a
        # dist.gradcomm.CommOptions here switches the executor onto the
        # explicit comm-efficient gradient exchange — bucketed /
        # accumulated / quantized all-reduce instead of GSPMD's
        # one-all-reduce-per-parameter placement. None keeps the
        # implicit path.
        self.comm_options = None


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.use_thread_pool = False
        # reference ParallelExecutor ERRORS when a batch can't split
        # across devices (parallel_executor.py:28); opt in to run such
        # feeds replicated (correct result, zero DP speedup) instead
        self.allow_replicated_fallback = False


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = ExecutionStrategy()
        self._data_parallel = False
        self._loss_name = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None, comm_options=None):
        """Mark this program for SPMD data parallelism: the Executor will
        shard the feed batch axis over all local devices and keep
        persistables replicated; since it is ONE logical program over the
        global batch, the loss/grads match a single-device run of the same
        global batch (no explicit grad averaging needed).

        ``comm_options`` (a ``dist.gradcomm.CommOptions``, or set on
        ``build_strategy.comm_options``) opts into the comm-efficient
        gradient exchange: per-parameter grad all-reduces coalesced into
        size-bounded flat buckets, optional once-per-N-microbatches
        accumulation inside fused ``run_steps`` windows, and an optional
        int8-quantized exchange with error feedback. The fp32 bucketed
        path is bitwise-stable vs the implicit GSPMD placement on
        power-of-two meshes (see dist/gradcomm.py)."""
        self._data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        if exec_strategy is not None:
            self._exec_strategy = exec_strategy
        if comm_options is not None:
            self._build_strategy.comm_options = comm_options
        # a DP-transformed program compiles as ONE SPMD executable; verify
        # its structure now so graph bugs surface at with_data_parallel
        # (where the reference's SSA-graph build would have failed) rather
        # than deep inside the partitioner
        from .program import Program
        from ..analysis import verify_program

        if isinstance(self._program, Program) and \
                self._program.global_block.ops:
            verify_program(self._program, infer_shapes=False)
        return self


class ParallelExecutor:
    """Data-parallel executor (ref: python/paddle/fluid/parallel_executor.py
    :28). The reference builds per-device SSA graphs + NCCL all-reduce ops;
    here it is a thin front over ``CompiledProgram.with_data_parallel`` —
    the single jitted SPMD program sharded over the local device mesh.
    """

    def __init__(self, use_cuda=None, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from .executor import Executor
        from .program import default_main_program

        program = main_program
        if program is None:
            program = default_main_program()
        self._compiled = CompiledProgram(
            program, build_strategy=build_strategy).with_data_parallel(
                loss_name=loss_name, exec_strategy=exec_strategy,
                share_vars_from=share_vars_from)
        self._exe = Executor()
        self._scope = scope

    @property
    def device_count(self):
        import jax

        return jax.local_device_count()

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._compiled, feed=feed,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)

    def drop_local_exe_scopes(self):
        self._exe._cache.clear()
