"""CompiledProgram / BuildStrategy / ExecutionStrategy.

Ref: python/paddle/fluid/compiler.py + parallel_executor.cc. The reference's
ParallelExecutor replicates the graph per GPU and all-reduces grads over
NCCL; on TPU the same thing is a sharding annotation: the Executor runs the
single fused XLA program, and ``with_data_parallel`` marks the feed batch
axis to be sharded over the device mesh so XLA partitions the program and
inserts ICI all-reduces itself (see dist/ for the Mesh machinery).
"""
from __future__ import annotations

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class BuildStrategy:
    """Knob container for API parity; XLA owns the actual fusion/memory
    decisions that these flags tuned in the reference."""

    def __init__(self):
        self.reduce_strategy = "all_reduce"
        self.gradient_scale_strategy = "coeff_num_device"
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_optimizer_ops = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.use_thread_pool = False


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = ExecutionStrategy()
        self._data_parallel = False
        self._loss_name = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        if exec_strategy is not None:
            self._exec_strategy = exec_strategy
        return self
