"""Static graph: Program / Block / Operator / Variable.

TPU-native analog of the reference's ``python/paddle/fluid/framework.py``
(Program, Block, Operator, Variable) and C++ ``framework/program_desc.*``.

Key design departure: the reference interprets the program op-by-op through
per-op CPU/CUDA kernels; here a recorded Program is *replayed symbolically*
into one jax function which the Executor compiles with ``jax.jit`` into a
single fused XLA executable — whole-program fusion instead of kernel
launches, which is the only way to feed the MXU efficiently.

An Operator stores the pure jax kernel (from the op registry) plus static
attrs, so replay is exact. Shape/dtype inference uses ``jax.eval_shape`` —
the same tracing machinery XLA uses, so inference can never drift from
execution.
"""
from __future__ import annotations

import contextlib
import functools
import itertools

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor
from ..core.dtype import convert_dtype
from ..utils import unique_name

__all__ = [
    "Variable", "Operator", "Block", "Program", "program_guard",
    "default_main_program", "default_startup_program", "data",
    "Scope", "global_scope", "scope_guard", "name_scope",
]


class Variable(Tensor):
    """Symbolic tensor inside a Program (ref: framework.py Variable).

    ``_data`` holds a ShapeDtypeStruct — shape/dtype inspection works
    everywhere a concrete Tensor does, but there is no value until the
    Executor runs the program.
    """

    __slots__ = ("block", "is_parameter", "initializer", "is_data", "_stale",
                 "trainable", "optimize_attr", "regularizer", "need_clip",
                 "dynamic_dims")

    def __init__(self, block, name, shape, dtype, persistable=False,
                 stop_gradient=True, is_data=False):
        shape = tuple(shape)
        # -1/None dims are DYNAMIC: they record as the placeholder 1 (the
        # Executor re-traces per fed shape) but the original mask is kept
        # so the verifier can tell an intentional dynamic dim from a feed
        # that contradicts a declared static dim (analysis PTA009).
        dynamic = tuple(i for i, s in enumerate(shape) if s in (-1, None))
        aval = jax.ShapeDtypeStruct(
            tuple(1 if i in dynamic else int(s)
                  for i, s in enumerate(shape)),
            convert_dtype(dtype))
        super().__init__(aval, stop_gradient=stop_gradient, _internal=True)
        self.dynamic_dims = dynamic
        self.name = name
        self.block = block
        self.persistable = persistable
        self.is_parameter = False
        self.initializer = None
        self.is_data = is_data
        self._stale = False
        self.trainable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return jnp.dtype(self._data.dtype)

    @property
    def ndim(self):
        return len(self._data.shape)

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' has no value outside Executor.run(); "
            "fetch it via fetch_list")

    def set_value(self, value):
        # In-graph assignment (ref: assign op writing to an existing var)
        tracer = dispatch.current_tracer()
        if tracer is not None:
            tracer.record_assign(self, value)
        else:
            raise RuntimeError("set_value on a Variable outside program building")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self._data.dtype}, persistable={self.persistable})")


class Operator:
    """ref: framework.py Operator / OpDesc. Stores the jax kernel + attrs."""

    __slots__ = ("type", "fn", "input_names", "output_names", "attrs", "idx")

    def __init__(self, type, fn, input_names, output_names, attrs):
        self.type = type
        self.fn = fn
        self.input_names = input_names  # list[str|None]
        self.output_names = output_names  # list[str]
        self.attrs = attrs

    def __repr__(self):
        return (f"{{{', '.join(self.output_names)}}} = {self.type}"
                f"({', '.join(str(n) for n in self.input_names)})")


class Block:
    """ref: framework.py Block. Single-block programs cover the jax design
    (control flow is expressed with lax ops inside a kernel, not sub-blocks),
    but the container keeps the reference's shape for API parity."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: dict[str, Variable] = {}
        self.ops: list[Operator] = []

    def var(self, name):
        if name not in self.vars:
            raise ValueError(f"variable {name} not in block {self.idx}")
        return self.vars[name]

    def has_var(self, name):
        return name in self.vars

    def create_var(self, name=None, shape=(), dtype="float32",
                   persistable=False, stop_gradient=True, is_data=False):
        name = name or unique_name.generate("tmp_var")
        v = Variable(self, name, shape, dtype, persistable, stop_gradient,
                     is_data)
        self.vars[name] = v
        return v

    def append_op(self, op):
        self.ops.append(op)

    def all_parameters(self):
        return [v for v in self.vars.values() if v.is_parameter]


class Program:
    """ref: framework.py Program."""

    # monotonic uid: Executor cache keys use this instead of id(program)
    # — a GC'd Program's id() can be recycled by the allocator, which
    # would make a stale cache entry hit for a brand-new Program
    _uid_counter = itertools.count()

    def __init__(self):
        self._uid = next(Program._uid_counter)
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._constants: dict[str, jax.Array] = {}
        self.random_seed = None
        self._version = 0
        self._lr_getter = None  # set by build_optimize_ops for schedulers

    @property
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def all_parameters(self):
        return self.global_block.all_parameters()

    def list_vars(self):
        return list(self.global_block.vars.values())

    def clone(self, for_test=False):
        import copy

        p = Program()
        blk = p.global_block
        for name, v in self.global_block.vars.items():
            nv = Variable(blk, name, v.shape, v._data.dtype, v.persistable,
                          v.stop_gradient, v.is_data)
            nv.is_parameter = v.is_parameter
            nv.initializer = v.initializer
            nv.dynamic_dims = getattr(v, "dynamic_dims", ())
            blk.vars[name] = nv
        for op in self.global_block.ops:
            attrs = dict(op.attrs)
            if for_test and op.type in ("dropout", "dropout_axes", "alpha_dropout"):
                attrs["p"] = 0.0
            blk.append_op(Operator(op.type, op.fn, list(op.input_names),
                                   list(op.output_names), attrs))
        p._constants = dict(self._constants)
        p._lr_getter = self._lr_getter
        # stochastic replay must be reproducible across clones (ref:
        # Program.clone copies the desc, random_seed rides the desc)
        p.random_seed = self.random_seed
        return p

    def __str__(self):
        lines = [f"Program(ops={len(self.global_block.ops)})"]
        for v in self.global_block.vars.values():
            tag = "param" if v.is_parameter else ("data" if v.is_data else "tmp")
            lines.append(f"  var {v.name}: {v.shape} {v._data.dtype} [{tag}]")
        for op in self.global_block.ops:
            lines.append(f"  {op!r}")
        return "\n".join(lines)

    def to_string(self, throw_on_error=False, with_details=False):
        return str(self)

    def bump(self):
        self._version += 1


# -- defaults / guards ------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    old_main, old_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = old_main, old_startup


@contextlib.contextmanager
def name_scope(prefix=None):
    with unique_name.guard(prefix + "/" if prefix else None):
        yield


# -- scope ------------------------------------------------------------------


class Scope:
    """ref: framework/scope.h — name → concrete array storage."""

    def __init__(self):
        self._vars: dict[str, jax.Array] = {}

    def var(self, name):
        return self._vars.get(name)

    def find_var(self, name):
        return self._vars.get(name)

    def set(self, name, value):
        self._vars[name] = value

    def drop_kids(self):
        self._vars.clear()


_global_scope = Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    old, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = old


# -- data placeholder -------------------------------------------------------


def data(name, shape, dtype="float32", lod_level=0):
    """ref: fluid.data / static.data. ``-1``/``None`` dims pass through to
    the Variable, which records them on ``dynamic_dims`` (placeholder 1 in
    the aval) so the verifier can distinguish them from static dims."""
    prog = default_main_program()
    v = prog.global_block.create_var(name=name, shape=shape, dtype=dtype,
                                     is_data=True, stop_gradient=True)
    return v


# -- the tracer -------------------------------------------------------------


class ProgramTracer:
    """Records dispatch.apply calls into a Program (ref: imperative tracer
    flipped: here recording happens at build time, execution at run time)."""

    def __init__(self, program):
        self.program = program

    def _var_of(self, x):
        blk = self.program.current_block()
        if isinstance(x, Variable):
            return x.name
        if isinstance(x, Tensor):
            # concrete constant captured into the program
            name = unique_name.generate("const")
            v = blk.create_var(name=name, shape=x.shape, dtype=x._data.dtype)
            self.program._constants[name] = x._data
            return name
        if x is None:
            return None
        # raw python scalar / ndarray
        arr = jnp.asarray(x)
        name = unique_name.generate("const")
        blk.create_var(name=name, shape=arr.shape, dtype=arr.dtype)
        self.program._constants[name] = arr
        return name

    def trace_op(self, name, fn, args, attrs):
        blk = self.program.current_block()
        in_names = [self._var_of(a) for a in args]
        specs = []
        for a, n in zip(args, in_names):
            if n is None:
                specs.append(None)
            elif isinstance(a, Variable):
                specs.append(jax.ShapeDtypeStruct(tuple(a._data.shape),
                                                  a._data.dtype))
            else:
                c = self.program._constants[n]
                specs.append(jax.ShapeDtypeStruct(c.shape, c.dtype))
        out_shape = jax.eval_shape(functools.partial(fn, **attrs), *specs)
        multi = isinstance(out_shape, tuple)
        outs = out_shape if multi else (out_shape,)
        out_vars = []
        any_grad = any(isinstance(a, Tensor) and not a.stop_gradient
                       for a in args)
        for o in outs:
            v = blk.create_var(name=unique_name.generate(name + ".out"),
                               shape=o.shape, dtype=o.dtype,
                               stop_gradient=not any_grad)
            out_vars.append(v)
        blk.append_op(Operator(name, fn, in_names,
                               [v.name for v in out_vars], attrs))
        self.program.bump()
        return tuple(out_vars) if multi else out_vars[0]

    def record_assign(self, target, value):
        from ..ops._base import OP_REGISTRY, register

        if "assign_to" not in OP_REGISTRY:
            register("assign_to")(lambda x: x)
        blk = self.program.current_block()
        vname = self._var_of(value)
        blk.append_op(Operator("assign_to", OP_REGISTRY["assign_to"], [vname],
                               [target.name], {}))
        self.program.bump()
