"""paddle_tpu.static — static (Program/Executor) mode.

Mirrors ``paddle.static`` / fluid's graph mode (ref:
python/paddle/fluid/{framework,executor,compiler,backward}.py) on top of the
op-dispatch tracer: while static mode is on, every framework op records into
the default Program instead of executing; ``Executor.run`` compiles the
recorded graph to one XLA executable.
"""
from .program import (  # noqa: F401
    Variable, Operator, Block, Program, program_guard, default_main_program,
    default_startup_program, data, Scope, global_scope, scope_guard,
    name_scope, ProgramTracer,
)
from .backward import append_backward, gradients  # noqa: F401
from .executor import Executor, build_optimize_ops  # noqa: F401
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa: F401

import contextlib as _ctx

from ..core import dispatch as _dispatch

_static_ctx = None


def enable_static():
    """Switch the process into static-graph mode (ref: paddle.enable_static)."""
    global _static_ctx
    if _static_ctx is not None:
        return
    tracer = ProgramTracer(None)  # program resolved per-op via default
    # bind tracer to the *current default* program dynamically:
    tracer.__class__ = _DynamicTracer
    _static_ctx = _dispatch.register_tracer(tracer)
    _static_ctx.__enter__()


def disable_static():
    global _static_ctx
    if _static_ctx is not None:
        _static_ctx.__exit__(None, None, None)
        _static_ctx = None


def in_static_mode():
    return _static_ctx is not None


class _DynamicTracer(ProgramTracer):
    """Tracer whose target program is whatever program_guard made current."""

    @property
    def program(self):
        from .program import default_main_program

        return default_main_program()

    @program.setter
    def program(self, v):
        pass
