"""paddle_tpu.static — static (Program/Executor) mode.

Mirrors ``paddle.static`` / fluid's graph mode (ref:
python/paddle/fluid/{framework,executor,compiler,backward}.py) on top of the
op-dispatch tracer: while static mode is on, every framework op records into
the default Program instead of executing; ``Executor.run`` compiles the
recorded graph to one XLA executable.
"""
from .program import (  # noqa: F401
    Variable, Operator, Block, Program, program_guard, default_main_program,
    default_startup_program, data, Scope, global_scope, scope_guard,
    name_scope, ProgramTracer,
)
from .backward import append_backward, gradients  # noqa: F401
from .executor import CacheKey, Executor, build_optimize_ops  # noqa: F401
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa: F401

import contextlib as _ctx

from ..core import dispatch as _dispatch

_static_ctx = None


def enable_static():
    """Switch the process into static-graph mode (ref: paddle.enable_static)."""
    global _static_ctx
    if _static_ctx is not None:
        return
    tracer = ProgramTracer(None)  # program resolved per-op via default
    # bind tracer to the *current default* program dynamically:
    tracer.__class__ = _DynamicTracer
    _static_ctx = _dispatch.register_tracer(tracer)
    _static_ctx.__enter__()


def disable_static():
    global _static_ctx
    if _static_ctx is not None:
        _static_ctx.__exit__(None, None, None)
        _static_ctx = None


def in_static_mode():
    return _static_ctx is not None


class _DynamicTracer(ProgramTracer):
    """Tracer whose target program is whatever program_guard made current."""

    @property
    def program(self):
        from .program import default_main_program

        return default_main_program()

    @program.setter
    def program(self, v):
        pass


# 2.x paddle.static surface: the op-level builders live in fluid.layers;
# expose the common ones here so static-mode scripts written either way
# resolve (paddle.static.nn.fc == fluid.layers.fc, etc.)
def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """ref: paddle.static.create_parameter (impl: fluid.layers).
    ``is_bias`` forwards: bias parameters initialize to zero."""
    from ..fluid.layers import create_parameter as _cp

    return _cp(shape, dtype=dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def _nn_namespace():
    import types

    from ..fluid import layers as _layers

    ns = types.SimpleNamespace()
    for name in ("fc", "conv2d", "conv3d", "batch_norm", "layer_norm",
                 "embedding", "sequence_conv", "conv2d_transpose",
                 "deformable_conv", "group_norm", "instance_norm",
                 "nce", "prelu", "row_conv", "spectral_norm",
                 "multi_box_head"):
        if hasattr(_layers, name):
            setattr(ns, name, getattr(_layers, name))
    return ns


nn = _nn_namespace()
