"""Static-graph autodiff: append_backward / gradients.

TPU-native analog of ``python/paddle/fluid/backward.py``: instead of
registered per-op grad kernels (ops like ``elementwise_add_grad``), each
forward Operator's grad op wraps ``jax.vjp`` of the SAME pure kernel — so a
grad op can never disagree with its forward, and XLA fuses the pair.
Grad vars follow the reference naming: ``<var>@GRAD``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils import unique_name
from .program import Operator, Variable, default_main_program

__all__ = ["append_backward", "gradients", "grad_name"]


def grad_name(name):
    return name + "@GRAD"


def _make_grad_fn(fwd_fn, attrs, n_inputs, multi_out):
    """Build grad kernel: (inputs..., out_grads...) -> input grads tuple."""

    def gfn(*args):
        xs = args[:n_inputs]
        gys = args[n_inputs:]
        f = functools.partial(fwd_fn, **attrs)
        _, vjp = jax.vjp(f, *xs)
        gxs = vjp(tuple(gys) if multi_out else gys[0])
        return tuple(gxs) if len(gxs) > 1 else gxs[0]

    return gfn


def _ensure_grad_var(block, src_var, gname):
    if block.has_var(gname):
        return block.var(gname)
    v = block.create_var(name=gname, shape=src_var.shape,
                         dtype=src_var._data.dtype)
    return v


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, program=None):
    """Append grad ops computing d loss / d params (ref: backward.py).

    Returns list of (param_var, grad_var).
    """
    program = program or default_main_program()
    block = program.global_block
    no_grad_set = set(no_grad_set or ())

    # differentiating a malformed forward program would crash mid-surgery
    # (or worse, append wrong grads): check structure up front, with coded
    # diagnostics instead of a KeyError deep in the reverse walk
    from ..analysis import verify_program

    verify_program(program, infer_shapes=False)

    # seed: d loss/d loss = 1
    gname = grad_name(loss.name)
    seed_var = _ensure_grad_var(block, loss, gname)
    block.append_op(Operator(
        "fill_ones_like",
        lambda x: jnp.ones(x.shape, x.dtype), [loss.name], [gname], {}))

    # has-grad tracking: which vars currently have a grad var appended
    have_grad = {loss.name}

    fwd_ops = [op for op in block.ops if not op.type.endswith("@grad")
               and op.type not in ("fill_ones_like",)]
    for op in reversed(fwd_ops):
        out_with_grad = [n for n in op.output_names if n in have_grad]
        if not out_with_grad:
            continue
        if op.type == "assign_to":
            # pass-through: grad of target flows to source
            src = op.input_names[0]
            tgt = op.output_names[0]
            if src is not None and tgt in have_grad:
                g_src = _ensure_grad_var(block, block.var(src), grad_name(src))
                block.append_op(Operator(
                    "assign_to@grad", lambda g: g,
                    [grad_name(tgt)], [g_src.name], {}))
                have_grad.add(src)
            continue
        n_in = len(op.input_names)
        multi_out = len(op.output_names) > 1
        gfn = _make_grad_fn(op.fn, op.attrs, n_in, multi_out)
        # inputs of grad op: fwd inputs + grads of all outputs (zeros if
        # an output has no grad yet — realized via fill_zeros ops)
        g_out_names = []
        for oname in op.output_names:
            go = grad_name(oname)
            if oname not in have_grad:
                ov = block.var(oname)
                _ensure_grad_var(block, ov, go)
                block.append_op(Operator(
                    "fill_zeros_like",
                    lambda x: jnp.zeros(x.shape, x.dtype), [oname], [go], {}))
            g_out_names.append(go)
        grad_outputs = []
        for iname in op.input_names:
            if iname is None or iname in no_grad_set:
                grad_outputs.append(None)
                continue
            iv = block.var(iname)
            if iv.is_data or (iv.stop_gradient and not iv.is_parameter):
                grad_outputs.append(None)
                continue
            grad_outputs.append(iname)

        if not any(g is not None for g in grad_outputs):
            continue

        # each grad-op invocation produces fresh partials; accumulate into
        # the canonical @GRAD var with add ops (ref: sum_op insertion).
        # An input appearing twice in ONE op (e.g. multiply(x, x)) must get
        # two distinct partial names or the second write clobbers the first.
        partial_names = []
        seen_this_op: set[str] = set()
        for iname in grad_outputs:
            if iname is None:
                partial_names.append(unique_name.generate("_gsink"))
            elif iname in have_grad or iname in seen_this_op:
                partial_names.append(unique_name.generate(grad_name(iname) + ".p"))
            else:
                partial_names.append(grad_name(iname))
                seen_this_op.add(iname)
        for iname, pname in zip(grad_outputs, partial_names):
            ref = block.var(iname) if iname is not None else None
            if ref is not None:
                _ensure_grad_var(block, ref, pname)
            else:
                # dummy sink var shaped like the op input position; shape
                # inferred lazily by executor (scalar placeholder)
                block.create_var(name=pname, shape=(), dtype="float32")
        block.append_op(Operator(
            op.type + "@grad", gfn,
            list(op.input_names) + g_out_names, partial_names, {}))
        for iname, pname in zip(grad_outputs, partial_names):
            if iname is None:
                continue
            gn = grad_name(iname)
            if iname in have_grad and pname != gn:
                block.append_op(Operator(
                    "grad_accumulate", lambda a, b: a + b,
                    [gn, pname], [gn], {}))
            have_grad.add(iname)

    params = parameter_list if parameter_list is not None else [
        v for v in block.vars.values() if v.is_parameter]
    out = []
    for p in params:
        if isinstance(p, str):
            p = block.var(p)
        gn = grad_name(p.name)
        if block.has_var(gn) and p.name in have_grad:
            out.append((p, block.var(gn)))
    program.bump()
    # autodiff surgery is the classic source of malformed graphs (dangling
    # grad inputs, clobbered accumulators): catch it at append time with
    # the structural verifier, not as an XLA trace error at run time. The
    # Executor re-runs the FULL verifier (incl. shape re-inference) at
    # compile, so the cheap structural pass suffices here.
    from ..analysis import verify_program

    verify_program(program, infer_shapes=False)
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """ref: fluid.gradients — grads of targets wrt arbitrary inputs."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    loss = targets[0]
    for t in targets[1:]:
        # gradient of a list of targets is the gradient of their sum
        from ..ops.math import add

        loss = add(loss, t)
    pg = append_backward(loss, parameter_list=list(inputs),
                         no_grad_set=no_grad_set)
    got = {p.name: g for p, g in pg}
    block = default_main_program().global_block
    out = []
    for i in inputs:
        gn = grad_name(i.name)
        out.append(block.var(gn) if block.has_var(gn) else got.get(i.name))
    return out
