"""High-level training API: ``Model.fit / evaluate / predict``.

Ref (capability target): the reference's high-level-api book suite
(python/paddle/fluid/tests/book/high-level-api/ — Trainer/Inferencer
abstractions) and the 2.0-era ``paddle.Model`` hapi surface.

TPU-native: ``fit`` drives the fused ``TrainStep`` (fwd+bwd+update in one
donated XLA executable), eval/predict run through a shape-cached jitted
forward, and data comes from ``io_.DataLoader`` so host batching overlaps
device compute.
"""
from __future__ import annotations

import inspect
import os
import time

import numpy as np

from .core.tensor import Tensor
from .framework.jit import TrainStep, StaticFunction
from .io_.dataloader import DataLoader
from .io_.dataset import Dataset

__all__ = ["Model", "Callback", "EarlyStopping"]


class Callback:
    """Hook points for fit (ref: hapi callbacks)."""

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_end(self, step, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class EarlyStopping(Callback):
    """Stop fit when a monitored metric stops improving."""

    def __init__(self, monitor="loss", patience=3, mode="min", min_delta=0.0):
        self.monitor = monitor
        self.patience = patience
        self.sign = -1.0 if mode == "min" else 1.0
        self.min_delta = min_delta
        self.best = -np.inf
        self.wait = 0
        self.stop_training = False

    def on_eval_end(self, logs=None):
        cur = self.sign * float((logs or {}).get(self.monitor, np.nan))
        if cur > self.best + self.min_delta:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True


def _as_loader(data, batch_size, shuffle):
    if data is None or isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
    raise TypeError(f"expected Dataset or DataLoader, got {type(data)}")


def _num_forward_inputs(network):
    sig = inspect.signature(network.forward)
    n = 0
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD) \
                and p.default is p.empty:
            n += 1
    return max(n, 1)


class Model:
    """``Model(network).prepare(opt, loss, metrics)`` then ``fit``.

    >>> m = Model(LeNet())
    >>> m.prepare(optim.Adam(1e-3, parameters=m.parameters()),
    ...           F.cross_entropy, metrics.Accuracy())
    >>> m.fit(train_ds, epochs=2, batch_size=64)
    >>> m.evaluate(test_ds)["acc"]
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._n_in = len(inputs) if inputs is not None \
            else _num_forward_inputs(network)
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self._train_step = None
        self._fwd = StaticFunction(lambda net, *xs: net(*xs), model=network)
        self.stop_training = False

    def parameters(self):
        return self.network.parameters()

    # -- setup --------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]
        if optimizer is not None and loss is not None:
            self._train_step = TrainStep(self.network, optimizer,
                                         self._loss_fn())
        return self

    def _loss_fn(self):
        n_in, loss = self._n_in, self._loss

        def fn(net, *batch):
            xs, ys = batch[:n_in], batch[n_in:]
            out = net(*xs)
            if isinstance(out, (list, tuple)):
                return loss(*out, *ys)
            return loss(out, *ys)

        return fn

    # -- single-batch ops (ref: hapi Model.train_batch etc.) ---------------
    def train_batch(self, inputs, labels=None):
        if self._train_step is None:
            raise RuntimeError("call prepare(optimizer, loss) before fit")
        batch = list(inputs) + list(labels or [])
        self.network.train()
        loss = self._train_step(*batch)
        return float(np.asarray(loss._data))

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        out = self._fwd(*inputs)
        logs = {}
        if self._loss is not None and labels:
            pred = out if not isinstance(out, (list, tuple)) else out[0]
            logs["loss"] = float(np.asarray(
                self._loss(pred, *labels)._data))
        for m in self._metrics:
            pred = out if not isinstance(out, (list, tuple)) else out[0]
            m.update(*m.compute(pred, *labels)) if labels else None
        return out, logs

    def predict_batch(self, inputs):
        self.network.eval()
        out = self._fwd(*inputs)
        if isinstance(out, (list, tuple)):
            return [np.asarray(o._data) for o in out]
        return np.asarray(out._data)

    # -- loops --------------------------------------------------------------
    def fit(self, train_data, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            shuffle=True, verbose=1, callbacks=None):
        loader = _as_loader(train_data, batch_size, shuffle)
        eval_loader = _as_loader(eval_data, batch_size, False)
        callbacks = list(callbacks or [])
        history = {"loss": []}
        self.stop_training = False
        for epoch in range(epochs):
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            t0 = time.time()
            losses = []
            for step, batch in enumerate(loader):
                loss = self.train_batch(batch[:self._n_in],
                                        batch[self._n_in:])
                losses.append(loss)
                logs = {"loss": loss, "epoch": epoch, "step": step}
                for cb in callbacks:
                    cb.on_batch_end(step, logs)
                if verbose and log_freq and step % log_freq == 0:
                    print(f"epoch {epoch} step {step}: loss {loss:.4f}")
            epoch_logs = {"loss": float(np.mean(losses)) if losses else None,
                          "time": time.time() - t0}
            history["loss"].append(epoch_logs["loss"])
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                epoch_logs.update({f"eval_{k}": v
                                   for k, v in eval_logs.items()})
                for cb in callbacks:
                    cb.on_eval_end(eval_logs)
            for cb in callbacks:
                cb.on_epoch_end(epoch, epoch_logs)
            if verbose:
                print(f"epoch {epoch}: " + ", ".join(
                    f"{k} {v:.4f}" if isinstance(v, float) else f"{k} {v}"
                    for k, v in epoch_logs.items()))
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, f"epoch_{epoch}"))
            if self.stop_training or any(
                    getattr(cb, "stop_training", False) for cb in callbacks):
                break
        return history

    def evaluate(self, eval_data, batch_size=1, verbose=1):
        loader = _as_loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            xs, ys = batch[:self._n_in], batch[self._n_in:]
            _, logs = self.eval_batch(xs, ys)
            if "loss" in logs:
                losses.append(logs["loss"])
        out = {}
        if losses:
            out["loss"] = float(np.mean(losses))
        for m in self._metrics:
            name = m.name() if callable(m.name) else m.name
            out[name] = m.accumulate()
        if verbose:
            print("eval: " + ", ".join(f"{k} {v}" for k, v in out.items()))
        return out

    def predict(self, test_data, batch_size=1):
        loader = _as_loader(test_data, batch_size, False)
        outs = []
        for batch in loader:
            outs.append(self.predict_batch(batch[:self._n_in]))
        return outs

    # -- persistence --------------------------------------------------------
    def save(self, path):
        from .framework import io

        io.save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            io.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path):
        from .framework import io

        self.network.set_state_dict(io.load(path + ".pdparams"))
        if self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(io.load(path + ".pdopt"))

    def summary(self):
        """Param-count summary (ref: hapi Model.summary)."""
        rows, total = [], 0
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if len(p.shape) else 1
            total += n
            rows.append((name, tuple(p.shape), n))
        lines = [f"{n:<48} {str(s):<20} {c:>12,}" for n, s, c in rows]
        lines.append(f"Total params: {total:,}")
        text = "\n".join(lines)
        print(text)
        return {"total_params": total, "layers": rows}
