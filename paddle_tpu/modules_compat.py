"""2.x-era top-level module names as real importable modules.

ref: python/paddle/__init__.py binds `paddle.tensor`, `paddle.io`,
`paddle.metric`, `paddle.optimizer`, `paddle.distributed`,
`paddle.fleet`, `paddle.imperative`, `paddle.regularizer` as PACKAGES —
reference scripts spell `import paddle.distributed.launch`,
`python -m paddle.distributed.launch train.py`, `from paddle.tensor
import creation`. paddle_tpu already exposes all of them as top-level
*attributes*; this module additionally registers the dotted names in
``sys.modules`` and installs a meta-path finder so EVERY submodule
reachable through an alias resolves to the same module object as the
real spelling. Without the finder, the default PathFinder would locate
alias submodules through the aliased package's ``__path__`` and
re-execute the source under the alias name — a duplicate module with
independent state (e.g. a second ``dist/env.py`` whose mesh globals
the real collectives never see).
"""
from __future__ import annotations

import importlib
import importlib.util
import sys

__all__ = ["install"]

# alias (under paddle_tpu.) -> implementation home (relative import)
_ALIASES = {
    "tensor": ".ops",                 # ref: python/paddle/tensor/__init__.py
    "tensor.creation": ".ops.creation",
    "tensor.math": ".ops.math",
    "tensor.linalg": ".ops.linalg",
    "tensor.manipulation": ".ops.manipulation",
    "tensor.logic": ".ops.compare",   # ref tensor/logic.py: equal/allclose
    "tensor.random": ".ops.random_ops",
    "tensor.search": ".ops.manipulation",  # ref search.py: where/sort/index_sample
    "io": ".io_",                     # ref: python/paddle/io (DataLoader home in 2.x)
    "metric": ".metrics",
    "optimizer": ".optim",
    "regularizer": ".optim.regularizer",
    "distributed": ".dist",           # ref: python/paddle/distributed/launch.py
    # paddle.fleet -> the auto-parallel package (PR 10), which re-exports
    # the whole pre-plan dist.fleet surface and PEP-562-forwards the
    # singleton, so old fleet.* call sites resolve unchanged
    "fleet": ".fleet",
    "imperative": ".fluid.dygraph",   # ref: python/paddle/imperative (dygraph alias)
    "static": ".static_",
    "device": ".core.device",
}


class _AliasLoader:
    """Loader that hands back the REAL module object (shared identity)
    for plain imports, while still exposing get_code/get_source so
    ``python -m`` (runpy) can exec the real source as __main__."""

    def __init__(self, real_name):
        self._real = real_name

    def create_module(self, spec):
        mod = importlib.import_module(self._real)
        # module_from_spec overwrites these with the alias spelling;
        # remember the real values so exec_module can restore them
        # (otherwise importlib.reload of the real module would route
        # through this loader's no-op exec and silently do nothing)
        self._saved = {k: getattr(mod, k, None)
                       for k in ("__spec__", "__loader__", "__package__",
                                 "__name__")}
        return mod

    def exec_module(self, module):  # already executed under its real name
        for k, v in self._saved.items():
            if v is not None:
                setattr(module, k, v)

    def _real_spec(self):
        return importlib.util.find_spec(self._real)

    def get_code(self, fullname):
        return self._real_spec().loader.get_code(self._real)

    def get_source(self, fullname):
        return self._real_spec().loader.get_source(self._real)

    def is_package(self, fullname):
        return self._real_spec().submodule_search_locations is not None


class _AliasFinder:
    """Meta-path finder mapping ``<pkg>.<alias>[.rest]`` onto the real
    dotted name. Must sit ahead of PathFinder, which would otherwise
    re-load alias submodules through the aliased package's __path__."""

    def __init__(self, pkg_name):
        self._pkg_prefix = pkg_name + "."
        self._map = {f"{pkg_name}.{a}": f"{pkg_name}{t}"
                     for a, t in _ALIASES.items()}
        # longest alias prefix wins (tensor.creation over tensor)
        self._prefixes = sorted(self._map, key=len, reverse=True)

    def _real_name(self, fullname):
        # this finder sits at meta_path[0] and sees EVERY import in the
        # process — bail on the common case with one str compare
        if not fullname.startswith(self._pkg_prefix):
            return None
        if fullname in self._map:
            return self._map[fullname]
        for alias in self._prefixes:
            if fullname.startswith(alias + "."):
                return self._map[alias] + fullname[len(alias):]
        return None

    def find_spec(self, fullname, path=None, target=None):
        real = self._real_name(fullname)
        if real is None:
            return None
        try:
            real_spec = importlib.util.find_spec(real)
        except (ImportError, ValueError):
            return None
        if real_spec is None:
            return None
        return importlib.util.spec_from_loader(
            fullname, _AliasLoader(real),
            is_package=real_spec.submodule_search_locations is not None)


def install(pkg_name):
    """Register the dotted names, bind the single-segment aliases as
    top-level package attributes (the ONLY place they're bound — keeps
    the alias table in one file), and mount the finder."""
    pkg = sys.modules[pkg_name]
    for alias, target in _ALIASES.items():
        mod = importlib.import_module(target, pkg_name)
        sys.modules[f"{pkg_name}.{alias}"] = mod
        if "." not in alias:
            setattr(pkg, alias, mod)
            if alias not in pkg.__all__:
                pkg.__all__.append(alias)
    # reload-safe: never stack a second finder for the same package
    # (type identity won't survive a reload, so match by name+prefix)
    for f in sys.meta_path:
        if (type(f).__name__ == "_AliasFinder"
                and getattr(f, "_pkg_prefix", None) == pkg_name + "."):
            return
    sys.meta_path.insert(0, _AliasFinder(pkg_name))
