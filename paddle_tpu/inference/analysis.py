"""Fluid-era deploy API: AnalysisConfig / create_paddle_predictor /
zero-copy tensors (ref: paddle/fluid/pybind/inference_api.cc — the
`from paddle.fluid.core import AnalysisConfig, create_paddle_predictor`
entry every 1.x deployment script uses; C++ AnalysisPredictor in
paddle/fluid/inference/api/analysis_predictor.cc).

The graph-optimization knobs the reference exposes (IR passes, MKLDNN,
TensorRT, memory optim) are owned by XLA here, so the switches are
accepted and recorded; the execution engine is inference.Predictor
(shape-bucketed jit). Zero-copy semantics hold in spirit: copy_from_cpu
stages the array once and the compiled executable consumes it directly.
"""
from __future__ import annotations

import os

import numpy as np

from .predictor import Config, Predictor

__all__ = ["AnalysisConfig", "AnalysisPredictor", "ZeroCopyTensor",
           "PaddleTensor", "create_paddle_predictor"]


def _resolve_prefix(model_arg):
    """Accept a save_inference_model prefix, a <prefix>.pdmodel path, or
    a directory containing exactly one bundle."""
    m = str(model_arg)
    if m.endswith(".pdmodel"):
        return m[: -len(".pdmodel")]
    if os.path.isdir(m):
        bundles = [f for f in os.listdir(m) if f.endswith(".pdmodel")]
        if len(bundles) == 1:
            return os.path.join(m, bundles[0][: -len(".pdmodel")])
        if not bundles:
            raise ValueError(f"no .pdmodel bundle under {m}")
        raise ValueError(f"multiple bundles under {m}: {bundles}; pass "
                         "the prefix explicitly")
    return m


class AnalysisConfig:
    """ref: inference_api.cc AnalysisConfig bindings."""

    class Precision:
        Float32 = "float32"
        Half = "float16"
        Int8 = "int8"

    def __init__(self, model_dir=None, params_file=None):
        self._model_arg = model_dir
        self._params_file = params_file
        self._use_gpu = False
        self._use_feed_fetch_ops = True
        self._specify_input_names = False
        self._ir_optim = True
        self._memory_optim = False
        self._cpu_threads = 1
        self._glog_info = True
        self._mkldnn = False

    # -- model location -----------------------------------------------------
    def set_model(self, model_dir, params_file=None):
        self._model_arg = model_dir
        self._params_file = params_file

    def model_dir(self):
        return str(self._model_arg)

    def prog_file(self):
        return _resolve_prefix(self._model_arg) + ".pdmodel"

    def params_file(self):
        return self._params_file or \
            _resolve_prefix(self._model_arg) + ".pdiparams"

    # -- device / engine knobs (XLA owns the engine; recorded) --------------
    def disable_gpu(self):
        self._use_gpu = False

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # the TPU/XLA runtime decides placement; recorded for parity
        self._use_gpu = True

    def use_gpu(self):
        return self._use_gpu

    def gpu_device_id(self):
        return 0

    def switch_use_feed_fetch_ops(self, x=True):
        self._use_feed_fetch_ops = bool(x)

    def switch_specify_input_names(self, x=True):
        self._specify_input_names = bool(x)

    def switch_ir_optim(self, x=True):
        self._ir_optim = bool(x)  # XLA always optimizes; recorded

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self):
        self._memory_optim = True  # XLA buffer assignment owns this

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = int(n)

    def cpu_math_library_num_threads(self):
        return self._cpu_threads

    def disable_glog_info(self):
        self._glog_info = False

    def glog_info_disabled(self):
        return not self._glog_info

    def enable_mkldnn(self):
        self._mkldnn = True  # x86-only in the reference; XLA here

    def mkldnn_enabled(self):
        return self._mkldnn

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT is a CUDA engine; the XLA executable IS the "
            "optimized engine here (SURVEY §4b rationale)")

    def to_native_config(self):
        return self


class ZeroCopyTensor:
    """ref: zero-copy input/output tensors — stage once, no feed op."""

    def __init__(self, name, predictor, is_input):
        self.name = name
        self._pred = predictor
        self._is_input = is_input
        self._shape = None

    def reshape(self, shape):
        self._shape = tuple(int(s) for s in shape)

    def copy_from_cpu(self, arr):
        if not self._is_input:
            raise ValueError(f"{self.name} is an output tensor")
        arr = np.ascontiguousarray(arr)
        if self._shape is not None:
            arr = arr.reshape(self._shape)
        self._pred._staged[self.name] = arr

    def copy_to_cpu(self):
        if self._is_input:
            return np.asarray(self._pred._staged[self.name])
        outs = self._pred._last_outputs
        if outs is None:
            raise RuntimeError("call zero_copy_run() first")
        return np.asarray(outs[self.name])

    def shape(self):
        if self._is_input:
            a = self._pred._staged.get(self.name)
            return list(a.shape) if a is not None else list(
                self._shape or [])
        return list(np.asarray(self.copy_to_cpu()).shape)


class PaddleTensor:
    """ref: PaddleTensor — the feed-fetch-ops run() data holder."""

    def __init__(self, data=None, name=None, lod=None):
        arr = np.asarray(data) if data is not None else None
        self.name = name
        self.data = arr
        self.shape = list(arr.shape) if arr is not None else []
        self.lod = lod or []

    def as_ndarray(self):
        return self.data


class AnalysisPredictor:
    """ref: analysis_predictor.cc — served by inference.Predictor."""

    def __init__(self, config):
        prefix = _resolve_prefix(config.model_dir())
        pcfg = Config(prefix)
        self._config = config
        self._pred = Predictor(pcfg)
        self._staged = {}
        self._last_outputs = None

    def get_input_names(self):
        return self._pred.get_input_names()

    def get_output_names(self):
        return self._pred.get_output_names()

    def get_input_tensor(self, name):
        if name not in self.get_input_names():
            raise KeyError(f"{name} not an input "
                           f"(inputs: {self.get_input_names()})")
        return ZeroCopyTensor(name, self, is_input=True)

    def get_output_tensor(self, name):
        if name not in self.get_output_names():
            raise KeyError(f"{name} not an output "
                           f"(outputs: {self.get_output_names()})")
        return ZeroCopyTensor(name, self, is_input=False)

    def zero_copy_run(self):
        missing = [n for n in self.get_input_names()
                   if n not in self._staged]
        if missing:
            raise ValueError(f"inputs not staged: {missing}")
        outs = self._pred.run(dict(self._staged))
        self._last_outputs = dict(zip(self.get_output_names(), outs))
        return True

    def run(self, inputs):
        """Feed-fetch-ops path: list of PaddleTensor in input order (or
        by .name) -> list of PaddleTensor."""
        names = self.get_input_names()
        feed = {}
        for i, t in enumerate(inputs):
            feed[t.name or names[i]] = t.data
        outs = self._pred.run(feed)
        return [PaddleTensor(o, name=n)
                for n, o in zip(self.get_output_names(), outs)]


def create_paddle_predictor(config, *a, **k):
    """ref: inference_api.cc create_paddle_predictor."""
    return AnalysisPredictor(config)
