"""Predictor: compiled inference over a saved program.

Ref (capability target): paddle/fluid/inference/api/analysis_predictor.h:82
(AnalysisPredictor::Run), paddle_inference_api.h (Config / PaddlePredictor).

TPU-native design: the loaded program is replayed into a single pure
function ``feeds -> fetches`` and compiled with ``jax.jit`` once per input
shape signature. Weights stay resident on device between calls (passed as
jit arguments, never donated, so many Predictors and repeated calls share
one device copy). Optional batch bucketing pads the leading dim to a small
set of sizes so a serving workload with ragged batch sizes compiles a
handful of executables instead of one per batch size — the analog of the
reference's shape-optimized subgraphs.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """ref: paddle_infer.Config (model path + tuning knobs)."""

    def __init__(self, model_prefix=None):
        self.model_prefix = model_prefix
        self.batch_bucketing = True
        self.buckets = (1, 2, 4, 8, 16, 32, 64, 128, 256)

    def disable_batch_bucketing(self):
        self.batch_bucketing = False

    def set_buckets(self, buckets):
        self.buckets = tuple(sorted(int(b) for b in buckets))


class Predictor:
    """Run a saved inference model (ref: AnalysisPredictor).

    >>> pred = Predictor("/tmp/model")            # prefix from
    ...                                           # save_inference_model
    >>> out, = pred.run({"x": np.zeros((4, 784), "float32")})
    """

    def __init__(self, config_or_prefix):
        cfg = config_or_prefix if isinstance(config_or_prefix, Config) \
            else Config(str(config_or_prefix))
        if cfg.model_prefix is None:
            raise ValueError("Config.model_prefix not set")
        self._config = cfg
        from ..framework.io import load_inference_model
        from ..static_.program import global_scope

        program, feed_names, fetch_names = load_inference_model(
            cfg.model_prefix)
        self._program = program
        self._feed_names = list(feed_names)
        self._fetch_names = list(fetch_names)
        # snapshot weights now: later loads into the global scope must not
        # be able to corrupt this predictor
        scope = global_scope()
        blk = program.global_block
        self._weight_names = tuple(
            v.name for v in blk.vars.values()
            if v.persistable and scope.find_var(v.name) is not None)
        self._weights = [jnp.asarray(scope.find_var(n))
                         for n in self._weight_names]
        self._compiled = {}

    # -- introspection (ref: PaddlePredictor::GetInputNames) ----------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    # -- compile ------------------------------------------------------------
    def _replay(self):
        ops = list(self._program.global_block.ops)
        consts = dict(self._program._constants)
        feed_names = tuple(self._feed_names)
        weight_names = self._weight_names
        fetch_names = tuple(self._fetch_names)

        def fn(feeds, weights):
            env = dict(consts)
            env.update(zip(feed_names, feeds))
            env.update(zip(weight_names, weights))
            for op in ops:
                args = [env[n] if n is not None else None
                        for n in op.input_names]
                out = op.fn(*args, **op.attrs)
                if isinstance(out, tuple):
                    env.update(zip(op.output_names, out))
                else:
                    env[op.output_names[0]] = out
            return [env[n] for n in fetch_names]

        return fn

    def _bucket(self, b):
        for cap in self._config.buckets:
            if b <= cap:
                return cap
        return b

    def run(self, feed, return_numpy=True):
        """``feed``: dict name->array, or list in get_input_names() order."""
        if not isinstance(feed, dict):
            feed = dict(zip(self._feed_names, feed))
        arrays = [np.asarray(feed[n]._data if isinstance(feed[n], Tensor)
                             else feed[n]) for n in self._feed_names]
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise KeyError(f"missing feeds {missing}")

        B = arrays[0].shape[0] if arrays and arrays[0].ndim else None
        pad_to = None
        if (self._config.batch_bucketing and B is not None
                and all(a.ndim and a.shape[0] == B for a in arrays)):
            cap = self._bucket(B)
            if cap != B:
                pad_to = cap
                arrays = [np.concatenate(
                    [a, np.zeros((cap - B,) + a.shape[1:], a.dtype)])
                    for a in arrays]

        sig = tuple((a.shape, str(a.dtype)) for a in arrays)
        if sig not in self._compiled:
            self._compiled[sig] = jax.jit(self._replay())
        outs = self._compiled[sig]([jnp.asarray(a) for a in arrays],
                                   self._weights)
        if pad_to is not None:
            # slice padding back off any fetch that kept the batch dim
            outs = [o[:B] if hasattr(o, "ndim") and o.ndim
                    and o.shape[0] == pad_to else o for o in outs]
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o, _internal=True) for o in outs]

    __call__ = run


def create_predictor(config):
    """ref: paddle_infer.create_predictor."""
    return Predictor(config)
