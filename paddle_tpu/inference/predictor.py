"""Predictor: compiled inference over a saved program.

Ref (capability target): paddle/fluid/inference/api/analysis_predictor.h:82
(AnalysisPredictor::Run), paddle_inference_api.h (Config / PaddlePredictor).

TPU-native design: the loaded program is replayed into a single pure
function ``feeds -> fetches`` and compiled with ``jax.jit`` once per input
shape signature. Weights stay resident on device between calls (passed as
jit arguments, never donated, so many Predictors and repeated calls share
one device copy). Optional batch bucketing pads the leading dim to a small
set of sizes so a serving workload with ragged batch sizes compiles a
handful of executables instead of one per batch size — the analog of the
reference's shape-optimized subgraphs.
"""
from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..obs import journal as _journal
from ..obs import metrics as _metrics
from ..obs import trace as _trace

__all__ = ["Config", "Predictor", "create_predictor"]

# process-wide mirrors of the per-instance cache stats, the
# executor.jit_cache.* pattern — serving runs get the same accounting
_M_HITS = _metrics.counter("predictor.jit_cache.hits")
_M_MISSES = _metrics.counter("predictor.jit_cache.misses")
_M_DISPATCHES = _metrics.counter("predictor.dispatches")
_M_RUN_MS = _metrics.histogram("predictor.run_ms")


class _PredictorEntry:
    """One compiled shape-signature entry, shaped like the Executor's
    ``_Compiled`` (``fn`` + 3-part ``arg_structs`` + name/role
    metadata) so the whole entry toolchain — ``obs.mfu.entry_analysis``,
    ``obs.spmd.sharding_summary``, ``tools/perf_gate.entry_hlo`` /
    ``check_entry`` — reads serving entries exactly like training
    ones. Weights ride the ``frozen`` role: a Predictor never updates
    (or donates) them, many Predictor calls share one device copy."""

    def __init__(self, fn, feed_structs, weight_structs, feed_names,
                 weight_names, fetch_names, program):
        self.fn = fn
        self.arg_structs = (list(feed_structs), [], list(weight_structs))
        self.feed_names = tuple(feed_names)
        self.updated = ()
        self.frozen = tuple(weight_names)
        self.fetch_names = tuple(fetch_names)
        self.program_uid = program._uid
        self.program_version = program._version
        self.optimize_level = 0
        lead = [s.shape[0] for s in feed_structs if len(s.shape) >= 1]
        self.examples_hint = max(lead) if lead else None


class Config:
    """ref: paddle_infer.Config (model path + tuning knobs)."""

    def __init__(self, model_prefix=None):
        self.model_prefix = model_prefix
        self.batch_bucketing = True
        self.buckets = (1, 2, 4, 8, 16, 32, 64, 128, 256)
        # AOT executable cache directory (runtime.aot): set to hydrate
        # compiled entries from disk per-instance; None defers to the
        # process-wide cache (configure() / env PADDLE_TPU_AOT_CACHE)
        self.aot_cache_dir = None

    def disable_batch_bucketing(self):
        self.batch_bucketing = False

    def set_buckets(self, buckets):
        self.buckets = tuple(sorted(int(b) for b in buckets))


class Predictor:
    """Run a saved inference model (ref: AnalysisPredictor).

    >>> pred = Predictor("/tmp/model")            # prefix from
    ...                                           # save_inference_model
    >>> out, = pred.run({"x": np.zeros((4, 784), "float32")})
    """

    def __init__(self, config_or_prefix):
        cfg = config_or_prefix if isinstance(config_or_prefix, Config) \
            else Config(str(config_or_prefix))
        if cfg.model_prefix is None:
            raise ValueError("Config.model_prefix not set")
        self._config = cfg
        from ..framework.io import load_inference_model
        from ..static_.program import global_scope

        program, feed_names, fetch_names = load_inference_model(
            cfg.model_prefix)
        self._program = program
        self._feed_names = list(feed_names)
        self._fetch_names = list(fetch_names)
        # snapshot weights now: later loads into the global scope must not
        # be able to corrupt this predictor
        scope = global_scope()
        blk = program.global_block
        self._weight_names = tuple(
            v.name for v in blk.vars.values()
            if v.persistable and scope.find_var(v.name) is not None)
        self._weights = [jnp.asarray(scope.find_var(n))
                         for n in self._weight_names]
        self._compiled = {}
        self._cache_hits = 0
        self._cache_misses = 0
        self._dispatches = 0

    # -- introspection (ref: PaddlePredictor::GetInputNames) ----------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    # -- compile ------------------------------------------------------------
    def _replay(self):
        ops = list(self._program.global_block.ops)
        consts = dict(self._program._constants)
        feed_names = tuple(self._feed_names)
        weight_names = self._weight_names
        fetch_names = tuple(self._fetch_names)

        def fn(feeds, _updated, weights):
            # (feeds, updated, frozen) — the Executor entry signature,
            # so entry_analysis/perf_gate lower both the same way;
            # a predictor has no updated persistables (_updated = [])
            env = dict(consts)
            env.update(zip(feed_names, feeds))
            env.update(zip(weight_names, weights))
            for op in ops:
                args = [env[n] if n is not None else None
                        for n in op.input_names]
                out = op.fn(*args, **op.attrs)
                if isinstance(out, tuple):
                    env.update(zip(op.output_names, out))
                else:
                    env[op.output_names[0]] = out
            return [env[n] for n in fetch_names]

        return fn

    def _bucket(self, b):
        for cap in self._config.buckets:
            if b <= cap:
                return cap
        return b

    def run(self, feed, return_numpy=True):
        """``feed``: dict name->array, or list in get_input_names() order."""
        if not isinstance(feed, dict):
            feed = dict(zip(self._feed_names, feed))
        missing = [n for n in self._feed_names if n not in feed]
        if missing:  # before indexing, or a bare KeyError beats us to it
            raise KeyError(f"missing feeds {missing}")
        arrays = [np.asarray(feed[n]._data if isinstance(feed[n], Tensor)
                             else feed[n]) for n in self._feed_names]

        B = arrays[0].shape[0] if arrays and arrays[0].ndim else None
        pad_to = None
        if (self._config.batch_bucketing and B is not None
                and all(a.ndim and a.shape[0] == B for a in arrays)):
            cap = self._bucket(B)
            if cap != B:
                pad_to = cap
                arrays = [np.concatenate(
                    [a, np.zeros((cap - B,) + a.shape[1:], a.dtype)])
                    for a in arrays]

        sig = tuple((a.shape, str(a.dtype)) for a in arrays)
        entry = self._compiled.get(sig)
        if entry is None:
            self._cache_misses += 1
            _M_MISSES.inc()
            t0 = time.perf_counter()
            with _trace.span("predictor.compile", uid=self._program._uid,
                             signature=len(self._compiled)):
                entry = _PredictorEntry(
                    jax.jit(self._replay()),
                    [jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for a in arrays],
                    [jax.ShapeDtypeStruct(w.shape, w.dtype)
                     for w in self._weights],
                    self._feed_names, self._weight_names,
                    self._fetch_names, self._program)
            # AOT executable cache (runtime.aot): hydrate this entry
            # from disk (or compile eagerly + publish) when a cache is
            # active — per-instance Config.aot_cache_dir wins over the
            # process-wide one. Inactive -> lazy jit as before.
            from ..runtime import aot as _aot

            aot_info = None
            cache = _aot.resolve_cache(self._config.aot_cache_dir)
            if cache is not None:
                exe, aot_info = _aot.load_or_compile(
                    entry.fn, entry.arg_structs, kind="predictor",
                    cache=cache, label=self._config.model_prefix)
                if exe is not None:
                    entry.fn = exe
            # NOTE: jax.jit is lazy — like the Executor's compile
            # event, ms times entry construction; XLA's own compile
            # lands in this signature's first predictor.run_ms sample
            # (with an AOT cache active the compile is EAGER instead,
            # and the `via` provenance fields carry its cost)
            compile_ms = (time.perf_counter() - t0) * 1e3
            if _journal.ACTIVE is not None:
                # the Executor's per-compile events, serving flavor —
                # run_report/shard_report see predictor entries too
                _journal.ACTIVE.event(
                    "compile", source="predictor",
                    uid=self._program._uid,
                    version=self._program._version, ms=compile_ms,
                    **_aot.provenance_fields(aot_info))
                from ..obs import spmd as _spmd

                _journal.ACTIVE.event("sharding",
                                      **_spmd.sharding_summary(entry))
            self._compiled[sig] = entry
        else:
            self._cache_hits += 1
            _M_HITS.inc()
        t0 = time.perf_counter()
        with _trace.span("predictor.run", uid=self._program._uid):
            outs = entry.fn([jnp.asarray(a) for a in arrays], [],
                            self._weights)
        self._dispatches += 1
        _M_DISPATCHES.inc()
        run_ms = (time.perf_counter() - t0) * 1e3
        _M_RUN_MS.observe(run_ms)
        if _journal.ACTIVE is not None:
            _journal.ACTIVE.record_executor_run(
                entry, outs, run_ms, synced=return_numpy,
                source="predictor",
                # B is the caller's batch BEFORE bucket padding — the
                # entry's struct-derived hint would overcount padding
                examples=B)
        if pad_to is not None:
            # slice padding back off any fetch that kept the batch dim
            outs = [o[:B] if hasattr(o, "ndim") and o.ndim
                    and o.shape[0] == pad_to else o for o in outs]
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o, _internal=True) for o in outs]

    __call__ = run

    @property
    def dispatches(self):
        """Compiled-fn invocations across ``run`` calls (the Executor's
        ``dispatches`` contract — perf_gate call-count gates read it)."""
        return self._dispatches

    def cache_stats(self, per_entry=False):
        """Hit/miss/size of this predictor's shape-signature cache —
        the same dict shape ``Executor.cache_stats`` pins, so
        ``run_report``/``shard_report`` tooling reads serving runs with
        no special casing. ``per_entry=True`` adds ``dispatches`` and
        an ``entries`` list with the Executor fields (bytes / FLOPs /
        collectives via the same lazy ``obs.mfu.entry_analysis``)."""
        out = {"hits": self._cache_hits, "misses": self._cache_misses,
               "size": len(self._compiled)}
        if per_entry:
            from ..obs.mfu import entry_analysis

            out["dispatches"] = self._dispatches
            entries = []
            for entry in self._compiled.values():
                a = entry_analysis(entry)
                mem = a["memory"]
                entries.append({
                    "program_uid": entry.program_uid,
                    "program_version": entry.program_version,
                    "optimize_level": entry.optimize_level,
                    "feed_names": list(entry.feed_names),
                    "memory_bytes": (sum(v for k, v in mem.items()
                                         if k != "generated_code_size")
                                     if mem else None),
                    "memory": mem,
                    "flops": (a["cost"] or {}).get("flops"),
                    "collectives": a.get("collectives"),
                    "mesh": None,
                    "steps_fused": None,
                })
            out["entries"] = entries
        return out


def create_predictor(config):
    """ref: paddle_infer.create_predictor."""
    return Predictor(config)
