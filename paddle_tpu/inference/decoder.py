"""Generic decode library: beam search, greedy search, dynamic_decode.

Ref (capability target): python/paddle/fluid/layers/rnn.py:1052
``dynamic_decode``, :2699 ``beam_search``, :2849 ``beam_search_decode``,
and the Decoder/BeamSearchDecoder classes of the 2.0 ``paddle.nn`` API.

TPU-native design: everything is expressed over fixed-shape dense
tensors — the token history is a preallocated (batch, beam, max_len)
buffer updated per step, beams/batches stay merged on the leading axis so
each step is one batched matmul-heavy call, and finished beams keep
"running" with EOS forced at zero cost (no dynamic shapes, no host sync
inside the loop). The eager loop is jax-traceable, so the whole decode
can be wrapped in ``paddle_tpu.jit`` for a single compiled program.

The model plugs in as ``step_fn(tokens, state, t) -> (logits, state)``
with ``tokens: (batch*beam, 1)`` and any pytree state (e.g. KV caches)
whose leaves carry the merged batch*beam leading dim.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from ..core.tensor import Tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode",
           "beam_search", "beam_search_xla", "greedy_search", "tile_beam",
           "gather_beams", "tree_unwrap", "tree_wrap"]


def tree_unwrap(tree):
    """Framework-Tensor pytree -> raw jnp pytree (Tensors are leaves)."""
    import jax

    return jax.tree.map(
        lambda x: x._data if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def tree_wrap(tree):
    """Raw jnp pytree -> framework-Tensor pytree."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda x: Tensor(x, _internal=True)
        if isinstance(x, jnp.ndarray) else x, tree)

_NEG_INF = -1e9


def _map_state(fn, state):
    """tree-map over nested tuples/lists/dicts/namedtuples of Tensors."""
    if isinstance(state, Tensor):
        return fn(state)
    if isinstance(state, dict):
        return {k: _map_state(fn, v) for k, v in state.items()}
    if isinstance(state, tuple) and hasattr(state, "_fields"):  # namedtuple
        return type(state)(*(_map_state(fn, v) for v in state))
    if isinstance(state, (list, tuple)):
        return type(state)(_map_state(fn, v) for v in state)
    return state


def tile_beam(state, beam_size):
    """Tile every leaf (B, ...) -> (B*beam, ...), beams contiguous per
    batch item (ref: BeamSearchDecoder.tile_beam_merge_with_batch)."""

    def tile(t):
        expanded = ops.unsqueeze(t, 1)
        reps = [1, beam_size] + [1] * (len(t.shape) - 1)
        return ops.reshape(ops.tile(expanded, reps),
                           [-1] + list(t.shape[1:]))

    return _map_state(tile, state)


def gather_beams(state, beam_idx, batch_size, beam_size):
    """Reorder every leaf's merged (B*K, ...) leading dim by the chosen
    parent beam ``beam_idx (B, K)`` (the backtrace step the reference does
    with beam_search_decode's gather tree)."""
    flat = ops.reshape(
        beam_idx + ops.unsqueeze(
            ops.arange(0, batch_size, dtype="int64") * beam_size, 1),
        [-1])

    def gather(t):
        return ops.index_select(t, flat, axis=0)

    return _map_state(gather, state)


def _length_penalty(lengths, alpha):
    """GNMT length normalization ((5+len)/6)^alpha."""
    if not alpha:
        return ops.ones_like(lengths.astype("float32"))
    return ops.pow((lengths.astype("float32") + 5.0) / 6.0,
                   ops.full_like(lengths.astype("float32"), alpha))


def beam_search(step_fn, init_state, batch_size, bos_id, eos_id, beam_size,
                max_len, length_penalty=0.6, return_all=False,
                state_is_tiled=False):
    """Batched beam search over a stepwise model.

    Returns ``(tokens, scores)``: best sequence per batch item
    ``(B, max_len)`` and its length-normalized score ``(B,)``; with
    ``return_all=True`` all beams, sorted best-first: ``(B, K, max_len)``
    and ``(B, K)``. Pass ``state_is_tiled=True`` when init_state leaves
    already carry the merged batch*beam leading dim.
    """
    B, K, = batch_size, beam_size
    state = init_state if (init_state is None or state_is_tiled) \
        else tile_beam(init_state, K)

    cur = ops.full([B * K, 1], bos_id, dtype="int64")
    tokens = ops.full([B, K, max_len], eos_id, dtype="int64")
    tokens[:, :, 0] = ops.full([B, K], bos_id, dtype="int64")
    # beam 0 live, the rest dead-on-arrival so identical initial beams
    # don't crowd the first topk
    log_probs = ops.tile(ops.reshape(ops.to_tensor(
        np.array([0.0] + [_NEG_INF] * (K - 1), np.float32)), [1, K]), [B, 1])
    finished = ops.zeros([B, K], dtype="bool")
    lengths = ops.ones([B, K], dtype="int64")

    for t in range(max_len - 1):
        logits, state = step_fn(cur, state, t)
        V = logits.shape[-1]
        lp = ops.reshape(F_log_softmax(logits.astype("float32")), [B, K, V])
        # finished beams may only emit EOS, at no cost
        eos_row = ops.to_tensor(
            np.full((V,), _NEG_INF, np.float32))
        eos_row[eos_id] = ops.to_tensor(np.float32(0.0))
        lp = ops.where(ops.unsqueeze(finished, 2),
                       ops.reshape(eos_row, [1, 1, V]), lp)
        total = ops.unsqueeze(log_probs, 2) + lp
        top_v, top_i = ops.topk(ops.reshape(total, [B, K * V]), K, axis=-1)
        beam_idx = (top_i // V).astype("int64")
        tok = (top_i % V).astype("int64")

        log_probs = top_v
        tokens = gather_beams(tokens.reshape([B * K, max_len]), beam_idx,
                              B, K).reshape([B, K, max_len])
        tokens[:, :, t + 1] = tok
        finished = gather_beams(finished.reshape([B * K]), beam_idx, B, K) \
            .reshape([B, K])
        lengths = gather_beams(lengths.reshape([B * K]), beam_idx, B, K) \
            .reshape([B, K])
        lengths = lengths + (~finished).astype("int64")
        finished = ops.logical_or(finished, ops.equal(
            tok, ops.full_like(tok, eos_id)))
        if state is not None:
            state = gather_beams(state, beam_idx, B, K)
        cur = ops.reshape(tok, [B * K, 1])
        if bool(ops.all(finished)):
            break

    scores = log_probs / _length_penalty(lengths, length_penalty)
    order = ops.argsort(-scores, axis=-1)
    scores = ops.take_along_axis(scores, order, axis=1)
    tokens = gather_beams(tokens.reshape([B * K, max_len]),
                          order.astype("int64"), B, K) \
        .reshape([B, K, max_len])
    if return_all:
        return tokens, scores
    return tokens[:, 0], scores[:, 0]


def beam_search_xla(step_fn, init_state, batch_size, bos_id, eos_id,
                    beam_size, max_len, length_penalty=0.6,
                    return_all=False):
    """Fully-traced beam search: one ``lax.while_loop`` whose body is a
    decode step, so the whole decode compiles to a SINGLE XLA executable
    with on-device early exit. The eager ``beam_search`` above syncs the
    host every token (``bool(all(finished))``) — one device round-trip
    per step, which dominates latency on a remote TPU; this version
    never leaves the device.

    Contract as ``beam_search`` with ``state_is_tiled=True``: step_fn
    takes/returns framework Tensors; ``init_state`` leaves carry the
    merged batch*beam leading dim and must be FIXED-SHAPE (use
    ``TransformerDecoder.gen_static_cache``, not the concat-growing
    ``gen_cache``). Call under ``jax.jit`` (or let the model wrapper jit
    the surrounding encode+decode).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, K = batch_size, beam_size
    _unwrap, _wrap = tree_unwrap, tree_wrap

    def _gather(tree, flat_idx):
        def g(x):
            if getattr(x, "ndim", 0) >= 1 and x.shape[0] == B * K:
                return x[flat_idx]
            return x  # scalars (cache idx) are beam-invariant

        return jax.tree.map(g, tree)

    state0 = _unwrap(init_state)
    tokens0 = jnp.full((B, K, max_len), eos_id, jnp.int32)
    tokens0 = tokens0.at[:, :, 0].set(bos_id)
    # beam 0 live, the rest dead-on-arrival so identical initial beams
    # don't crowd the first topk (same convention as the eager path)
    lps0 = jnp.tile(jnp.array([0.0] + [_NEG_INF] * (K - 1), jnp.float32),
                    (B, 1))
    carry0 = (jnp.zeros((), jnp.int32),
              jnp.full((B * K, 1), bos_id, jnp.int32),
              tokens0, lps0,
              jnp.zeros((B, K), bool),
              jnp.ones((B, K), jnp.int32),
              state0)

    def cond(c):
        t, _, _, _, finished, _, _ = c
        return jnp.logical_and(t < max_len - 1, ~jnp.all(finished))

    def body(c):
        t, cur, tokens, log_probs, finished, lengths, state = c
        logits_t, new_state_t = step_fn(
            Tensor(cur, _internal=True), _wrap(state), t)
        logits = logits_t._data.astype(jnp.float32)
        V = logits.shape[-1]
        lp = jax.nn.log_softmax(logits.reshape(B, K, V), axis=-1)
        eos_row = jnp.full((V,), _NEG_INF, jnp.float32).at[eos_id].set(0.0)
        lp = jnp.where(finished[:, :, None], eos_row[None, None, :], lp)
        total = log_probs[:, :, None] + lp
        top_v, top_i = lax.top_k(total.reshape(B, K * V), K)
        beam_idx = top_i // V
        tok = (top_i % V).astype(jnp.int32)
        flat = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
        tokens = tokens.reshape(B * K, max_len)[flat] \
            .reshape(B, K, max_len).at[:, :, t + 1].set(tok)
        finished = finished.reshape(B * K)[flat].reshape(B, K)
        lengths = lengths.reshape(B * K)[flat].reshape(B, K)
        lengths = lengths + (~finished).astype(jnp.int32)
        finished = jnp.logical_or(finished, tok == eos_id)
        new_state = _gather(_unwrap(new_state_t), flat)
        return (t + 1, tok.reshape(B * K, 1), tokens, top_v, finished,
                lengths, new_state)

    _, _, tokens, log_probs, finished, lengths, _ = lax.while_loop(
        cond, body, carry0)

    if length_penalty:
        pen = jnp.power((lengths.astype(jnp.float32) + 5.0) / 6.0,
                        length_penalty)
    else:
        pen = jnp.ones_like(lengths, jnp.float32)
    scores = log_probs / pen
    order = jnp.argsort(-scores, axis=-1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    flat = (jnp.arange(B)[:, None] * K + order).reshape(-1)
    tokens = tokens.reshape(B * K, max_len)[flat].reshape(B, K, max_len)
    # dtype contract (advisor r4): tokens come back in the framework's
    # canonical "int64" — which core/dtype.py maps to int32 (the TPU int)
    # — exactly like the eager beam_search's ops.full(dtype="int64")
    # tensors, so the two decode paths are interchangeable for callers.
    if return_all:
        return Tensor(tokens, _internal=True), Tensor(scores, _internal=True)
    return (Tensor(tokens[:, 0], _internal=True),
            Tensor(scores[:, 0], _internal=True))


def greedy_search(step_fn, init_state, batch_size, bos_id, eos_id, max_len):
    """Argmax decode through the same step_fn contract; returns
    ``(tokens (B, max_len), finished-lengths (B,))``."""
    state = init_state
    cur = ops.full([batch_size, 1], bos_id, dtype="int64")
    toks = [cur]
    finished = ops.zeros([batch_size], dtype="bool")
    lengths = ops.ones([batch_size], dtype="int64")
    for t in range(max_len - 1):
        logits, state = step_fn(cur, state, t)
        nxt = ops.argmax(logits, axis=-1).astype("int64")
        nxt = ops.where(finished, ops.full_like(nxt, eos_id), nxt)
        lengths = lengths + (~finished).astype("int64")
        finished = ops.logical_or(finished, ops.equal(
            nxt, ops.full_like(nxt, eos_id)))
        cur = ops.reshape(nxt, [batch_size, 1])
        toks.append(cur)
        if bool(ops.all(finished)):
            break
    out = ops.concat(toks, axis=1)
    if out.shape[1] < max_len:
        pad = ops.full([batch_size, max_len - out.shape[1]], eos_id,
                       dtype="int64")
        out = ops.concat([out, pad], axis=1)
    return out, lengths


def F_log_softmax(x):
    from ..nn import functional as F

    return F.log_softmax(x, axis=-1)


# -- fluid-style Decoder objects -------------------------------------------


class Decoder:
    """Abstract stepwise decoder (ref: fluid layers/rnn.py Decoder)."""

    def initialize(self, inits):
        """-> (initial_inputs, initial_states, initial_finished)"""
        raise NotImplementedError

    def step(self, time, inputs, states):
        """-> (outputs, next_states, next_inputs, finished)"""
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam-search decoding as a Decoder (ref: rnn.py BeamSearchDecoder /
    paddle.nn.BeamSearchDecoder), for use with ``dynamic_decode``.

    ``step_fn(tokens (B*K, 1), states, t) -> (logits, next_states)``.
    """

    def __init__(self, step_fn, start_token, end_token, beam_size,
                 length_penalty=0.6):
        self._step_fn = step_fn
        self.bos = int(start_token)
        self.eos = int(end_token)
        self.beam_size = int(beam_size)
        self.length_penalty = length_penalty
        self._B = None

    def initialize(self, inits):
        """``inits``: (batch_size, model state pytree)."""
        B, state = inits
        self._B = int(B)
        K = self.beam_size
        state = tile_beam(state, K) if state is not None else None
        inputs = ops.full([self._B * K, 1], self.bos, dtype="int64")
        lp0 = ops.tile(ops.reshape(ops.to_tensor(
            np.array([0.0] + [_NEG_INF] * (K - 1), np.float32)), [1, K]),
            [self._B, 1])
        states = {"cell": state, "log_probs": lp0,
                  "finished": ops.zeros([self._B, K], dtype="bool"),
                  "lengths": ops.ones([self._B, K], dtype="int64")}
        return inputs, states, ops.zeros([self._B, K], dtype="bool")

    def step(self, time, inputs, states):
        B, K = self._B, self.beam_size
        logits, cell = self._step_fn(inputs, states["cell"], time)
        V = logits.shape[-1]
        lp = ops.reshape(F_log_softmax(logits.astype("float32")), [B, K, V])
        eos_row = ops.to_tensor(np.full((V,), _NEG_INF, np.float32))
        eos_row[self.eos] = ops.to_tensor(np.float32(0.0))
        lp = ops.where(ops.unsqueeze(states["finished"], 2),
                       ops.reshape(eos_row, [1, 1, V]), lp)
        total = ops.unsqueeze(states["log_probs"], 2) + lp
        top_v, top_i = ops.topk(ops.reshape(total, [B, K * V]), K, axis=-1)
        beam_idx = (top_i // V).astype("int64")
        tok = (top_i % V).astype("int64")
        fin = gather_beams(states["finished"].reshape([B * K]), beam_idx,
                           B, K).reshape([B, K])
        lens = gather_beams(states["lengths"].reshape([B * K]), beam_idx,
                            B, K).reshape([B, K])
        lens = lens + (~fin).astype("int64")
        fin = ops.logical_or(fin, ops.equal(tok, ops.full_like(tok, self.eos)))
        cell = gather_beams(cell, beam_idx, B, K) if cell is not None else None
        next_states = {"cell": cell, "log_probs": top_v, "finished": fin,
                       "lengths": lens}
        outputs = {"token": tok, "parent": beam_idx}
        return outputs, next_states, ops.reshape(tok, [B * K, 1]), fin

    @property
    def tracks_own_finished(self):
        return True

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrace parent pointers into full sequences
        (ref: beam_search_decode op, rnn.py:2849)."""
        B, K = self._B, self.beam_size
        toks = [np.asarray(o["token"].numpy()) for o in outputs]
        parents = [np.asarray(o["parent"].numpy()) for o in outputs]
        T = len(toks)
        seq = np.full((B, K, T + 1), self.eos, np.int64)
        seq[:, :, 0] = self.bos
        beam = np.tile(np.arange(K)[None], (B, 1))
        cols = np.empty((B, K, T), np.int64)
        for t in range(T - 1, -1, -1):
            cols[:, :, t] = np.take_along_axis(toks[t], beam, axis=1)
            beam = np.take_along_axis(parents[t], beam, axis=1)
        seq[:, :, 1:] = cols
        scores = states_scores = final_states["log_probs"] / _length_penalty(
            final_states["lengths"], self.length_penalty)
        order = ops.argsort(-states_scores, axis=-1)
        scores = ops.take_along_axis(states_scores, order, axis=1)
        onp = np.asarray(order.numpy())
        seq = np.take_along_axis(seq, onp[:, :, None], axis=1)
        return (ops.to_tensor(seq), scores), final_states


def dynamic_decode(decoder, inits=None, max_step_num=64, output_time_major=
                   False, impute_finished=False, is_test=False,
                   return_length=False, **kwargs):
    """Drive a Decoder until every sequence finishes or ``max_step_num``
    (ref: fluid layers/rnn.py:1052 dynamic_decode)."""
    inputs, states, finished = decoder.initialize(inits)
    outputs = []
    for t in range(max_step_num):
        step_out, next_states, next_inputs, next_finished = \
            decoder.step(t, inputs, states)
        if not decoder.tracks_own_finished:
            next_finished = ops.logical_or(next_finished, finished)
        outputs.append(step_out)
        inputs, states, finished = next_inputs, next_states, next_finished
        if bool(ops.all(finished)):
            break
    final, final_states = decoder.finalize(
        outputs, states, states.get("lengths")
        if isinstance(states, dict) else None)
    if return_length:
        lens = states["lengths"] if isinstance(states, dict) else None
        return final, final_states, lens
    return final, final_states
