"""Inference engine.

TPU-native analog of the reference deployment stack
(paddle/fluid/inference/api/analysis_predictor.h:82 AnalysisPredictor,
paddle_infer::Config/Predictor): a saved inference program is replayed
into one pure jax function and compiled per input-shape bucket; the
reference's analysis/IR passes (fusion, constant fold, layout) are XLA's
job here.

Also hosts the generic decode library (dynamic_decode, BeamSearchDecoder,
beam_search/greedy_search) — the reusable analog of
python/paddle/fluid/layers/rnn.py:1052 dynamic_decode, :2699 beam_search.
"""
from .predictor import Config, Predictor, create_predictor
from .analysis import (AnalysisConfig, AnalysisPredictor, PaddleTensor,
                       ZeroCopyTensor, create_paddle_predictor)
from .decoder import (Decoder, BeamSearchDecoder, dynamic_decode,
                      beam_search, beam_search_xla, greedy_search,
                      tile_beam, gather_beams)

__all__ = [
    "Config", "Predictor", "create_predictor",
    "AnalysisConfig", "AnalysisPredictor", "PaddleTensor",
    "ZeroCopyTensor", "create_paddle_predictor",
    "Decoder", "BeamSearchDecoder", "dynamic_decode",
    "beam_search", "beam_search_xla", "greedy_search", "tile_beam",
    "gather_beams",
]
