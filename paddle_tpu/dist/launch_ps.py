"""Parameter-server launcher (ref: python/paddle/distributed/
launch_ps.py). PS mode is the recorded SURVEY §4b descope: there are no
server processes to start on a TPU pod — sparse tables shard over the
mesh and gradients ride ICI collectives. The entry points exist so
`python -m paddle.distributed.launch_ps`-era tooling fails with the
design pointer instead of an ImportError; collective launches go
through dist/launch.py.
"""
from __future__ import annotations

__all__ = ["parse_args", "start_procs", "launch"]

_DESCOPE = (
    "parameter-server launch is descoped on TPU (SURVEY §4b): use "
    "python -m paddle_tpu.distributed.launch for collective "
    "multi-process runs; sparse embeddings shard via "
    "VocabParallelEmbedding")


def parse_args():
    raise NotImplementedError(_DESCOPE)


def start_procs(args):
    raise NotImplementedError(_DESCOPE)


def launch():
    raise NotImplementedError(_DESCOPE)


if __name__ == "__main__":
    raise SystemExit(_DESCOPE)
