"""Fleet: the distributed-training facade.

Ref: python/paddle/fluid/incubate/fleet/ (collective mode) and
DistributedStrategy. The strategy's knobs map onto mesh-axis layout +
TrainStep features instead of NCCL/program-transpiler passes: dp/mp/pp/sp
degrees build the Mesh; amp/recompute toggle the corresponding TrainStep
behaviors; sharding (ZeRO-ish) maps to optimizer-state PartitionSpecs.
"""
from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from . import env as _env
from .env import init_mesh, get_mesh, init_parallel_env
from .parallel import DistributedTrainStep

__all__ = ["DistributedStrategy", "init", "distributed_optimizer",
           "worker_num", "worker_index", "is_first_worker", "fleet"]


class DistributedStrategy:
    """ref: DistributedStrategy — degrees + feature toggles."""

    def __init__(self):
        self.dp_degree = -1        # -1: whatever is left
        self.mp_degree = 1
        self.pp_degree = 1
        self.sp_degree = 1
        self.ep_degree = 1
        self.sharding = False      # shard optimizer state over dp axis
        self.sharding_degree = 1
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.lamb = False
        self.localsgd = False
        self.hybrid_configs = {}

    def mesh_axes(self):
        axes = {}
        if self.pp_degree > 1:
            axes["pipe"] = self.pp_degree
        axes["data"] = self.dp_degree
        if self.mp_degree > 1:
            axes["model"] = self.mp_degree
        if self.sp_degree > 1:
            axes["sp"] = self.sp_degree
        if self.ep_degree > 1:
            axes["expert"] = self.ep_degree
        return axes


class _Fleet:
    def __init__(self):
        self._strategy = None
        self._inited = False

    def init(self, role_maker=None, is_collective=True, strategy=None):
        init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hybrid = self._strategy.hybrid_configs or {}
        if hybrid:
            self._strategy.dp_degree = hybrid.get("dp_degree",
                                                  self._strategy.dp_degree)
            self._strategy.mp_degree = hybrid.get("mp_degree",
                                                  self._strategy.mp_degree)
            self._strategy.pp_degree = hybrid.get("pp_degree",
                                                  self._strategy.pp_degree)
            self._strategy.sp_degree = hybrid.get("sp_degree",
                                                  self._strategy.sp_degree)
            self._strategy.ep_degree = hybrid.get("ep_degree",
                                                  self._strategy.ep_degree)
        if get_mesh() is None:
            init_mesh(self._strategy.mesh_axes())
        self._inited = True
        return self

    @property
    def strategy(self):
        return self._strategy

    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        optimizer._dist_strategy = self._strategy
        return optimizer

    def distributed_model(self, model):
        return model  # SPMD: sharding decisions live on params / TrainStep

    def build_train_step(self, model, optimizer, loss_fn, **kw):
        if self._strategy is not None and self._strategy.sharding:
            kw.setdefault("shard_opt_state", True)
        if self._strategy is not None and self._strategy.recompute and \
                hasattr(model, "set_recompute"):
            model.set_recompute(True)
        if self._strategy is not None and self._strategy.amp:
            from .. import amp as amp_mod

            cfgs = self._strategy.amp_configs or {}
            dtype = cfgs.get("dtype", "bfloat16")
            level = cfgs.get("level", "O1")
            if level == "O2":
                amp_mod.decorate(model, optimizer, level="O2", dtype=dtype)
            # wrap the loss so white-listed ops compute in half precision —
            # a scaler alone is NOT mixed precision
            white = cfgs.get("custom_white_list")
            black = cfgs.get("custom_black_list")
            inner_loss = loss_fn

            def loss_fn(m, *batch, _inner=inner_loss):  # noqa: F811
                with amp_mod.auto_cast(custom_white_list=white,
                                       custom_black_list=black,
                                       dtype=dtype):
                    return _inner(m, *batch)

            if "scaler" not in kw:
                use_dyn = cfgs.get("use_dynamic_loss_scaling",
                                   dtype == "float16")
                if use_dyn:
                    kw["scaler"] = amp_mod.DynamicLossScaler(
                        init_loss_scaling=cfgs.get("init_loss_scaling",
                                                   2.0 ** 15),
                        incr_ratio=cfgs.get("incr_ratio", 2.0),
                        decr_ratio=cfgs.get("decr_ratio", 0.5),
                        incr_every_n_steps=cfgs.get("incr_every_n_steps",
                                                    1000),
                        decr_every_n_nan_or_inf=cfgs.get(
                            "decr_every_n_nan_or_inf", 1))
                elif cfgs.get("init_loss_scaling") is not None:
                    kw["scaler"] = amp_mod.StaticLossScaler(
                        cfgs["init_loss_scaling"])
        return DistributedTrainStep(model, optimizer, loss_fn,
                                    mesh=get_mesh(), **kw)

    # role queries (ref: fleet.worker_num()/worker_index())
    def worker_num(self):
        return jax.process_count()

    def worker_index(self):
        return jax.process_index()

    def is_first_worker(self):
        return jax.process_index() == 0

    def barrier_worker(self):
        from .collective import barrier

        barrier()

    def init_worker(self):
        pass

    def stop_worker(self):
        pass


fleet = _Fleet()
init = fleet.init
distributed_optimizer = fleet.distributed_optimizer
worker_num = fleet.worker_num
worker_index = fleet.worker_index
is_first_worker = fleet.is_first_worker


def __getattr__(name):
    """Forward the rest of the singleton API (strategy, init_worker,
    build_train_step, ...) at module level. Any submodule import
    (``import paddle_tpu.dist.fleet`` or the 2.x alias spelling)
    makes the import system clobber the parent package's ``fleet``
    attribute with this MODULE; forwarding makes the module a strict
    superset of the instance so both spellings expose the same API."""
    try:
        return getattr(fleet, name)
    except AttributeError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
