"""Filesystem wrappers (ref: python/paddle/distributed/fs_wrapper.py):
the FS protocol checkpoint utilities program against. LocalFS is fully
live; BDFS (Baidu AFS over its client binary) raises the recorded
descope — HDFS-style remote checkpointing goes through
fluid.contrib_utils.HDFSClient, which wraps the `hadoop fs` CLI like
the reference.
"""
from __future__ import annotations

import abc
import os
import shutil

__all__ = ["FS", "LocalFS", "BDFS"]


class FS(abc.ABC):
    @abc.abstractmethod
    def list_dirs(self, fs_path):
        ...

    @abc.abstractmethod
    def ls_dir(self, fs_path):
        ...

    @abc.abstractmethod
    def stat(self, fs_path):
        ...

    @abc.abstractmethod
    def upload(self, local_path, fs_path):
        ...

    @abc.abstractmethod
    def download(self, fs_path, local_path):
        ...

    @abc.abstractmethod
    def mkdir(self, fs_path):
        ...

    @abc.abstractmethod
    def mv(self, fs_src_path, fs_dst_path):
        ...

    @abc.abstractmethod
    def rmr(self, fs_path):
        ...

    @abc.abstractmethod
    def rm(self, fs_path):
        ...

    @abc.abstractmethod
    def delete(self, fs_path):
        ...

    @abc.abstractmethod
    def need_upload_download(self):
        ...


class LocalFS(FS):
    """ref: fs_wrapper.py LocalFS — the local filesystem as an FS."""

    def list_dirs(self, fs_path):
        if not self.stat(fs_path):
            return []
        return [d for d in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, d))]

    def ls_dir(self, fs_path):
        return list(os.listdir(fs_path))

    def stat(self, fs_path):
        return os.path.exists(fs_path)

    def upload(self, local_path, fs_path):
        # COPY semantics (the reference renames, which destroys the
        # caller's local checkpoint and fails across mounts; download
        # here copies, so upload stays symmetric)
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path, dirs_exist_ok=True)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        if os.path.isdir(fs_path):
            shutil.copytree(fs_path, local_path, dirs_exist_ok=True)
        else:
            shutil.copy2(fs_path, local_path)

    def mkdir(self, fs_path):
        assert not os.path.isfile(fs_path), f"{fs_path} is a file"
        os.makedirs(fs_path, exist_ok=True)

    def mv(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def rmr(self, fs_path):
        shutil.rmtree(fs_path)

    def rm(self, fs_path):
        os.remove(fs_path)

    def delete(self, fs_path):
        if not self.stat(fs_path):
            return
        if os.path.isfile(fs_path):
            return self.rm(fs_path)
        return self.rmr(fs_path)

    def need_upload_download(self):
        return False


class BDFS(FS):
    """ref: fs_wrapper.py BDFS — Baidu AFS via its client binary;
    infra-specific, recorded descope (use LocalFS or
    contrib_utils.HDFSClient)."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "BDFS drives Baidu's AFS client binary (infra-specific); "
            "use LocalFS, or fluid.contrib_utils.HDFSClient for "
            "hadoop-compatible stores")

    list_dirs = ls_dir = stat = upload = download = mkdir = mv = rmr = \
        rm = delete = need_upload_download = None
