"""Ring attention: sequence/context parallelism for long sequences.

TPU-native long-context attention (SURVEY §2 #24): q/k/v are sharded along
the sequence axis over the 'sp' mesh axis; K/V blocks rotate around the
ring with ppermute while each device accumulates its queries' attention in
an online-softmax (flash-attention-style) running state. Peak memory per
device is O(L_local²-ish block) instead of O(L²), and the ppermute overlaps
with the block matmuls on ICI.

The reference has no sequence-parallel attention (its long-context story is
capped by single-GPU memory); this is a required capability per the build
spec, patterned on the public ring-attention formulation.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from .env import get_mesh

__all__ = ["ring_attention_inner", "ring_attention"]


def ring_attention_inner(q, k, v, axis_name, causal=False, scale=None):
    """Per-shard kernel: call inside shard_map over ``axis_name``.

    q,k,v: (B, H, L_local, D) — this shard's sequence slice.
    Returns (B, H, L_local, D).
    """
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32) * scale

    q_pos = idx * Lq + jnp.arange(Lq)

    def step(carry, t):
        m, l, o, k_cur, v_cur = carry
        src = (idx - t) % n  # whose K/V block we now hold
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            k_pos = src * Lk + jnp.arange(Lk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, o_new, k_next, v_next), None

    # pvary: accumulators must carry the same varying-over-axis type as the
    # rotating K/V blocks or scan rejects the carry
    m0 = jax.lax.pcast(jnp.full((B, H, Lq), -jnp.inf, jnp.float32), axis_name, to='varying')
    l0 = jax.lax.pcast(jnp.zeros((B, H, Lq), jnp.float32), axis_name, to='varying')
    o0 = jax.lax.pcast(jnp.zeros((B, H, Lq, D), jnp.float32), axis_name, to='varying')
    (m, l, o, _, _), _ = jax.lax.scan(step, (m0, l0, o0, k, v),
                                      jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, axis_name="sp", causal=False, mesh=None):
    """Layer-level entry: q,k,v (B, H, L, D) Tensors; L sharded over the
    mesh axis. Usable eagerly or under jit within the mesh."""
    mesh = mesh or get_mesh()
    if mesh is None or axis_name not in mesh.shape or \
            mesh.shape[axis_name] == 1:
        # single-shard world: plain flash-style dense attention
        from ..nn.functional.attention import sdpa_bhld

        return sdpa_bhld(q, k, v, is_causal=causal)

    from ..ops._base import register, apply, OP_REGISTRY

    if "ring_attention" not in OP_REGISTRY:
        @register("ring_attention")
        def _ring(qa, ka, va, *, axis_name, causal, mesh_id):
            m = get_mesh()
            spec = P(None, None, axis_name, None)
            fn = functools.partial(ring_attention_inner, axis_name=axis_name,
                                   causal=causal)
            return jax.shard_map(fn, mesh=m, in_specs=(spec, spec, spec),
                                 out_specs=spec)(qa, ka, va)

    from . import env as denv

    prev = denv.get_mesh()
    if mesh is not prev:  # the op kernel resolves the mesh via get_mesh()
        denv.set_mesh(mesh)
    try:
        return apply("ring_attention", q, k, v, axis_name=axis_name,
                     causal=bool(causal), mesh_id=id(mesh))
    finally:
        if mesh is not prev:
            denv.set_mesh(prev)
