"""Collective communication.

TPU-native analog of the reference's collective op set
(paddle/fluid/operators/collective/c_allreduce_op.h, c_allgather_op,
c_reducescatter_op, c_broadcast_op, alltoall) and its NCCL rings
(platform/nccl_helper.h): each collective is the corresponding XLA
primitive (psum / all_gather / psum_scatter / ppermute / all_to_all) over a
named mesh axis. Inside a shard_map/pjit region they compile to ICI
collectives; called eagerly on a sharded array they run as a tiny jitted
program over the global mesh.

API mirrors paddle.distributed.* so reference training scripts map 1:1.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from .env import get_mesh

__all__ = [
    "all_reduce", "all_gather", "reduce_scatter", "broadcast", "all_to_all",
    "ppermute", "reduce", "scatter", "barrier", "ReduceOp", "split_axis",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _maybe_wrap(arr, like):
    return Tensor(arr, _internal=True) if isinstance(like, Tensor) else arr


def _axis(axis_name):
    if axis_name is not None:
        return axis_name
    mesh = get_mesh()
    if mesh is None:
        return None
    return mesh.axis_names[0]


def _eager_shard_map(fn, x, axis_name):
    """Run a collective eagerly by wrapping it in a one-op shard_map over the
    global mesh (the eager-mode path of the reference's c_* ops).

    Single-controller semantics: the GLOBAL array is the concatenation of
    per-rank values along dim 0. A scalar has no per-rank axis — it is
    already the global aggregate, so the collective is an identity on it
    (signalled by returning None). A non-scalar whose dim 0 does not
    divide the axis size is an ERROR: silently skipping the reduction
    would hand back unreduced per-rank data.
    """
    mesh = get_mesh()
    if mesh is None or axis_name is None:
        return None
    size = mesh.shape[axis_name]
    if jnp.ndim(x) == 0:
        return None
    if x.shape[0] % size != 0:
        raise ValueError(
            f"eager collective over axis '{axis_name}' (size {size}): "
            f"leading dim {x.shape[0]} is not divisible — the global view "
            f"must concatenate equal per-rank shards along dim 0. Reshape "
            f"or pad the input, or run the collective inside shard_map.")
    spec = P(axis_name)
    mapped = jax.shard_map(fn, mesh=mesh, in_specs=spec, out_specs=spec)
    return mapped(x)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, axis_name=None,
               sync_op=True):
    """ref: c_allreduce_sum/max/min/prod."""
    x = _unwrap(tensor)
    name = _axis(axis_name)

    def _pprod(v, n):
        # sign-safe product: exp of summed log-magnitudes, sign from the
        # parity of negative factors, zero if any factor is zero
        neg = jax.lax.psum((v < 0).astype(jnp.int32), n)
        mag = jnp.exp(jax.lax.psum(jnp.log(jnp.maximum(jnp.abs(v), 1e-38)), n))
        any_zero = jax.lax.pmin(jnp.abs(v), n) == 0
        sign = jnp.where(neg % 2 == 1, -1.0, 1.0).astype(v.dtype)
        return jnp.where(any_zero, jnp.zeros((), v.dtype), sign * mag)

    red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
           ReduceOp.MIN: jax.lax.pmin, ReduceOp.PROD: _pprod}[op]
    if _is_traced(x):
        out = red(x, name)
    else:
        if name is None:
            return tensor  # single-device world: identity
        out = _eager_shard_map(lambda v: red(v, name), x, name)
        if out is None:
            return tensor
    if isinstance(tensor, Tensor):
        tensor._replace(out) if not _is_traced(x) else None
        return _maybe_wrap(out, tensor)
    return out


def all_gather(tensor_or_list, tensor=None, group=None, axis_name=None,
               axis=0, tiled=True):
    """ref: c_allgather. Returns the gathered array (paddle's list-output
    form fills ``tensor_or_list`` when it is a list)."""
    out_list = None
    if isinstance(tensor_or_list, list):
        out_list = tensor_or_list
        src = tensor
    else:
        src = tensor_or_list
    x = _unwrap(src)
    name = _axis(axis_name)
    if _is_traced(x):
        out = jax.lax.all_gather(x, name, axis=axis, tiled=tiled)
    else:
        # single-controller eager view: the global array IS the
        # concatenation of every rank's shard, so the gather is an identity
        out = x
    if out_list is not None:
        mesh = get_mesh()
        n = mesh.shape[name] if (mesh is not None and name in mesh.shape) else 1
        chunk = out.shape[0] // n
        out_list.extend(
            _maybe_wrap(out[i * chunk:(i + 1) * chunk], src) for i in range(n))
        return out_list
    return _maybe_wrap(out, src)


def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, axis_name=None,
                   scatter_dimension=0):
    """ref: c_reducescatter."""
    x = _unwrap(tensor)
    name = _axis(axis_name)
    if name is None:
        return tensor
    fn = lambda v: jax.lax.psum_scatter(v, name, scatter_dimension=scatter_dimension,
                                        tiled=True)
    out = fn(x) if _is_traced(x) else _eager_shard_map(fn, x, name)
    return _maybe_wrap(out if out is not None else x, tensor)


def broadcast(tensor, src=0, group=None, axis_name=None):
    """ref: c_broadcast — everyone takes rank ``src``'s value."""
    x = _unwrap(tensor)
    name = _axis(axis_name)
    if name is None:
        return tensor

    def fn(v):
        idx = jax.lax.axis_index(name)
        n = jax.lax.axis_size(name)
        # rotate src's shard to everyone via psum of masked value
        mask = (idx == src).astype(v.dtype)
        return jax.lax.psum(v * mask, name)

    out = fn(x) if _is_traced(x) else _eager_shard_map(fn, x, name)
    if out is None:
        return tensor
    if isinstance(tensor, Tensor) and not _is_traced(x):
        tensor._replace(out)
    return _maybe_wrap(out, tensor)


def all_to_all(tensor, group=None, axis_name=None, split_axis=0,
               concat_axis=0):
    """ref: alltoall op. Leading dim is split over the axis; shards are
    exchanged so rank i holds slice i of every peer."""
    x = _unwrap(tensor)
    name = _axis(axis_name)
    if name is None:
        return tensor
    fn = lambda v: jax.lax.all_to_all(v, name, split_axis=split_axis,
                                      concat_axis=concat_axis, tiled=True)
    out = fn(x) if _is_traced(x) else _eager_shard_map(fn, x, name)
    return _maybe_wrap(out if out is not None else x, tensor)


def ppermute(tensor, perm, axis_name=None):
    """Neighbor exchange (ring step); the primitive under pipeline/ring-attn."""
    x = _unwrap(tensor)
    name = _axis(axis_name)
    fn = lambda v: jax.lax.ppermute(v, name, perm)
    out = fn(x) if _is_traced(x) else _eager_shard_map(fn, x, name)
    return _maybe_wrap(out if out is not None else x, tensor)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, axis_name=None):
    """ref: c_reduce — SPMD keeps the value everywhere; matching the
    reference's semantics only rank dst's copy is meaningful."""
    return all_reduce(tensor, op=op, group=group, axis_name=axis_name)


def scatter(tensor, tensor_list=None, src=0, group=None, axis_name=None):
    """ref: c_scatter: rank i receives slice i of src's concatenated input.

    Single-controller semantics: the result's GLOBAL view is the
    concatenation of the scattered slices, laid out sharded over the axis.
    """
    if tensor_list is not None:
        full = jnp.concatenate([_unwrap(t) for t in tensor_list], axis=0)
    else:
        full = _unwrap(tensor)
    name = _axis(axis_name)
    mesh = get_mesh()
    if name is None or mesh is None or _is_traced(full):
        return _maybe_wrap(full, tensor)
    out = jax.device_put(full, jax.sharding.NamedSharding(mesh, P(name)))
    if isinstance(tensor, Tensor):
        tensor._replace(out)
        return tensor
    return out


def barrier(group=None):
    """ref: barrier op — under SPMD-on-XLA every program is naturally
    bulk-synchronous per executable; block on all outstanding device work."""
    for d in jax.live_arrays():
        d.block_until_ready()


def split_axis(x, axis_name, axis=0):
    """Utility: this shard's slice of x along ``axis`` (for manual sharding)."""
    name = _axis(axis_name)
    idx = jax.lax.axis_index(name)
    n = jax.lax.axis_size(name)
    size = x.shape[axis] // n
    return jax.lax.dynamic_slice_in_dim(x, idx * size, size, axis)
