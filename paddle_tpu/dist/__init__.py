"""paddle_tpu.distributed — mesh, collectives, dp/tp/pp/sp/ep parallelism.

Mirrors ``paddle.distributed`` + fleet (ref: incubate/fleet, collective
ops); see each module for the TPU-native design notes.
"""
from .env import (  # noqa: F401
    init_parallel_env, get_world_size, get_rank, ParallelEnv, init_mesh,
    get_mesh, set_mesh, mesh_axis_size, MeshGuard,
)
from .collective import (  # noqa: F401
    all_reduce, all_gather, reduce_scatter, broadcast, all_to_all, ppermute,
    reduce, scatter, barrier, ReduceOp,
)
from .parallel import (  # noqa: F401
    DataParallel, DistributedTrainStep, shard_tensor, param_spec,
)
from .tp_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, mark_sharding,
)
from .gradcomm import CommOptions, plan_buckets  # noqa: F401
from .ring_attention import ring_attention, ring_attention_inner  # noqa: F401
from .ulysses import all_to_all_attention, all_to_all_attention_inner  # noqa: F401
from .moe import MoEMLP, top2_gating, moe_dispatch_combine  # noqa: F401
from .pipeline import pipeline_forward, PipelineStage, gpipe_inner  # noqa: F401
from . import fleet as _fleet_mod  # noqa: F401
from .fleet import fleet, DistributedStrategy  # noqa: F401

spawn = None  # single-controller SPMD: no process spawning needed
