"""Comm-efficient gradient exchange: bucketed, accumulated, quantized.

The reference's DataParallel coalesces per-parameter NCCL all-reduces
into flat comm buffers (``comm_buffer_size`` MB, fluid/dygraph/
parallel.py) and its DGC/fp16 strategies compress the wire payload. On
TPU the grad all-reduce is normally *implicit*: GSPMD partitions the
one program and inserts one all-reduce per parameter gradient right at
the dot that produced it (see tests: an 8-device MLP emits exactly
n_params + 1 all-reduces). That placement is correct but fixed — no
bucketing, no accumulation window, no payload compression.

This module takes explicit control of the gradient exchange. The key
move is computing per-device *local* gradient sums as real sharded
tensors instead of GSPMD-internal partials: the training step's
forward+backward runs under ``jax.vmap`` over an explicit device-major
batch axis (``(B, ...) -> (ndev, B/ndev, ...)`` sharded ``P('data')``),
which is embarrassingly parallel — zero collectives — and yields every
gradient as an ``(ndev, ...)`` tensor whose rows live on their own
device. The exchange is then ordinary jax code whose collectives WE
place with sharding constraints:

- **fp32 bucketed**: concat the flat grads of each size-bounded bucket
  into one ``(ndev, F)`` buffer and reduce over the device axis — ONE
  all-reduce per bucket instead of one per parameter. Because the
  all-reduce performs the same per-element partial-sum additions GSPMD
  would, the loss trajectory is BITWISE identical to the implicit path
  on power-of-two meshes (pinned by tests/test_gradcomm.py).
- **int8 quantized** (EQuARX, arXiv:2506.17615): both phases of the
  ring exchange move int8. Phase 1 quantizes the local partials with a
  per-device scale (stochastic rounding) and swaps shards via an
  all-to-all; phase 2 requantizes the reduced chunks and all-gathers
  them. Wire bytes drop ~4x vs fp32; the phase-1 quantization error is
  carried as a persistent per-device error-feedback residual (in
  optimizer state / a ``@comm@ef`` persistable), so the bias does not
  accumulate; stochastic rounding keeps both phases unbiased.
- **accumulation**: ``accumulate_steps=N`` adds local partials for N
  microbatches (zero comm inside the inner scan) and exchanges once —
  the all-reduce fires once per N microbatches inside
  ``Executor.run_steps`` / ``TrainStep.run_fused`` windows.

Buckets are ordered by gradient availability (production order of the
backward = reverse-topological order of the forward), so the first
bucket's all-reduce is schedulable while the rest of the backward still
computes — the overlap structure tools/perf_gate.py gates on.

Semantic contract (same as the reference's DataParallel / PyTorch DDP):
the loss must average over the batch axis (``gradient_scale="mean"``,
the default, divides the exchanged sum by ndev — the reference's
``coeff_num_device`` strategy); batch-shaped inputs must split evenly
over the data mesh; per-shard reductions (e.g. un-synced BatchNorm
stats) follow rank-local DDP semantics and are averaged across shards.
"""
from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["CommOptions", "Bucket", "BucketPlan", "plan_buckets",
           "exchange_bucketed", "hash_uniform", "split_update_segment",
           "device_major", "EF_PREFIX", "STEP_VAR"]

MB = 1 << 20

# reserved persistable names for the static path's exchange state
EF_PREFIX = "@comm@ef@"       # per-bucket error-feedback residual
STEP_VAR = "@comm@step"       # stochastic-rounding salt counter


@dataclasses.dataclass(frozen=True)
class CommOptions:
    """Gradient-exchange configuration (the reference DataParallel's
    ``comm_buffer_size`` / ``last_comm_buffer_size`` knobs, now live,
    plus the EQuARX-style quantization switch).

    - ``bucket_bytes``: flat-buffer cap per all-reduce bucket (the
      reference's comm_buffer_size, in bytes here). A parameter larger
      than the cap gets a bucket of its own.
    - ``last_bucket_bytes``: cap for the FIRST bucket to fire (the
      reference's last_comm_buffer_size — "last" in forward order =
      first gradients ready in backward): a small leading bucket gets
      its all-reduce onto the wire earliest, maximizing overlap.
    - ``accumulate_steps``: exchange once per N microbatches inside a
      fused window (must divide the window's step count).
    - ``quantize``: None (fp32 wire) or "int8" (quantized two-phase
      exchange with error feedback).
    - ``gradient_scale``: "mean" divides the cross-device sum by the
      device count (reference ``coeff_num_device`` — correct for
      batch-averaged losses, the default everywhere); "sum" leaves the
      sum (for losses that sum over the batch).
    """

    bucket_bytes: int = 25 * MB
    last_bucket_bytes: int = 1 * MB
    accumulate_steps: int = 1
    quantize: str | None = None
    gradient_scale: str = "mean"

    def __post_init__(self):
        if self.bucket_bytes <= 0 or self.last_bucket_bytes <= 0:
            raise ValueError("bucket caps must be positive byte counts, "
                             f"got {self.bucket_bytes}/"
                             f"{self.last_bucket_bytes}")
        if self.accumulate_steps < 1:
            raise ValueError(
                f"accumulate_steps must be >= 1, got {self.accumulate_steps}")
        if self.quantize not in (None, "int8"):
            raise ValueError(f"quantize must be None or 'int8', "
                             f"got {self.quantize!r}")
        if self.gradient_scale not in ("mean", "sum"):
            raise ValueError("gradient_scale must be 'mean' or 'sum', "
                             f"got {self.gradient_scale!r}")

    def cache_axis(self):
        """Hashable tuple for the executor's CacheKey ``comm`` field."""
        return (self.bucket_bytes, self.last_bucket_bytes,
                self.accumulate_steps, self.quantize, self.gradient_scale)


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One flat exchange buffer: member grads in availability order."""

    names: tuple          # grad var / param names, production order
    shapes: tuple         # per-member logical shapes
    sizes: tuple          # per-member element counts
    offsets: tuple        # per-member start offset in the flat buffer
    numel: int            # sum(sizes)
    padded: int           # numel padded up to a multiple of ndev


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    buckets: tuple
    ndev: int
    options: CommOptions

    @property
    def n_buckets(self):
        return len(self.buckets)

    def flatten_local(self, locals_):
        """dict name -> (ndev, *shape) local-partial grads into one
        ``(ndev, padded)`` flat per bucket (zero-padded tail so the
        quantized path's device chunks divide evenly)."""
        out = []
        for b in self.buckets:
            flat = jnp.concatenate(
                [locals_[n].reshape(self.ndev, -1) for n in b.names], axis=1)
            if b.padded != b.numel:
                flat = jnp.pad(flat, ((0, 0), (0, b.padded - b.numel)))
            out.append(flat)
        return out

    def unflatten(self, flats, dtypes=None):
        """Per-bucket reduced ``(padded,)`` flats back to a dict of
        full-shape global grads."""
        out = {}
        for b, flat in zip(self.buckets, flats):
            for n, shape, size, off in zip(b.names, b.shapes, b.sizes,
                                           b.offsets):
                g = flat[off:off + size].reshape(shape)
                if dtypes is not None and n in dtypes:
                    g = g.astype(dtypes[n])
                out[n] = g
        return out


def plan_buckets(entries, options, ndev):
    """Assign gradients to size-bounded flat buckets.

    ``entries``: sequence of ``(name, shape, dtype)`` in gradient
    AVAILABILITY order — the order the backward produces them (static
    path: production order of the grad ops; eager path: reverse
    parameter order). The first bucket is capped at
    ``last_bucket_bytes`` so the earliest-ready gradients hit the wire
    with minimal latency; subsequent buckets at ``bucket_bytes``. A
    single gradient larger than its cap becomes a bucket of its own
    (never split: the flat view must stay a contiguous concat).
    """
    buckets = []
    cur, cur_bytes = [], 0

    def cap():
        return options.last_bucket_bytes if not buckets \
            else options.bucket_bytes

    def close():
        nonlocal cur, cur_bytes
        if not cur:
            return
        names = tuple(n for n, _, _ in cur)
        shapes = tuple(tuple(int(d) for d in s) for _, s, _ in cur)
        sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
        offsets, off = [], 0
        for sz in sizes:
            offsets.append(off)
            off += sz
        padded = off + ((-off) % ndev)
        buckets.append(Bucket(names, shapes, sizes, tuple(offsets),
                              off, padded))
        cur, cur_bytes = [], 0

    for name, shape, dtype in entries:
        # size by the EXCHANGE dtype, not the param dtype: the flat
        # buffers are always f32 (flatten_local upcasts), so counting
        # a bf16 param at 2 bytes would build wire buckets 2x the cap
        nbytes = int(np.prod(shape) if len(shape) else 1) * 4
        if cur and cur_bytes + nbytes > cap():
            close()
        cur.append((name, shape, dtype))
        cur_bytes += nbytes
        if cur_bytes >= cap():
            close()
    close()
    if not buckets:
        raise ValueError("no gradients to plan buckets over")
    return BucketPlan(tuple(buckets), int(ndev), options)


# -- stochastic rounding noise ------------------------------------------------


def hash_uniform(shape, salt):
    """Deterministic elementwise uniform noise in [-0.5, 0.5) from a
    lattice hash (xxhash-style avalanche over the element index).

    Used for stochastic rounding instead of ``jax.random``: threefry
    random bits do NOT partition over a sharded lattice on this jax
    (each device would generate — then all-reduce — the full bit
    tensor, swamping the very wire bytes quantization saves, observed
    as a u32 all-reduce larger than the payload), while a pure
    elementwise hash over an iota shards with zero communication.
    ``salt`` is a traced uint32 (step counter x bucket index) so the
    rounding pattern is fresh each step but reproducible per run.
    """
    idx = jnp.arange(int(np.prod(shape)), dtype=jnp.uint32).reshape(shape)
    x = (idx ^ jnp.uint32(salt)) * jnp.uint32(2654435761)
    x = (x ^ (x >> 16)) * jnp.uint32(2246822519)
    x = (x ^ (x >> 13)) * jnp.uint32(3266489917)
    x = x ^ (x >> 16)
    # top 24 bits -> [0, 1) exactly representable in f32, shift to +-0.5
    return (x >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24) - 0.5


# -- device-major batching ----------------------------------------------------


def device_major(arrays, ndev, mesh, batch_flags=None):
    """Reshape batch-carrying arrays ``(B, ...) -> (ndev, B/ndev, ...)``
    sharded ``P('data')`` and return ``(batched, axes)`` ready for
    ``jax.vmap(..., in_axes=(axes,))`` over the device axis — the shared
    front half of both comm-efficient paths (eager ``_comm_local``,
    static ``_comm_raw``). ``batch_flags`` overrides the per-array
    carries-the-batch-axis rule (leading dim present, nonzero, and
    divisible by ``ndev``); non-batch arrays pass through with a
    ``None`` axis (vmap broadcasts them)."""
    sh_data = NamedSharding(mesh, P("data"))
    batched, axes = [], []
    for i, a in enumerate(arrays):
        div = (batch_flags[i] if batch_flags is not None
               else a.ndim >= 1 and a.shape[0] and a.shape[0] % ndev == 0)
        if div:
            r = jnp.reshape(
                a, (ndev, a.shape[0] // ndev) + tuple(a.shape[1:]))
            batched.append(jax.lax.with_sharding_constraint(r, sh_data))
            axes.append(0)
        else:
            batched.append(a)
            axes.append(None)
    return batched, axes


# -- the exchange -------------------------------------------------------------


def _shard0(mesh):
    return NamedSharding(mesh, P("data", None))


def _rep(mesh):
    return NamedSharding(mesh, P())


def _quantize_rows(x, noise):
    """Per-row symmetric int8 quantization with stochastic rounding:
    returns (q int8, scale (rows,1) f32). Unbiased: E[q*scale] = x."""
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=1, keepdims=True) / 127.0,
                        jnp.float32(1e-30))
    q = jnp.clip(jnp.round(x / scale + noise), -127, 127).astype(jnp.int8)
    return q, scale


def exchange_bucketed(plan, flats, mesh, residuals=None, salt=None,
                      denom=None):
    """Reduce per-device local partial-sum buckets across the data mesh.

    ``flats``: list of ``(ndev, padded)`` f32 buffers (one per bucket)
    whose rows are device-local partial grad sums, sharded
    ``P('data', None)``. Returns ``(reduced, new_residuals)`` where
    ``reduced`` is a list of ``(padded,)`` replicated buffers holding
    ``sum_over_devices(flat) / denom`` and ``new_residuals`` is the
    updated error-feedback state (None on the fp32 path).

    ``denom`` defaults to ndev ("mean" scale) x accumulate_steps; pass
    an explicit value to override. fp32: one all-reduce per bucket —
    the same per-element additions GSPMD's implicit all-reduce
    performs, so results are bitwise-stable vs the implicit path when
    the scale factors are powers of two. int8: per bucket, one s8
    all-to-all (phase 1: swap quantized partial shards), a local
    dequant+reduce, and one s8 all-gather (phase 2: requantized reduced
    chunks), plus two 4-byte-per-device scale all-gathers — ~4x fewer
    wire bytes than the fp32 all-reduce at realistic sizes.
    ``optimization_barrier`` pins the int8 conversions on the sharded
    side of each collective (XLA otherwise hoists the dequantize across
    the gather and moves f32 on the wire).
    """
    opts = plan.options
    ndev = plan.ndev
    if denom is None:
        denom = (float(ndev) if opts.gradient_scale == "mean" else 1.0) * \
            float(opts.accumulate_steps)
    inv = jnp.float32(1.0 / denom)
    rep, sh0 = _rep(mesh), _shard0(mesh)

    if opts.quantize is None:
        reduced = [jax.lax.with_sharding_constraint(f.sum(0) * inv, rep)
                   for f in flats]
        return reduced, None

    if residuals is None or len(residuals) != len(flats):
        raise ValueError(
            "int8 exchange needs one error-feedback residual per bucket "
            f"(got {None if residuals is None else len(residuals)} for "
            f"{len(flats)} buckets)")
    if salt is None:
        raise ValueError("int8 exchange needs a salt (step counter) for "
                         "stochastic rounding")
    salt = jnp.asarray(salt).astype(jnp.uint32)
    reduced, new_residuals = [], []
    for i, (flat, resid) in enumerate(zip(flats, residuals)):
        n, F = flat.shape
        C = F // n
        # error feedback: what previous rounds lost rides into this one
        c = flat * inv + resid
        bsalt = salt * jnp.uint32(0x9E3779B1) + jnp.uint32(i)
        q1, scale1 = _quantize_rows(c, hash_uniform((n, F), bsalt))
        new_residuals.append(c - q1.astype(jnp.float32) * scale1)
        # phase 1: swap int8 partial shards (all-to-all). Pin the s8
        # tensor sharded BEFORE resharding, or XLA moves f32.
        x = jax.lax.optimization_barrier(
            jax.lax.with_sharding_constraint(
                q1.reshape(n, n, C), NamedSharding(mesh, P("data", None,
                                                           None))))
        x = jax.lax.optimization_barrier(
            jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(None, "data", None))))
        s1 = jax.lax.with_sharding_constraint(scale1, rep)
        # local dequant + reduce: each device sums the n partials of
        # its own chunk — no communication
        y = (x.astype(jnp.float32) * s1[:, :, None]).sum(0)
        y = jax.lax.with_sharding_constraint(y, sh0)
        # phase 2: requantize the reduced chunks, all-gather int8.
        # Stochastic rounding keeps this unbiased, so its error is NOT
        # fed back (it never accumulates; feeding it back would cost a
        # second all-to-all).
        q2, scale2 = _quantize_rows(
            y, hash_uniform(y.shape, bsalt ^ jnp.uint32(0xA5A5A5A5)))
        q2 = jax.lax.optimization_barrier(
            jax.lax.with_sharding_constraint(q2, sh0))
        q2r = jax.lax.optimization_barrier(
            jax.lax.with_sharding_constraint(q2, rep))
        s2r = jax.lax.with_sharding_constraint(scale2, rep)
        reduced.append((q2r.astype(jnp.float32) * s2r).reshape(F))
    return reduced, new_residuals


# -- static-program surgery helpers ------------------------------------------

# ops from these families form the parameter-update segment: everything
# before the first of them is the (vmappable) forward+backward segment
_UPDATE_TYPES = ("grad_clip", "amp_check_finite_and_unscale",
                 "amp_update_loss_scaling")


def _is_update_op(op):
    return op.type.startswith("optimize_") or op.type in _UPDATE_TYPES


def split_update_segment(ops):
    """Split a replayed op list at the forward+backward / update
    boundary. Returns ``(comp_ops, update_ops, cross_names)`` where
    ``cross_names`` are the values produced by the computation segment
    that the update segment consumes (the raw gradients, in production
    order — the order their all-reduces can fire).

    Raises when the program has no update segment (nothing to
    exchange) or interleaves compute ops after update ops (the comm
    rewrite needs the two-phase shape ``minimize()`` builds).
    """
    boundary = None
    for i, op in enumerate(ops):
        if _is_update_op(op):
            boundary = i
            break
    if boundary is None:
        raise ValueError(
            "comm-efficient data parallelism needs a training program "
            "(no optimizer/update ops found — was minimize() called?)")
    comp_ops, update_ops = list(ops[:boundary]), list(ops[boundary:])
    trailing_bwd = [op.type for op in update_ops
                    if op.type.endswith("@grad")
                    or op.type == "fill_ones_like"]
    if trailing_bwd:
        raise ValueError(
            "comm-efficient data parallelism needs the two-phase "
            "forward+backward -> update shape a single minimize() "
            f"builds; found backward ops {trailing_bwd[:4]} AFTER the "
            "first update op (a second minimize()/backward on this "
            "program?)")
    produced = []
    seen = set()
    for op in comp_ops:
        for n in op.output_names:
            if n not in seen:
                seen.add(n)
                produced.append(n)
    consumed = set()
    for op in update_ops:
        consumed.update(n for n in op.input_names if n is not None)
    cross = [n for n in produced if n in consumed]
    return comp_ops, update_ops, cross
