"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

TPU-native analog of the reference's section/pipeline training in Fleet
(pipeline_optimizer): stage parameters live stacked on a leading axis
sharded over the 'pipe' mesh axis; one shard_map program runs the whole
schedule, rotating activations ring-wise with ppermute each tick. The
schedule (M microbatches, S stages → M+S-1 ticks) is a lax.scan, so
forward AND the autodiff'd backward compile into a single XLA while-loop —
no per-stage host orchestration like the reference's section executor.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from .env import get_mesh

__all__ = ["pipeline_forward", "PipelineStage", "gpipe_inner"]

# jitted partial-manual schedules, keyed on (stage_fn, mesh, axes,
# microbatches, param tree/shapes, input aval) — see pipeline_forward.
# Bounded LRU: entries strongly reference stage_fn (usually a bound
# method pinning a whole model) plus its executables, so evict oldest.
from collections import OrderedDict

_partial_manual_cache: OrderedDict = OrderedDict()
_PARTIAL_MANUAL_CACHE_MAX = 16


def partial_manual_supported():
    """Whether this jax/XLA can run a PARTIAL-manual shard_map (manual
    pipe ring + automatic GSPMD axes in one program). jax that ships a
    native top-level ``jax.shard_map`` can; the 0.4.x line (where the
    name is the paddle_tpu compat alias over jax.experimental) cannot —
    its SPMD partitioner rejects PartitionId inside manual subgroups
    (and the workaround trips a fatal XLA CHECK)."""
    return not getattr(jax.shard_map, "_paddle_tpu_compat", False)


def gpipe_inner(stage_fn, stage_params, x_mb, axis_name):
    """Per-shard GPipe loop. Call inside shard_map over ``axis_name``.

    stage_fn(params, x) -> y: one stage's computation (same structure for
    every stage — the usual homogeneous-transformer-block case).
    stage_params: this shard's stage parameters (pytree; leading stage axis
    already stripped by shard_map).
    x_mb: (M, ...) microbatches — only stage 0's copy is consumed.
    Returns (M, ...) outputs — meaningful on the LAST stage (replicated out
    by the caller if needed).
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    M = x_mb.shape[0]
    total = M + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    y0 = jax.eval_shape(lambda p, x: stage_fn(p, x), stage_params,
                        jax.eval_shape(lambda a: a[0], x_mb))
    out_buf = jnp.zeros((M,) + y0.shape, y0.dtype)
    carry_act = jnp.zeros(y0.shape, y0.dtype)  # activation arriving from left

    def tick(state, t):
        carry, outs = state
        # stage 0 injects microbatch t; other stages consume the carry
        mb_idx = jnp.clip(t - idx, 0, M - 1)
        x_in = jnp.where(idx == 0,
                         jax.lax.dynamic_index_in_dim(x_mb, mb_idx, 0,
                                                      keepdims=False),
                         carry)
        y = stage_fn(stage_params, x_in)
        # last stage writes result for microbatch (t - n + 1)
        out_idx = jnp.clip(t - (n - 1), 0, M - 1)
        valid = (idx == n - 1) & (t >= n - 1) & (t - (n - 1) < M)
        outs = jnp.where(
            valid,
            jax.lax.dynamic_update_index_in_dim(outs, y, out_idx, 0),
            outs)
        carry_next = jax.lax.ppermute(y, axis_name, perm)
        return (carry_next, outs), None

    (carry, outs), _ = jax.lax.scan(tick, (carry_act, out_buf),
                                    jnp.arange(total))
    # replicate the last stage's results to every shard so the caller can
    # use out_specs=P() (grads of the loss then flow back through the ring)
    outs = jax.lax.psum(
        jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def pipeline_forward(stage_fn, stacked_params, x, num_microbatches,
                     axis_name="pipe", mesh=None, batch_axis=None):
    """Run x (batch-major) through the pipeline; returns last-stage output.

    stacked_params: pytree whose leaves have leading dim = n_layers, a
    multiple of the ``axis_name`` mesh size (each stage applies its
    n_layers/n_stages resident layers in order — the usual
    layers-per-stage grouping). x: (B, ...) split into M microbatches.
    ``batch_axis``: optional dp mesh axis; microbatches are then sharded
    over it so dp x pp runs in one shard_map.
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis_name]
    B = x.shape[0]
    M = num_microbatches
    assert B % M == 0, "batch must divide into microbatches"
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    assert n_layers % n == 0, \
        f"{n_layers} stacked layers not divisible by {n} pipeline stages"
    if batch_axis:
        dp = mesh.shape[batch_axis]
        assert (B // M) % dp == 0, \
            f"microbatch size {B // M} not divisible by " \
            f"{batch_axis} mesh size {dp}"

    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    mb = arr.reshape((M, B // M) + arr.shape[1:])

    def local_stage(params, x):
        # apply this shard's resident layers (leading dim n_layers/n)
        for i in range(n_layers // n):
            p_i = jax.tree_util.tree_map(lambda a: a[i], params)
            x = stage_fn(p_i, x)
        return x

    def shard_fn(params, xs):
        return gpipe_inner(local_stage, params, xs, axis_name)

    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    xspec = P(None, batch_axis) if batch_axis else P()
    # manual only over the pipe (+ dp batch) axes: any OTHER mesh axis
    # (e.g. the 'model' tensor-parallel axis) stays automatic, so GSPMD
    # keeps honoring the TP layers' sharding constraints INSIDE each
    # stage — this is what composes dp x tp x pp into one executable
    manual = frozenset({axis_name} | ({batch_axis} if batch_axis else set()))
    if manual != frozenset(mesh.axis_names):
        if not partial_manual_supported():
            # old jax/XLA (<= 0.4.x): the partial-auto shard_map path is
            # broken below us — axis_index lowers to a PartitionId the
            # SPMD partitioner rejects, and working around it trips a
            # FATAL CHECK (hlo_sharding_util IsManualSubgroup) that
            # kills the process. Raise fast instead of crashing or
            # hanging the caller; full-manual meshes (dp x pp) work.
            raise NotImplementedError(
                "partial-manual shard_map (pipeline composed with an "
                "automatic tensor-parallel axis) needs a newer jax/XLA "
                f"than this one: mesh axes {tuple(mesh.axis_names)} "
                f"with manual={sorted(manual)} leaves auto axes the "
                "installed XLA cannot partition around a GPipe ring. "
                "Drop the extra mesh axes or upgrade jax")
        # partial-manual + check_vma=False hits a jax-0.9 bug in the
        # EAGER dispatch path (_unmatch builds a dst spec over ALL mesh
        # axes); under jit the rearrangement never runs, so compile the
        # call — inside an outer trace this just inlines. Cached so
        # repeated eager calls (e.g. batched eval) don't retrace.
        leaves, treedef = jax.tree_util.tree_flatten(stacked_params)
        key = (stage_fn, mesh, axis_name, batch_axis, M, treedef,
               tuple((l.shape, str(l.dtype)) for l in leaves),
               mb.shape, str(mb.dtype))
        sm_fn = _partial_manual_cache.get(key)
        if sm_fn is None:
            sm_fn = jax.jit(jax.shard_map(
                shard_fn, mesh=mesh,
                in_specs=(pspec, xspec), out_specs=xspec,
                axis_names=manual, check_vma=False))
            _partial_manual_cache[key] = sm_fn
            while len(_partial_manual_cache) > _PARTIAL_MANUAL_CACHE_MAX:
                _partial_manual_cache.popitem(last=False)
        else:
            _partial_manual_cache.move_to_end(key)
    else:
        sm_fn = jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(pspec, xspec), out_specs=xspec,
            axis_names=manual, check_vma=False)
    out = sm_fn(stacked_params, mb)
    out = out.reshape((B,) + out.shape[2:])
    return Tensor(out, _internal=True) if isinstance(x, Tensor) else out


class PipelineStage:
    """Helper bundling a stage callable + stacked params for the schedule."""

    def __init__(self, stage_fn, stacked_params, num_microbatches=4,
                 axis_name="pipe"):
        self.stage_fn = stage_fn
        self.stacked_params = stacked_params
        self.num_microbatches = num_microbatches
        self.axis_name = axis_name

    def __call__(self, x):
        return pipeline_forward(self.stage_fn, self.stacked_params, x,
                                self.num_microbatches, self.axis_name)
