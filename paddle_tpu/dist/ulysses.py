"""All-to-all (Ulysses-style) sequence parallelism.

The second long-context mode alongside ring attention (SURVEY §2 #24,
"ring attention or all-to-all sequence/context parallelism"): activations
arrive sharded on the SEQUENCE axis; two all_to_all collectives re-shard
q/k/v onto the HEAD axis for the attention proper (each device then holds
full-length sequences for H/n heads, so any dense/flash kernel applies
unchanged), and a final all_to_all restores sequence sharding.

Versus the ring: a2a moves each activation twice over ICI but keeps the
attention itself completely local (no per-step ppermute on the critical
path), which wins when H >= n and the per-device attention block is
MXU-bound. Patterned on the public DeepSpeed-Ulysses formulation.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .env import get_mesh

__all__ = ["all_to_all_attention_inner", "all_to_all_attention"]


def _a2a(x, axis_name, split_axis, concat_axis):
    """lax.all_to_all with tiled=True: split ``split_axis`` across the
    group, concatenate received blocks on ``concat_axis``."""
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def all_to_all_attention_inner(q, k, v, axis_name, causal=False,
                               scale=None):
    """Per-shard kernel: call inside shard_map over ``axis_name``.

    q,k,v: (B, H, L_local, D) — sequence-sharded like the ring kernel.
    Internally re-shards to (B, H/n, L_full, D), runs local dense
    attention with the full sequence in view, and re-shards back.
    Requires H % axis_size == 0.
    """
    B, H, Lq, D = q.shape
    n = jax.lax.axis_size(axis_name)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    # seq-sharded -> head-sharded: split heads, gather sequence
    qh = _a2a(q, axis_name, 1, 2)        # (B, H/n, L_full, D)
    kh = _a2a(k, axis_name, 1, 2)
    vh = _a2a(v, axis_name, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                   kh.astype(jnp.float32)) * scale
    if causal:
        L = s.shape[-1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    oh = jnp.einsum("bhqk,bhkd->bhqd", p,
                    vh.astype(jnp.float32)).astype(q.dtype)
    # head-sharded -> seq-sharded
    return _a2a(oh, axis_name, 2, 1)


def all_to_all_attention(q, k, v, axis_name="sp", causal=False, mesh=None):
    """Layer-level entry, drop-in alternative to ``ring_attention``:
    q,k,v (B, H, L, D) Tensors with L sharded over ``axis_name``."""
    mesh = mesh or get_mesh()
    if mesh is None or axis_name not in mesh.shape or \
            mesh.shape[axis_name] == 1:
        from ..nn.functional.attention import sdpa_bhld

        return sdpa_bhld(q, k, v, is_causal=causal)

    from ..ops._base import register, apply, OP_REGISTRY

    if "ulysses_attention" not in OP_REGISTRY:
        @register("ulysses_attention")
        def _ua(qa, ka, va, *, axis_name, causal, mesh_id):
            m = get_mesh()
            n = m.shape[axis_name]
            if qa.shape[1] % n:
                raise ValueError(
                    f"all_to_all attention needs heads ({qa.shape[1]}) "
                    f"divisible by the '{axis_name}' axis ({n}); use "
                    "ring_attention otherwise")
            spec = P(None, None, axis_name, None)
            fn = functools.partial(all_to_all_attention_inner,
                                   axis_name=axis_name, causal=causal)
            return jax.shard_map(fn, mesh=m, in_specs=(spec, spec, spec),
                                 out_specs=spec)(qa, ka, va)

    from . import env as denv

    prev = denv.get_mesh()
    if mesh is not prev:  # the op kernel resolves the mesh via get_mesh()
        denv.set_mesh(mesh)
    try:
        return apply("ulysses_attention", q, k, v, axis_name=axis_name,
                     causal=bool(causal), mesh_id=id(mesh))
    finally:
        if mesh is not prev:
            denv.set_mesh(prev)
