"""Cluster topology model + local-process helpers
(ref: python/paddle/distributed/utils.py).

The Cluster/Pod/Trainer model describes multi-node launch topology —
ranks, endpoints, per-trainer accelerators. The reference's launch
scripts build it from node lists or cloud env; dist/launch.py here
spawns the local trainers. "gpus" keeps the reference field name and
holds whatever accelerator indices the launcher assigns (TPU chips
under XLA).
"""
from __future__ import annotations

import logging
import socket
from contextlib import closing

from ..fluid.log_helper import get_logger as _get_logger

__all__ = [
    "Hdfs", "Cluster", "JobServer", "Trainer", "Pod", "TrainerProc",
    "get_logger", "get_cluster", "terminate_local_procs",
    "get_host_name_ip", "add_arguments", "find_free_ports",
]

logger = _get_logger(__name__, logging.INFO,
                     fmt="%(asctime)s %(levelname)s %(message)s")


def get_logger(log_level=20, name="root"):
    return _get_logger(name, log_level,
                       fmt="%(asctime)s %(levelname)s %(message)s")


class Hdfs:
    """ref: utils.py Hdfs — checkpoint filesystem coordinates."""

    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return (self.hdfs_ugi is not None and self.hdfs_name is not None
                and self.hdfs_path is not None)

    def __str__(self):
        return (f"hdfs_ugi:{self.hdfs_ugi} hdfs_name:{self.hdfs_name} "
                f"hdfs_path:{self.hdfs_path}")

    def __eq__(self, n):
        if not isinstance(n, Hdfs):
            return NotImplemented
        return (self.hdfs_ugi == n.hdfs_ugi
                and self.hdfs_name == n.hdfs_name
                and self.hdfs_path == n.hdfs_path)

    def __ne__(self, n):
        return not self == n


class JobServer:
    def __init__(self):
        self.endpoint = None

    def __str__(self):
        return f"{self.endpoint}"

    def __eq__(self, j):
        if not isinstance(j, JobServer):
            return NotImplemented
        return self.endpoint == j.endpoint

    def __ne__(self, j):
        return not self == j


class Trainer:
    def __init__(self):
        self.gpus = []          # accelerator indices (ref field name)
        self.endpoint = None
        self.rank = None

    def __str__(self):
        return (f"gpu:{self.gpus} endpoint:{self.endpoint} "
                f"rank:{self.rank}")

    def __eq__(self, t):
        if not isinstance(t, Trainer):
            return NotImplemented
        return (self.gpus == t.gpus and self.endpoint == t.endpoint
                and self.rank == t.rank)

    def __ne__(self, t):
        return not self == t

    def rank_(self):
        return self.rank


class Pod:
    """One node's worth of trainers."""

    def __init__(self):
        self.rank = None
        self.id = None
        self.addr = None
        self.port = None
        self.trainers = []
        self.servers = []
        self.gpus = []

    def __str__(self):
        return (f"rank:{self.rank} id:{self.id} addr:{self.addr} "
                f"port:{self.port} trainers:"
                f"{[str(t) for t in self.trainers]}")

    def __eq__(self, pod):
        if not isinstance(pod, Pod):
            return NotImplemented
        if (self.rank != pod.rank or self.id != pod.id
                or self.addr != pod.addr or self.port != pod.port
                or len(self.trainers) != len(pod.trainers)):
            return False
        return all(a == b for a, b in zip(self.trainers, pod.trainers))

    def __ne__(self, pod):
        return not self == pod

    def parse_response(self, res_pods):
        pass

    def get_visible_gpus(self):
        return ",".join(str(g) for t in self.trainers for g in t.gpus)


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods = []
        self.hdfs = hdfs
        self.job_stage_flag = None

    def __str__(self):
        return (f"job_server:{self.job_server} "
                f"pods:{[str(p) for p in self.pods]} "
                f"job_stage_flag:{self.job_stage_flag} hdfs:{self.hdfs}")

    def __eq__(self, cluster):
        if not isinstance(cluster, Cluster):
            return NotImplemented
        if len(self.pods) != len(cluster.pods):
            return False
        return all(a == b for a, b in zip(self.pods, cluster.pods))

    def __ne__(self, cluster):
        return not self == cluster

    def update_pods(self, cluster):
        self.pods = list(cluster.pods)

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def pods_nranks(self):
        return len(self.pods)

    def trainers_endpoints(self):
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pods_endpoints(self):
        return [f"{p.addr}:{p.port}" for p in self.pods]

    def get_pod_by_id(self, pod_id):
        for p in self.pods:
            if str(p.id) == str(pod_id):
                return p
        return None


def get_cluster(node_ips, node_ip, paddle_ports, selected_gpus):
    """Build the Cluster/Pod model for a node list (ref: utils.py:230)."""
    assert isinstance(paddle_ports, list), "paddle_ports must be list"
    assert len(paddle_ports) >= len(selected_gpus), (
        f"need one port per trainer: {len(paddle_ports)} ports for "
        f"{len(selected_gpus)} trainers")
    cluster = Cluster(hdfs=None)
    trainer_rank = 0
    for node_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = node_rank
        pod.addr = ip
        pod.id = node_rank
        for i, gpu in enumerate(selected_gpus):
            trainer = Trainer()
            trainer.gpus.append(gpu)
            trainer.endpoint = f"{ip}:{paddle_ports[i]}"
            trainer.rank = trainer_rank
            trainer_rank += 1
            pod.trainers.append(trainer)
        cluster.pods.append(pod)
    pod_rank = node_ips.index(node_ip)
    return cluster, cluster.pods[pod_rank]


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.log_offset = None
        self.rank = None
        self.local_rank = None
        self.cmd = None


def terminate_local_procs(procs):
    """SIGTERM, bounded wait, then SIGKILL; reap and close logs."""
    import subprocess

    live = []
    for p in procs:
        proc = getattr(p, "proc", p)
        if proc is None:
            continue
        if proc.poll() is None:
            proc.terminate()
        live.append((p, proc))
    # one SHARED deadline (not 10s per process): stragglers past it
    # are killed together
    import time

    deadline = time.time() + 10
    for p, proc in live:
        try:
            proc.wait(timeout=max(0.0, deadline - time.time()))
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        log_fn = getattr(p, "log_fn", None)
        if log_fn is not None and hasattr(log_fn, "close"):
            log_fn.close()


def get_host_name_ip():
    try:
        host_name = socket.gethostname()
        return host_name, socket.gethostbyname(host_name)
    except OSError:
        return None


def add_arguments(argname, type, default, help, argparser, **kwargs):
    """ref: utils.py add_arguments — argparse helper with bool support."""
    if type is bool:
        def type(v):  # noqa: A001
            return str(v).lower() in ("true", "1", "yes")
    argparser.add_argument("--" + argname, default=default, type=type,
                           help=f"{help} Default: %(default)s.", **kwargs)


def find_free_ports(num):
    """``num`` distinct currently-free TCP ports (ref: utils.py)."""
    ports = set()
    for _ in range(num * 50):
        with closing(socket.socket(socket.AF_INET,
                                   socket.SOCK_STREAM)) as s:
            s.bind(("", 0))
            ports.add(s.getsockname()[1])
        if len(ports) >= num:
            return set(list(ports)[:num])
    return None
