"""Data/model-parallel training drivers.

TPU-native analog of the reference's ParallelExecutor + dygraph
DataParallel (python/paddle/fluid/dygraph/parallel.py): instead of NCCL
all-reduce hooks on gradients, the train step is compiled over a device
Mesh with the batch sharded on the 'data' axis and parameters sharded
according to their PartitionSpec (replicated by default) — XLA's SPMD
partitioner inserts the grad all-reduce (and any TP collectives) on ICI.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..framework.jit import TrainStep
from .env import get_mesh

__all__ = ["DataParallel", "DistributedTrainStep", "shard_tensor",
           "param_spec"]


def param_spec(p):
    return getattr(p, "sharding_spec", None) or P()


def shard_tensor(t, mesh=None, spec=P()):
    """Place a tensor onto the mesh with the given PartitionSpec
    (ref: shard_tensor in paddle.distributed.auto_parallel)."""
    mesh = mesh or get_mesh()
    arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
    out = jax.device_put(arr, NamedSharding(mesh, spec))
    if isinstance(t, Tensor):
        t._data = out
        return t
    return Tensor(out, _internal=True)


class DistributedTrainStep(TrainStep):
    """TrainStep over a Mesh: batch sharded on ``batch_axis``, params laid
    out by their ``sharding_spec`` (set by TP layers / fleet strategies)."""

    def __init__(self, model, optimizer, loss_fn, mesh=None,
                 batch_axis="data", batch_specs=None, models=None,
                 donate=True, shard_opt_state=False, scaler=None,
                 check_nan=False):
        super().__init__(model, optimizer, loss_fn, models=models,
                         donate=donate, scaler=scaler, check_nan=check_nan)
        self.mesh = mesh or get_mesh()
        if self.mesh is None:
            raise ValueError("no mesh: call dist.init_mesh(...) first")
        self.batch_axis = batch_axis
        self.batch_specs = batch_specs
        # place parameters/buffers/opt-state once; jit then infers layouts
        # from its (donated) arguments, so placement is sticky across steps
        for p in self._params:
            p._data = jax.device_put(p._data,
                                     NamedSharding(self.mesh, param_spec(p)))
        for b in self._buffers:
            b._data = jax.device_put(b._data, NamedSharding(self.mesh, P()))
        dp_size = self.mesh.shape.get(batch_axis, 1)
        for p in self._trainable:
            st = self.optimizer._accumulators[p.name]
            spec = param_spec(p)
            for k, v in st.items():
                # moment slots mirror the param layout; scalars replicate
                s = spec if tuple(v.shape) == tuple(p.shape) else P()
                if shard_opt_state and s == P() and v.ndim >= 1 and \
                        dp_size > 1 and v.shape[0] % dp_size == 0:
                    # ZeRO-style: split otherwise-replicated moment slots
                    # over the dp axis (ref: fleet sharding strategy)
                    s = P(batch_axis)
                st[k] = jax.device_put(v, NamedSharding(self.mesh, s))

    def _place_batch(self, arrays):
        out = []
        for i, a in enumerate(arrays):
            if self.batch_specs is not None:
                spec = self.batch_specs[i]
            else:
                spec = P(self.batch_axis) if a.ndim >= 1 else P()
            out.append(jax.device_put(a, NamedSharding(self.mesh, spec)))
        return out

    def __call__(self, *batch):
        arrays = [b._data if isinstance(b, Tensor)
                  else jnp.asarray(np.asarray(b)) for b in batch]
        placed = [Tensor(a, _internal=True) for a in self._place_batch(arrays)]
        with self.mesh:
            return super().__call__(*placed)

    def collective_profile(self, mesh=None):
        """Collective accounting of the compiled SPMD step, attributed
        to this step's mesh axes (see ``TrainStep.collective_profile``/
        ``obs.spmd``)."""
        return super().collective_profile(mesh=mesh or self.mesh)


class DataParallel:
    """ref: paddle.DataParallel(layer). Under SPMD the wrapper is only an
    API shim: gradient synchronization is compiled into the step, so the
    wrapped layer behaves exactly like the original."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        self._layers = layers

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    @property
    def scale_loss(self):
        return 1.0
