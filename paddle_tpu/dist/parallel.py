"""Data/model-parallel training drivers.

TPU-native analog of the reference's ParallelExecutor + dygraph
DataParallel (python/paddle/fluid/dygraph/parallel.py): instead of NCCL
all-reduce hooks on gradients, the train step is compiled over a device
Mesh with the batch sharded on the 'data' axis and parameters sharded
according to their PartitionSpec (replicated by default) — XLA's SPMD
partitioner inserts the grad all-reduce (and any TP collectives) on ICI.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..framework.jit import TrainStep
from .env import get_mesh

__all__ = ["DataParallel", "DistributedTrainStep", "shard_tensor",
           "param_spec"]


def param_spec(p):
    return getattr(p, "sharding_spec", None) or P()


def shard_tensor(t, mesh=None, spec=P()):
    """Place a tensor onto the mesh with the given PartitionSpec
    (ref: shard_tensor in paddle.distributed.auto_parallel)."""
    mesh = mesh or get_mesh()
    arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
    out = jax.device_put(arr, NamedSharding(mesh, spec))
    if isinstance(t, Tensor):
        t._data = out
        return t
    return Tensor(out, _internal=True)


class DistributedTrainStep(TrainStep):
    """TrainStep over a Mesh: batch sharded on ``batch_axis``, params laid
    out by their ``sharding_spec`` (set by TP layers / fleet strategies).

    ``comm_options`` (a ``gradcomm.CommOptions``) — or wrapping the
    model in ``DataParallel(layer, comm_buffer_size=...)`` — switches
    the gradient synchronization from GSPMD's implicit one-all-reduce-
    per-parameter placement onto the explicit comm-efficient exchange:
    size-bounded flat buckets, optional per-N-microbatch accumulation
    (``run_fused``), optional int8 quantization with error feedback
    carried in optimizer state. Requires a pure data-parallel layout
    (single mesh axis, replicated parameters) and a batch-averaged
    loss; see ``dist.gradcomm``."""

    def __init__(self, model, optimizer, loss_fn, mesh=None,
                 batch_axis="data", batch_specs=None, models=None,
                 donate=True, shard_opt_state=False, scaler=None,
                 check_nan=False, comm_options=None):
        super().__init__(model, optimizer, loss_fn, models=models,
                         donate=donate, scaler=scaler, check_nan=check_nan)
        self.mesh = mesh or get_mesh()
        if self.mesh is None:
            raise ValueError("no mesh: call dist.init_mesh(...) first")
        self.batch_axis = batch_axis
        self.batch_specs = batch_specs
        comm_inherited = False
        if comm_options is None:
            # the DataParallel wrapper's comm knobs apply to the step
            # that actually owns gradient synchronization — this one
            comm_options = getattr(model, "comm_options", None)
            comm_inherited = comm_options is not None
        # place parameters/buffers/opt-state once; jit then infers layouts
        # from its (donated) arguments, so placement is sticky across steps
        for p in self._params:
            p._data = jax.device_put(p._data,
                                     NamedSharding(self.mesh, param_spec(p)))
        for b in self._buffers:
            b._data = jax.device_put(b._data, NamedSharding(self.mesh, P()))
        dp_size = self.mesh.shape.get(batch_axis, 1)
        for p in self._trainable:
            st = self.optimizer._accumulators[p.name]
            spec = param_spec(p)
            for k, v in st.items():
                # moment slots mirror the param layout; scalars replicate
                s = spec if tuple(v.shape) == tuple(p.shape) else P()
                if shard_opt_state and s == P() and v.ndim >= 1 and \
                        dp_size > 1 and v.shape[0] % dp_size == 0:
                    # ZeRO-style: split otherwise-replicated moment slots
                    # over the dp axis (ref: fleet sharding strategy)
                    s = P(batch_axis)
                st[k] = jax.device_put(v, NamedSharding(self.mesh, s))
        if comm_options is not None:
            try:
                self._setup_comm(comm_options)
            except ValueError:
                if not comm_inherited:
                    raise
                # source compat: reference code passes comm_buffer_size
                # on DataParallel for layouts (TP meshes, sharded
                # params, scaler) the explicit exchange can't serve —
                # there the wrapper stays the inert shim it always was
                import warnings

                warnings.warn(
                    "DataParallel comm_buffer_size ignored: this layout "
                    "is not pure data parallelism (or composes with a "
                    "GradScaler); gradient sync falls back to the "
                    "implicit GSPMD placement. Pass comm_options= to "
                    "DistributedTrainStep explicitly to make this an "
                    "error", RuntimeWarning)

    def _setup_comm(self, options):
        """Enable the explicit bucketed/quantized gradient exchange
        (``dist.gradcomm``): build the bucket plan over the trainable
        parameters in reverse order (the order the backward produces
        their gradients) and materialize the error-feedback state under
        reserved optimizer-accumulator keys so it is donated, carried
        across fused windows, and checkpointed with
        ``optimizer.state_dict()``."""
        from . import gradcomm as gc

        if options.quantize and self.scaler is not None:
            raise ValueError(
                "quantize='int8' cannot compose with a GradScaler: the "
                "exchange runs on SCALED gradients, so error-feedback "
                "residuals would be stored in loss-scale units (stale "
                "after every scale change) and an overflow step would "
                "quantize inf into the persistent residual. Use int8 "
                "without dynamic loss scaling (or fp32 bucketing with "
                "it)")
        axes = dict(self.mesh.shape)
        ndev = axes.get(self.batch_axis, 1)
        if set(axes) != {self.batch_axis} or ndev < 2 or \
                self.batch_axis != "data":
            raise ValueError(
                "comm-efficient gradient exchange needs a pure data-"
                "parallel mesh over a single 'data' axis with >= 2 "
                f"devices, got mesh axes {axes} "
                f"(batch_axis={self.batch_axis!r})")
        for p in self._trainable:
            if param_spec(p) != P():
                raise ValueError(
                    f"comm-efficient exchange needs replicated params "
                    f"(pure DP); {p.name} is sharded {param_spec(p)}")
        # reverse parameter order = gradient production order in the
        # backward: the first bucket closes over the LAST layers, whose
        # all-reduce can overlap the rest of the backward
        entries = [(p.name, tuple(p._data.shape), np.dtype(p._data.dtype))
                   for p in reversed(self._trainable)]
        self._comm = gc.plan_buckets(entries, options, ndev)
        self._comm_mesh = self.mesh
        keys = []
        if options.quantize:
            opt = self.optimizer
            for i, b in enumerate(self._comm.buckets):
                name = gc.EF_PREFIX + str(i)
                if name not in opt._accumulators:
                    opt._accumulators[name] = {"residual": jax.device_put(
                        jnp.zeros((ndev, b.padded), jnp.float32),
                        NamedSharding(self.mesh, P(self.batch_axis, None)))}
                keys.append(name)
            if gc.STEP_VAR not in opt._accumulators:
                opt._accumulators[gc.STEP_VAR] = {"count": jnp.int32(0)}
            keys.append(gc.STEP_VAR)
        self._comm_state_keys = tuple(keys)

    def _place_batch(self, arrays):
        out = []
        for i, a in enumerate(arrays):
            if self.batch_specs is not None:
                spec = self.batch_specs[i]
            else:
                spec = P(self.batch_axis) if a.ndim >= 1 else P()
            out.append(jax.device_put(a, NamedSharding(self.mesh, spec)))
        return out

    def __call__(self, *batch):
        arrays = [b._data if isinstance(b, Tensor)
                  else jnp.asarray(np.asarray(b)) for b in batch]
        placed = [Tensor(a, _internal=True) for a in self._place_batch(arrays)]
        with self.mesh:
            return super().__call__(*placed)

    def collective_profile(self, mesh=None):
        """Collective accounting of the compiled SPMD step, attributed
        to this step's mesh axes (see ``TrainStep.collective_profile``/
        ``obs.spmd``)."""
        return super().collective_profile(mesh=mesh or self.mesh)


class DataParallel:
    """ref: paddle.DataParallel(layer). Under SPMD the wrapper is an API
    shim for the forward — gradient synchronization is compiled into the
    step — but the reference's comm knobs are now LIVE: passing
    ``comm_buffer_size`` (MB, like the reference) attaches a
    ``gradcomm.CommOptions`` that ``DistributedTrainStep`` picks up,
    coalescing the per-parameter grad all-reduces into flat buckets of
    that size (``last_comm_buffer_size`` caps the first-to-fire bucket).
    Left at the default ``None``, behavior is exactly as before: GSPMD
    places the all-reduces implicitly."""

    def __init__(self, layers, strategy=None, comm_buffer_size=None,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 comm_options=None):
        self._layers = layers
        if comm_options is None and comm_buffer_size is not None:
            from .gradcomm import MB, CommOptions

            comm_options = CommOptions(
                bucket_bytes=int(comm_buffer_size * MB),
                last_bucket_bytes=int(last_comm_buffer_size * MB))
        self.comm_options = comm_options

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    @property
    def scale_loss(self):
        return 1.0
