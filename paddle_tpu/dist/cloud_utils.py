"""PaddleCloud environment helpers
(ref: python/paddle/distributed/cloud_utils.py): build the Cluster
model from the PADDLE_TRAINERS / POD_IP env the cloud scheduler sets.
"""
from __future__ import annotations

import os

from .utils import get_cluster, logger

__all__ = ["get_cloud_cluster", "get_trainers_num"]


def get_cloud_cluster(args_node_ips=None, args_node_ip=None,
                      args_port=6170, selected_gpus=None):
    """ref: cloud_utils.py:21 — env wins over CLI args (with the same
    warnings the reference prints)."""
    env_ips = os.getenv("PADDLE_TRAINERS")
    if env_ips:
        node_ips = env_ips.split(",")
        # POD_IP is only meaningful alongside the env node list (k8s
        # injects POD_IP into unrelated pods too)
        node_ip = os.getenv("POD_IP", args_node_ip)
        if node_ip is None:
            if len(node_ips) > 1:
                raise ValueError(
                    "multi-node PADDLE_TRAINERS is set but neither "
                    "POD_IP nor --node_ip identifies THIS node — "
                    "defaulting would give every node rank 0")
            node_ip = node_ips[0]
        if args_node_ips and isinstance(args_node_ips, str) and \
                args_node_ips != "127.0.0.1" and \
                args_node_ips != env_ips:
            logger.warning(
                "PADDLE_TRAINERS from the cloud environment overrides "
                "--cluster_node_ips")
    else:
        node_ips = (args_node_ips.split(",")
                    if isinstance(args_node_ips, str)
                    else list(args_node_ips or ["127.0.0.1"]))
        node_ip = args_node_ip or node_ips[0]
    if node_ip not in node_ips:
        raise ValueError(
            f"this node's ip {node_ip!r} is not in the trainer node "
            f"list {node_ips} (check POD_IP / --node_ip)")
    selected = list(selected_gpus or [0])
    started_port = int(os.getenv("PADDLE_PORT", args_port))
    ports = [started_port + i for i in range(len(selected))]
    cluster, pod = get_cluster(node_ips, node_ip, ports, selected)
    return cluster, pod


def get_trainers_num():
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
