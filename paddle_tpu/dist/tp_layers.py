"""Tensor-parallel layers.

TPU-native analog of the reference's model-parallel layers (ERNIE-era
c_allgather/c_reducescatter column/row parallel FC, ParallelCrossEntropy —
operators/collective/*): instead of explicit collectives around sharded
weights, each layer declares a PartitionSpec on its weight and constrains
its activations; XLA's SPMD partitioner materializes the same
all-gather/reduce-scatter pattern on ICI, fused into surrounding matmuls.

Mesh axis convention: 'model' is the TP axis (override via mp_axis).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer import Layer
from ..nn import initializer as I
from ..ops._base import register, apply

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "mark_sharding",
]


def mark_sharding(param, spec):
    """Attach a PartitionSpec to a Parameter; honored by
    DistributedTrainStep placement and with_sharding_constraint."""
    param.sharding_spec = spec
    return param


@register("sharding_constraint")
def _sharding_constraint(x, *, spec):
    try:
        return jax.lax.with_sharding_constraint(x, P(*spec))
    except (ValueError, RuntimeError):
        return x  # outside a mesh context: no-op


def _constrain(x, spec):
    from .env import get_mesh

    if get_mesh() is None:
        return x
    return apply("sharding_constraint", x, spec=tuple(spec))


class ColumnParallelLinear(Layer):
    """Weight (in, out) sharded on out: y = x @ W is column-sliced; with
    gather_output the result is re-replicated (ref: c_allgather after the
    partial matmul)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, mp_axis="model",
                 name=None):
        super().__init__()
        self.gather_output = gather_output
        self.mp_axis = mp_axis
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        mark_sharding(self.weight, P(None, mp_axis))
        self.bias = self.create_parameter((out_features,), attr=has_bias if
                                          has_bias is not True else None,
                                          is_bias=True) if has_bias else None
        if self.bias is not None:
            mark_sharding(self.bias, P(mp_axis))

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = _constrain(y, (None,) * (len(y.shape) - 1) + (None,))
        else:
            y = _constrain(y, (None,) * (len(y.shape) - 1) + (self.mp_axis,))
        return y


class RowParallelLinear(Layer):
    """Weight (in, out) sharded on in: partial products psum into the full
    output (ref: c_allreduce after row-parallel matmul)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, mp_axis="model",
                 name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.mp_axis = mp_axis
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        mark_sharding(self.weight, P(mp_axis, None))
        self.bias = self.create_parameter((out_features,), is_bias=True) \
            if has_bias else None
        if self.bias is not None:
            mark_sharding(self.bias, P())

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, (None,) * (len(x.shape) - 1) + (self.mp_axis,))
        y = F.linear(x, self.weight, self.bias)
        return _constrain(y, (None,) * (len(y.shape) - 1) + (None,))


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over vocab (ref: c_embedding +
    c_allreduce_sum)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_axis="model", name=None):
        super().__init__()
        self.mp_axis = mp_axis
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        mark_sharding(self.weight, P(mp_axis, None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, (None,) * (len(out.shape) - 1) + (None,))


class ParallelCrossEntropy(Layer):
    """CE over class-sharded logits (ref: c_softmax_with_cross_entropy):
    constrain logits to the class sharding and let GSPMD turn the softmax
    reductions into psums over the model axis."""

    def __init__(self, mp_axis="model", ignore_index=-100, name=None):
        super().__init__()
        self.mp_axis = mp_axis
        self.ignore_index = ignore_index

    def forward(self, logits, label):
        logits = _constrain(
            logits, (None,) * (len(logits.shape) - 1) + (self.mp_axis,))
        return F.cross_entropy(logits, label, reduction="none",
                               ignore_index=self.ignore_index)
