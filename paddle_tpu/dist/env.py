"""Distributed environment + device mesh management.

TPU-native analog of the reference's fleet environment
(python/paddle/fluid/incubate/fleet/base/role_maker.py, gen_comm_id /
NCCL bootstrap in platform/collective_helper.cc): there is no comm-id
handshake to port — jax.distributed + the XLA runtime own process bootstrap,
and the device Mesh replaces communicator rings. Collectives are compiled
into the step executable and ride ICI (intra-slice) / DCN (cross-slice)
according to the mesh axis layout.
"""
from __future__ import annotations

import os

import numpy as np
import jax

__all__ = [
    "init_parallel_env", "get_world_size", "get_rank", "ParallelEnv",
    "init_mesh", "get_mesh", "set_mesh", "mesh_axis_size", "MeshGuard",
]

_MESH = None
_initialized = False


def init_parallel_env():
    """ref: paddle.distributed.init_parallel_env. Multi-host jax runtime
    bootstrap when launched under a cluster coordinator; single-host is a
    no-op (all local devices already visible)."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_TPU_COORDINATOR")  # host:port
    if coord and jax.process_count() == 1:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("PADDLE_TPU_NUM_PROCESSES", 1)),
            process_id=int(os.environ.get("PADDLE_TPU_PROCESS_ID", 0)))
    _initialized = True
    return ParallelEnv()


def get_world_size():
    return jax.device_count()


def get_rank():
    return jax.process_index()


class ParallelEnv:
    """ref: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def world_size(self):
        return jax.device_count()

    @property
    def rank(self):
        return jax.process_index()

    @property
    def local_rank(self):
        return jax.process_index()

    @property
    def device_id(self):
        return 0

    @property
    def nranks(self):
        return jax.device_count()


def init_mesh(axes=None, devices=None):
    """Create and install the global device mesh.

    axes: dict name->size (in order, e.g. {"data": 2, "model": 4}) or None
    for a 1-D {"data": n_devices} mesh. The product must equal the device
    count (use -1 once for "whatever is left").
    """
    global _MESH
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if axes is None:
        axes = {"data": n}
    names, sizes = list(axes.keys()), list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} != {n} devices")
    mesh = jax.sharding.Mesh(devices.reshape(sizes), tuple(names))
    _MESH = mesh
    return mesh


def set_mesh(mesh):
    global _MESH
    _MESH = mesh


def get_mesh():
    return _MESH


def mesh_axis_size(name):
    m = get_mesh()
    if m is None or name not in m.shape:
        return 1
    return m.shape[name]


class MeshGuard:
    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        self._old = get_mesh()
        set_mesh(self.mesh)
        return self.mesh

    def __exit__(self, *a):
        set_mesh(self._old)
