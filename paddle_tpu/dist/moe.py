"""Mixture-of-Experts with expert parallelism.

TPU-native analog of the reference's incubate MoE (expert-parallel FFN with
all-to-all dispatch): GShard-style top-k gating with capacity, dispatch /
combine einsums, and an all_to_all over the 'expert' mesh axis so each
device runs only its local experts. Everything is dense einsums + one
collective — exactly the layout the MXU and ICI want.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..nn import functional as F
from .env import get_mesh

__all__ = ["top2_gating", "moe_dispatch_combine", "MoEMLP"]


def top2_gating(logits, capacity):
    """GShard top-2 gating. logits: (N, E). Returns combine (N, E, C) and
    dispatch mask (N, E, C) plus aux load-balancing loss."""
    N, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    g1_idx = jnp.argmax(probs, axis=-1)
    g1 = jnp.take_along_axis(probs, g1_idx[:, None], axis=-1)[:, 0]
    probs_wo1 = probs * (1.0 - jax.nn.one_hot(g1_idx, E))
    g2_idx = jnp.argmax(probs_wo1, axis=-1)
    g2 = jnp.take_along_axis(probs_wo1, g2_idx[:, None], axis=-1)[:, 0]

    # aux loss: mean prob per expert * fraction dispatched per expert
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(g1_idx, E), axis=0)
    aux = jnp.sum(me * ce) * E

    def positions(idx):
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based position
        return onehot, pos

    oh1, pos1 = positions(g1_idx)
    # second choice queues behind all first choices
    count1 = jnp.sum(oh1, axis=0, keepdims=True)
    oh2, pos2 = positions(g2_idx)
    pos2 = pos2 + count1 * oh2

    keep1 = (pos1 > 0) & (pos1 <= capacity)
    keep2 = (pos2 > 0) & (pos2 <= capacity)

    denom = g1 + g2 + 1e-9
    w1 = jnp.where(jnp.any(keep1, -1), g1 / denom, 0.0)
    w2 = jnp.where(jnp.any(keep2, -1), g2 / denom, 0.0)

    def scatter(onehot, pos, keep, w):
        slot = jax.nn.one_hot(pos - 1, capacity, dtype=jnp.float32)  # (N,E,C)
        return w[:, None, None] * onehot[..., None] * slot * keep[..., None]

    combine = scatter(oh1, pos1, keep1, w1) + scatter(oh2, pos2, keep2, w2)
    dispatch = (combine > 0).astype(logits.dtype)
    return combine.astype(logits.dtype), dispatch, aux


def moe_dispatch_combine(x, gate_logits, expert_fn, capacity_factor=2.0,
                         axis_name=None):
    """Dense dispatch→experts→combine. x: (N, D); gate_logits: (N, E).
    ``expert_fn(expert_inputs)`` maps (E, C, D) -> (E, C, D_out); when
    axis_name is set it runs under expert-parallel all_to_all."""
    N, E = gate_logits.shape
    capacity = max(1, int(capacity_factor * N / E))
    combine, dispatch, aux = top2_gating(gate_logits, capacity)
    expert_in = jnp.einsum("nd,nec->ecd", x, dispatch)  # (E, C, D)
    expert_out = expert_fn(expert_in)
    out = jnp.einsum("ecd,nec->nd", expert_out, combine.astype(expert_out.dtype))
    return out, aux


def _moe_mlp_kernel(xa, gw, w1, b1, w2, b2, *, use_ep, axis, activation,
                    capacity_factor):
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "silu": jax.nn.silu}[activation]
    xt = xa.reshape(-1, xa.shape[-1])
    logits = xt @ gw

    def dense_expert(ein):  # (E, C, D)
        h = act(jnp.einsum("ecd,edh->ech", ein, w1) + b1)
        return jnp.einsum("ech,ehd->ecd", h, w2) + b2

    if not use_ep:
        out, aux = moe_dispatch_combine(xt, logits, dense_expert,
                                        capacity_factor)
        return out.reshape(xa.shape[:-1] + (out.shape[-1],)), aux

    m = get_mesh()

    def shard_fn(xt_l, logits_l, w1_l, b1_l, w2_l, b2_l):
        # xt_l: this shard's tokens; w*_l: this shard's local experts
        def ep_expert(ein):  # (E, C, D): local tokens grouped by expert
            ein = jax.lax.all_to_all(ein, axis, split_axis=0,
                                     concat_axis=1, tiled=True)
            # now (E_local, C*n, D): every shard holds ALL tokens for its
            # local experts
            h = act(jnp.einsum("ecd,edh->ech", ein, w1_l) + b1_l)
            out = jnp.einsum("ech,ehd->ecd", h, w2_l) + b2_l
            return jax.lax.all_to_all(out, axis, split_axis=1,
                                      concat_axis=0, tiled=True)

        out, aux = moe_dispatch_combine(xt_l, logits_l, ep_expert,
                                        capacity_factor)
        return out, jax.lax.pmean(aux, axis)

    tok_spec = P(axis, None)
    exp_spec = P(axis, None, None)
    out, aux = jax.shard_map(
        shard_fn, mesh=m,
        in_specs=(tok_spec, tok_spec, exp_spec, exp_spec, exp_spec, exp_spec),
        out_specs=(tok_spec, P()))(xt, logits, w1, b1, w2, b2)
    return out.reshape(xa.shape[:-1] + (out.shape[-1],)), aux


from ..ops._base import register as _register  # noqa: E402

_register("moe_mlp")(_moe_mlp_kernel)


class MoEMLP(Layer):
    """Expert-parallel FFN block (ref: incubate MoE layer).

    Experts stacked on the leading axis of the weights and sharded over the
    'expert' mesh axis; dispatch runs through all_to_all inside shard_map.
    Falls back to dense (single-shard) execution without a mesh.
    """

    def __init__(self, d_model, d_hidden, num_experts, capacity_factor=2.0,
                 ep_axis="expert", activation="gelu", name=None):
        super().__init__()
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        self.activation = activation
        self.gate = self.create_parameter((d_model, num_experts))
        self.w1 = self.create_parameter((num_experts, d_model, d_hidden))
        self.b1 = self.create_parameter((num_experts, 1, d_hidden), is_bias=True)
        self.w2 = self.create_parameter((num_experts, d_hidden, d_model))
        self.b2 = self.create_parameter((num_experts, 1, d_model), is_bias=True)
        for p, spec in ((self.w1, P(ep_axis, None, None)),
                        (self.b1, P(ep_axis, None, None)),
                        (self.w2, P(ep_axis, None, None)),
                        (self.b2, P(ep_axis, None, None))):
            p.sharding_spec = spec
        self.aux_loss = None

    def forward(self, x):
        from ..ops._base import apply

        mesh = get_mesh()
        ep = self.ep_axis
        use_ep = mesh is not None and ep in getattr(mesh, "shape", {}) and \
            mesh.shape[ep] > 1
        out, aux = apply("moe_mlp", x, self.gate, self.w1, self.b1, self.w2,
                         self.b2, use_ep=use_ep, axis=ep,
                         activation=self.activation,
                         capacity_factor=self.capacity_factor)
        self.aux_loss = aux
        return out
