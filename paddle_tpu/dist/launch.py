"""Distributed launcher (ref: python/paddle/distributed/launch.py).

``python -m paddle_tpu.dist.launch [--nproc_per_node=N] train.py args``
spawns one trainer process per rank with the PADDLE_TRAINER_* env the
role makers read (fluid/incubate.py PaddleCloudRoleMaker).

TPU semantics differ from the reference's one-process-per-GPU model:
one process drives ALL local chips (SPMD over the mesh), so
``--nproc_per_node`` defaults to 1 per host and exists mainly for
CPU-simulation runs (each child gets JAX_PLATFORMS=cpu +
xla_force_host_platform_device_count). Multi-host pods launch one
process per host with ``--ips`` listing the hosts; jax.distributed
wires the DCN side in dist/env.py.

Failure semantics: when any worker exits nonzero, the survivors are
TERMINATED (no orphaned gang) and the first failure's exact code is
propagated — a signal death becomes the shell's 128+signum. With
``--elastic`` the gang instead runs under
``resilience.elastic.GangSupervisor``: hung workers are detected via
heartbeat files and killed, preemptions (exit 75 from
``resilience.graceful_shutdown``) relaunch budget-free, and crashes
relaunch from the newest intact checkpoint under ``--max_restarts``
with jittered backoff.

Fleet observability: with ``--run_dir`` (default: the inherited
``PADDLE_TPU_RUN_DIR``) every worker journals into its own
``<run_dir>/rank_NN`` subdir with a ``PADDLE_TPU_RANK`` identity —
``tools/fleet_report.py`` aggregates the per-rank records into one
cross-rank skew/straggler view.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

__all__ = ["launch", "get_cluster_endpoints", "get_gpus",
           "get_cluster_from_args"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.dist.launch")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="trainer processes on this host (TPU: keep 1; "
                        ">1 forces CPU simulation per child)")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host list (multi-host pods)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--elastic", action="store_true",
                   help="supervise the gang elastically: watchdog-kill "
                        "hung workers, relaunch the whole gang from the "
                        "newest intact checkpoint on failure, treat "
                        "preemption exits (75) as budget-free restarts")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="crash/hang restart budget in --elastic mode")
    p.add_argument("--hang_timeout", type=float, default=300.0,
                   help="seconds without a worker heartbeat before the "
                        "watchdog kills it (--elastic; workers opt in "
                        "by beating resilience.Heartbeat.from_env())")
    p.add_argument("--ckpt_dir", type=str, default=None,
                   help="checkpoint dir the supervisor inspects to "
                        "journal each restart's resume step (--elastic)")
    p.add_argument("--run_dir", type=str,
                   default=os.environ.get("PADDLE_TPU_RUN_DIR") or None,
                   help="fleet flight-record root: each worker journals "
                        "into <run_dir>/rank_NN (PADDLE_TPU_RUN_DIR + "
                        "PADDLE_TPU_RANK per rank); defaults to "
                        "PADDLE_TPU_RUN_DIR so a journaled launch is "
                        "fleet-observable without extra flags "
                        "(tools/fleet_report.py aggregates)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_endpoints(ips, nproc_per_node, started_port):
    """All trainer endpoints, hosts-major (ref: get_cluster_from_args)."""
    eps = []
    for ip in ips.split(","):
        for i in range(nproc_per_node):
            eps.append(f"{ip}:{started_port + i}")
    return eps


def _trainer_env(args, eps, world, local, run_dir=None):
    """The PADDLE_TRAINER_* (+ CPU-simulation) env UPDATE for one local
    worker — shared by the plain and elastic paths. ``run_dir`` hands
    the worker its per-rank journal subdir + rank identity (the
    elastic path passes None: GangSupervisor owns that wiring)."""
    rank = args.node_rank * args.nproc_per_node + local
    env = {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(eps),
        "PADDLE_CURRENT_ENDPOINT": eps[rank],
    }
    if run_dir:
        from ..obs.journal import RANK_ENV, rank_subdir

        env["PADDLE_TPU_RUN_DIR"] = os.path.join(run_dir,
                                                 rank_subdir(rank))
        env[RANK_ENV] = str(rank)
    if args.nproc_per_node > 1:
        # multiple processes cannot share the TPU client: children
        # run on the virtual-device CPU backend (test/sim mode)
        env["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            # APPEND: the user's other XLA flags must survive
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()
    return env


def _wait_gang(procs):
    """Wait for all workers; on the FIRST nonzero exit, terminate the
    survivors (no orphaned gang) and return that worker's exact exit
    code — a signal death becomes the shell's 128+signum, instead of
    the old OR-style collapse that garbled both."""
    from ..resilience.elastic import normalize_exit_code
    from .utils import terminate_local_procs

    try:
        while True:
            for p, _ in procs:
                rc = p.poll()
                if rc is not None and rc != 0:
                    terminate_local_procs([q for q, _ in procs
                                           if q is not p])
                    return normalize_exit_code(rc)
            if all(p.poll() is not None for p, _ in procs):
                return 0
            time.sleep(0.05)
    finally:
        for _, out in procs:
            if out:
                out.close()


def launch(args=None):
    args = args or _parse_args()
    eps = get_cluster_endpoints(args.ips, args.nproc_per_node,
                                args.started_port)
    world = len(eps)
    cmd = [sys.executable, args.training_script] + \
        args.training_script_args

    if getattr(args, "elastic", False):
        from ..resilience.elastic import ElasticBudgetError, GangSupervisor

        sup = GangSupervisor(
            cmd, nprocs=args.nproc_per_node,
            env_for_rank=lambda rank, attempt: _trainer_env(
                args, eps, world, rank),
            log_dir=args.log_dir, ckpt_dir=args.ckpt_dir,
            run_dir=getattr(args, "run_dir", None),
            # global rank identity: node 1's local rank 0 journals as
            # rank_NN of node_rank*nproc, never over node 0's rank_00
            rank_base=args.node_rank * args.nproc_per_node,
            max_restarts=args.max_restarts,
            hang_timeout_s=args.hang_timeout)
        try:
            return sup.run()
        except ElasticBudgetError as e:
            print(f"paddle_tpu.dist.launch: {e}", file=sys.stderr)
            return sup.state.get("exit_code") or 1

    procs = []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for local in range(args.nproc_per_node):
        rank = args.node_rank * args.nproc_per_node + local
        env = dict(os.environ)
        env.update(_trainer_env(args, eps, world, local,
                                run_dir=getattr(args, "run_dir", None)))
        out = None
        if args.log_dir:
            out = open(os.path.join(args.log_dir,
                                    f"worker.{rank}.log"), "w")
        procs.append((subprocess.Popen(cmd, env=env, stdout=out,
                                       stderr=subprocess.STDOUT
                                       if out else None), out))
    return _wait_gang(procs)


def get_gpus(selected_gpus):
    """ref: launch.py get_gpus — resolve the selected accelerator list
    against the visible-devices env (CUDA_VISIBLE_DEVICES there; the
    name is kept, the indices are whatever accelerators the runtime
    exposes). ``None`` enumerates every visible/local device, like the
    reference."""
    visible = os.getenv("CUDA_VISIBLE_DEVICES") or \
        os.getenv("TPU_VISIBLE_DEVICES")
    if selected_gpus is None or selected_gpus == "":
        if visible:
            return list(range(len(visible.split(","))))
        import jax

        return list(range(jax.local_device_count()))
    sel = [s.strip() for s in str(selected_gpus).split(",") if s.strip()]
    if not visible:
        return [int(s) for s in sel]
    vis = [v.strip() for v in visible.split(",")]
    for s in sel:
        if s not in vis:
            raise ValueError(
                f"selected device {s} not in visible devices {vis}")
    return [vis.index(s) for s in sel]


def get_cluster_from_args(args, selected_gpus):
    """ref: launch.py get_cluster_from_args — Cluster/Pod from parsed
    launcher args. Accepts this module's --ips spelling and the
    reference's cluster_node_ips/node_ip; unknown topology raises
    rather than silently defaulting."""
    from .utils import get_cluster

    ips_arg = getattr(args, "ips", None) or \
        getattr(args, "cluster_node_ips", None)
    if ips_arg is None:
        raise ValueError("args carries neither 'ips' nor "
                         "'cluster_node_ips'")
    node_ips = [ip.strip() for ip in str(ips_arg).split(",")]
    node_ip = getattr(args, "node_ip", None)
    if node_ip is None:
        rank = getattr(args, "node_rank", 0) or 0
        node_ip = node_ips[int(rank)]
    if node_ip not in node_ips:
        raise ValueError(
            f"this node's ip {node_ip!r} is not in the node list "
            f"{node_ips} (check --node_ip / --ips)")
    started = int(getattr(args, "started_port", 6170) or 6170)
    sel = get_gpus(None) if selected_gpus is None else list(selected_gpus)
    ports = [started + i for i in range(len(sel))]
    return get_cluster(node_ips, node_ip, ports, sel)



if __name__ == "__main__":
    sys.exit(launch())
