"""Distributed launcher (ref: python/paddle/distributed/launch.py).

``python -m paddle_tpu.dist.launch [--nproc_per_node=N] train.py args``
spawns one trainer process per rank with the PADDLE_TRAINER_* env the
role makers read (fluid/incubate.py PaddleCloudRoleMaker).

TPU semantics differ from the reference's one-process-per-GPU model:
one process drives ALL local chips (SPMD over the mesh), so
``--nproc_per_node`` defaults to 1 per host and exists mainly for
CPU-simulation runs (each child gets JAX_PLATFORMS=cpu +
xla_force_host_platform_device_count). Multi-host pods launch one
process per host with ``--ips`` listing the hosts; jax.distributed
wires the DCN side in dist/env.py.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

__all__ = ["launch", "get_cluster_endpoints"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.dist.launch")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="trainer processes on this host (TPU: keep 1; "
                        ">1 forces CPU simulation per child)")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host list (multi-host pods)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_endpoints(ips, nproc_per_node, started_port):
    """All trainer endpoints, hosts-major (ref: get_cluster_from_args)."""
    eps = []
    for ip in ips.split(","):
        for i in range(nproc_per_node):
            eps.append(f"{ip}:{started_port + i}")
    return eps


def launch(args=None):
    args = args or _parse_args()
    eps = get_cluster_endpoints(args.ips, args.nproc_per_node,
                                args.started_port)
    world = len(eps)
    procs = []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for local in range(args.nproc_per_node):
        rank = args.node_rank * args.nproc_per_node + local
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(eps),
            "PADDLE_CURRENT_ENDPOINT": eps[rank],
        })
        if args.nproc_per_node > 1:
            # multiple processes cannot share the TPU client: children
            # run on the virtual-device CPU backend (test/sim mode)
            env["JAX_PLATFORMS"] = "cpu"
            env.setdefault(
                "XLA_FLAGS", "--xla_force_host_platform_device_count=2")
        cmd = [sys.executable, args.training_script] + \
            args.training_script_args
        out = None
        if args.log_dir:
            out = open(os.path.join(args.log_dir,
                                    f"worker.{rank}.log"), "w")
        procs.append((subprocess.Popen(cmd, env=env, stdout=out,
                                       stderr=subprocess.STDOUT
                                       if out else None), out))
    rc = 0
    for p, out in procs:
        code = p.wait()
        if code != 0:  # collapse: OR-ing codes garbles signals/values
            rc = 1
        if out:
            out.close()
    return rc


if __name__ == "__main__":
    sys.exit(launch())
