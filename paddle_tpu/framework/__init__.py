"""paddle_tpu.framework — jit train-step fusion, io, trainer utilities."""
from .jit import jit, to_static, TrainStep, no_jit  # noqa: F401
from . import io  # noqa: F401
from .io import (  # noqa: F401
    save, load, save_inference_model, load_inference_model,
    save_checkpoint, load_checkpoint,
)
