"""Fused train/eval steps: the dygraph perf path.

TPU-native analog of the reference's CompiledProgram / ParallelExecutor
speedups for imperative code (and of paddle.jit.to_static,
python/paddle/fluid/dygraph/jit.py): a Python step function written against
eager Layers is traced ONCE into a pure jax function over the pytree of
(params, optimizer state, buffers, rng key, batch) and compiled with
``jax.jit`` — forward, backward, grad clip, and the optimizer update all
fuse into a single donated-buffer XLA executable. Per-step Python cost is
one dictionary of array handles; the reference pays per-op kernel launches.

Mechanism: Parameters/buffers are temporarily rebound to tracers while the
user's eager code runs under the trace (the same swap trick the fused RNN
runner uses), so arbitrary Layer code works unmodified, including
``loss.backward()`` — the eager tape walk is jax-traceable by design
(core/autograd.py).
"""
from __future__ import annotations

import contextlib
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core import random as prandom
from ..core.tensor import Tensor, Parameter

__all__ = ["jit", "to_static", "TrainStep", "no_jit"]


# canonical Tensor-unwrap / device-array pass-through (core.tensor):
# batch items that are already on device must NOT round-trip host numpy
from ..core.tensor import as_device_array as _as_array  # noqa: E402


@contextlib.contextmanager
def _rebind(tensors, arrays):
    old = [t._data for t in tensors]
    for t, a in zip(tensors, arrays):
        t._data = a
    try:
        yield
    finally:
        for t, o in zip(tensors, old):
            t._data = o


def _collect_state(models):
    """All Parameters and Buffers reachable from the given layers."""
    params, buffers = [], []
    seen = set()
    for m in models:
        for _, p in m.named_parameters():
            if id(p) not in seen:
                seen.add(id(p))
                params.append(p)
        for _, b in m.named_buffers():
            if id(b) not in seen and b is not None:
                seen.add(id(b))
                buffers.append(b)
    return params, buffers


class TrainStep:
    """One fused (forward + backward + clip + update) step.

    >>> step = TrainStep(model, optimizer, loss_fn)
    >>> loss = step(x, y)            # compiled on first call per shape

    ``loss_fn(model, *batch)`` must return a scalar loss Tensor. Extra
    models (e.g. a frozen teacher) can be passed via ``models=[...]``.
    """

    def __init__(self, model, optimizer, loss_fn, models=None, donate=True,
                 scaler=None, check_nan=False):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.scaler = scaler  # amp.StaticLossScaler / DynamicLossScaler
        self.check_nan = check_nan  # on-device finite check, host raise
        self._models = list(models) if models is not None else [model]
        if model not in self._models:
            self._models.insert(0, model)
        self._params, self._buffers = _collect_state(self._models)
        self._trainable = [p for p in self._params
                           if isinstance(p, Parameter) and p.trainable]
        self._donate = donate
        self._compiled = {}
        self._arg_structs = {}   # sig -> shape/dtype/sharding structs
        self._profiles = {}      # sig -> cached CollectiveProfile
        self.last_found_inf = None  # device bool after each call
        self._scaler_state = scaler.state() if scaler is not None else {}
        # comm-efficient gradient exchange (dist.gradcomm), configured
        # by DistributedTrainStep: a BucketPlan + its mesh, plus the
        # reserved optimizer-state keys carrying error-feedback state
        self._comm = None
        self._comm_mesh = None
        self._comm_state_keys = ()
        # materialize optimizer slots eagerly so they join the carried state
        for p in self._trainable:
            optimizer._state_for(p)

    # -- the pure function --------------------------------------------------
    def _make_tape(self):
        """The forward+backward closure: ``(param_arrs, buf_arrs, key,
        batch, scale) -> (loss_val, grads dict, new_buf_arrs)``. Shared
        by the plain pure step (full batch) and the comm-efficient step
        (vmapped over the device-major batch axis)."""
        buffers = self._buffers
        trainable = self._trainable

        def tape(param_arrs, buf_arrs, key, batch, scale):
            # only TRAINABLE params are threaded as jit arguments; frozen
            # params stay bound to their concrete arrays and become XLA
            # constants in the compiled step
            with _rebind(trainable, list(param_arrs)), \
                    _rebind(buffers, list(buf_arrs)), \
                    prandom.key_context(key), \
                    dispatch.fresh_tape():
                ts = [Tensor(a, _internal=True) for a in batch]
                loss = self.loss_fn(self.model, *ts)
                for p in self._params:
                    # ALL collected params, not just trainable: a frozen
                    # teacher's stale .grad (possibly a tracer from its
                    # own earlier TrainStep trace) must not be
                    # accumulated into by this backward
                    p.grad = None
                if scale is not None:
                    (loss * Tensor(scale, _internal=True)).backward()
                else:
                    loss.backward()
                grads = {p.name: (p.grad._data if p.grad is not None
                                  else None)
                         for p in trainable}
                new_bufs = [b._data for b in buffers]
                loss_val = loss._data
            return loss_val, grads, new_bufs

        return tape

    def _comm_local(self, tape):
        """Comm-efficient forward+backward: reshape batch items
        device-major, vmap the tape over the device axis (zero
        collectives), and return per-device local grads as bucket
        flats: ``(param_arrs, buf_arrs, key, batch, scale) ->
        (loss_val, flats, new_bufs)``. Buffers and the loss aggregate
        across shards (mean — rank-local BN semantics); gradients stay
        local for the explicit exchange."""
        plan, mesh = self._comm, self._comm_mesh
        ndev = plan.ndev
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..dist.gradcomm import device_major

        def local(param_arrs, buf_arrs, key, batch, scale):
            batched, axes = device_major(batch, ndev, mesh)
            if not any(ax == 0 for ax in axes):
                shapes = [tuple(a.shape) for a in batch]
                raise ValueError(
                    "comm-efficient gradient exchange needs a batch arg "
                    f"whose leading dim divides the {ndev}-device data "
                    f"mesh (batch shapes: {shapes}); a fully replicated "
                    "step would run the whole batch on every device")
            # per-shard subkeys: shards must draw INDEPENDENT noise
            # (dropout etc.), not ndev copies of one mask
            keys = jax.lax.with_sharding_constraint(
                jax.random.split(key, ndev),
                NamedSharding(mesh, P("data", None)))
            losses, grads_sh, bufs_sh = jax.vmap(
                lambda b, k: tape(param_arrs, buf_arrs, k, list(b), scale),
                in_axes=(axes, 0))(batched, keys)
            denom = ndev if plan.options.gradient_scale == "mean" else 1
            loss_val = losses.sum(0) / denom
            locals_ = {}
            unreached = set()
            for p in self._trainable:
                g = grads_sh.get(p.name)
                if g is None:
                    # unreached param: exchange zeros to keep the bucket
                    # layout static, but record it (trace-time constant)
                    # so the update is SKIPPED like the non-comm path
                    unreached.add(p.name)
                    g = jnp.zeros((ndev,) + tuple(p._data.shape),
                                  jnp.float32)
                locals_[p.name] = g.astype(jnp.float32)
            self._comm_unreached = unreached
            flats = plan.flatten_local(locals_)
            new_bufs = [
                (b.sum(0) / ndev).astype(old.dtype)
                if jnp.issubdtype(old.dtype, jnp.floating)
                else b[0]
                for b, old in zip(bufs_sh, buf_arrs)]
            return loss_val, flats, new_bufs

        return local

    def _comm_exchange(self, flats, opt_state, denom=None):
        """Run the bucketed (possibly quantized) exchange over local
        bucket flats, pulling/advancing the error-feedback state from
        the reserved optimizer-state keys. Returns
        ``(grads dict, comm_updates dict)``."""
        from ..dist import gradcomm as gc

        comm = self._comm
        residuals = salt = None
        if comm.options.quantize:
            residuals = [opt_state[gc.EF_PREFIX + str(i)]["residual"]
                         for i in range(comm.n_buckets)]
            salt = opt_state[gc.STEP_VAR]["count"]
        reduced, new_resid = gc.exchange_bucketed(
            comm, flats, self._comm_mesh, residuals=residuals, salt=salt,
            denom=denom)
        grads = comm.unflatten(
            reduced,
            dtypes={p.name: p._data.dtype for p in self._trainable})
        for n in getattr(self, "_comm_unreached", ()):
            # params the backward never reached exchanged zeros (static
            # bucket layout) but must SKIP the update, exactly like the
            # non-comm path — a zero grad would still decay Adam moments
            grads[n] = None
        comm_updates = {}
        if comm.options.quantize:
            for i, r in enumerate(new_resid):
                comm_updates[gc.EF_PREFIX + str(i)] = {"residual": r}
            comm_updates[gc.STEP_VAR] = {"count": salt + 1}
        return grads, comm_updates

    def _make_pure(self):
        opt = self.optimizer
        buffers = self._buffers
        trainable = self._trainable
        t_names = [p.name for p in trainable]
        scaler = self.scaler
        tape = self._make_tape()
        comm = self._comm
        local = self._comm_local(tape) if comm is not None else None
        apply = self._make_apply()

        def pure(param_arrs, buf_arrs, opt_state, lr, key, batch,
                 scaler_state):
            scale = scaler_state["scale"] if scaler is not None else None
            comm_updates = {}
            if comm is None:
                loss_val, grads, new_bufs = tape(param_arrs, buf_arrs,
                                                 key, batch, scale)
            else:
                loss_val, flats, new_bufs = local(param_arrs, buf_arrs,
                                                  key, batch, scale)
                grads, comm_updates = self._comm_exchange(flats, opt_state)
            return apply(grads, loss_val, new_bufs, param_arrs, buf_arrs,
                         opt_state, lr, scaler_state, comm_updates)

        return pure

    def _make_apply(self):
        """The post-backward half of the step — unscale/finite-check,
        clip, optimizer update, scaler advance — as a closure over
        *global* gradients, shared by the plain pure step and the
        comm-efficient exchange paths."""
        opt = self.optimizer
        trainable = self._trainable
        t_names = [p.name for p in trainable]
        scaler = self.scaler

        def apply(grads, loss_val, new_bufs, param_arrs, buf_arrs,
                  opt_state, lr, scaler_state, comm_updates):
            found_inf = jnp.bool_(False)
            if scaler is not None:
                # unscale + single fused finite-check over every grad
                inv = 1.0 / scaler_state["scale"]
                flags = []
                for n in t_names:
                    if grads[n] is not None:
                        g = grads[n].astype(jnp.float32) * inv
                        grads[n] = g
                        flags.append(jnp.any(~jnp.isfinite(g)))
                if flags:
                    found_inf = jnp.stack(flags).any()
            elif self.check_nan:
                flags = [jnp.any(~jnp.isfinite(loss_val))]
                for n in t_names:
                    if grads[n] is not None:
                        flags.append(jnp.any(~jnp.isfinite(grads[n])))
                found_inf = jnp.stack(flags).any()

            pgs = [(p, grads[p.name]) for p in trainable
                   if grads[p.name] is not None]
            if opt._grad_clip is not None:
                pgs = opt._grad_clip(pgs)
            new_params = dict(zip(t_names, param_arrs))
            new_state = dict(opt_state)
            for p, g in pgs:
                reg = p.regularizer if p.regularizer is not None \
                    else opt._regularization
                from ..optim.optimizer import AdamW

                s = opt_state[p.name]
                master = s.get("master")  # multi_precision fp32 copy
                pw = master if master is not None else new_params[p.name]
                if reg is not None and not isinstance(opt, AdamW):
                    g = reg(pw, g)
                plr = lr * p.optimize_attr.get("learning_rate", 1.0)
                opt._current_param = p
                np_, ns_ = opt._update(pw, g.astype(pw.dtype), s, plr)
                if master is not None:
                    ns_ = {**ns_, "master": np_}
                    np_ = np_.astype(new_params[p.name].dtype)
                if scaler is not None:
                    # inf/nan step: keep params and optimizer state frozen
                    old_p, old_s = new_params[p.name], s
                    np_ = jnp.where(found_inf, old_p, np_)
                    ns_ = {k: jnp.where(found_inf, old_s[k], v)
                           if k in old_s else v for k, v in ns_.items()}
                new_params[p.name] = np_
                new_state[p.name] = ns_
            if scaler is not None:
                # skipped step: buffer updates (e.g. BN running stats) from
                # the overflowed forward must not be committed either
                new_bufs = [jnp.where(found_inf, old, new)
                            for old, new in zip(buf_arrs, new_bufs)]
            new_scaler_state = scaler.update_state(scaler_state, found_inf) \
                if scaler is not None else scaler_state
            out_state = {n: new_state[n] for n in t_names}
            out_state.update(comm_updates)  # EF residuals + salt counter
            return loss_val, [new_params[n] for n in t_names], new_bufs, \
                out_state, new_scaler_state, found_inf

        return apply

    def _maybe_aot(self, sig, call_args, kind):
        """AOT executable cache (``runtime.aot``): with a cache active,
        the first call per compiled signature hydrates the fused step
        from disk (or compiles eagerly and publishes) instead of
        letting ``jax.jit`` compile lazily — a warm replica pays
        deserialize time, not XLA compile time. The cache entry
        replaces the lazy wrapper in ``self._compiled`` (same calling
        convention, donation baked in, outputs bitwise identical); no
        cache, or any AOT failure, keeps the lazy jit untouched."""
        fn = self._compiled[sig]
        if not hasattr(fn, "lower"):
            return fn  # already hydrated for this signature
        from ..runtime import aot as _aot

        cache = _aot.active_cache()
        if cache is None:
            return fn
        import time

        t0 = time.perf_counter()
        exe, info = _aot.load_or_compile(
            fn, call_args, kind=kind, cache=cache,
            label=type(self.model).__name__)
        if exe is None:
            return fn
        self._compiled[sig] = exe
        from ..obs import journal as _journal

        if _journal.ACTIVE is not None:
            prov = _aot.provenance_fields(info)
            _journal.ACTIVE.event(
                "compile", source=prov.get("via", "xla"),
                site="trainstep",
                ms=(time.perf_counter() - t0) * 1e3, **prov)
        return exe

    def _capture_arg_structs(self, sig, args):
        """Once per compiled shape (NOT per step): shape/dtype/sharding
        structs of the call args, so obs.spmd can later re-lower the
        exact executable for its CollectiveProfile without holding
        the (donated) arrays alive. Only COMMITTED shardings are
        kept (a mesh-placed param next to an uncommitted lr scalar
        must not read as a device conflict); uncommitted args
        replicate over the committed arrays' mesh."""
        mesh = None
        for a in jax.tree_util.tree_leaves(args):
            sh = getattr(a, "sharding", None)
            if getattr(a, "committed", False) and \
                    getattr(sh, "mesh", None) is not None:
                mesh = sh.mesh
                break
        rep = None if mesh is None else \
            jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec())

        def _struct(a):
            try:
                sh = a.sharding if getattr(a, "committed", False) \
                    else rep
                if sh is None:
                    return jax.ShapeDtypeStruct(a.shape, a.dtype)
                return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                            sharding=sh)
            except (AttributeError, TypeError):
                return jax.ShapeDtypeStruct(np.shape(a),
                                            np.asarray(a).dtype)

        self._arg_structs[sig] = jax.tree_util.tree_map(_struct, args)

    def __call__(self, *batch):
        if self._comm is not None and \
                self._comm.options.accumulate_steps > 1:
            raise ValueError(
                "accumulate_steps > 1 exchanges gradients once per N "
                "microbatches and therefore needs the fused path: call "
                "run_fused(batches, steps=K) with K a multiple of N")
        arrays = [_as_array(b) for b in batch]
        sig = tuple((a.shape, str(a.dtype)) for a in arrays)
        if sig not in self._compiled:
            pure = self._make_pure()
            donate = (0, 1, 2) if self._donate else ()
            self._compiled[sig] = jax.jit(pure, donate_argnums=donate)
        fn = self._compiled[sig]
        opt = self.optimizer
        opt_state = {p.name: opt._accumulators[p.name] for p in self._trainable}
        for k in self._comm_state_keys:
            opt_state[k] = opt._accumulators[k]
        param_arrs = [p._data for p in self._trainable]
        buf_arrs = [b._data for b in self._buffers]
        lr = jnp.float32(opt.get_lr())
        key = prandom.next_key()
        if sig not in self._arg_structs:
            self._capture_arg_structs(
                sig, (param_arrs, buf_arrs, opt_state, lr, key, arrays,
                      self._scaler_state))
        fn = self._maybe_aot(
            sig, (param_arrs, buf_arrs, opt_state, lr, key, arrays,
                  self._scaler_state), "trainstep")
        loss, new_params, new_bufs, new_state, new_scaler, found_bad = fn(
            param_arrs, buf_arrs, opt_state, lr, key, arrays,
            self._scaler_state)
        for p, a in zip(self._trainable, new_params):
            p._data = a
        for b, a in zip(self._buffers, new_bufs):
            b._data = a
        for n, s in new_state.items():
            opt._accumulators[n] = s
        self._scaler_state = new_scaler
        opt._global_step += 1
        # the raw device flag (no sync): resilience.GuardedStep and tests
        # read it to count in-graph scaler skips without a host round-trip
        self.last_found_inf = found_bad
        if self.check_nan and self.scaler is None and bool(found_bad):
            from ..utils.nan_guard import NanInfError, nonfinite_summary

            # only the loss is still on hand (grads died with the trace);
            # attach its summary when IT is the nonfinite value, and an
            # empty one when the overflow was grad-only — a zero-count
            # summary would be an actively misleading postmortem
            s = nonfinite_summary(loss)
            raise NanInfError(
                f"NaN/Inf in loss or gradients at step {opt._global_step} "
                f"(loss={float(np.asarray(loss))})",
                summary=s if s["num_nan"] or s["num_inf"] else None)
        return Tensor(loss, _internal=True)

    def _make_fused_accum(self, K, N):
        """Fused window with gradient accumulation (comm-efficient path
        only): a nested scan over (K/N windows, N microbatches). The
        inner scan runs the vmapped tape and ADDS the per-device local
        bucket flats — zero communication; the exchange + optimizer
        update run once per window, so the all-reduce fires once per N
        microbatches. Buffers (BN stats) evolve per microbatch through
        the inner carry; the scaler's found-inf freeze applies to the
        whole window (its skip decision is made on the accumulated
        gradient)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        plan, mesh = self._comm, self._comm_mesh
        scaler = self.scaler
        tape = self._make_tape()
        local = self._comm_local(tape)
        apply = self._make_apply()
        W = K // N
        sh_acc = NamedSharding(mesh, P("data", None))

        def fused(param_arrs, buf_arrs, opt_state, lrs, keys,
                  stacked_batch, scaler_state):
            def resh(x):
                return jnp.reshape(x, (W, N) + tuple(x.shape[1:]))

            def outer(carry, xs):
                params, bufs, state, sstate = carry
                lr_w, key_w, batch_w = xs
                scale = sstate["scale"] if scaler is not None else None

                def inner(ic, xk):
                    accs, ibufs = ic
                    key_k, batch_k = xk
                    loss_k, flats, nb = local(params, ibufs, key_k,
                                              list(batch_k), scale)
                    return ([a + f for a, f in zip(accs, flats)], nb), \
                        loss_k

                accs0 = [jax.lax.with_sharding_constraint(
                    jnp.zeros((plan.ndev, b.padded), jnp.float32), sh_acc)
                    for b in plan.buckets]
                (accs, nbufs), losses_w = jax.lax.scan(
                    inner, (accs0, list(bufs)), (key_w, list(batch_w)))
                # denom defaults to ndev * N: the exchanged gradient is
                # the mean over the whole N x B effective batch
                grads, comm_updates = self._comm_exchange(accs, state)
                _, np_, nb_, ns_, nss_, finf = apply(
                    grads, losses_w[-1], nbufs, params, bufs, state,
                    lr_w[-1], sstate, comm_updates)
                return (np_, nb_, ns_, nss_), (losses_w, finf)

            (np_, nb_, ns_, nss_), (losses, finfs) = jax.lax.scan(
                outer,
                (list(param_arrs), list(buf_arrs), dict(opt_state),
                 scaler_state),
                (resh(lrs), resh(keys), [resh(b) for b in stacked_batch]))
            # (W, N) microbatch losses -> the (K,) trajectory; the
            # per-window found-inf flag covers each of its N microbatches
            return (jnp.reshape(losses, (K,)), np_, nb_, ns_, nss_,
                    jnp.repeat(finfs, N))

        return fused

    def run_fused(self, batches, steps=None):
        """Run K microbatches through ONE fused ``lax.scan`` executable.

        ``batches`` is a sequence of K per-step batch tuples (uniform
        shapes/dtypes — the same tuples K ``step(*batch)`` calls would
        take), or a single pre-stacked tuple of arrays with a leading K
        axis (then ``steps=K`` is required). The whole training state —
        params, buffers, optimizer slots, scaler state — rides the scan
        as a DONATED carry; per-step PRNG keys are pre-drawn from the
        host RNG stream (the same draws K sequential calls would make),
        so the K-step loss trajectory matches K sequential
        ``step(*batch)`` calls step for step — same ops, same keys, same
        LR; XLA may fuse the scan body marginally differently than the
        standalone step (last-ulp float drift after a few steps), so
        equality is to float tolerance here. (The static
        ``Executor.run_steps`` path IS pinned bitwise.) Cost: one
        compile + one dispatch per window instead of K.

        Host-side per-step work necessarily happens at WINDOW
        granularity: the learning rate is sampled once for all K
        microbatches, ``optimizer._global_step`` advances by K at the
        end (it counts MICROBATCHES, matching the per-call path and the
        journal's ``steps``, even when ``accumulate_steps=N`` means only
        K/N optimizer updates ran — LR schedulers here key on their own
        explicit ``scheduler.step()`` calls, not this counter), and with
        ``check_nan`` a nonfinite ANY microbatch raises after the
        window. ``last_found_inf`` becomes the any-step flag;
        ``last_found_inf_per_step`` keeps the per-step (K,) vector.

        Returns the (K,) per-microbatch loss trajectory as a Tensor.
        """
        if steps is None:
            try:
                steps = len(batches)
            except TypeError:
                raise ValueError(
                    "run_fused needs steps=K when batches is not a "
                    "sized sequence of per-step batch tuples")
        K = int(steps)
        if K <= 0:
            raise ValueError(f"steps must be >= 1, got {K}")

        seq = list(batches)
        if seq and isinstance(seq[0], (list, tuple)):
            # K per-step batch tuples (the same tuples __call__ takes;
            # a single-input loss still passes [(x0,), (x1,), ...])
            if len(seq) != K:
                raise ValueError(
                    f"steps={K} but {len(seq)} microbatches were given")
            rows = [tuple(_as_array(b) for b in row) for row in seq]
            sig0 = tuple((a.shape, str(a.dtype)) for a in rows[0])
            for i, row in enumerate(rows[1:], 1):
                if tuple((a.shape, str(a.dtype)) for a in row) != sig0:
                    raise ValueError(
                        f"microbatch {i} signature "
                        f"{[(a.shape, str(a.dtype)) for a in row]} != "
                        f"microbatch 0 {list(sig0)}: fused steps need "
                        "uniform shapes")
            stacked = [jnp.stack([row[i] for row in rows])
                       for i in range(len(rows[0]))]
        else:  # pre-stacked tuple of (K, ...) arrays
            stacked = [_as_array(b) for b in seq]
            for a in stacked:
                if a.ndim < 1 or a.shape[0] != K:
                    raise ValueError(
                        f"pre-stacked batch array has shape {a.shape}; "
                        f"expected a leading microbatch axis of {K}")
            sig0 = tuple((a.shape[1:], str(a.dtype)) for a in stacked)
        N = (self._comm.options.accumulate_steps
             if self._comm is not None else 1)
        if N > 1 and K % N:
            raise ValueError(
                f"accumulate_steps={N} must divide the fused window "
                f"(steps={K}): partial accumulation windows would "
                "silently change the effective batch")
        fsig = ("fused", K) + sig0
        if fsig not in self._compiled:
            if N == 1:
                pure = self._make_pure()

                def fused(param_arrs, buf_arrs, opt_state, lrs, keys,
                          stacked_batch, scaler_state):
                    def body(carry, xs):
                        params, bufs, state, sstate = carry
                        lr, key, batch = xs
                        loss, np_, nb_, ns_, nss_, finf = pure(
                            params, bufs, state, lr, key, list(batch),
                            sstate)
                        return (np_, nb_, ns_, nss_), (loss, finf)

                    (np_, nb_, ns_, nss_), (losses, finfs) = jax.lax.scan(
                        body,
                        (list(param_arrs), list(buf_arrs), dict(opt_state),
                         scaler_state),
                        (lrs, keys, list(stacked_batch)), length=K)
                    return losses, np_, nb_, ns_, nss_, finfs
            else:
                fused = self._make_fused_accum(K, N)

            donate = (0, 1, 2) if self._donate else ()
            self._compiled[fsig] = jax.jit(fused, donate_argnums=donate)
        fn = self._compiled[fsig]
        opt = self.optimizer
        opt_state = {p.name: opt._accumulators[p.name]
                     for p in self._trainable}
        for k in self._comm_state_keys:
            opt_state[k] = opt._accumulators[k]
        param_arrs = [p._data for p in self._trainable]
        buf_arrs = [b._data for b in self._buffers]
        # one LR sample per window; per-step keys are PRE-DRAWN from the
        # host stream — bitwise the draws K sequential calls would make
        lrs = jnp.full((K,), jnp.float32(opt.get_lr()))
        keys = jnp.stack([prandom.next_key() for _ in range(K)])
        if fsig not in self._arg_structs:
            self._capture_arg_structs(
                fsig, (param_arrs, buf_arrs, opt_state, lrs, keys,
                       stacked, self._scaler_state))
        fn = self._maybe_aot(
            fsig, (param_arrs, buf_arrs, opt_state, lrs, keys, stacked,
                   self._scaler_state), "trainstep_fused")
        losses, new_params, new_bufs, new_state, new_scaler, finfs = fn(
            param_arrs, buf_arrs, opt_state, lrs, keys, stacked,
            self._scaler_state)
        for p, a in zip(self._trainable, new_params):
            p._data = a
        for b, a in zip(self._buffers, new_bufs):
            b._data = a
        for n, s in new_state.items():
            opt._accumulators[n] = s
        self._scaler_state = new_scaler
        opt._global_step += K
        # raw device flags, no sync (same contract as __call__)
        self.last_found_inf = jnp.any(finfs)
        self.last_found_inf_per_step = finfs
        if self.check_nan and self.scaler is None and \
                bool(np.asarray(self.last_found_inf)):
            from ..utils.nan_guard import NanInfError

            bad = np.flatnonzero(np.asarray(finfs))
            raise NanInfError(
                f"NaN/Inf in loss or gradients in fused window ending at "
                f"step {opt._global_step} (microbatch index(es) "
                f"{bad.tolist()} of {K})")
        return Tensor(losses, _internal=True)

    def collective_profile(self, mesh=None):
        """CollectiveProfile of the most recently compiled step shape
        (``obs.spmd``): per-kind collective op counts and byte volumes
        parsed from the executable's HLO, attributed to ``mesh``'s axes
        when given (``DistributedTrainStep`` passes its own mesh).
        BLOCKING — re-lowers the step against the arg structs captured
        at compile time (shardings preserved), so call it from reporting
        code, never inside the training loop. None before the first
        step or when lowering fails; cached per (compiled shape, mesh)
        — a failed lowering is NOT cached, so a transient backend
        hiccup doesn't poison later calls."""
        if not self._arg_structs:
            return None
        sig = next(reversed(self._arg_structs))
        key = (sig, None if mesh is None else tuple(mesh.shape.items()))
        if key not in self._profiles:
            from ..obs import spmd as _spmd

            prof = _spmd.profile_jit_fn(
                self._compiled[sig], self._arg_structs[sig], mesh=mesh)
            if prof is None:
                return None
            self._profiles[key] = prof
        return self._profiles[key]


class StaticFunction:
    """jit-compiled forward wrapper (ref: dygraph/jit.py StaticFunction)."""

    def __init__(self, fn, model=None, train=False):
        self._fn = fn
        self.__wrapped__ = fn  # functools convention: inspect/unwrap
        self._model = model
        self._train = train
        self._compiled = {}
        if model is not None:
            self._params, self._buffers = _collect_state([model])
        else:
            self._params, self._buffers = [], []

    def __call__(self, *args):
        arrays = [a._data if isinstance(a, Tensor)
                  else jnp.asarray(np.asarray(a)) for a in args]
        sig = tuple((a.shape, str(a.dtype)) for a in arrays)
        if sig not in self._compiled:
            params, buffers = self._params, self._buffers

            def pure(param_arrs, buf_arrs, key, xs):
                with _rebind(params, list(param_arrs)), \
                        _rebind(buffers, list(buf_arrs)), \
                        prandom.key_context(key), \
                        dispatch.no_grad(), dispatch.fresh_tape():
                    ts = [Tensor(a, _internal=True) for a in xs]
                    out = self._fn(*ts) if self._model is None \
                        else self._fn(self._model, *ts)
                    return jax.tree_util.tree_map(
                        lambda t: t._data if isinstance(t, Tensor) else t, out,
                        is_leaf=lambda t: isinstance(t, Tensor))

            self._compiled[sig] = jax.jit(pure)
        param_arrs = [p._data for p in self._params]
        buf_arrs = [b._data for b in self._buffers]
        out = self._compiled[sig](param_arrs, buf_arrs, prandom.next_key(),
                                  arrays)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a, _internal=True) if isinstance(a, jax.Array) else a,
            out)


def to_static(layer_or_fn=None, input_spec=None, **kwargs):
    """ref: paddle.jit.to_static. Wraps a Layer (its forward) or a function
    into a shape-cached jax.jit callable."""
    from ..nn.layer import Layer

    def wrap(obj):
        if isinstance(obj, Layer):
            sf = StaticFunction(lambda m, *xs: m(*xs), model=obj)
            obj._static_forward = sf
            return sf
        return StaticFunction(obj)

    if layer_or_fn is None:
        return wrap
    return wrap(layer_or_fn)


def jit(fn=None, **kwargs):
    """Decorator alias: ``@paddle_tpu.jit`` compiles an eager function."""
    return to_static(fn, **kwargs)


_no_jit = contextlib.nullcontext
no_jit = _no_jit
