"""Serialization: save/load, inference models, train checkpoints.

Refs: python/paddle/fluid/io.py (save/load_params,
save/load_inference_model), python/paddle/framework/io.py (paddle.save /
paddle.load), fluid/dygraph/checkpoint.py.

Formats are TPU-native rather than protobuf: state dicts go to ``.npz``
(zero-copy into jax arrays), programs to pickle of (op type, var names,
attrs) — kernels are reconstructed from the op registry by name, so a saved
inference program replays into the same single fused XLA executable.
"""
from __future__ import annotations

import binascii
import json
import os
import pickle
import shutil
import threading
import time
import warnings

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..obs import journal as _journal
from ..obs import lockdep as _lockdep
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..resilience import inject as _chaos

_M_SAVE_MS = _metrics.histogram("checkpoint.save_ms")
_M_SNAPSHOT_MS = _metrics.histogram("checkpoint.snapshot_ms")
_M_LOAD_MS = _metrics.histogram("checkpoint.load_ms")
_M_VERIFY_MS = _metrics.histogram("checkpoint.verify_ms")
_M_SAVES = _metrics.counter("checkpoint.saves")
_M_SAVE_FAILURES = _metrics.counter("checkpoint.save_failures")
_M_LOADS = _metrics.counter("checkpoint.loads")
_M_FALLBACKS = _metrics.counter("checkpoint.fallbacks")

__all__ = [
    "save", "load", "save_inference_model", "load_inference_model",
    "save_checkpoint", "load_checkpoint", "verify_checkpoint",
    "AsyncCheckpoint", "wait_checkpoints",
    "CheckpointError",
    "save_vars", "load_vars", "save_params", "load_params",
    "save_persistables", "load_persistables",
    "get_program_parameter", "get_program_persistable_vars",
    "persistable_footprint",
    "load_program_state", "set_program_state", "batch",
]


def _to_numpy_tree(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_tree(v) for v in obj)
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):  # jax array
        return np.asarray(obj)
    return obj


def save(obj, path, protocol=4):
    """ref: paddle.save — state_dicts and nested containers of tensors."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_numpy_tree(obj), f, protocol=protocol)


def load(path, return_numpy=False):
    """ref: paddle.load."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if return_numpy:
        return obj
    return obj  # set_state_dict accepts numpy directly


# -- inference model --------------------------------------------------------


def _forward_slice(program, feed_names, fetch_names):
    """Ops needed to compute fetches from feeds, excluding grad/opt ops
    (ref: prune() in framework.py)."""
    needed = set(fetch_names)
    ops = []
    for op in reversed(program.global_block.ops):
        if op.type.endswith("@grad") or op.type.startswith("optimize_") or \
                op.type in ("fill_ones_like", "fill_zeros_like",
                            "grad_accumulate", "grad_clip"):
            continue
        if any(o in needed for o in op.output_names):
            ops.append(op)
            needed.update(n for n in op.input_names if n is not None)
    return list(reversed(ops)), needed


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, main_program=None, aot_warm=None,
                         **kwargs):
    """ref: fluid.io.save_inference_model. Writes <prefix>.pdmodel (program
    pickle) + <prefix>.pdiparams (weights npz). ``main_program`` is the
    fluid-era spelling of ``program``.

    ``aot_warm``: with an AOT executable cache active
    (``runtime.aot.configure`` / env ``PADDLE_TPU_AOT_CACHE`` /
    ``set_compilation_cache``), the saved model also ships a WARM cache:
    the model is reloaded through the real ``Predictor`` path and its
    batch-1 entry compiled + published, so a serving replica's first
    request hydrates instead of compiling. ``None`` (default) warms iff
    a cache is active, ``False`` never, a directory string warms into
    that cache. Warming is best-effort — a failure journals, it never
    fails the save."""
    from ..static_.program import default_main_program, global_scope

    program = program or main_program or default_main_program()
    feed_names = [v if isinstance(v, str) else v.name for v in feed_vars]
    fetch_names = [v if isinstance(v, str) else v.name for v in fetch_vars]
    ops, needed = _forward_slice(program, feed_names, fetch_names)

    scope = global_scope()
    weights, consts = {}, {}
    for name in needed:
        blk = program.global_block
        if name in program._constants:
            consts[name] = np.asarray(program._constants[name])
        elif blk.has_var(name) and blk.var(name).persistable:
            arr = scope.find_var(name)
            if arr is not None:
                weights[name] = np.asarray(arr)

    desc = {
        "feed_names": feed_names,
        "fetch_names": fetch_names,
        "ops": [(op.type, list(op.input_names), list(op.output_names),
                 op.attrs) for op in ops],
        "vars": {v.name: (list(v.shape), str(np.dtype(v._data.dtype)))
                 for v in program.global_block.vars.values()},
    }
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(desc, f, protocol=4)
    np.savez(path_prefix + ".pdiparams", __consts__=np.array(list(consts)),
             **{("c!" + k): v for k, v in consts.items()},
             **{("w!" + k): v for k, v in weights.items()})
    if aot_warm is not False:
        from ..runtime import aot as _aot

        cache = _aot.resolve_cache(
            aot_warm if isinstance(aot_warm, (str, bytes)) else None)
        if cache is not None:
            try:
                _aot.warm_inference_model(path_prefix, cache=cache)
            except Exception as e:
                # best-effort: a failed warmup never fails the save,
                # but it must leave a trace — replicas will cold-
                # compile and the journal should say why
                _aot._journal_event(action="warm_failed",
                                    prefix=str(path_prefix),
                                    reason=type(e).__name__)
    return feed_names


def load_inference_model(path_prefix, executor=None, **kwargs):
    """ref: fluid.io.load_inference_model → (program, feed_names,
    fetch_names); weights land in the global scope."""
    from ..ops._base import OP_REGISTRY
    from ..static_.program import Program, Operator, global_scope

    with open(path_prefix + ".pdmodel", "rb") as f:
        desc = pickle.load(f)
    data = np.load(path_prefix + ".pdiparams.npz"
                   if os.path.exists(path_prefix + ".pdiparams.npz")
                   else path_prefix + ".pdiparams")

    program = Program()
    blk = program.global_block
    for name, (shape, dtype) in desc["vars"].items():
        v = blk.create_var(name=name, shape=shape, dtype=dtype)
        if any(k == "w!" + name for k in data.files):
            v.persistable = True
    scope = global_scope()
    for k in data.files:
        if k.startswith("w!"):
            scope.set(k[2:], jnp.asarray(data[k]))
        elif k.startswith("c!"):
            program._constants[k[2:]] = jnp.asarray(data[k])
    # int8 bundle entries (quant.quantize_inference_model): the q!/s!
    # pair becomes two persistables and a prepended dequantize_weight op
    # re-emitting the original weight name — downstream ops, the
    # Executor, and the Predictor all run unchanged, with the int8 array
    # as the resident HBM copy and the dequant fused by XLA
    dequant_ops = []
    for k in data.files:
        if not k.startswith("q!"):
            continue
        name = k[2:]
        qarr, sarr = data[k], data["s!" + name]
        dtype = desc["vars"].get(name, (None, "float32"))[1]
        qv = blk.create_var(name=name + "@INT8", shape=list(qarr.shape),
                            dtype=str(qarr.dtype))
        qv.persistable = True
        sv = blk.create_var(name=name + "@SCALE", shape=list(sarr.shape),
                            dtype=str(sarr.dtype))
        sv.persistable = True
        scope.set(name + "@INT8", jnp.asarray(qarr))
        scope.set(name + "@SCALE", jnp.asarray(sarr))
        dequant_ops.append(Operator(
            "dequantize_weight", OP_REGISTRY["dequantize_weight"],
            [name + "@INT8", name + "@SCALE"], [name], {"dtype": dtype}))
    for op in dequant_ops:
        blk.append_op(op)
    for type_, in_names, out_names, attrs in desc["ops"]:
        if type_ not in OP_REGISTRY:
            raise ValueError(
                f"op '{type_}' not in kernel registry; model saved by an "
                "incompatible version")
        blk.append_op(Operator(type_, OP_REGISTRY[type_], in_names,
                               out_names, attrs))
    program.bump()
    return program, desc["feed_names"], desc["fetch_names"]


# -- training checkpoints (ref: fluid incubate checkpoint + SURVEY §2 #45) --


class CheckpointError(RuntimeError):
    """A checkpoint exists but cannot be trusted (truncated, bit-flipped,
    unreadable, or missing files the manifest promises)."""


def _ckpt_step(dirname):
    """int step from a ckpt_* dir name, or None for garbage (a stray
    'ckpt_latest' symlink, 'ckpt_12.bak', editor droppings...)."""
    tail = dirname[len("ckpt_"):]
    return int(tail) if tail.isdigit() else None


def _array_checksums(state):
    """{path: {crc32, shape, dtype}} for every array leaf of a (possibly
    nested) state dict — the per-array integrity record in the manifest."""
    out = {}

    def walk(obj, path):
        if isinstance(obj, dict):
            for k in obj:
                walk(obj[k], f"{path}/{k}" if path else str(k))
        elif isinstance(obj, (list, tuple)):
            for i, v in enumerate(obj):
                walk(v, f"{path}[{i}]")
        elif hasattr(obj, "shape") and hasattr(obj, "dtype"):
            a = np.asarray(obj)
            if not a.flags.c_contiguous:
                a = np.ascontiguousarray(a)
            # crc32 reads the array's buffer directly: no tobytes() copy
            out[path] = {"crc32": binascii.crc32(a) & 0xFFFFFFFF,
                         "shape": list(a.shape), "dtype": str(a.dtype)}

    walk(state, "")
    return out


class _CrcWriter:
    """File-like sink that crc32s what passes through — lets pickle
    STREAM to disk (no whole-checkpoint blob in host RAM) while still
    digesting the exact bytes written."""

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.size = 0

    def write(self, b):
        self._f.write(b)
        self.crc = binascii.crc32(b, self.crc)
        self.size += len(b)
        return len(b)


def _dump_with_digest(obj, path):
    """Stream-pickle an already-numpy-converted tree to ``path``; return
    the manifest file entry. The crc is computed on the exact bytes
    written, so any later truncation/bit-flip of the file is
    detectable."""
    with open(path, "wb") as f:
        w = _CrcWriter(f)
        pickle.dump(obj, w, protocol=4)
    return {"size": w.size, "crc32": w.crc & 0xFFFFFFFF}


class AsyncCheckpoint:
    """Handle for one in-flight ``save_checkpoint(..., async_=True)``.

    The step-path cost (host snapshot of every array) was already paid
    when the handle was returned; the serialized pickle+crc write,
    manifest, and atomic publish run on a background writer thread.
    ``done()`` polls; ``result()`` joins, re-raises any writer failure,
    and returns the published path. A writer that dies mid-save never
    published anything — only a ``.tmp_ckpt_*`` orphan remains, so
    ``load_checkpoint``'s newest-intact fallback stays sound."""

    __slots__ = ("directory", "step", "path", "error", "_done", "_thread")

    def __init__(self, directory, step):
        self.directory = str(directory)
        self.step = int(step)
        self.path = None
        self.error = None
        self._done = threading.Event()
        self._thread = None

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"checkpoint step {self.step} still writing after "
                f"{timeout}s")
        # this save is settled: release the module barrier slot so an
        # already-observed failure is raised once, not at every
        # subsequent save
        global _ASYNC_PENDING
        with _ASYNC_LOCK:
            if _ASYNC_PENDING is self:
                _ASYNC_PENDING = None
        if self.error is not None:
            raise self.error
        return self.path


# held only around _ASYNC_PENDING handoff — wait_checkpoints() blocks
# on handle.result() strictly AFTER releasing (lockdep-checked)
_ASYNC_LOCK = _lockdep.lock("checkpoint.async_barrier")
_ASYNC_PENDING = None  # at most ONE async save is ever in flight


def wait_checkpoints(timeout=None):
    """Barrier on the in-flight async checkpoint save: returns its
    published path (or None when nothing is pending) and re-raises a
    writer failure. Call before a clean exit — e.g. the graceful-
    preemption path — so the last snapshot is durable."""
    with _ASYNC_LOCK:
        handle = _ASYNC_PENDING
    if handle is None:
        return None
    return handle.result(timeout)


def _host_copy_tree(obj):
    """Numpy-materialize AND copy a state tree: the async writer must
    own its bytes outright — ``np.asarray`` on a CPU-backend jax array
    can alias the device buffer, which the next (donating) train step
    is free to invalidate while the writer is still serializing."""
    out = _to_numpy_tree(obj)

    def walk(o):
        if isinstance(o, dict):
            return {k: walk(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return type(o)(walk(v) for v in o)
        if isinstance(o, np.ndarray):
            return np.array(o, copy=True)
        return o

    return walk(out)


def _snapshot_checkpoint(step, model, optimizer, scheduler, extra, copy):
    """Host-side materialization of everything the writer needs. This is
    the ONLY part of a save that reads live model state — it runs on the
    caller's thread, so by the time an async writer starts, the step
    loop may mutate/donate freely."""
    tree = _host_copy_tree if copy else _to_numpy_tree
    state = {"step": int(step), "extra": extra or {}}
    snap = {"model": None, "opt": None}
    if model is not None:
        snap["model"] = tree({k: v for k, v in model.state_dict().items()})
    if optimizer is not None:
        snap["opt"] = tree(optimizer.state_dict())
    if scheduler is not None:
        state["scheduler"] = scheduler.state_dict()
    snap["state"] = tree(state)
    return snap


def save_checkpoint(directory, step, model=None, optimizer=None,
                    scheduler=None, keep_last=3, extra=None, async_=False):
    """Atomic checkpoint with keep-last-k rotation, resume metadata, and
    an integrity manifest (per-file and per-array crc32) that
    ``load_checkpoint`` verifies before trusting the data.

    ``async_=True`` keeps the serialized write off the step loop:
    the state is snapshotted to host arrays on the calling thread (the
    only step-path cost), and the pickle+crc write, manifest, and
    atomic publish happen on a background writer thread; the call
    returns an :class:`AsyncCheckpoint` handle. Exactly one save is in
    flight at a time — any save (sync or async) first barriers on the
    previous in-flight one and re-raises its failure (once). The
    ``ckpt_<step>`` dir only appears when the writer COMPLETED, so a
    writer that dies mid-save leaves nothing the newest-intact fallback
    could mistake for a checkpoint."""
    # barrier: the previous writer owns the directory (rotation!) until
    # it finishes; its failure must surface, not vanish
    wait_checkpoints()
    t0 = time.perf_counter()
    if not async_:
        with _trace.span("checkpoint.save", step=int(step)):
            snap = _snapshot_checkpoint(step, model, optimizer, scheduler,
                                        extra, copy=False)
            out = _write_checkpoint(directory, step, snap, keep_last)
        # a save that died (e.g. injected ckpt_crash) published nothing:
        # checkpoint.saves counts only durable checkpoints
        save_ms = (time.perf_counter() - t0) * 1e3
        _M_SAVE_MS.observe(save_ms)
        _M_SAVES.inc()
        if _journal.ACTIVE is not None:
            _journal.ACTIVE.event("checkpoint.save", step=int(step),
                                  ms=save_ms, dir=str(directory))
        return out

    with _trace.span("checkpoint.snapshot", step=int(step)):
        snap = _snapshot_checkpoint(step, model, optimizer, scheduler,
                                    extra, copy=True)
    _M_SNAPSHOT_MS.observe((time.perf_counter() - t0) * 1e3)
    handle = AsyncCheckpoint(directory, step)

    def _writer():
        try:
            with _trace.span("checkpoint.save", step=int(step), async_=1):
                handle.path = _write_checkpoint(directory, step, snap,
                                                keep_last)
            save_ms = (time.perf_counter() - t0) * 1e3
            _M_SAVE_MS.observe(save_ms)
            _M_SAVES.inc()  # published: NOW it counts
            if _journal.ACTIVE is not None:
                _journal.ACTIVE.event("checkpoint.save", step=int(step),
                                      ms=save_ms, dir=str(directory),
                                      async_=True)
        except BaseException as e:  # surfaced by the next barrier
            handle.error = e
            _M_SAVE_FAILURES.inc()
            if _journal.ACTIVE is not None:
                _journal.ACTIVE.event(
                    "checkpoint.save_failed", step=int(step),
                    dir=str(directory),
                    error=f"{type(e).__name__}: {e}")
        finally:
            handle._done.set()

    # non-daemon: a CLEAN interpreter exit joins the writer (free
    # durability); a crash/SIGKILL still orphans only the tmp dir
    t = threading.Thread(target=_writer, name=f"ckpt-writer-{step}",
                         daemon=False)
    handle._thread = t
    global _ASYNC_PENDING
    with _ASYNC_LOCK:
        _ASYNC_PENDING = handle
    t.start()
    return handle


def _write_checkpoint(directory, step, snap, keep_last):
    """Serialize an already-snapshotted state tree to
    ``.tmp_ckpt_<step>`` and atomically publish it as ``ckpt_<step>``.
    Runs on the caller thread (sync save) or the writer thread (async
    save); touches only the snapshot, never live model state."""
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f".tmp_ckpt_{step}")
    final = os.path.join(directory, f"ckpt_{step}")
    os.makedirs(tmp, exist_ok=True)
    manifest = {"format": 1, "step": int(step), "files": {}, "arrays": {}}
    if snap["model"] is not None:
        manifest["files"]["model.pdparams"] = _dump_with_digest(
            snap["model"], os.path.join(tmp, "model.pdparams"))
        manifest["arrays"]["model.pdparams"] = _array_checksums(
            snap["model"])
    if snap["opt"] is not None:
        manifest["files"]["opt.pdopt"] = _dump_with_digest(
            snap["opt"], os.path.join(tmp, "opt.pdopt"))
        manifest["arrays"]["opt.pdopt"] = _array_checksums(snap["opt"])
    manifest["files"]["meta.pkl"] = _dump_with_digest(
        snap["state"], os.path.join(tmp, "meta.pkl"))
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    if _chaos.ACTIVE:
        _chaos.fire("ckpt_slow", tmp)  # stall window: a writer killed
        # here leaves only the tmp orphan — publish never ran
        _chaos.fire("ckpt_crash", tmp)  # simulated death: tmp orphaned
    if os.path.exists(final):
        shutil.rmtree(final)
    os.replace(tmp, final)  # atomic publish: readers never see partial state
    if _chaos.ACTIVE:  # post-publish media corruption
        _chaos.fire("ckpt_truncate", final)
        _chaos.fire("ckpt_bitflip", final)
    # rotate (ignoring garbage dirs a crashed/foreign writer left behind)
    ckpts = sorted(
        (d for d in os.listdir(directory)
         if d.startswith("ckpt_") and _ckpt_step(d) is not None),
        key=_ckpt_step)
    for old in ckpts[:-keep_last]:
        shutil.rmtree(os.path.join(directory, old))
    return final


def _read_verified(path, name, entry):
    """Read + verify one checkpoint file against its manifest entry;
    returns the unpickled object or raises CheckpointError."""
    fpath = os.path.join(path, name)
    if not os.path.exists(fpath):
        raise CheckpointError(f"{path}: manifest lists {name} but the "
                              "file is missing")
    if entry is not None:
        # chunked digest pass: O(chunk) host memory even for huge files
        crc, size = 0, 0
        with open(fpath, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                crc = binascii.crc32(chunk, crc)
                size += len(chunk)
        if size != entry["size"]:
            raise CheckpointError(
                f"{fpath}: size {size} != manifest {entry['size']} "
                "(truncated write?)")
        if (crc & 0xFFFFFFFF) != entry["crc32"]:
            raise CheckpointError(f"{fpath}: crc32 mismatch (corrupt)")
    try:
        with open(fpath, "rb") as f:
            return pickle.load(f)
    except Exception as e:
        raise CheckpointError(f"{fpath}: unreadable ({e})") from e


def _load_and_verify(path, deep=False):
    """Load every file of one checkpoint dir, verifying against the
    manifest when present (legacy manifest-less checkpoints are accepted
    if their pickles parse). Returns {filename: object}. ``deep``
    additionally re-verifies every per-array crc32 — the file-level crc
    over the same bytes already subsumes that on the normal load path,
    so the deep pass is for ``verify_checkpoint`` audits, where it
    pins down WHICH array diverged (and catches a file whose file-level
    digest was regenerated around an array-level edit)."""
    mpath = os.path.join(path, "manifest.json")
    manifest = None
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except Exception as e:
            raise CheckpointError(
                f"{mpath}: unreadable manifest ({e})") from e
        # a bit-flipped manifest can stay valid JSON with a broken
        # shape; that must read as "corrupt checkpoint" (fallback), not
        # KeyError (abort)
        if not isinstance(manifest, dict) or \
                not isinstance(manifest.get("files"), dict) or not all(
                    isinstance(e, dict) and "size" in e and "crc32" in e
                    for e in manifest["files"].values()):
            raise CheckpointError(
                f"{mpath}: malformed manifest structure (corrupt)")
    out = {}
    names = list(manifest["files"]) if manifest else \
        [n for n in ("meta.pkl", "model.pdparams", "opt.pdopt")
         if os.path.exists(os.path.join(path, n))]
    if "meta.pkl" not in names:
        raise CheckpointError(f"{path}: no meta.pkl")
    for name in names:
        entry = manifest["files"][name] if manifest else None
        obj = _read_verified(path, name, entry)
        if deep and manifest and name in manifest.get("arrays", {}):
            got = _array_checksums(obj)
            want = manifest["arrays"][name]
            if got != want:
                bad = sorted(set(want) ^ set(got)) or sorted(
                    k for k in want if got.get(k) != want[k])
                raise CheckpointError(
                    f"{path}/{name}: per-array checksum mismatch "
                    f"({bad[:4]})")
        out[name] = obj
    return out


def verify_checkpoint(path):
    """(ok, problems): integrity audit of one checkpoint dir without
    applying it to any model — includes the deep per-array checksum
    pass, so a mismatch names the specific corrupt array."""
    t0 = time.perf_counter()
    try:
        _load_and_verify(path, deep=True)
        return True, []
    except CheckpointError as e:
        return False, [str(e)]
    finally:
        _M_VERIFY_MS.observe((time.perf_counter() - t0) * 1e3)


def _tmp_age(path):
    """Seconds since the newest mtime under a tmp artifact (a LIVE
    save_checkpoint is actively writing, so its newest file is fresh)."""
    import time

    newest = os.path.getmtime(path)
    if os.path.isdir(path):
        for f in os.listdir(path):
            try:
                newest = max(newest, os.path.getmtime(
                    os.path.join(path, f)))
            except OSError:
                pass
    return time.time() - newest


def _clean_orphan_tmp(directory, grace_secs=60.0):
    """Remove ``.tmp_ckpt_*`` dirs (and stray ``*.tmp`` files) a crashed
    ``save_checkpoint`` left behind — they hold partial state and would
    otherwise accumulate forever. Artifacts younger than ``grace_secs``
    are left alone: they may belong to a CONCURRENT saver in another
    process, and tmp dirs never match the ``ckpt_*`` load pattern, so
    deferring their cleanup to a later load costs nothing."""
    for d in os.listdir(directory):
        if d.startswith(".tmp_ckpt_") or d.endswith(".tmp"):
            p = os.path.join(directory, d)
            try:
                if _tmp_age(p) < grace_secs:
                    continue
            except OSError:
                continue  # vanished: the concurrent saver published it
            warnings.warn(
                f"removing orphaned checkpoint artifact {p} (crashed "
                "save_checkpoint)", RuntimeWarning)
            if os.path.isdir(p):
                shutil.rmtree(p, ignore_errors=True)
            else:
                try:
                    os.remove(p)
                except OSError:
                    pass


def load_checkpoint(directory, model=None, optimizer=None, scheduler=None,
                    step=None):
    """Load the newest *intact* checkpoint (or the given ``step``);
    returns the resume step or None when the directory holds none.

    Robustness contract (chaos-tested): orphaned ``.tmp_ckpt_*`` dirs
    from crashed saves are cleaned up; garbage ``ckpt_*`` names are
    ignored with a warning; a corrupt/truncated newest checkpoint makes
    the loader fall back to the next-newest intact one. Only when every
    checkpoint fails verification — or an explicitly requested ``step``
    does — is ``CheckpointError`` raised.
    """
    t0 = time.perf_counter()
    with _trace.span("checkpoint.load"):
        out = _load_checkpoint(directory, model, optimizer, scheduler, step)
    if out is not None:  # an empty/missing directory loaded nothing:
        # checkpoint.loads counts only actual resumes (mirroring saves)
        load_ms = (time.perf_counter() - t0) * 1e3
        _M_LOAD_MS.observe(load_ms)
        _M_LOADS.inc()
        if _journal.ACTIVE is not None:
            _journal.ACTIVE.event("checkpoint.load", step=int(out),
                                  ms=load_ms, dir=str(directory))
    return out


def _load_checkpoint(directory, model, optimizer, scheduler, step):
    if not os.path.isdir(directory):
        return None
    with _ASYNC_LOCK:
        pending = _ASYNC_PENDING
    if pending is None or pending.done():
        # never sweep while OUR writer thread is mid-save: its live
        # .tmp_ckpt_* is not an orphan (cross-process savers are
        # already covered by the mtime grace period)
        _clean_orphan_tmp(directory)
    entries = []
    for d in os.listdir(directory):
        if not d.startswith("ckpt_"):
            continue
        s = _ckpt_step(d)
        if s is None:
            warnings.warn(
                f"ignoring non-checkpoint entry {d!r} in {directory}",
                RuntimeWarning)
            continue
        entries.append((s, d))
    entries.sort()
    if not entries:
        return None
    if step is not None:
        match = [d for s, d in entries if s == int(step)]
        if not match:
            raise CheckpointError(
                f"no checkpoint for step {step} in {directory} "
                f"(have steps {[s for s, _ in entries]})")
        payload = _load_and_verify(os.path.join(directory, match[0]))
    else:
        payload, failures = None, []
        for s, d in reversed(entries):
            try:
                payload = _load_and_verify(os.path.join(directory, d))
                break
            except CheckpointError as e:
                failures.append(str(e))
                _M_FALLBACKS.inc()
                if _journal.ACTIVE is not None:
                    _journal.ACTIVE.event("checkpoint.fallback",
                                          ckpt=d, error=str(e))
                warnings.warn(
                    f"checkpoint {d} failed verification ({e}); falling "
                    "back to the next-newest", RuntimeWarning)
        if payload is None:
            raise CheckpointError(
                f"every checkpoint in {directory} is corrupt:\n  " +
                "\n  ".join(failures))
    meta = payload["meta.pkl"]
    if model is not None:
        if "model.pdparams" not in payload:
            raise CheckpointError(
                f"checkpoint step {meta['step']} has no model state")
        model.set_state_dict(payload["model.pdparams"])
    if optimizer is not None and "opt.pdopt" in payload:
        optimizer.set_state_dict(payload["opt.pdopt"])
    if scheduler is not None and "scheduler" in meta:
        scheduler.set_state_dict(meta["scheduler"])
    return meta["step"]


# -- fluid.io var-level save/load (ref: fluid/io.py __all__) -----------------


def _program_vars(program, predicate):
    out = []
    for v in program.global_block.vars.values():
        if predicate(v):
            out.append(v)
    return out


def get_program_parameter(program):
    """ref: io.py get_program_parameter."""
    return _program_vars(program, lambda v: v.is_parameter)


def get_program_persistable_vars(program):
    """ref: io.py get_program_persistable_vars."""
    return _program_vars(program, lambda v: v.persistable)


def persistable_footprint(program, scope=None):
    """Byte footprint of a Program's persistables as materialized in the
    scope — what a checkpoint of this program writes and what every
    device holds when the Executor replicates persistables under SPMD
    (``obs.spmd.sharding_report`` reports the same totals per cache
    entry). Returns ``{"vars": [{name, shape, dtype, bytes}],
    "total_bytes": N}``; vars not yet in the scope report their
    metadata with ``bytes=None``. Metadata reads only — never syncs an
    array off-device."""
    import numpy as _np

    from ..static_.program import global_scope

    scope = scope or global_scope()
    rows = []
    total = 0
    for v in get_program_persistable_vars(program):
        arr = scope.find_var(v.name)
        if arr is not None:
            shape = tuple(int(s) for s in arr.shape)
            dtype = str(_np.dtype(arr.dtype))
            nbytes = int(_np.prod(shape)) * _np.dtype(arr.dtype).itemsize \
                if shape else _np.dtype(arr.dtype).itemsize
            total += nbytes
        else:
            shape = tuple(v.shape) if v.shape is not None else None
            dtype = str(getattr(v, "dtype", None))
            nbytes = None
        rows.append({"name": v.name, "shape": shape, "dtype": dtype,
                     "bytes": nbytes})
    return {"vars": rows, "total_bytes": total}


def _var_values(program, vars_, scope=None):
    from ..static_.program import global_scope

    scope = scope or global_scope()
    out = {}
    for v in vars_:
        name = v if isinstance(v, str) else v.name
        arr = scope.find_var(name)
        if arr is None and hasattr(v, "_data") and v._data is not None:
            arr = v._data
        if arr is not None:
            out[name] = np.asarray(arr)
    return out


def _vars_path(dirname, filename, default):
    """np.savez appends .npz on write but np.load does NOT on read —
    normalize once so non-default filenames round-trip."""
    p = os.path.join(dirname, filename or default) if dirname \
        else (filename or default)
    return p if p.endswith(".npz") else p + ".npz"


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    """Save selected program variables as one npz (ref: io.py
    save_vars; per-var files collapse into one archive here)."""
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    if vars is None:
        vars = _program_vars(program, predicate or
                             (lambda v: v.persistable))
    values = _var_values(program, vars)
    wanted = [v if isinstance(v, str) else v.name for v in vars]
    valueless = sorted(set(wanted) - set(values))
    if valueless:  # a silent partial save only fails at restore time
        raise ValueError(
            f"save_vars: no value in scope for {valueless} — run the "
            "startup program (initializers) before saving")
    path = _vars_path(dirname, filename, "__vars__.npz")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **values)
    return path


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """Load variables saved by save_vars into the scope (ref: io.py
    load_vars)."""
    from ..static_.program import default_main_program, global_scope

    program = main_program or default_main_program()
    path = _vars_path(dirname, filename, "__vars__.npz")
    data = np.load(path, allow_pickle=False)
    scope = scope or global_scope()
    want = None
    if vars is not None:
        want = {v if isinstance(v, str) else v.name for v in vars}
    elif predicate is not None:
        want = {v.name for v in _program_vars(program, predicate)}
    if want is not None:
        missing = sorted(want - set(data.files))
        if missing:  # a silent partial restore looks like success
            raise ValueError(
                f"load_vars: {path} is missing variables {missing}")
    for name in data.files:
        if want is None or name in want:
            scope.set(name, jnp.asarray(data[name]))


def save_params(executor=None, dirname=None, main_program=None,
                filename=None):
    """ref: io.py save_params — parameters only."""
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    return save_vars(executor, dirname, program,
                     vars=get_program_parameter(program),
                     filename=filename or "__params__.npz")


def load_params(executor=None, dirname=None, main_program=None,
                filename=None):
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    load_vars(executor, dirname, program,
              vars=get_program_parameter(program),
              filename=filename or "__params__.npz")


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """ref: io.py save_persistables — all persistable vars (params +
    optimizer state recorded in the program)."""
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    return save_vars(executor, dirname, program,
                     vars=get_program_persistable_vars(program),
                     filename=filename or "__persistables__.npz")


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    load_vars(executor, dirname, program,
              vars=get_program_persistable_vars(program),
              filename=filename or "__persistables__.npz")


def load_program_state(model_path, var_list=None):
    """ref: io.py load_program_state -> dict name->ndarray. Accepts an
    .npz path, a save_params/save_persistables dirname, or a save()
    pickle path."""
    p = model_path if model_path.endswith(".npz") else model_path + ".npz"
    if os.path.isdir(model_path):
        # the reference usage passes the save_* dirname
        for fn in ("__params__.npz", "__persistables__.npz",
                   "__vars__.npz"):
            cand = os.path.join(model_path, fn)
            if os.path.exists(cand):
                p = cand
                break
        else:
            raise FileNotFoundError(
                f"no saved variable archive under {model_path}")
    elif not os.path.exists(p):
        if not os.path.exists(model_path):
            raise FileNotFoundError(
                f"no program state at {model_path} (tried {p} too)")
        obj = load(model_path, return_numpy=True)  # a save() pickle
        if not isinstance(obj, dict):
            raise ValueError(
                f"{model_path} holds {type(obj).__name__}, not a "
                "name->array state dict")
        state = {k: np.asarray(v) for k, v in obj.items()}
        if var_list is not None:  # same strictness as the npz branch
            want = {v if isinstance(v, str) else v.name
                    for v in var_list}
            missing = sorted(want - set(state))
            if missing:
                raise ValueError(
                    f"load_program_state: {model_path} is missing "
                    f"{missing}")
            state = {k: v for k, v in state.items() if k in want}
        return state
    data = np.load(p, allow_pickle=False)
    want = None if var_list is None else {
        v if isinstance(v, str) else v.name for v in var_list}
    if want is not None:
        missing = sorted(want - set(data.files))
        if missing:  # same strictness as load_vars
            raise ValueError(
                f"load_program_state: {p} is missing {missing}")
    return {n: data[n] for n in data.files
            if want is None or n in want}


def set_program_state(program, state_dict):
    """ref: io.py set_program_state: write arrays into the program's
    scope (and any materialized Variable handles)."""
    from ..static_.program import global_scope

    scope = global_scope()
    blk = program.global_block
    for name, arr in state_dict.items():
        scope.set(name, jnp.asarray(arr))
        if blk.has_var(name):
            v = blk.var(name)
            if getattr(v, "_data", None) is not None:
                v._data = jnp.asarray(arr)
from ..reader import batch  # noqa: F401,E402  (fluid.io.batch)
