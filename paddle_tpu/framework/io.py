"""Serialization: save/load, inference models, train checkpoints.

Refs: python/paddle/fluid/io.py (save/load_params,
save/load_inference_model), python/paddle/framework/io.py (paddle.save /
paddle.load), fluid/dygraph/checkpoint.py.

Formats are TPU-native rather than protobuf: state dicts go to ``.npz``
(zero-copy into jax arrays), programs to pickle of (op type, var names,
attrs) — kernels are reconstructed from the op registry by name, so a saved
inference program replays into the same single fused XLA executable.
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "save", "load", "save_inference_model", "load_inference_model",
    "save_checkpoint", "load_checkpoint",
    "save_vars", "load_vars", "save_params", "load_params",
    "save_persistables", "load_persistables",
    "get_program_parameter", "get_program_persistable_vars",
    "load_program_state", "set_program_state", "batch",
]


def _to_numpy_tree(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_tree(v) for v in obj)
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):  # jax array
        return np.asarray(obj)
    return obj


def save(obj, path, protocol=4):
    """ref: paddle.save — state_dicts and nested containers of tensors."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_numpy_tree(obj), f, protocol=protocol)


def load(path, return_numpy=False):
    """ref: paddle.load."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if return_numpy:
        return obj
    return obj  # set_state_dict accepts numpy directly


# -- inference model --------------------------------------------------------


def _forward_slice(program, feed_names, fetch_names):
    """Ops needed to compute fetches from feeds, excluding grad/opt ops
    (ref: prune() in framework.py)."""
    needed = set(fetch_names)
    ops = []
    for op in reversed(program.global_block.ops):
        if op.type.endswith("@grad") or op.type.startswith("optimize_") or \
                op.type in ("fill_ones_like", "fill_zeros_like",
                            "grad_accumulate", "grad_clip"):
            continue
        if any(o in needed for o in op.output_names):
            ops.append(op)
            needed.update(n for n in op.input_names if n is not None)
    return list(reversed(ops)), needed


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, main_program=None, **kwargs):
    """ref: fluid.io.save_inference_model. Writes <prefix>.pdmodel (program
    pickle) + <prefix>.pdiparams (weights npz). ``main_program`` is the
    fluid-era spelling of ``program``."""
    from ..static_.program import default_main_program, global_scope

    program = program or main_program or default_main_program()
    feed_names = [v if isinstance(v, str) else v.name for v in feed_vars]
    fetch_names = [v if isinstance(v, str) else v.name for v in fetch_vars]
    ops, needed = _forward_slice(program, feed_names, fetch_names)

    scope = global_scope()
    weights, consts = {}, {}
    for name in needed:
        blk = program.global_block
        if name in program._constants:
            consts[name] = np.asarray(program._constants[name])
        elif blk.has_var(name) and blk.var(name).persistable:
            arr = scope.find_var(name)
            if arr is not None:
                weights[name] = np.asarray(arr)

    desc = {
        "feed_names": feed_names,
        "fetch_names": fetch_names,
        "ops": [(op.type, list(op.input_names), list(op.output_names),
                 op.attrs) for op in ops],
        "vars": {v.name: (list(v.shape), str(np.dtype(v._data.dtype)))
                 for v in program.global_block.vars.values()},
    }
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(desc, f, protocol=4)
    np.savez(path_prefix + ".pdiparams", __consts__=np.array(list(consts)),
             **{("c!" + k): v for k, v in consts.items()},
             **{("w!" + k): v for k, v in weights.items()})
    return feed_names


def load_inference_model(path_prefix, executor=None, **kwargs):
    """ref: fluid.io.load_inference_model → (program, feed_names,
    fetch_names); weights land in the global scope."""
    from ..ops._base import OP_REGISTRY
    from ..static_.program import Program, Operator, global_scope

    with open(path_prefix + ".pdmodel", "rb") as f:
        desc = pickle.load(f)
    data = np.load(path_prefix + ".pdiparams.npz"
                   if os.path.exists(path_prefix + ".pdiparams.npz")
                   else path_prefix + ".pdiparams")

    program = Program()
    blk = program.global_block
    for name, (shape, dtype) in desc["vars"].items():
        v = blk.create_var(name=name, shape=shape, dtype=dtype)
        if any(k == "w!" + name for k in data.files):
            v.persistable = True
    scope = global_scope()
    for k in data.files:
        if k.startswith("w!"):
            scope.set(k[2:], jnp.asarray(data[k]))
        elif k.startswith("c!"):
            program._constants[k[2:]] = jnp.asarray(data[k])
    # int8 bundle entries (quant.quantize_inference_model): the q!/s!
    # pair becomes two persistables and a prepended dequantize_weight op
    # re-emitting the original weight name — downstream ops, the
    # Executor, and the Predictor all run unchanged, with the int8 array
    # as the resident HBM copy and the dequant fused by XLA
    dequant_ops = []
    for k in data.files:
        if not k.startswith("q!"):
            continue
        name = k[2:]
        qarr, sarr = data[k], data["s!" + name]
        dtype = desc["vars"].get(name, (None, "float32"))[1]
        qv = blk.create_var(name=name + "@INT8", shape=list(qarr.shape),
                            dtype=str(qarr.dtype))
        qv.persistable = True
        sv = blk.create_var(name=name + "@SCALE", shape=list(sarr.shape),
                            dtype=str(sarr.dtype))
        sv.persistable = True
        scope.set(name + "@INT8", jnp.asarray(qarr))
        scope.set(name + "@SCALE", jnp.asarray(sarr))
        dequant_ops.append(Operator(
            "dequantize_weight", OP_REGISTRY["dequantize_weight"],
            [name + "@INT8", name + "@SCALE"], [name], {"dtype": dtype}))
    for op in dequant_ops:
        blk.append_op(op)
    for type_, in_names, out_names, attrs in desc["ops"]:
        if type_ not in OP_REGISTRY:
            raise ValueError(
                f"op '{type_}' not in kernel registry; model saved by an "
                "incompatible version")
        blk.append_op(Operator(type_, OP_REGISTRY[type_], in_names,
                               out_names, attrs))
    program.bump()
    return program, desc["feed_names"], desc["fetch_names"]


# -- training checkpoints (ref: fluid incubate checkpoint + SURVEY §2 #45) --


def save_checkpoint(directory, step, model=None, optimizer=None,
                    scheduler=None, keep_last=3, extra=None):
    """Atomic checkpoint with keep-last-k rotation and resume metadata."""
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f".tmp_ckpt_{step}")
    final = os.path.join(directory, f"ckpt_{step}")
    os.makedirs(tmp, exist_ok=True)
    state = {"step": int(step), "extra": extra or {}}
    if model is not None:
        save({k: v for k, v in model.state_dict().items()},
             os.path.join(tmp, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(tmp, "opt.pdopt"))
    if scheduler is not None:
        state["scheduler"] = scheduler.state_dict()
    save(state, os.path.join(tmp, "meta.pkl"))
    if os.path.exists(final):
        import shutil

        shutil.rmtree(final)
    os.replace(tmp, final)  # atomic publish: readers never see partial state
    # rotate
    ckpts = sorted((d for d in os.listdir(directory) if d.startswith("ckpt_")),
                   key=lambda d: int(d.split("_")[1]))
    for old in ckpts[:-keep_last]:
        import shutil

        shutil.rmtree(os.path.join(directory, old))
    return final


def load_checkpoint(directory, model=None, optimizer=None, scheduler=None,
                    step=None):
    """Load latest (or given) checkpoint; returns resume step or None."""
    if not os.path.isdir(directory):
        return None
    ckpts = sorted((d for d in os.listdir(directory) if d.startswith("ckpt_")),
                   key=lambda d: int(d.split("_")[1]))
    if not ckpts:
        return None
    name = f"ckpt_{step}" if step is not None else ckpts[-1]
    path = os.path.join(directory, name)
    meta = load(os.path.join(path, "meta.pkl"))
    if model is not None:
        model.set_state_dict(load(os.path.join(path, "model.pdparams")))
    if optimizer is not None and os.path.exists(os.path.join(path, "opt.pdopt")):
        optimizer.set_state_dict(load(os.path.join(path, "opt.pdopt")))
    if scheduler is not None and "scheduler" in meta:
        scheduler.set_state_dict(meta["scheduler"])
    return meta["step"]


# -- fluid.io var-level save/load (ref: fluid/io.py __all__) -----------------


def _program_vars(program, predicate):
    out = []
    for v in program.global_block.vars.values():
        if predicate(v):
            out.append(v)
    return out


def get_program_parameter(program):
    """ref: io.py get_program_parameter."""
    return _program_vars(program, lambda v: v.is_parameter)


def get_program_persistable_vars(program):
    """ref: io.py get_program_persistable_vars."""
    return _program_vars(program, lambda v: v.persistable)


def _var_values(program, vars_, scope=None):
    from ..static_.program import global_scope

    scope = scope or global_scope()
    out = {}
    for v in vars_:
        name = v if isinstance(v, str) else v.name
        arr = scope.find_var(name)
        if arr is None and hasattr(v, "_data") and v._data is not None:
            arr = v._data
        if arr is not None:
            out[name] = np.asarray(arr)
    return out


def _vars_path(dirname, filename, default):
    """np.savez appends .npz on write but np.load does NOT on read —
    normalize once so non-default filenames round-trip."""
    p = os.path.join(dirname, filename or default) if dirname \
        else (filename or default)
    return p if p.endswith(".npz") else p + ".npz"


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    """Save selected program variables as one npz (ref: io.py
    save_vars; per-var files collapse into one archive here)."""
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    if vars is None:
        vars = _program_vars(program, predicate or
                             (lambda v: v.persistable))
    values = _var_values(program, vars)
    wanted = [v if isinstance(v, str) else v.name for v in vars]
    valueless = sorted(set(wanted) - set(values))
    if valueless:  # a silent partial save only fails at restore time
        raise ValueError(
            f"save_vars: no value in scope for {valueless} — run the "
            "startup program (initializers) before saving")
    path = _vars_path(dirname, filename, "__vars__.npz")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **values)
    return path


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """Load variables saved by save_vars into the scope (ref: io.py
    load_vars)."""
    from ..static_.program import default_main_program, global_scope

    program = main_program or default_main_program()
    path = _vars_path(dirname, filename, "__vars__.npz")
    data = np.load(path, allow_pickle=False)
    scope = scope or global_scope()
    want = None
    if vars is not None:
        want = {v if isinstance(v, str) else v.name for v in vars}
    elif predicate is not None:
        want = {v.name for v in _program_vars(program, predicate)}
    if want is not None:
        missing = sorted(want - set(data.files))
        if missing:  # a silent partial restore looks like success
            raise ValueError(
                f"load_vars: {path} is missing variables {missing}")
    for name in data.files:
        if want is None or name in want:
            scope.set(name, jnp.asarray(data[name]))


def save_params(executor=None, dirname=None, main_program=None,
                filename=None):
    """ref: io.py save_params — parameters only."""
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    return save_vars(executor, dirname, program,
                     vars=get_program_parameter(program),
                     filename=filename or "__params__.npz")


def load_params(executor=None, dirname=None, main_program=None,
                filename=None):
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    load_vars(executor, dirname, program,
              vars=get_program_parameter(program),
              filename=filename or "__params__.npz")


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """ref: io.py save_persistables — all persistable vars (params +
    optimizer state recorded in the program)."""
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    return save_vars(executor, dirname, program,
                     vars=get_program_persistable_vars(program),
                     filename=filename or "__persistables__.npz")


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    from ..static_.program import default_main_program

    program = main_program or default_main_program()
    load_vars(executor, dirname, program,
              vars=get_program_persistable_vars(program),
              filename=filename or "__persistables__.npz")


def load_program_state(model_path, var_list=None):
    """ref: io.py load_program_state -> dict name->ndarray. Accepts an
    .npz path, a save_params/save_persistables dirname, or a save()
    pickle path."""
    p = model_path if model_path.endswith(".npz") else model_path + ".npz"
    if os.path.isdir(model_path):
        # the reference usage passes the save_* dirname
        for fn in ("__params__.npz", "__persistables__.npz",
                   "__vars__.npz"):
            cand = os.path.join(model_path, fn)
            if os.path.exists(cand):
                p = cand
                break
        else:
            raise FileNotFoundError(
                f"no saved variable archive under {model_path}")
    elif not os.path.exists(p):
        if not os.path.exists(model_path):
            raise FileNotFoundError(
                f"no program state at {model_path} (tried {p} too)")
        obj = load(model_path, return_numpy=True)  # a save() pickle
        if not isinstance(obj, dict):
            raise ValueError(
                f"{model_path} holds {type(obj).__name__}, not a "
                "name->array state dict")
        state = {k: np.asarray(v) for k, v in obj.items()}
        if var_list is not None:  # same strictness as the npz branch
            want = {v if isinstance(v, str) else v.name
                    for v in var_list}
            missing = sorted(want - set(state))
            if missing:
                raise ValueError(
                    f"load_program_state: {model_path} is missing "
                    f"{missing}")
            state = {k: v for k, v in state.items() if k in want}
        return state
    data = np.load(p, allow_pickle=False)
    want = None if var_list is None else {
        v if isinstance(v, str) else v.name for v in var_list}
    if want is not None:
        missing = sorted(want - set(data.files))
        if missing:  # same strictness as load_vars
            raise ValueError(
                f"load_program_state: {p} is missing {missing}")
    return {n: data[n] for n in data.files
            if want is None or n in want}


def set_program_state(program, state_dict):
    """ref: io.py set_program_state: write arrays into the program's
    scope (and any materialized Variable handles)."""
    from ..static_.program import global_scope

    scope = global_scope()
    blk = program.global_block
    for name, arr in state_dict.items():
        scope.set(name, jnp.asarray(arr))
        if blk.has_var(name):
            v = blk.var(name)
            if getattr(v, "_data", None) is not None:
                v._data = jnp.asarray(arr)
from ..reader import batch  # noqa: F401,E402  (fluid.io.batch)
